"""Host-side span tracer: Dapper-style spans over the control plane.

The training engine's device time is already observable through
``jax.profiler`` traces (summarized by ``benchmarks/trace_top.py``);
what was missing is the HOST half — which unit, epoch, or serving
request the device lanes were working for.  This module records
host-side spans (unit fires, workflow runs, epochs, serving batch
dispatches, compiles) into a bounded ring buffer and exports them as
Chrome-trace/Perfetto JSON (``ph: "X"`` complete events), so
``chrome://tracing`` / Perfetto can show them, ``WebStatusServer``
serves them live at ``/trace.json``, and ``trace_top.py --spans``
merges them with a device-trace summary.

Correlation with XLA device lanes: inside every span the tracer also
enters ``jax.profiler.TraceAnnotation`` (a TraceMe), so when a
``jax.profiler`` trace window is open the SAME span appears on the
profiler's host thread lane, lined up against the device lanes — one
timeline, two sources.  (``jax.named_scope`` is the tracing-time
cousin: the jit-region builder enters it per member unit so device-op
names carry unit attribution — see
``JitRegion.build_callable``.)

:func:`profile_window` is the capture helper: a context manager that
opens a ``jax.profiler`` trace around any region (N training steps, a
bench's timed loop) and drops the window's host spans beside it as
``host_spans.trace.json`` — every committed BENCH row can carry both.

All recording is gated on :func:`znicz_tpu.observe.metrics.enabled`
(``root.common.engine.telemetry``); a disabled tracer costs one dict
lookup per span.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager

from znicz_tpu.observe import metrics as _metrics

#: trace time zero (module import); spans report microseconds since
_EPOCH = time.perf_counter()


def now_us() -> float:
    """Microseconds since the tracer epoch (the Chrome-trace ``ts``
    time base)."""
    return (time.perf_counter() - _EPOCH) * 1e6


#: True while a :func:`profile_window` device trace is open — the ONLY
#: time a host span pays for a ``jax.profiler.TraceAnnotation`` (there
#: is nobody to see the annotation otherwise, and the decode token
#: loop opens a span per step, so the idle cost is a hot-path tax)
_DEVICE_TRACE_OPEN = False


def _trace_annotation(name: str):
    """A ``jax.profiler.TraceAnnotation`` for ``name`` when jax is
    importable (it always is in this framework; the guard keeps the
    tracer usable standalone)."""
    try:
        import jax
        return jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001 — tracer must never break the host loop
        return None


class _NullSpan:
    """The span handed out when telemetry is off — a shared, stateless
    no-op context manager."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """One open host span (class-based: the generator-frame cost of
    ``@contextmanager`` is measurable at decode-step cadence)."""

    __slots__ = ("_tracer", "_name", "_cat", "_args", "_ann", "_t0",
                 "_depth")

    def __init__(self, tracer, name, cat, args) -> None:
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self):
        stack = self._tracer._stack()
        self._depth = len(stack)
        stack.append(self._name)
        self._ann = (_trace_annotation(self._name)
                     if _DEVICE_TRACE_OPEN else None)
        if self._ann is not None:
            self._ann.__enter__()
        self._t0 = now_us()
        return self

    def __exit__(self, *exc):
        t1 = now_us()
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
        self._tracer._stack().pop()
        self._tracer._append({
            "ph": "X", "name": self._name, "cat": self._cat,
            "pid": self._tracer._pid,
            "tid": threading.get_native_id(),
            "ts": self._t0, "dur": t1 - self._t0,
            "args": {**self._args, "depth": self._depth}})
        return False


class SpanTracer:
    """Bounded ring buffer of completed host spans."""

    def __init__(self, max_events: int = 65536) -> None:
        self._events: deque = deque(maxlen=max_events)
        self._lock = threading.Lock()
        self._local = threading.local()
        self._seq = 0
        self._pid = os.getpid()

    # ------------------------------------------------------------------
    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _append(self, event: dict) -> None:
        with self._lock:
            self._seq += 1
            event["_seq"] = self._seq
            self._events.append(event)

    def mark(self) -> int:
        """A position marker; pass to :meth:`to_chrome_trace` /
        :meth:`export` as ``since`` to keep only later events."""
        with self._lock:
            return self._seq

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    # ------------------------------------------------------------------
    def span(self, name: str, cat: str = "host", **args):
        """Record a span around the with-body.  Nesting is tracked per
        thread (the ``depth`` arg on the event); while a
        :func:`profile_window` device trace is open a
        ``jax.profiler.TraceAnnotation`` rides the span so the
        captured device trace carries it on its host lane.  This is
        the decode loop's per-step hot path: a class-based context
        manager (no generator frame) and the annotation gated on an
        open device trace keep the always-on cost to two clock reads
        and one ring append."""
        if not _metrics.enabled():
            return _NULL_SPAN
        return _Span(self, name, cat, args)

    def complete(self, name: str, t0_us: float, t1_us: float,
                 cat: str = "host", **args) -> None:
        """Record a retroactive span from explicit timestamps (epoch
        boundaries are only known at the END of the epoch)."""
        if not _metrics.enabled():
            return
        self._append({
            "ph": "X", "name": name, "cat": cat,
            "pid": self._pid, "tid": threading.get_native_id(),
            "ts": t0_us, "dur": max(0.0, t1_us - t0_us),
            "args": {**args, "depth": 0}})

    def instant(self, name: str, cat: str = "host", **args) -> None:
        if not _metrics.enabled():
            return
        self._append({
            "ph": "i", "s": "t", "name": name, "cat": cat,
            "pid": self._pid, "tid": threading.get_native_id(),
            "ts": now_us(), "args": dict(args)})

    # ------------------------------------------------------------------
    def to_chrome_trace(self, since: int = 0) -> dict:
        """The Chrome-trace/Perfetto JSON object (``traceEvents``)."""
        with self._lock:
            events = [ev for ev in self._events if ev["_seq"] > since]
        out_events = [{"ph": "M", "name": "process_name",
                       "pid": self._pid, "tid": 0,
                       "args": {"name": "znicz_tpu host spans"}}]
        for ev in events:
            ev = dict(ev)
            ev.pop("_seq", None)
            out_events.append(ev)
        return {"traceEvents": out_events, "displayTimeUnit": "ms"}

    def export(self, path: str, since: int = 0) -> str:
        with open(path, "w") as fh:
            json.dump(self.to_chrome_trace(since=since), fh)
        return path


#: the process-global tracer every instrumentation site records on
TRACER = SpanTracer()


# ----------------------------------------------------------------------
# round 24: request-scoped trace context
# ----------------------------------------------------------------------
#: process-unique trace-id sequence (pid-prefixed so merged traces
#: from a gang of processes never collide)
_TRACE_SEQ = itertools.count(1)


class RequestTrace:
    """Trace context minted at ``submit()`` and riding the REQUEST
    object (not a thread-local) through every hop it takes — batcher
    queue, prefill dispatch, the disagg handoff payload, the decode
    token loop — because a request crosses threads and pools while a
    single logical trace must survive all of them.

    Phases are begun/ended from whatever thread owns the request at
    that moment; each closed phase lands in the process tracer as a
    ``cat="request"`` complete span parented under the request's root
    span (``trace_id``/``span_id``/``parent_span_id`` in ``args``), so
    ``/trace.json`` renders one request's life as a span tree and
    ``trace_top.py --requests`` can aggregate per-phase percentiles.
    :meth:`phase_end` returns the phase duration in seconds so the
    engine can feed its windowed-p99 gauges from the same clock.
    """

    __slots__ = ("trace_id", "name", "args", "t0_us", "_phase_t0",
                 "_span_seq", "phases", "events", "_finished")

    def __init__(self, name: str = "request", **args) -> None:
        self.trace_id = f"{os.getpid():x}-{next(_TRACE_SEQ):06x}"
        self.name = name
        self.args = dict(args)
        self.t0_us = now_us()
        self._phase_t0: dict[str, float] = {}
        #: root span is 1; child spans/events count up from 2
        self._span_seq = itertools.count(2)
        self.phases: dict[str, float] = {}
        self.events: list[str] = []
        self._finished = False

    def phase_begin(self, phase: str) -> None:
        """Open ``phase`` (idempotent: a retry re-entering the same
        phase keeps the FIRST begin, so retried work is charged to the
        phase that absorbed it)."""
        self._phase_t0.setdefault(phase, now_us())

    def phase_end(self, phase: str, **args) -> float:
        """Close ``phase`` and record it as a child span; returns the
        phase duration in seconds (0.0 when the phase never began)."""
        t0 = self._phase_t0.pop(phase, None)
        if t0 is None:
            return 0.0
        t1 = now_us()
        dur_s = (t1 - t0) / 1e6
        self.phases[phase] = self.phases.get(phase, 0.0) + dur_s
        TRACER.complete(f"req.{phase}", t0, t1, cat="request",
                        trace_id=self.trace_id,
                        span_id=next(self._span_seq),
                        parent_span_id=1, phase=phase, **args)
        return dur_s

    def event(self, name: str, **args) -> None:
        """An instant under the request's root span (breaker shed,
        deadline eviction, handoff drop, swap pause, routing choice)."""
        self.events.append(name)
        TRACER.instant(f"req.{name}", cat="request",
                       trace_id=self.trace_id,
                       span_id=next(self._span_seq),
                       parent_span_id=1, **args)

    def finish(self, outcome: str = "ok", **args) -> None:
        """Close the root span (idempotent — the first outcome
        wins)."""
        if self._finished:
            return
        self._finished = True
        for phase in list(self._phase_t0):  # close any dangling phase
            self.phase_end(phase)
        TRACER.complete(self.name, self.t0_us, now_us(), cat="request",
                        trace_id=self.trace_id, span_id=1,
                        parent_span_id=0, outcome=outcome,
                        **{**self.args, **args})


class _NullTrace:
    """The no-op trace every call site holds when telemetry is off —
    keeps the instrumentation unconditional at one attribute call."""

    __slots__ = ()
    trace_id = "-"
    phases: dict = {}
    events: list = []

    def phase_begin(self, phase: str) -> None:
        pass

    def phase_end(self, phase: str, **args) -> float:
        return 0.0

    def event(self, name: str, **args) -> None:
        pass

    def finish(self, outcome: str = "ok", **args) -> None:
        pass


NULL_TRACE = _NullTrace()


def new_request_trace(name: str = "request", **args):
    """Mint a request trace (:class:`NULL_TRACE` when telemetry is
    off, so call sites never branch)."""
    if not _metrics.enabled():
        return NULL_TRACE
    return RequestTrace(name, **args)


#: fleet→engine adoption channel: FleetEngine mints the trace (so the
#: routing decision is on it), parks it here, and the engine's
#: synchronous same-thread submit() adopts it instead of minting a new
#: one — no API change on every submit signature in between
_PENDING = threading.local()


def set_pending_trace(trace) -> None:
    _PENDING.trace = trace


def adopt_pending_trace():
    """Pop the thread's parked trace (None when nothing was parked)."""
    trace = getattr(_PENDING, "trace", None)
    _PENDING.trace = None
    return trace


@contextmanager
def profile_window(outdir: str, n_steps: int | None = None,
                   device: bool = True, tracer: SpanTracer | None = None):
    """Capture a ``jax.profiler`` device trace plus the window's host
    spans around the with-body.

    ``outdir`` receives the profiler's trace directory (the usual
    ``*.trace.json.gz`` tree ``trace_top.py`` reads) and
    ``host_spans.trace.json`` (Chrome-trace JSON of the host spans
    recorded during the window — feed it to ``trace_top.py --spans``).
    ``n_steps`` is recorded on the window span so per-step math in the
    post-processors has its divisor.  ``device=False`` skips the jax
    profiler (host spans only — cheap enough for always-on use).

    Usage mid-training::

        with observe.profile_window("profiles/r09", n_steps=32):
            for _ in range(32):
                step()
    """
    if tracer is None:  # NOT `or`: an empty SpanTracer is falsy
        tracer = TRACER
    os.makedirs(outdir, exist_ok=True)
    started = False
    global _DEVICE_TRACE_OPEN
    if device:
        try:
            import jax
            jax.profiler.start_trace(outdir)
            started = True
            _DEVICE_TRACE_OPEN = True
        except Exception as exc:  # noqa: BLE001 — an open trace must not kill the run
            import logging
            logging.getLogger("znicz_tpu.observe").warning(
                "profile_window: device trace unavailable (%s) — "
                "recording host spans only", exc)
    mark = tracer.mark()
    try:
        with tracer.span("profile_window", cat="profile",
                         n_steps=n_steps or 0):
            yield outdir
    finally:
        if started:
            import jax
            _DEVICE_TRACE_OPEN = False
            try:
                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001 — already stopped elsewhere
                pass
        tracer.export(os.path.join(outdir, "host_spans.trace.json"),
                      since=mark)
