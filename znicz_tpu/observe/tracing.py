"""Host-side span tracer: Dapper-style spans over the control plane.

The training engine's device time is already observable through
``jax.profiler`` traces (summarized by ``benchmarks/trace_top.py``);
what was missing is the HOST half — which unit, epoch, or serving
request the device lanes were working for.  This module records
host-side spans (unit fires, workflow runs, epochs, serving batch
dispatches, compiles) into a bounded ring buffer and exports them as
Chrome-trace/Perfetto JSON (``ph: "X"`` complete events), so
``chrome://tracing`` / Perfetto can show them, ``WebStatusServer``
serves them live at ``/trace.json``, and ``trace_top.py --spans``
merges them with a device-trace summary.

Correlation with XLA device lanes: inside every span the tracer also
enters ``jax.profiler.TraceAnnotation`` (a TraceMe), so when a
``jax.profiler`` trace window is open the SAME span appears on the
profiler's host thread lane, lined up against the device lanes — one
timeline, two sources.  (``jax.named_scope`` is the tracing-time
cousin: the jit-region builder enters it per member unit so device-op
names carry unit attribution — see
``JitRegion.build_callable``.)

:func:`profile_window` is the capture helper: a context manager that
opens a ``jax.profiler`` trace around any region (N training steps, a
bench's timed loop) and drops the window's host spans beside it as
``host_spans.trace.json`` — every committed BENCH row can carry both.

All recording is gated on :func:`znicz_tpu.observe.metrics.enabled`
(``root.common.engine.telemetry``); a disabled tracer costs one dict
lookup per span.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager

from znicz_tpu.observe import metrics as _metrics

#: trace time zero (module import); spans report microseconds since
_EPOCH = time.perf_counter()


def now_us() -> float:
    """Microseconds since the tracer epoch (the Chrome-trace ``ts``
    time base)."""
    return (time.perf_counter() - _EPOCH) * 1e6


def _trace_annotation(name: str):
    """A ``jax.profiler.TraceAnnotation`` for ``name`` when jax is
    importable (it always is in this framework; the guard keeps the
    tracer usable standalone)."""
    try:
        import jax
        return jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001 — tracer must never break the host loop
        return None


class SpanTracer:
    """Bounded ring buffer of completed host spans."""

    def __init__(self, max_events: int = 65536) -> None:
        self._events: deque = deque(maxlen=max_events)
        self._lock = threading.Lock()
        self._local = threading.local()
        self._seq = 0
        self._pid = os.getpid()

    # ------------------------------------------------------------------
    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _append(self, event: dict) -> None:
        with self._lock:
            self._seq += 1
            event["_seq"] = self._seq
            self._events.append(event)

    def mark(self) -> int:
        """A position marker; pass to :meth:`to_chrome_trace` /
        :meth:`export` as ``since`` to keep only later events."""
        with self._lock:
            return self._seq

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    # ------------------------------------------------------------------
    @contextmanager
    def span(self, name: str, cat: str = "host", **args):
        """Record a span around the with-body.  Nesting is tracked per
        thread (the ``depth`` arg on the event); inside the span a
        ``jax.profiler.TraceAnnotation`` is open so a concurrently
        captured device trace carries the same span on its host lane."""
        if not _metrics.enabled():
            yield
            return
        stack = self._stack()
        depth = len(stack)
        stack.append(name)
        ann = _trace_annotation(name)
        if ann is not None:
            ann.__enter__()
        t0 = now_us()
        try:
            yield
        finally:
            t1 = now_us()
            if ann is not None:
                ann.__exit__(None, None, None)
            stack.pop()
            self._append({
                "ph": "X", "name": name, "cat": cat,
                "pid": self._pid, "tid": threading.get_native_id(),
                "ts": t0, "dur": t1 - t0,
                "args": {**args, "depth": depth}})

    def complete(self, name: str, t0_us: float, t1_us: float,
                 cat: str = "host", **args) -> None:
        """Record a retroactive span from explicit timestamps (epoch
        boundaries are only known at the END of the epoch)."""
        if not _metrics.enabled():
            return
        self._append({
            "ph": "X", "name": name, "cat": cat,
            "pid": self._pid, "tid": threading.get_native_id(),
            "ts": t0_us, "dur": max(0.0, t1_us - t0_us),
            "args": {**args, "depth": 0}})

    def instant(self, name: str, cat: str = "host", **args) -> None:
        if not _metrics.enabled():
            return
        self._append({
            "ph": "i", "s": "t", "name": name, "cat": cat,
            "pid": self._pid, "tid": threading.get_native_id(),
            "ts": now_us(), "args": dict(args)})

    # ------------------------------------------------------------------
    def to_chrome_trace(self, since: int = 0) -> dict:
        """The Chrome-trace/Perfetto JSON object (``traceEvents``)."""
        with self._lock:
            events = [ev for ev in self._events if ev["_seq"] > since]
        out_events = [{"ph": "M", "name": "process_name",
                       "pid": self._pid, "tid": 0,
                       "args": {"name": "znicz_tpu host spans"}}]
        for ev in events:
            ev = dict(ev)
            ev.pop("_seq", None)
            out_events.append(ev)
        return {"traceEvents": out_events, "displayTimeUnit": "ms"}

    def export(self, path: str, since: int = 0) -> str:
        with open(path, "w") as fh:
            json.dump(self.to_chrome_trace(since=since), fh)
        return path


#: the process-global tracer every instrumentation site records on
TRACER = SpanTracer()


@contextmanager
def profile_window(outdir: str, n_steps: int | None = None,
                   device: bool = True, tracer: SpanTracer | None = None):
    """Capture a ``jax.profiler`` device trace plus the window's host
    spans around the with-body.

    ``outdir`` receives the profiler's trace directory (the usual
    ``*.trace.json.gz`` tree ``trace_top.py`` reads) and
    ``host_spans.trace.json`` (Chrome-trace JSON of the host spans
    recorded during the window — feed it to ``trace_top.py --spans``).
    ``n_steps`` is recorded on the window span so per-step math in the
    post-processors has its divisor.  ``device=False`` skips the jax
    profiler (host spans only — cheap enough for always-on use).

    Usage mid-training::

        with observe.profile_window("profiles/r09", n_steps=32):
            for _ in range(32):
                step()
    """
    if tracer is None:  # NOT `or`: an empty SpanTracer is falsy
        tracer = TRACER
    os.makedirs(outdir, exist_ok=True)
    started = False
    if device:
        try:
            import jax
            jax.profiler.start_trace(outdir)
            started = True
        except Exception as exc:  # noqa: BLE001 — an open trace must not kill the run
            import logging
            logging.getLogger("znicz_tpu.observe").warning(
                "profile_window: device trace unavailable (%s) — "
                "recording host spans only", exc)
    mark = tracer.mark()
    try:
        with tracer.span("profile_window", cat="profile",
                         n_steps=n_steps or 0):
            yield outdir
    finally:
        if started:
            import jax
            try:
                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001 — already stopped elsewhere
                pass
        tracer.export(os.path.join(outdir, "host_spans.trace.json"),
                      since=mark)
