"""Pipeline parallelism over the workflow's ordered unit chain
(round 20): split the forward/backward chain into K contiguous stages
and schedule them 1F1B over the ``engine.grad_accum`` microbatches
(GPipe — Huang et al. 2019, arXiv:1811.06965; the one-forward-
one-backward schedule — Narayanan et al., PipeDream, arXiv:1806.03377;
see PAPERS.md).

Execution model
---------------
Each stage owns TWO :class:`~znicz_tpu.accelerated_units.JitRegion`
programs built over the SAME unit and Vector objects as the unstaged
region:

- forward region ``s``: that stage's forward units (stage 0 is led by
  the loader, which advances the device-resident schedule cursor once
  per microbatch — exactly as it does inside ``run_accum``'s scan);
- backward region ``s``: that stage's GD units in reverse layer order
  (stage K−1 is led by the evaluator; stage 0 is trailed by the
  anomaly guard, keeping the guard's commit the LAST program of the
  optimizer step, its position in the unstaged trace order).

Backward dispatches ride the gradient-accumulation phases: microbatch
``m < M−1`` runs ``("accum", M)`` (gradients buffer, no parameter
write), the last runs ``("apply", M)`` — each stage applies its own
parameters at its final backward, which is legal in any valid schedule
because every forward of the step reads pre-step parameters.

Because the host dispatches one program at a time, on a single-process
CPU/TPU mesh this is **temporal MPMD**: stages time-multiplex the same
devices, so what pipelining buys here is the ACCUMULATION memory
profile (one microbatch of activations per stage) plus a faithfully
modeled schedule.  The bubble metrics are computed from measured
per-op wall times laid onto the schedule's tick structure — the cost
model a spatial (``Stage(k)`` placements on a ``pipe`` mesh axis,
``PP_TPU=1``) deployment realizes physically.

Microbatch context
------------------
1F1B interleaves microbatches, so stage buffers (activations, error
tensors, minibatch data) are VERSIONED per in-flight microbatch: before
an op runs, every batch-major leaf of its region that microbatch ``m``
has already produced is restored; after it runs, the region's
batch-major leaves are saved under ``m``.  Weights, optimizer state,
PRNG chains, epoch accumulators and other non-batch leaves are shared
mutable state, exactly as in the fused program.  Vector objects are
shared across stage regions, so a stage boundary is nothing but the
producer's save followed by the consumer's restore — no explicit
send/recv plumbing in the temporal executor.
"""

from __future__ import annotations

import re
import time

import numpy as np

from znicz_tpu.utils.logger import Logger
from znicz_tpu.observe import metrics as _metrics
from znicz_tpu.parallel.partition import Stage


def split_stages(n_layers: int, n_stages: int) -> list[list[int]]:
    """Contiguous balanced split of ``n_layers`` forward indices into
    ``n_stages`` groups (earlier stages take the remainder, matching
    ``np.array_split``)."""
    if not 1 <= n_stages <= n_layers:
        raise ValueError(
            f"cannot split {n_layers} layers into {n_stages} stages")
    return [list(chunk) for chunk in
            np.array_split(np.arange(n_layers), n_stages)]


# ----------------------------------------------------------------------
# schedules: per-stage local op sequences + readiness merge
# ----------------------------------------------------------------------
def _local_1f1b(n_stages: int, n_micro: int, stage: int) -> list[tuple]:
    """Stage-local 1F1B sequence: ``min(K−s−1, M)`` warmup forwards,
    then alternate F/B until forwards run out, then drain backwards."""
    warmup = min(n_stages - stage - 1, n_micro)
    ops: list[tuple] = [("F", stage, m) for m in range(warmup)]
    f, b = warmup, 0
    while f < n_micro:
        ops.append(("F", stage, f))
        f += 1
        ops.append(("B", stage, b))
        b += 1
    while b < n_micro:
        ops.append(("B", stage, b))
        b += 1
    return ops


def _local_gpipe(n_stages: int, n_micro: int, stage: int) -> list[tuple]:
    """Stage-local GPipe (naive-sequential) sequence: every forward,
    then every backward."""
    return ([("F", stage, m) for m in range(n_micro)]
            + [("B", stage, m) for m in range(n_micro)])


_LOCAL = {"1f1b": _local_1f1b, "gpipe": _local_gpipe}


def build_schedule(n_stages: int, n_micro: int,
                   kind: str = "1f1b") -> list[list[tuple]]:
    """Merge the per-stage local sequences into parallel **ticks**.

    Each tick is the set of ops the K stages would execute
    concurrently on a spatial deployment: every stage fires its next
    local op as soon as its dependencies are done.  ``F(s, m)`` needs
    ``F(s−1, m)``; ``B(s, m)`` needs ``F(s, m)`` and ``B(s+1, m)``.
    Flattening the ticks (stage-descending inside a tick for B-first
    determinism) gives the host dispatch order; the tick structure is
    the cost model the bubble metrics are read from.
    """
    try:
        local = _LOCAL[kind]
    except KeyError:
        raise ValueError(f"unknown pipeline schedule '{kind}' "
                         f"(have: {sorted(_LOCAL)})") from None
    seqs = [local(n_stages, n_micro, s) for s in range(n_stages)]
    ptr = [0] * n_stages
    done: set[tuple] = set()
    ticks: list[list[tuple]] = []
    total = sum(len(s) for s in seqs)
    while len(done) < total:
        fired: list[tuple] = []
        for s in range(n_stages):
            if ptr[s] >= len(seqs[s]):
                continue
            kind_, st, m = op = seqs[s][ptr[s]]
            if kind_ == "F":
                ready = st == 0 or ("F", st - 1, m) in done
            else:
                ready = (("F", st, m) in done
                         and (st == n_stages - 1
                              or ("B", st + 1, m) in done))
            if ready:
                fired.append(op)
        if not fired:
            raise RuntimeError(
                f"pipeline schedule '{kind}' deadlocked at "
                f"{sum(ptr)}/{total} ops — malformed local sequences")
        for op in fired:
            ptr[op[1]] += 1
            done.add(op)
        # backward-bearing stages first inside the tick: on the
        # temporal executor this drains gradients (and frees their
        # microbatch context) at the earliest legal point
        ticks.append(sorted(fired, key=lambda o: (o[0] == "F", -o[1])))
    return ticks


def bubble_fraction(n_stages: int, n_micro: int) -> float:
    """The 1F1B/GPipe steady-state bubble fraction (K−1)/(M+K−1) —
    the analytic curve PP_BENCH.json compares measured ticks against."""
    return (n_stages - 1) / (n_micro + n_stages - 1)


# ----------------------------------------------------------------------
# the executor
# ----------------------------------------------------------------------
class PipelineExecutor(Logger):
    """Temporal-MPMD pipeline executor over a ``StandardWorkflow``.

    Built AFTER ``workflow.initialize`` (unit chain and Vectors
    exist); owns the per-stage forward/backward JitRegions, the merged
    schedule, and the per-microbatch context store.  One
    :meth:`run_step` consumes M already-staged TRAIN microbatches
    (the caller advances the loader's host bookkeeping M times, same
    contract as ``JitRegion.run_accum``) and commits exactly one
    optimizer step.
    """

    def __init__(self, workflow, n_stages: int, n_micro: int,
                 schedule: str = "1f1b") -> None:
        super().__init__()
        from znicz_tpu.accelerated_units import JitRegion
        if n_micro < 2:
            raise ValueError(
                "pipeline execution rides the gradient-accumulation "
                "phases: engine.grad_accum (microbatches) must be ≥ 2")
        self.workflow = workflow
        self.n_stages = int(n_stages)
        self.n_micro = int(n_micro)
        self.schedule_kind = schedule
        self.stages = split_stages(len(workflow.forwards), self.n_stages)
        device = workflow.device
        loader = workflow.loader
        guard = getattr(workflow, "anomaly_guard", None)
        self.fwd_regions = []
        self.bwd_regions = []
        for s, idxs in enumerate(self.stages):
            f_units = [workflow.forwards[i] for i in idxs]
            if s == 0:
                f_units = [loader] + f_units
            b_units = [workflow.gds[i] for i in reversed(idxs)]
            if s == self.n_stages - 1:
                b_units = [workflow.evaluator] + b_units
            if s == 0 and guard is not None:
                b_units = b_units + [guard]
            self.fwd_regions.append(JitRegion(
                f"{workflow.name}_pp_f{s}", f_units, device))
            self.bwd_regions.append(JitRegion(
                f"{workflow.name}_pp_b{s}", b_units, device))
        self.ticks = build_schedule(self.n_stages, self.n_micro, schedule)
        self._declare_stage_rules()
        #: in-flight microbatch contexts: m -> {id(vec): (vec, leaf)}
        self._ctx: dict[int, dict[int, tuple]] = {}
        self.last_makespan = 0.0
        self.last_bubble_seconds = 0.0
        _metrics.pipeline_stages(workflow.name).set(self.n_stages)
        _metrics.grad_accum_microbatches(workflow.name).set(self.n_micro)

    # -- declarative stage assignment ----------------------------------
    def _declare_stage_rules(self) -> None:
        """Record each stage's unit→stage assignment as ``Stage(k)``
        tags in the workflow's partition table (and back-annotate
        already-bound leaves), so the placement story — including the
        spatial ``pipe``-axis arm — reads from the ONE rule table."""
        table = getattr(self.workflow, "partition", None)
        if table is None:
            return
        patterns = []
        for s, idxs in enumerate(self.stages):
            units = [self.workflow.forwards[i] for i in idxs] \
                + [self.workflow.gds[i] for i in idxs]
            for unit in units:
                pat = rf"^{re.escape(unit.name)}/"
                table.declare(pat, Stage(s))
                patterns.append((re.compile(pat), s))
        for path, resolved in table.leaves.items():
            for pat, s in patterns:
                if pat.search(path):
                    resolved.stage = s
                    break

    # -- microbatch context --------------------------------------------
    @staticmethod
    def _batch_leaves(region):
        if region._vectors is None:
            region._vectors = region._collect_vectors()
        return [v for v in region._vectors
                if getattr(v, "batch_major", False)]

    def _restore(self, region, m: int) -> None:
        ctx = self._ctx.get(m)
        if not ctx:
            return
        for vec in self._batch_leaves(region):
            saved = ctx.get(id(vec))
            if saved is not None:
                vec.devmem = saved[1]

    def _save(self, region, m: int) -> None:
        ctx = self._ctx.setdefault(m, {})
        for vec in self._batch_leaves(region):
            ctx[id(vec)] = (vec, vec._devmem)

    # -- execution ------------------------------------------------------
    def _dispatch(self, op: tuple) -> float:
        kind, s, m = op
        if kind == "F":
            region, phase = self.fwd_regions[s], None
        else:
            region = self.bwd_regions[s]
            phase = ("apply" if m == self.n_micro - 1 else "accum",
                     self.n_micro)
        self._restore(region, m)
        t0 = time.perf_counter()
        region.run_undonated(accum_phase=phase)
        dt = time.perf_counter() - t0
        self._save(region, m)
        if kind == "B" and s == 0:
            self._ctx.pop(m, None)  # microbatch fully drained
        return dt

    def run_step(self) -> dict:
        """Execute one optimizer step's schedule; returns the step's
        modeled timing ``{"makespan": s, "bubble_seconds": s}``.

        Timing model: per-op wall times are measured around each
        dispatch; a tick's span is its slowest op (the ops of one tick
        run concurrently on a spatial deployment), the makespan is the
        sum of tick spans, and the bubble is
        ``Σ_stages (makespan − stage busy time)`` — the idle-chip
        seconds a ``pipe``-axis deployment of this exact schedule and
        these exact op costs would spend.
        """
        busy = [0.0] * self.n_stages
        makespan = 0.0
        for tick in self.ticks:
            span = 0.0
            for op in tick:
                dt = self._dispatch(op)
                busy[op[1]] += dt
                span = max(span, dt)
            makespan += span
        self._ctx.clear()  # nothing may leak across optimizer steps
        bubble = sum(makespan - b for b in busy)
        self.last_makespan = makespan
        self.last_bubble_seconds = bubble
        _metrics.pipeline_bubble_seconds(self.workflow.name).inc(bubble)
        return {"makespan": makespan, "bubble_seconds": bubble}
