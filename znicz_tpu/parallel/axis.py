"""Mesh-axis context: lets unit math be written once and run either
single-device or under ``shard_map``/``pjit`` over a named mesh axis.

The backward units call :func:`maybe_pmean` on their weight gradients —
outside a mapped context it is the identity, inside it becomes an ICI
all-reduce.  This is the exact seam where the reference's master–slave
gradient fold lived (reference: ``GradientDescentBase.
generate_data_for_master`` / master ``apply_data_from_slave``).
"""

from __future__ import annotations

import contextlib
from contextvars import ContextVar

import jax

#: canonical axis names; keep stable so TP/PP can be added without
#: breaking DP configs (SURVEY.md §2.5: name axes now, build DP only).
DATA_AXIS = "data"
MODEL_AXIS = "model"
#: sequence/context parallelism (ring attention) — distinct from
#: data/model so DP × SP compose
SEQ_AXIS = "seq"
#: pipeline-stage axis (round 20): a workflow's unit chain splits into
#: K stages scheduled 1F1B over the gradient-accumulation microbatches
PIPE_AXIS = "pipe"

_active_data_axis: ContextVar[str | None] = ContextVar(
    "znicz_tpu_data_axis", default=None)


def current_data_axis() -> str | None:
    return _active_data_axis.get()


@contextlib.contextmanager
def data_axis(name: str | None = DATA_AXIS):
    """Declare that enclosed traces run under a mapped ``data`` axis."""
    token = _active_data_axis.set(name)
    try:
        yield
    finally:
        _active_data_axis.reset(token)


def maybe_pmean(x):
    """All-reduce-mean over the data axis when inside one; else identity."""
    axis = _active_data_axis.get()
    if axis is None:
        return x
    return jax.lax.pmean(x, axis_name=axis)


def maybe_psum(x):
    """All-reduce-sum over the data axis when inside one; else identity."""
    axis = _active_data_axis.get()
    if axis is None:
        return x
    return jax.lax.psum(x, axis_name=axis)
