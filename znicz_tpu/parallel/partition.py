"""Declarative partition-rule engine: one table per workflow.

Sharding decisions used to live as imperative per-``Vector`` slot
attributes (``model_shard_dim``, ``data_shard_dim``, ``member_axis``,
ZeRO-1 padding) scattered through the unit modules — bringing up a new
mesh meant auditing every set site.  This module replaces them with the
``match_partition_rules`` pattern (fmengine/EasyLM lineage; SNIPPETS.md
[1]/[3]): each workflow owns ONE ordered table of
``(name-regex, placement)`` rules over canonical ``unit.name/slot``
leaf paths, and resolution is

- **scalars replicated** — 0-d / single-element leaves short-circuit to
  ``PartitionSpec()`` before any rule is consulted;
- **first match wins** — the table is ordered: unit-declared overrides
  (exact, anchored paths) precede the framework's default tail;
- **unmatched leaves are a hard error** — there is no silent
  replicated fallback; a new slot name either matches a default rule
  or its unit must declare one.

ZeRO-1 padding and population member-axis placement are rule
*consequences*: the :class:`Zero1` / :class:`Member` placements derive
``(data_shard_dim, pad)`` / member-axis divisibility from the leaf's
logical shape at resolution time, instead of units hand-setting slot
attributes.  The legacy slot attributes survive only as a
**compatibility layer** populated FROM the resolved table
(:meth:`ResolvedPartition.apply_to`), so existing readers — the ZeRO-1
update path, ``kernel_shard_spec`` callers, snapshot pad
strip/re-pad — keep working while units stop writing them.

``root.common.engine.partition_rules = False`` is the A/B arm: the
same declarative call sites apply the equivalent legacy attributes
directly and ``backends.sharding_for`` derives placements from them —
the golden-table regression test pins the two arms bitwise-equal.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

import numpy as np

from znicz_tpu.parallel.axis import DATA_AXIS, MODEL_AXIS, SEQ_AXIS


def _pspec(*entries):
    from jax.sharding import PartitionSpec
    return PartitionSpec(*entries)


class UnmatchedLeafError(LookupError):
    """A leaf path matched no rule — the hard-error contract (no
    silent replicated fallback)."""


class PartitionMismatchError(ValueError):
    """A resolved placement contradicts the Vector's structural kind
    (e.g. a batch-major buffer resolved to a non-batch spec) — almost
    always a missing or mis-ordered rule."""


# ----------------------------------------------------------------------
# placements — the right-hand side of a rule
# ----------------------------------------------------------------------
class _Singleton:
    _NAME = "?"

    def __repr__(self) -> str:  # table dumps stay readable
        return self._NAME


class _Batch(_Singleton):
    """Dim 0 is the minibatch: rides the mesh's data axis."""
    _NAME = "BATCH"


class _Replicated(_Singleton):
    """Fully replicated (parameters, scalars, schedule tables)."""
    _NAME = "REPLICATED"


BATCH = _Batch()
REPLICATED = _Replicated()


@dataclass(frozen=True)
class Zero1:
    """ZeRO-1 optimizer-state placement: the data-sharded dim and its
    zero padding are DERIVED from the leaf's logical shape via
    ``mesh.zero1_partition`` (largest evenly-dividing dim, else the
    largest dim padded up); ``model_dim`` composes as a 2-D sharding
    exactly like the attribute path did."""
    model_dim: int | None = None

    def __repr__(self) -> str:
        return f"ZERO1(model_dim={self.model_dim})"


@dataclass(frozen=True)
class Member:
    """Population-stacked placement: dim 0 is the member axis and
    rides the mesh's data axis when the member count divides it (an
    indivisible K stays replicated — XLA time-slices the members);
    ``model_dim`` is a member's TP dim, already shifted by the leading
    member axis."""
    model_dim: int | None = None

    def __repr__(self) -> str:
        return f"MEMBER(model_dim={self.model_dim})"


@dataclass(frozen=True)
class Stage:
    """Pipeline-stage placement tag (round 20): every leaf the rule
    matches belongs to pipeline stage ``index`` on the mesh's ``pipe``
    axis.  A Stage rule decides WHICH stage owns a leaf, not how the
    leaf shards inside the stage: ``inner`` (when given) is the
    within-stage placement; ``inner=None`` falls through to the next
    matching rule — typically the defaults tail — so a unit's existing
    BATCH/ZERO1/TP declarations keep working verbatim under staging."""
    index: int
    inner: object = None

    def __repr__(self) -> str:
        return (f"STAGE({self.index})" if self.inner is None
                else f"STAGE({self.index}, {self.inner!r})")


def model_sharded(dim: int, axis: str = MODEL_AXIS, batch: bool = False):
    """Explicit spec with ``dim`` on ``axis`` (and dim 0 on the data
    axis when ``batch``) — the TP/ring building block."""
    entries: list = [None] * (dim + 1)
    if batch:
        if dim == 0:
            raise ValueError("dim 0 cannot carry both batch and model")
        entries[0] = DATA_AXIS
    entries[dim] = axis
    return _pspec(*entries)


def like(vec, batch_major: bool | None = None):
    """Placement inherited from an already-bound Vector: the target
    keeps its own structural batch flag while the source's model-axis
    sharding passes through (the declarative form of the old
    ``inherit_model_shard`` attribute copy)."""
    md = getattr(vec, "model_shard_dim", None)
    axis = getattr(vec, "model_shard_axis", MODEL_AXIS)
    batch = bool(getattr(vec, "batch_major", False)) \
        if batch_major is None else bool(batch_major)
    if md is None:
        return BATCH if batch else REPLICATED
    return model_sharded(md, axis=axis, batch=batch)


# ----------------------------------------------------------------------
# resolution result + compat layer
# ----------------------------------------------------------------------
@dataclass
class ResolvedPartition:
    """One leaf's resolved placement — the spec plus the derived
    attributes the compatibility layer stamps back onto the Vector."""
    path: str
    spec: object                       # jax PartitionSpec
    rule: str                          # matching pattern ("<scalar>")
    batch_major: bool = False
    model_shard_dim: int | None = None
    model_shard_axis: str = MODEL_AXIS
    data_shard_dim: int | None = None
    data_shard_pad: int = 0
    member_axis: bool = False
    logical_shape: tuple = ()
    #: True once the Vector's storage carries the derived pad rows —
    #: re-binds must not re-derive from the padded shape
    pad_applied: bool = False
    #: pipeline stage owning this leaf (round 20); None = unstaged
    stage: int | None = None

    def apply_to(self, vec) -> "ResolvedPartition":
        """Populate the legacy slot attributes FROM this resolution —
        the compatibility layer (existing readers keep working; units
        no longer write these directly)."""
        vec.model_shard_dim = self.model_shard_dim
        vec.model_shard_axis = self.model_shard_axis
        vec.data_shard_dim = self.data_shard_dim
        vec.data_shard_pad = self.data_shard_pad
        vec.member_axis = self.member_axis
        vec._partition = self
        return self

    def padded_shape(self) -> tuple:
        """:attr:`logical_shape` with the derived ZeRO-1 pad applied —
        the storage shape the allocator must use."""
        shape = list(self.logical_shape)
        if self.data_shard_dim is not None and self.data_shard_pad:
            shape[self.data_shard_dim] += self.data_shard_pad
        return tuple(shape)


def sharding_of(mesh, resolved: ResolvedPartition):
    """``NamedSharding`` for a resolved leaf on ``mesh`` — the whole
    of what ``backends.sharding_for`` does for table-bound Vectors."""
    from jax.sharding import NamedSharding
    for entry in resolved.spec:
        for ax in (entry,) if isinstance(entry, str) else (entry or ()):
            if ax not in mesh.shape:
                raise PartitionMismatchError(
                    f"partition leaf '{resolved.path}': spec "
                    f"{resolved.spec} names axis '{ax}' but the mesh "
                    f"has {dict(mesh.shape)}")
    return NamedSharding(mesh, resolved.spec)


# ----------------------------------------------------------------------
# the default tail — canonical slot-name coverage
# ----------------------------------------------------------------------
#: batch-major transients: the minibatch data plane plus every
#: per-sample buffer the standard units allocate (dim 0 = minibatch)
_BATCH_SLOTS = (
    r"output", r"out\d+", r"err_input\d*", r"err_output",
    r"minibatch_data", r"minibatch_labels", r"minibatch_indices",
    r"minibatch_raw", r"mask", r"max_idx", r"winners", r"input",
    r"reconstruction", r"targets", r"last_choice",
)
#: replicated persistent / host-bookkeeping state: parameters,
#: momentum (non-ZeRO-1 — the ZeRO-1 allocator declares overrides),
#: schedule tables, PRNG chains, metric accumulators
_REPLICATED_SLOTS = (
    r"weights", r"bias", r"weights_out", r"bias_out", r"vbias",
    r"weights_batch", r"acc_\w+", r"lr_state", r"rng_state",
    r"sched_\w+", r"epoch_\w+", r"n_err", r"confusion", r"coords",
    r"h_mean", r"v_mean", r"step_flags", r"anomaly_state",
    r"fault_inject", r"sdc_\w+", r"zero_mask", r"original_data",
    r"original_labels", r"minibatch_valid",
    r"pos_table", r"hits", r"metrics", r"time", r"histogram",
)


def default_rules() -> list:
    """The framework's default tail: two mutually-exclusive patterns
    over the canonical slot vocabulary.  Unit-declared overrides (TP,
    ring, ZeRO-1, population) precede these; anything matching neither
    is a hard :class:`UnmatchedLeafError` at bind time."""
    return [
        (rf"/({'|'.join(_BATCH_SLOTS)})$", BATCH),
        (rf"/({'|'.join(_REPLICATED_SLOTS)})$", REPLICATED),
    ]


# ----------------------------------------------------------------------
# the table
# ----------------------------------------------------------------------
class PartitionTable:
    """One workflow's ordered rule table.

    Two sections, matched in order: unit-declared **overrides** (exact
    anchored paths, replace-on-redeclare so re-initialization updates
    in place) then the framework's **default tail**
    (:func:`default_rules`).  ``rules`` exposes the concatenation —
    the ONE ordered table resolution walks first-match-wins.
    """

    def __init__(self, name: str = "", defaults=None) -> None:
        self.name = name
        self._overrides: list[tuple[str, object]] = []
        self._defaults: list[tuple[str, object]] = list(
            default_rules() if defaults is None else defaults)
        #: bound leaves: path → ResolvedPartition (audit + metrics)
        self.leaves: dict[str, ResolvedPartition] = {}
        #: axis sizes of the mesh the leaves last resolved against
        #: (round 18: elastic restarts attest that the SAME table
        #: re-resolved every placement onto the surviving — smaller —
        #: mesh; the rules are mesh-independent, this record is not)
        self.bound_mesh: dict[str, int] | None = None

    # -- authoring ------------------------------------------------------
    @property
    def rules(self) -> list[tuple[str, object]]:
        return self._overrides + self._defaults

    def declare(self, pattern: str, placement) -> None:
        """Add (or replace, keeping position) an override rule."""
        for i, (pat, _) in enumerate(self._overrides):
            if pat == pattern:
                self._overrides[i] = (pattern, placement)
                return
        self._overrides.append((pattern, placement))

    def declare_leaf(self, path: str, placement) -> str:
        """Exact-path override for one leaf; returns the pattern."""
        pattern = f"^{re.escape(path)}$"
        self.declare(pattern, placement)
        return pattern

    # -- matching -------------------------------------------------------
    def match(self, path: str,
              skip_stage: bool = False) -> tuple[str, object]:
        """First matching (pattern, placement); hard error otherwise.
        ``skip_stage`` ignores :class:`Stage` tags — the fall-through
        lookup for a Stage rule with no ``inner`` placement."""
        for pattern, placement in self.rules:
            if skip_stage and isinstance(placement, Stage):
                continue
            if re.search(pattern, path):
                return pattern, placement
        raise UnmatchedLeafError(
            f"partition table '{self.name}': no rule matches leaf "
            f"'{path}' ({len(self.rules)} rules) — declare one on the "
            f"owning unit (partition_leaf) or use a canonical slot "
            f"name; there is no silent replicated fallback")

    def audit(self, path: str) -> dict:
        """Every matching rule, split by section — the rule-coverage
        linter's view.  A well-formed table gives each leaf at most
        one (non-Stage) override and, when none, exactly one default
        match; :class:`Stage` tags are listed separately (``stages``)
        because a stage assignment composes WITH a placement rather
        than competing with it — at most one may match a leaf."""
        overrides, stages = [], []
        for p, pl in self._overrides:
            if re.search(p, path):
                (stages if isinstance(pl, Stage) else overrides).append(p)
        defaults = [p for p, _ in self._defaults if re.search(p, path)]
        return {"path": path, "overrides": overrides,
                "defaults": defaults, "stages": stages}

    # -- resolution -----------------------------------------------------
    def resolve(self, path: str, shape, n_data: int = 1,
                member_count: int | None = None) -> ResolvedPartition:
        """Resolve one leaf: scalar short-circuit → first match →
        placement materialized against the LOGICAL shape.  A
        :class:`Stage` match records the stage tag, then the effective
        placement is its ``inner`` (when given) or the NEXT matching
        non-Stage rule — so staging never silences the
        unmatched-leaf hard error."""
        shape = tuple(int(s) for s in shape)
        if len(shape) == 0 or int(np.prod(shape)) <= 1:
            return ResolvedPartition(path, _pspec(), "<scalar>",
                                     logical_shape=shape)
        pattern, placement = self.match(path)
        stage = None
        if isinstance(placement, Stage):
            stage = int(placement.index)
            if placement.inner is not None:
                placement = placement.inner
            else:
                pattern, placement = self.match(path, skip_stage=True)
        resolved = materialize(placement, path, shape, n_data,
                               rule=pattern)
        resolved.stage = stage
        return resolved

    def bind(self, vec, path: str, device) -> ResolvedPartition:
        """Resolve ``path`` for ``vec`` on ``device``, stamp the compat
        attributes, validate against the Vector's structural kind, and
        record the leaf.  Idempotent; a leaf whose storage already
        carries derived padding keeps its resolution."""
        prior = getattr(vec, "_partition", None)
        if prior is not None and prior.pad_applied \
                and prior.path == path:
            self.leaves[path] = prior
            self._publish()
            return prior
        n_data = getattr(device, "n_data_shards", 1)
        mesh = getattr(device, "mesh", None)
        if mesh is not None:
            self.bound_mesh = {ax: int(n) for ax, n in
                               zip(mesh.axis_names, mesh.devices.shape)}
        resolved = self.resolve(path, vec.shape, n_data=n_data)
        _validate_structure(vec, resolved)
        resolved.apply_to(vec)
        self.leaves[path] = resolved
        self._publish()
        return resolved

    # -- telemetry ------------------------------------------------------
    def _publish(self) -> None:
        from znicz_tpu.observe import metrics as _metrics
        if self.name and _metrics.enabled():
            _metrics.partition_rules(self.name).set(len(self.rules))
            _metrics.partition_leaves(self.name).set(len(self.leaves))

    def dump(self) -> list[tuple[str, str]]:
        """(pattern, placement-repr) rows — table introspection for
        dryruns / multi-process agreement checks."""
        return [(pat, repr(pl)) for pat, pl in self.rules]

    def __repr__(self) -> str:
        return (f"PartitionTable('{self.name}', "
                f"{len(self._overrides)} overrides + "
                f"{len(self._defaults)} defaults, "
                f"{len(self.leaves)} leaves)")


# ----------------------------------------------------------------------
# materialization
# ----------------------------------------------------------------------
def _spec_entries(spec) -> tuple:
    try:
        return tuple(spec)
    except TypeError:
        return (spec,)


def materialize(placement, path: str, shape: tuple, n_data: int,
                rule: str = "<direct>") -> ResolvedPartition:
    """Turn a rule's placement into a :class:`ResolvedPartition`
    against the leaf's LOGICAL shape — where ZeRO-1 (dim, pad) and
    member-axis divisibility become consequences."""
    ndim = len(shape)
    if isinstance(placement, _Replicated):
        return ResolvedPartition(path, _pspec(), rule,
                                 logical_shape=shape)
    if isinstance(placement, _Batch):
        if ndim == 0:
            return ResolvedPartition(path, _pspec(), rule,
                                     logical_shape=shape)
        # full-rank spec: NamedSharding equality (and therefore the
        # jit cache key) distinguishes P('data') from P('data', None)
        # — emit exactly what the legacy attribute branch emits
        entries = [DATA_AXIS] + [None] * (ndim - 1)
        return ResolvedPartition(path, _pspec(*entries), rule,
                                 batch_major=True, logical_shape=shape)
    if isinstance(placement, Zero1):
        from znicz_tpu.parallel.mesh import zero1_partition
        md = placement.model_dim
        dim, pad = zero1_partition(shape, n_data, md)
        entries: list = [None] * ndim
        if md is not None:
            entries[md] = MODEL_AXIS
        if dim is None:
            return ResolvedPartition(
                path, _pspec(*entries), rule, model_shard_dim=md,
                logical_shape=shape)
        entries[dim] = DATA_AXIS
        return ResolvedPartition(
            path, _pspec(*entries), rule, model_shard_dim=md,
            data_shard_dim=dim, data_shard_pad=pad,
            logical_shape=shape)
    if isinstance(placement, Member):
        md = placement.model_dim
        if md == 0:
            raise PartitionMismatchError(
                f"partition leaf '{path}': dim 0 is the member axis — "
                f"it cannot also carry the model axis")
        entries = [None] * ndim
        if ndim and n_data > 0 and shape[0] % n_data == 0:
            entries[0] = DATA_AXIS
        if md is not None:
            entries[md] = MODEL_AXIS
        return ResolvedPartition(
            path, _pspec(*entries), rule, model_shard_dim=md,
            member_axis=True, logical_shape=shape)
    # explicit PartitionSpec (or tuple) — derive the compat attributes
    entries = list(_spec_entries(placement))
    if len(entries) > ndim:
        raise PartitionMismatchError(
            f"partition leaf '{path}': spec {tuple(entries)} has more "
            f"entries than the {ndim}-d leaf {shape}")
    entries += [None] * (ndim - len(entries))
    batch = bool(entries) and entries[0] == DATA_AXIS
    model_dim = None
    model_axis = MODEL_AXIS
    data_dim = None
    for i, entry in enumerate(entries):
        if entry in (MODEL_AXIS, SEQ_AXIS):
            if model_dim is not None:
                raise PartitionMismatchError(
                    f"partition leaf '{path}': spec {tuple(entries)} "
                    f"shards two dims over model/seq axes — the "
                    f"compat layer carries exactly one")
            model_dim, model_axis = i, entry
        elif entry == DATA_AXIS and i > 0:
            data_dim = i
    if data_dim is not None and data_dim == model_dim:
        raise PartitionMismatchError(
            f"partition leaf '{path}': dim {data_dim} cannot carry "
            f"both the data and the model axis")
    return ResolvedPartition(
        path, _pspec(*entries), rule, batch_major=batch,
        model_shard_dim=model_dim, model_shard_axis=model_axis,
        data_shard_dim=data_dim, logical_shape=shape)


def _validate_structure(vec, resolved: ResolvedPartition) -> None:
    """The bind-time contract between structure and table: a mismatch
    is a missing/shadowed rule, caught loudly instead of silently
    mis-placing a buffer."""
    if resolved.rule == "<scalar>":
        return  # scalars replicate before structure is consulted
    vec_batch = bool(getattr(vec, "batch_major", False))
    vec_member = bool(getattr(vec, "member_axis", False))
    if vec_batch and not resolved.batch_major:
        raise PartitionMismatchError(
            f"partition leaf '{resolved.path}': batch-major Vector "
            f"resolved to non-batch spec {resolved.spec} via rule "
            f"{resolved.rule!r} — declare/repair the rule")
    if not vec_batch and resolved.batch_major:
        raise PartitionMismatchError(
            f"partition leaf '{resolved.path}': non-batch-major "
            f"Vector resolved to batch spec via rule "
            f"{resolved.rule!r}")
    if vec_member != resolved.member_axis:
        raise PartitionMismatchError(
            f"partition leaf '{resolved.path}': member-axis structure "
            f"({vec_member}) disagrees with rule {resolved.rule!r} "
            f"(member={resolved.member_axis})")


# ----------------------------------------------------------------------
# engine gate + unit-facing helpers
# ----------------------------------------------------------------------
def enabled() -> bool:
    """``root.common.engine.partition_rules`` (default ON).  OFF is
    the legacy A/B arm: declarative call sites apply the equivalent
    slot attributes directly (golden-table test pins parity)."""
    from znicz_tpu.utils.config import root
    return root.common.engine.get("partition_rules", True) \
        not in (False, 0, "off", "false")


def table_for(workflow) -> PartitionTable | None:
    """The owning workflow's table, or None when rules are off / the
    container carries none (bare Vectors keep the legacy attribute
    path in ``sharding_for``)."""
    if not enabled():
        return None
    return getattr(workflow, "partition", None)


def path_of(vec, owner: str | None = None) -> str:
    """Canonical ``unit.name/slot`` leaf path from a Vector's name
    (``fc1.output`` → ``fc1/output``); bare names fall under the
    owning unit."""
    name = getattr(vec, "name", "") or ""
    if "." in name:
        head, rest = name.split(".", 1)
        return f"{head}/{rest}"
    if owner:
        return f"{owner}/{name or 'vec'}"
    return name or "vec"


def declare(unit, vec, placement, slot: str | None = None,
            logical_shape=None) -> ResolvedPartition | None:
    """Unit-facing declaration: register the leaf's rule in the
    workflow table and stamp the resolution (rules ON), or apply the
    equivalent legacy attributes directly (rules OFF).  Returns the
    resolution when the leaf's shape is known."""
    path = (f"{unit.name}/{slot}" if slot is not None
            else path_of(vec, owner=unit.name))
    device = getattr(unit, "device", None)
    n_data = getattr(device, "n_data_shards", 1) if device is not None \
        else 1
    shape = tuple(logical_shape) if logical_shape is not None else (
        tuple(vec.shape) if vec else None)
    table = table_for(unit.workflow)
    if table is None:
        # legacy arm: same decision, applied as slot attributes
        if shape is None:
            return None
        resolved = materialize(placement, path, shape, n_data)
        apply_legacy(vec, resolved)
        return resolved
    table.declare_leaf(path, placement)
    if shape is None:
        return None
    resolved = table.resolve(path, shape, n_data=n_data)
    if not vec or tuple(vec.shape) != resolved.padded_shape():
        # declared against the logical shape before (padded)
        # allocation — the caller stamps after reset
        return resolved
    resolved.apply_to(vec)
    table.leaves[path] = resolved
    table._publish()
    return resolved


def apply_legacy(vec, resolved: ResolvedPartition) -> None:
    """Rules-off arm: the same decision expressed as the legacy slot
    attributes (``sharding_for``'s attribute branch reads these)."""
    vec.model_shard_dim = resolved.model_shard_dim
    vec.model_shard_axis = resolved.model_shard_axis
    vec.data_shard_dim = resolved.data_shard_dim
    vec.data_shard_pad = resolved.data_shard_pad
    if resolved.member_axis:
        vec.member_axis = True


def stamp(unit, vec, resolved: ResolvedPartition,
          pad_applied: bool = False) -> None:
    """Apply a resolution produced by :func:`declare` to a freshly
    allocated Vector (the Zero1 pre-alloc flow: declare against the
    logical shape, allocate padded, stamp)."""
    resolved.pad_applied = pad_applied
    table = table_for(unit.workflow)
    if table is None:
        apply_legacy(vec, resolved)
        return
    resolved.apply_to(vec)
    table.leaves[resolved.path] = resolved
    table._publish()


def bind(table: PartitionTable, vec, owner: str, device) -> None:
    """Bind one Vector against the table at ``init_vectors`` time —
    the lookup that replaced the imperative placement decisions.

    Only canonically named Vectors (``unit.slot``, the framework
    allocation convention) participate: bare-named ad-hoc buffers
    (test fixtures, externally linked arrays) keep the legacy
    attribute path in ``sharding_for`` — the rule namespace is the
    framework's slot vocabulary, and the hard-error contract applies
    inside it."""
    if "." not in (getattr(vec, "name", "") or ""):
        return
    path = path_of(vec, owner=owner)
    table.bind(vec, path, device)


# ----------------------------------------------------------------------
# derived shard / gather helpers (restore-onto-any-mesh)
# ----------------------------------------------------------------------
def make_shard_and_gather_fns(table: PartitionTable, mesh, device):
    """Per-leaf ``shard(host_array) → jax.Array`` /
    ``gather(jax.Array) → host_array`` function pairs for every bound
    leaf — the ``make_shard_and_gather_fns`` idiom over the resolved
    table.  ``shard`` pads a LOGICAL array to the derived ZeRO-1
    storage shape and places it on the resolved sharding; ``gather``
    fetches and strips the padding back off, so snapshots reshard
    bitwise onto any mesh the table resolves for."""
    import jax

    def _pair(resolved: ResolvedPartition):
        sharding = sharding_of(mesh, resolved)

        def shard_fn(arr: np.ndarray):
            arr = np.asarray(arr)
            if resolved.data_shard_dim is not None \
                    and resolved.data_shard_pad:
                dim = resolved.data_shard_dim
                want = resolved.padded_shape()[dim]
                if arr.shape[dim] < want:
                    widths = [(0, 0)] * arr.ndim
                    widths[dim] = (0, want - arr.shape[dim])
                    arr = np.pad(arr, widths)
            return jax.device_put(arr, sharding)

        def gather_fn(devarr) -> np.ndarray:
            arr = np.asarray(device.get(devarr))
            if resolved.data_shard_dim is not None \
                    and resolved.data_shard_pad:
                dim = resolved.data_shard_dim
                idx = [slice(None)] * arr.ndim
                idx[dim] = slice(0, resolved.logical_shape[dim])
                arr = arr[tuple(idx)]
            return arr

        return shard_fn, gather_fn

    shard_fns, gather_fns = {}, {}
    for path, resolved in table.leaves.items():
        shard_fns[path], gather_fns[path] = _pair(resolved)
    return shard_fns, gather_fns
