"""Parallelism: device meshes, SPMD data parallelism, distributed init.

Replaces the reference's L3 cluster layer (reference:
``veles/server.py``, ``veles/client.py``, ``veles/distributable.py`` —
asynchronous ZeroMQ master–slave parameter server) with synchronous
SPMD over a ``jax.sharding.Mesh``: the gradient fold that the reference
performed host-side in ``apply_data_from_slave`` becomes an in-program
ICI all-reduce (``lax.pmean`` over the ``data`` axis), and multi-host
bootstrap is ``jax.distributed.initialize`` over DCN (SURVEY.md §2.5,
§5.8).
"""

from znicz_tpu.parallel.axis import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    SEQ_AXIS,
    current_data_axis,
    data_axis,
    maybe_pmean,
    maybe_psum,
)
from znicz_tpu.parallel.distributed import (  # noqa: F401
    ensure_initialized,
)
from znicz_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    mesh_for_stage,
    batch_sharding,
    kernel_shard_spec,
    replicated_sharding,
    shard_map_fn,
    shard_map_unchecked,
    spec_divides,
    zero1_choice,
    zero1_partition,
    zero1_specs,
)
