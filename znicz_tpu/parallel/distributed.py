"""Multi-host bring-up: ``jax.distributed.initialize`` from env or
flags.

The reference's cluster bootstrap was an explicit Server/Client
handshake (``veles/server.py``); the TPU-native replacement is PJRT
multi-process SPMD — every host runs the same program over one global
mesh.  This module is the single home of that bootstrap so the
Launcher, ``bench.py`` and the dryrun all bring up a pod slice the
same way, **unmodified**: export three env vars and run the same
command on every host.

Environment contract (flags win over env; both optional):

- ``ZNICZ_COORDINATOR``  — ``host:port`` of process 0,
- ``ZNICZ_NUM_PROCESSES`` — total process count,
- ``ZNICZ_PROCESS_ID``   — this process's index (0 = master).

On TPU pods the PJRT plugin can discover all three; on CPU/GPU
clusters (and the two-process CI smoke) they must be given.
"""

from __future__ import annotations

import os

ENV_COORDINATOR = "ZNICZ_COORDINATOR"
ENV_NUM_PROCESSES = "ZNICZ_NUM_PROCESSES"
ENV_PROCESS_ID = "ZNICZ_PROCESS_ID"

_initialized = False


def env_spec() -> dict | None:
    """The env-var bring-up request, or None when unset."""
    coordinator = os.environ.get(ENV_COORDINATOR)
    if not coordinator:
        return None
    spec: dict = {"coordinator_address": coordinator}
    n = os.environ.get(ENV_NUM_PROCESSES)
    if n is not None:
        spec["num_processes"] = int(n)
    pid = os.environ.get(ENV_PROCESS_ID)
    if pid is not None:
        spec["process_id"] = int(pid)
    return spec


def ensure_initialized(coordinator: str | None = None,
                       num_processes: int | None = None,
                       process_id: int | None = None) -> bool:
    """Idempotent ``jax.distributed.initialize``.

    Explicit arguments win; otherwise the env contract above is
    consulted.  Returns True when this process is part of an
    initialized multi-process runtime (including when a caller
    already initialized it), False when nothing requested distributed
    mode — callers can branch mesh construction on the result.
    """
    global _initialized
    import jax

    if _initialized:
        return True
    spec = env_spec() or {}
    if coordinator is not None:
        spec["coordinator_address"] = coordinator
    if num_processes is not None:
        spec["num_processes"] = num_processes
    if process_id is not None:
        spec["process_id"] = process_id
    if not spec.get("coordinator_address"):
        return False
    try:
        # CPU backends need a collectives implementation for
        # cross-process computations (the default "none" fails every
        # multi-process program); harmless no-op on TPU pods
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):  # pragma: no cover - old jax
        pass
    jax.distributed.initialize(**spec)
    _initialized = True
    return True


def process_info() -> tuple[int, int]:
    """(process_index, process_count) of the current runtime."""
    import jax
    return jax.process_index(), jax.process_count()
