"""Multi-host bring-up: ``jax.distributed.initialize`` from env or
flags.

The reference's cluster bootstrap was an explicit Server/Client
handshake (``veles/server.py``); the TPU-native replacement is PJRT
multi-process SPMD — every host runs the same program over one global
mesh.  This module is the single home of that bootstrap so the
Launcher, ``bench.py`` and the dryrun all bring up a pod slice the
same way, **unmodified**: export three env vars and run the same
command on every host.

Environment contract (flags win over env; both optional):

- ``ZNICZ_COORDINATOR``  — ``host:port`` of process 0,
- ``ZNICZ_NUM_PROCESSES`` — total process count,
- ``ZNICZ_PROCESS_ID``   — this process's index (0 = master).

On TPU pods the PJRT plugin can discover all three; on CPU/GPU
clusters (and the two-process CI smoke) they must be given.
"""

from __future__ import annotations

import os

ENV_COORDINATOR = "ZNICZ_COORDINATOR"
ENV_NUM_PROCESSES = "ZNICZ_NUM_PROCESSES"
ENV_PROCESS_ID = "ZNICZ_PROCESS_ID"

_initialized = False


def env_spec() -> dict | None:
    """The env-var bring-up request, or None when unset."""
    coordinator = os.environ.get(ENV_COORDINATOR)
    if not coordinator:
        return None
    spec: dict = {"coordinator_address": coordinator}
    n = os.environ.get(ENV_NUM_PROCESSES)
    if n is not None:
        spec["num_processes"] = int(n)
    pid = os.environ.get(ENV_PROCESS_ID)
    if pid is not None:
        spec["process_id"] = int(pid)
    return spec


def ensure_initialized(coordinator: str | None = None,
                       num_processes: int | None = None,
                       process_id: int | None = None,
                       timeout_s: float | None = None) -> bool:
    """Idempotent ``jax.distributed.initialize`` with a bring-up
    deadline.

    Explicit arguments win; otherwise the env contract above is
    consulted.  Returns True when this process is part of an
    initialized multi-process runtime (including when a caller
    already initialized it), False when nothing requested distributed
    mode — callers can branch mesh construction on the result.

    Round 18 (elastic): bring-up is bounded instead of hanging
    forever on a wrong ``ZNICZ_COORDINATOR`` or a missing peer —
    ``engine.dist_init_timeout_s`` (default 300 s; the ``timeout_s``
    argument overrides) caps each attempt,
    ``engine.dist_init_retries`` (default 2) extra attempts run with
    ``engine.dist_init_backoff_s`` (default 2 s, doubling) between
    them, and final failure raises a RuntimeError naming the exact
    spec and the usual causes.  An elastic restart re-invokes this in
    the relaunched gang with the surviving host set (smaller
    ``ZNICZ_NUM_PROCESSES``, renumbered ids) — the partition table
    then re-resolves every placement onto the smaller mesh.
    """
    global _initialized
    import jax

    from znicz_tpu.utils.config import root

    if _initialized:
        return True
    spec = env_spec() or {}
    if coordinator is not None:
        spec["coordinator_address"] = coordinator
    if num_processes is not None:
        spec["num_processes"] = num_processes
    if process_id is not None:
        spec["process_id"] = process_id
    if not spec.get("coordinator_address"):
        return False
    try:
        # CPU backends need a collectives implementation for
        # cross-process computations (the default "none" fails every
        # multi-process program); harmless no-op on TPU pods
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):  # pragma: no cover - old jax
        pass
    engine = root.common.engine
    timeout = float(timeout_s if timeout_s is not None
                    else engine.get("dist_init_timeout_s", 300.0))
    retries = int(engine.get("dist_init_retries", 2))
    backoff = float(engine.get("dist_init_backoff_s", 2.0))
    last_exc: Exception | None = None
    for attempt in range(retries + 1):
        try:
            try:
                jax.distributed.initialize(
                    initialization_timeout=max(1, int(timeout)), **spec)
            except TypeError:  # pragma: no cover - jax without the kwarg
                jax.distributed.initialize(**spec)
            _initialized = True
            return True
        except (TypeError, ValueError):
            raise  # a bad spec never fixes itself — fail loudly now
        except Exception as exc:  # timeout / connection refused / ...
            last_exc = exc
            try:  # release a half-bound coordinator before retrying
                jax.distributed.shutdown()
            except Exception:
                pass
            if attempt < retries:
                import time as _time
                wait = backoff * (2 ** attempt)
                _time.sleep(wait)
    raise RuntimeError(
        f"jax.distributed bring-up failed after {retries + 1} "
        f"attempt(s) of {timeout:.0f}s each: {last_exc}.  Spec: "
        f"coordinator={spec.get('coordinator_address')!r}, "
        f"num_processes={spec.get('num_processes')}, "
        f"process_id={spec.get('process_id')}.  Check that (a) every "
        f"process exports the SAME {ENV_COORDINATOR} (host:port of "
        f"process 0) and a distinct {ENV_PROCESS_ID} in "
        f"[0, {ENV_NUM_PROCESSES}), (b) process 0 is actually running "
        f"and its port is reachable from this host, and (c) no stale "
        f"process from a previous gang still holds the port.  Raise "
        f"engine.dist_init_timeout_s for slow pod bring-up."
        ) from last_exc


def shutdown() -> None:
    """Tear down the distributed runtime (best effort) so a fresh
    :func:`ensure_initialized` can bring up a new gang — the elastic
    supervisor's relaunched workers are new OS processes, but tests
    and notebook drivers re-enter in-process."""
    global _initialized
    import jax
    try:
        if _initialized:
            jax.distributed.shutdown()
    finally:
        _initialized = False


def process_info() -> tuple[int, int]:
    """(process_index, process_count) of the current runtime."""
    import jax
    return jax.process_index(), jax.process_count()
