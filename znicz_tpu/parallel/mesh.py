"""Mesh construction and sharding helpers.

The recipe (scaling-book style): pick a mesh, annotate shardings on the
batch and (replicated) parameters, let XLA insert the collectives, and
keep collectives on ICI by making the ``data`` axis span the pod slice.

This module is also the one home of the **kernel shard-spec
derivation** (:func:`kernel_shard_spec`): an opaque ``pallas_call``
has no GSPMD sharding rule, so on a multi-device mesh it must run
per-shard under ``shard_map`` with an explicit PartitionSpec — the
flash-attention and fused layer-norm kernels and the ring-attention
entry all derive their specs here, one convention for all three.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from znicz_tpu.parallel.axis import (DATA_AXIS, MODEL_AXIS, PIPE_AXIS,
                                     SEQ_AXIS)


def shard_map_fn():
    """The ``shard_map`` entry point across jax versions (moved out of
    ``jax.experimental`` in 0.8)."""
    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # pragma: no cover - version-dependent
        from jax.experimental.shard_map import shard_map
    return shard_map


def shard_map_unchecked(f, mesh: Mesh, in_specs, out_specs):
    """``shard_map`` with the replication/varying-manual-axes check
    OFF — an opaque ``pallas_call`` (and ``custom_vjp`` around one)
    has no replication rule, so the checker would reject the body.
    Handles the kwarg rename across jax versions (``check_rep`` →
    ``check_vma``)."""
    sm = shard_map_fn()
    try:
        return sm(f, mesh=mesh, in_specs=in_specs,
                  out_specs=out_specs, check_rep=False)
    except TypeError:  # pragma: no cover - version-dependent
        return sm(f, mesh=mesh, in_specs=in_specs,
                  out_specs=out_specs, check_vma=False)


def kernel_shard_spec(mesh: Mesh | None, ndim: int,
                      model_shard_dim: int | None = None,
                      model_axis: str = MODEL_AXIS,
                      ) -> tuple[P, tuple[str, ...]]:
    """Derive the PartitionSpec for running a per-row kernel (flash
    attention, fused layer norm, the ring body) under ``shard_map``.

    Convention (matches ``XLADevice.sharding_for``): dim 0 is the
    batch and rides the ``data`` axis; ``model_shard_dim`` (a Vector's
    annotation — e.g. the time axis after a ring-attention unit) rides
    ``model_axis``.  Feature axes are never sharded here — these
    kernels reduce over the last axis per row, so rows must stay
    whole.

    Returns ``(spec, reduce_axes)``: ``reduce_axes`` are the mesh axes
    that actually split rows (size > 1) — the axes a kernel's
    cross-row reductions (γ/β gradient sums) must ``psum`` over.
    Size-1 axes stay in the spec (harmless, keeps one code path) but
    out of ``reduce_axes``.
    """
    spec: list = [None] * ndim
    axes: list[str] = []
    if mesh is not None:
        if (model_shard_dim != 0 and model_axis != DATA_AXIS
                and DATA_AXIS in mesh.shape):
            spec[0] = DATA_AXIS
            if mesh.shape[DATA_AXIS] > 1:
                axes.append(DATA_AXIS)
        if model_shard_dim is not None and model_axis in mesh.shape:
            spec[model_shard_dim] = model_axis
            if mesh.shape[model_axis] > 1:
                axes.append(model_axis)
    return P(*spec), tuple(axes)


def spec_divides(mesh: Mesh, shape, spec) -> bool:
    """True when every sharded dim of ``shape`` splits evenly over its
    mesh axis — the shard_map shape-legality gate (an indivisible dim
    falls back to the XLA path instead of erroring at trace)."""
    for dim, axis in enumerate(spec):
        if axis is None or dim >= len(shape):
            continue
        for name in (axis,) if isinstance(axis, str) else tuple(axis):
            if shape[dim] % mesh.shape[name]:
                return False
    return True


def shard_shape(mesh: Mesh, shape, spec) -> tuple:
    """The per-device shard shape of ``shape`` under ``spec`` — the
    shapes a ``shard_map`` body actually sees.  Kernel-legality gates
    (the ring's Pallas fold, the unit gates) must reason about THESE,
    not the global shape: T=2048 over an 8-way seq axis hands each
    device 256 rows, and that 256 is what the tiling must divide.
    Assumes :func:`spec_divides` holds."""
    out = list(shape)
    for dim, axis in enumerate(spec):
        if axis is None or dim >= len(out):
            continue
        for name in (axis,) if isinstance(axis, str) else tuple(axis):
            out[dim] //= mesh.shape[name]
    return tuple(out)


# ----------------------------------------------------------------------
# ZeRO-1 data-axis optimizer sharding (Rajbhandari et al., 2020, stage 1)
# ----------------------------------------------------------------------
def zero1_choice(device) -> bool:
    """Resolve the ``root.common.engine.zero1`` gate against a device.

    Auto (the default) engages whenever the device's mesh has a data
    axis of size > 1 — the regime where the replicated update wastes
    both HBM (N identical momentum copies) and ICI (an all-reduce
    moving 2× the bytes of the reduce-scatter + all-gather pair).
    ``root.common.engine.zero1 = False`` is the conservative opt-out;
    host-only and single-data-shard devices always keep the replicated
    update (nothing to shard over).
    """
    from znicz_tpu.utils.config import root
    if device is None or device.is_host_only:
        return False
    mesh = getattr(device, "mesh", None)
    if mesh is None or DATA_AXIS not in mesh.shape \
            or mesh.shape[DATA_AXIS] < 2:
        return False
    gate = root.common.engine.get("zero1", "auto")
    return gate not in (False, 0, "off", "false")


def zero1_partition(shape, n_shards: int,
                    model_shard_dim: int | None = None,
                    ) -> tuple[int | None, int]:
    """Pick ``(dim, pad)`` for sharding a parameter-shaped tensor over
    the data axis in the ZeRO-1 update.

    Preference order: the largest dim that divides evenly over
    ``n_shards`` (pad 0); otherwise the largest dim overall, padded up
    to the next multiple (jax shardings must divide evenly — the pad
    rows are zeros, invisible to the update math, and snapshots slice
    them off).  ``model_shard_dim`` is excluded — that dim already
    rides the model axis and the two compose as a 2-D sharding.
    Returns ``(None, 0)`` when there is nothing to shard (0-d, or
    every dim is the model dim).
    """
    if n_shards < 2:
        return None, 0
    candidates = [(size, d) for d, size in enumerate(shape)
                  if d != model_shard_dim and size > 0]
    if not candidates:
        return None, 0
    even = [(size, d) for size, d in candidates if size % n_shards == 0]
    if even:
        size, dim = max(even, key=lambda t: (t[0], -t[1]))
        return dim, 0
    size, dim = max(candidates, key=lambda t: (t[0], -t[1]))
    return dim, (-size) % n_shards


def zero1_specs(mesh: Mesh, ndim: int, data_shard_dim: int,
                model_shard_dim: int | None = None) -> tuple[P, P]:
    """The (sharded, gathered) PartitionSpec pair for one ZeRO-1
    parameter update: ``sharded`` places ``data_shard_dim`` on the
    data axis (the reduce-scatter target and the stored layout of the
    momentum), ``gathered`` keeps only the model axis (the layout
    every forward expects back).  Constraining grad→sharded and
    updated-param→gathered inside the jit region is what lets GSPMD
    fuse the all-reduce into a reduce-scatter + all-gather pair at
    half the bytes."""
    sharded: list = [None] * ndim
    gathered: list = [None] * ndim
    sharded[data_shard_dim] = DATA_AXIS
    if model_shard_dim is not None and model_shard_dim != data_shard_dim:
        sharded[model_shard_dim] = MODEL_AXIS
        gathered[model_shard_dim] = MODEL_AXIS
    return P(*sharded), P(*gathered)


def make_mesh(n_data: int | None = None, n_model: int = 1,
              n_seq: int = 1, devices=None, n_pipe: int = 1) -> Mesh:
    """Build a ([pipe, ]data, model[, seq]) mesh over the available
    devices.

    ``n_data=None`` uses all remaining devices on the data axis — the
    DP layout matching the reference's capability (its only scale-out
    strategy was data parallelism, SURVEY.md §2.5).  ``devices``
    defaults to ``jax.devices()``, which under a multi-process runtime
    (``parallel.distributed``) is the GLOBAL device list — the same
    call that builds an 8-way virtual CPU mesh builds a pod slice.

    ``n_seq > 1`` adds a third ``seq`` axis for sequence parallelism
    (the ring rides it instead of doubling up on ``model``, so
    DP × TP × SP compose); ``n_seq=1`` keeps the historical 2-D mesh
    so existing sharding specs and tests are untouched.

    ``n_pipe > 1`` (round 20) prepends a LEADING ``pipe`` axis — the
    slowest-varying position, so each pipeline stage owns a contiguous
    block of devices and stage-boundary transfers cross the fewest
    links.  The pipeline executor assigns stage ``k`` the sub-mesh
    ``mesh_for_stage(mesh, k)``; DP/TP/SP placements inside a stage
    are untouched.
    """
    if devices is None:
        devices = jax.devices()
    if n_data is None:
        n_data = len(devices) // (n_model * n_seq * n_pipe)
    use = n_data * n_model * n_seq * n_pipe
    shape = [n_data, n_model] + ([n_seq] if n_seq > 1 else [])
    names = [DATA_AXIS, MODEL_AXIS] + ([SEQ_AXIS] if n_seq > 1 else [])
    if n_pipe > 1:
        shape = [n_pipe] + shape
        names = [PIPE_AXIS] + names
    grid = np.asarray(devices[:use]).reshape(shape)
    return Mesh(grid, axis_names=tuple(names))


def mesh_for_stage(mesh: Mesh, stage: int) -> Mesh:
    """The per-stage sub-mesh of a pipelined mesh: index the leading
    ``pipe`` axis at ``stage`` and return the remaining
    (data, model[, seq]) mesh over that stage's device block.  A mesh
    without a pipe axis is returned unchanged (single-stage layouts and
    the CPU temporal-MPMD executor, which time-multiplexes every stage
    over the same devices)."""
    if PIPE_AXIS not in mesh.axis_names:
        return mesh
    k = mesh.axis_names.index(PIPE_AXIS)
    grid = np.take(mesh.devices, stage, axis=k)
    names = tuple(n for n in mesh.axis_names if n != PIPE_AXIS)
    return Mesh(grid, axis_names=names)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dim over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated (parameters, scalars)."""
    return NamedSharding(mesh, P())
