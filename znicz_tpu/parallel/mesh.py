"""Mesh construction and sharding helpers.

The recipe (scaling-book style): pick a mesh, annotate shardings on the
batch and (replicated) parameters, let XLA insert the collectives, and
keep collectives on ICI by making the ``data`` axis span the pod slice.

This module is also the one home of the **kernel shard-spec
derivation** (:func:`kernel_shard_spec`): an opaque ``pallas_call``
has no GSPMD sharding rule, so on a multi-device mesh it must run
per-shard under ``shard_map`` with an explicit PartitionSpec — the
flash-attention and fused layer-norm kernels and the ring-attention
entry all derive their specs here, one convention for all three.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from znicz_tpu.parallel.axis import DATA_AXIS, MODEL_AXIS


def shard_map_fn():
    """The ``shard_map`` entry point across jax versions (moved out of
    ``jax.experimental`` in 0.8)."""
    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # pragma: no cover - version-dependent
        from jax.experimental.shard_map import shard_map
    return shard_map


def shard_map_unchecked(f, mesh: Mesh, in_specs, out_specs):
    """``shard_map`` with the replication/varying-manual-axes check
    OFF — an opaque ``pallas_call`` (and ``custom_vjp`` around one)
    has no replication rule, so the checker would reject the body.
    Handles the kwarg rename across jax versions (``check_rep`` →
    ``check_vma``)."""
    sm = shard_map_fn()
    try:
        return sm(f, mesh=mesh, in_specs=in_specs,
                  out_specs=out_specs, check_rep=False)
    except TypeError:  # pragma: no cover - version-dependent
        return sm(f, mesh=mesh, in_specs=in_specs,
                  out_specs=out_specs, check_vma=False)


def kernel_shard_spec(mesh: Mesh | None, ndim: int,
                      model_shard_dim: int | None = None,
                      model_axis: str = MODEL_AXIS,
                      ) -> tuple[P, tuple[str, ...]]:
    """Derive the PartitionSpec for running a per-row kernel (flash
    attention, fused layer norm, the ring body) under ``shard_map``.

    Convention (matches ``XLADevice.sharding_for``): dim 0 is the
    batch and rides the ``data`` axis; ``model_shard_dim`` (a Vector's
    annotation — e.g. the time axis after a ring-attention unit) rides
    ``model_axis``.  Feature axes are never sharded here — these
    kernels reduce over the last axis per row, so rows must stay
    whole.

    Returns ``(spec, reduce_axes)``: ``reduce_axes`` are the mesh axes
    that actually split rows (size > 1) — the axes a kernel's
    cross-row reductions (γ/β gradient sums) must ``psum`` over.
    Size-1 axes stay in the spec (harmless, keeps one code path) but
    out of ``reduce_axes``.
    """
    spec: list = [None] * ndim
    axes: list[str] = []
    if mesh is not None:
        if (model_shard_dim != 0 and model_axis != DATA_AXIS
                and DATA_AXIS in mesh.shape):
            spec[0] = DATA_AXIS
            if mesh.shape[DATA_AXIS] > 1:
                axes.append(DATA_AXIS)
        if model_shard_dim is not None and model_axis in mesh.shape:
            spec[model_shard_dim] = model_axis
            if mesh.shape[model_axis] > 1:
                axes.append(model_axis)
    return P(*spec), tuple(axes)


def spec_divides(mesh: Mesh, shape, spec) -> bool:
    """True when every sharded dim of ``shape`` splits evenly over its
    mesh axis — the shard_map shape-legality gate (an indivisible dim
    falls back to the XLA path instead of erroring at trace)."""
    for dim, axis in enumerate(spec):
        if axis is None or dim >= len(shape):
            continue
        for name in (axis,) if isinstance(axis, str) else tuple(axis):
            if shape[dim] % mesh.shape[name]:
                return False
    return True


def shard_shape(mesh: Mesh, shape, spec) -> tuple:
    """The per-device shard shape of ``shape`` under ``spec`` — the
    shapes a ``shard_map`` body actually sees.  Kernel-legality gates
    (the ring's Pallas fold, the unit gates) must reason about THESE,
    not the global shape: T=2048 over an 8-way seq axis hands each
    device 256 rows, and that 256 is what the tiling must divide.
    Assumes :func:`spec_divides` holds."""
    out = list(shape)
    for dim, axis in enumerate(spec):
        if axis is None or dim >= len(out):
            continue
        for name in (axis,) if isinstance(axis, str) else tuple(axis):
            out[dim] //= mesh.shape[name]
    return tuple(out)


def make_mesh(n_data: int | None = None, n_model: int = 1,
              devices=None) -> Mesh:
    """Build a (data, model) mesh over the available devices.

    ``n_data=None`` uses all devices on the data axis — the DP layout
    matching the reference's capability (its only scale-out strategy
    was data parallelism, SURVEY.md §2.5).
    """
    if devices is None:
        devices = jax.devices()
    if n_data is None:
        n_data = len(devices) // n_model
    use = n_data * n_model
    grid = np.asarray(devices[:use]).reshape(n_data, n_model)
    return Mesh(grid, axis_names=(DATA_AXIS, MODEL_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dim over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated (parameters, scalars)."""
    return NamedSharding(mesh, P())
