"""Mesh construction and sharding helpers.

The recipe (scaling-book style): pick a mesh, annotate shardings on the
batch and (replicated) parameters, let XLA insert the collectives, and
keep collectives on ICI by making the ``data`` axis span the pod slice.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from znicz_tpu.parallel.axis import DATA_AXIS, MODEL_AXIS


def make_mesh(n_data: int | None = None, n_model: int = 1,
              devices=None) -> Mesh:
    """Build a (data, model) mesh over the available devices.

    ``n_data=None`` uses all devices on the data axis — the DP layout
    matching the reference's capability (its only scale-out strategy
    was data parallelism, SURVEY.md §2.5).
    """
    if devices is None:
        devices = jax.devices()
    if n_data is None:
        n_data = len(devices) // n_model
    use = n_data * n_model
    grid = np.asarray(devices[:use]).reshape(n_data, n_model)
    return Mesh(grid, axis_names=(DATA_AXIS, MODEL_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dim over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated (parameters, scalars)."""
    return NamedSharding(mesh, P())
