"""Process-level work sharding for population/ensemble evaluation.

The reference farmed independent jobs (genomes, ensemble members)
across the cluster through the master's job queue (reference:
``veles/genetics/__init__.py``, ``veles/ensemble/``; SURVEY.md §2.5
"population parallelism").  The TPU-first restatement: under
``jax.distributed`` every process holds the same deterministic work
list, evaluates the round-robin slice ``work[process_index::
process_count]`` on its *local* devices (no cross-process collectives
inside an evaluation — each job is an independent training run), and
the scores are merged with one all-gather per generation.  Single
process degrades to plain serial evaluation with zero jax calls.
"""

from __future__ import annotations

import numpy as np


def process_info() -> tuple[int, int]:
    """``(process_index, process_count)`` — (0, 1) when jax is not
    initialized for multi-process."""
    import jax
    try:
        count = jax.process_count()
    except Exception:  # pragma: no cover - jax always importable here
        return 0, 1
    return (jax.process_index(), count) if count > 1 else (0, 1)


def local_eval_device():
    """An :class:`~znicz_tpu.backends.XLADevice` pinned to this
    process's first *addressable* device — the evaluation device for
    process-sharded jobs.  (``XLADevice()``'s default ``jax.devices()
    [0]`` is a process-0 device globally; non-zero processes cannot
    place buffers there.)"""
    import jax

    from znicz_tpu.backends import XLADevice
    return XLADevice(device=jax.local_devices()[0])


def pick_eval_device(device_factory=None):
    """The one device-selection policy for process-sharded jobs:
    explicit factory wins; multi-process defaults to the local device
    (jobs are collective-free); single-process follows the config."""
    from znicz_tpu.backends import Device
    if device_factory:
        return device_factory()
    if process_info()[1] > 1:
        return local_eval_device()
    return Device.create()


def _exact_allgather(arr: np.ndarray) -> np.ndarray:
    """``process_allgather`` that survives jax's 32-bit dtype
    canonicalization: 8-byte dtypes (float64/int64) ride the wire as
    uint32 pairs and are restored bit-exactly, so multi-process
    results cannot diverge numerically from single-process ones."""
    import jax
    from jax.experimental import multihost_utils
    arr = np.ascontiguousarray(arr)
    wire = arr.view(np.uint32) if arr.dtype.itemsize == 8 else arr
    out = np.asarray(multihost_utils.process_allgather(wire))
    # older jax returns the array UNCHANGED at process_count == 1 (no
    # leading process axis; newer jax always stacks) — normalize so
    # callers always see (process_count, *arr.shape)
    out = out.reshape((jax.process_count(),) + wire.shape)
    if arr.dtype.itemsize == 8:
        return out.view(arr.dtype)
    return out


def merge_sharded_scores(scores: np.ndarray, owner_stride: int
                         ) -> np.ndarray:
    """All-gather a round-robin-sharded score vector.

    ``scores[i]`` is valid only on process ``i % owner_stride`` (the
    process that evaluated job *i*); other slots are don't-care.  Every
    process calls this in lockstep; returns the merged vector where
    slot *i* comes from its owning process.  ``owner_stride`` is the
    process count."""
    gathered = _exact_allgather(np.asarray(scores, np.float64))
    # gathered: (process_count, n) — row p is process p's local vector
    merged = np.empty_like(gathered[0])
    for i in range(merged.shape[0]):
        merged[i] = gathered[i % owner_stride, i]
    return merged


def merge_round_robin(local_values, pidx: int, pcount: int,
                      n: int) -> np.ndarray:
    """Merge per-job values when job *i* lives on process ``i %
    pcount`` at local slot ``i // pcount`` (the round-robin inverse):
    scatter this process's values into its global slots, then gather.
    ``local_values`` must have length ``len(range(pidx, n, pcount))``."""
    scores = np.full(n, np.nan)
    scores[pidx::pcount] = local_values
    return merge_sharded_scores(scores, pcount)


def allgather_sum(partial: np.ndarray) -> np.ndarray:
    """Sum a per-process partial array across processes (lockstep).
    Transport is bit-exact and the reduction runs on the host in the
    input's own precision (float64 stays float64)."""
    gathered = _exact_allgather(np.asarray(partial, np.float64))
    return gathered.sum(axis=0)


def broadcast_from_zero(arr: np.ndarray) -> np.ndarray:
    """Broadcast process 0's array to every process (lockstep,
    bit-exact for 8-byte dtypes)."""
    from jax.experimental import multihost_utils
    arr = np.ascontiguousarray(arr)
    if arr.dtype.itemsize == 8:
        return np.asarray(multihost_utils.broadcast_one_to_all(
            arr.view(np.uint32))).view(arr.dtype)
    return np.asarray(multihost_utils.broadcast_one_to_all(arr))
