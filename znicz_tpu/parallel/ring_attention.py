"""Ring attention: sequence/context parallelism over the device mesh.

The 2015 reference has no attention (SURVEY.md §5.7), but this
framework treats long-context machinery as first-class: sequences too
long for one chip's HBM shard over a mesh axis, and attention runs
**blockwise around the ICI ring** — each device keeps its Q shard and
passes K/V shards to its neighbor with ``jax.lax.ppermute``, folding
every incoming block into an **online-softmax accumulator** (running
max, normalizer and weighted-value sum), the numerically stable
streaming form.  Communication overlaps compute block by block and no
device ever materializes the full (T, T) score matrix.

Layout: ``(batch, time, heads, head_dim)``; time is sharded over
:data:`SEQ_AXIS`.  :func:`sequence_sharded_attention` is the user
entry — it ``shard_map``'s :func:`ring_attention_block` over the mesh
and is validated on the virtual CPU mesh against
:func:`local_attention` (the single-device oracle).  Causal masking
uses global positions, so it is exact across shard boundaries.

Since round 6 the production TPU fold is the fused flash KERNEL: each
hop is one :func:`znicz_tpu.ops.pallas_attention.ring_hop` pass at
the hop's global offset (:func:`_ring_kernel_fold`), and the XLA scan
fold below is the portable fallback (non-TPU backends,
kernel-illegal shard geometry — :func:`ring_fold_choice` resolves).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from znicz_tpu.parallel.axis import SEQ_AXIS

_NEG_INF = -1e30


def _visibility(tq: int, tk: int, q_pos=None, k_pos=None):
    """(1, 1, tq, tk) key-visibility mask: causal when global
    positions are given (exact across shard/block boundaries — the
    one masking rule both the ring and the blocked form use), all-ones
    otherwise."""
    if q_pos is None:
        return jnp.ones((1, 1, tq, tk), bool)
    return (q_pos[:, None] >= k_pos[None, :])[None, None]


def local_attention(q, k, v, causal: bool = False, dot_dtype=None):
    """Single-device softmax attention — the oracle.

    Shapes: q (B, Tq, H, D), k/v (B, Tk, H, D) → (B, Tq, H, D).

    ``dot_dtype`` (e.g. ``jnp.bfloat16``) casts the GEMM operands AND
    the materialized (T, T) score/probability tensors to that dtype —
    the profile of the T=2048 step (PERF.md round 5) shows the six
    attention-core GEMMs + the softmax reduction pinned at the HBM
    bandwidth roof (~660–775 GB/s, 11–24 TF/s) streaming f32 (B, H,
    T, T) tensors, so halving the bytes nearly halves the step.
    Softmax statistics (row max, normalizer) still reduce in f32 via
    ``preferred_element_type`` on the reductions' inputs; ``None``
    keeps the original full-f32 math (the CPU/oracle path).
    """
    d = q.shape[-1]
    if dot_dtype is not None:
        q, k, v = (a.astype(dot_dtype) for a in (q, k, v))
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) / np.sqrt(d)
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = (jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :])
        s = jnp.where(mask[None, None], s, _NEG_INF)
    if dot_dtype is not None:
        # stabilized softmax with the big (T, T) tensors STORED in
        # dot_dtype; exp/normalizer math in f32
        s = s.astype(dot_dtype)
        m = jax.lax.stop_gradient(
            s.max(axis=-1, keepdims=True).astype(jnp.float32))
        e = jnp.exp(s.astype(jnp.float32) - m)
        p = (e / e.sum(axis=-1, keepdims=True)).astype(dot_dtype)
    else:
        p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v,
                     preferred_element_type=jnp.float32)
    return out


def local_attention_blocked(q, k, v, causal: bool = False,
                            block_k: int = 512, dot_dtype=None):
    """Single-device FLASH-style attention as a plain-XLA ``lax.scan``
    over K/V blocks with the same online-softmax fold the ring uses,
    so the full (T, T) score matrix never materializes in HBM — per
    scan step only a (B, H, Tq, block_k) tile exists.

    Since round 5 this is the portable FALLBACK, not the production
    path: on TPU the fused Pallas kernel
    (:func:`znicz_tpu.ops.pallas_attention.flash_attention`) is the
    measured winner at every T (SEQ_BENCH.json / PERF.md round 5) and
    is the unit's default.  The scan form remains for platforms
    without Pallas and as the shard_map-compatible fold the ring path
    shares; while (T, T) fits HBM the plain fused form beats this
    scan (the carry round-trips dominate — measured round 4), so the
    scan is only selected explicitly via
    ``MultiHeadAttention(flash_block_k=...)`` on non-TPU backends.

    Exact same math as :func:`local_attention` (tested equal, fwd and
    vjp); ``jax.checkpoint`` on the fold keeps the backward from
    storing per-block softmax residuals (it recomputes the tile —
    the standard flash-attention backward tradeoff)."""
    b, t, h, d = q.shape
    tk = k.shape[1]
    if tk % block_k:
        raise ValueError(f"T_k {tk} not divisible by block_k {block_k}")
    n_blocks = tk // block_k
    qh = q  # (B, Tq, H, D); fold consumes this layout directly
    k_blocks = k.reshape(b, n_blocks, block_k, h, d) \
        .transpose(1, 0, 2, 3, 4)
    v_blocks = v.reshape(b, n_blocks, block_k, h, d) \
        .transpose(1, 0, 2, 3, 4)
    tq = t
    q_pos = jnp.arange(tq)

    m0 = jnp.full((b, h, tq), _NEG_INF, jnp.float32)
    denom0 = jnp.zeros((b, h, tq), jnp.float32)
    acc0 = jnp.zeros((b, h, tq, d), jnp.float32)

    @functools.partial(jax.checkpoint, prevent_cse=False)
    def fold(carry, blk):
        i, k_blk, v_blk = blk
        mask = _visibility(
            tq, block_k,
            *((q_pos, i * block_k + jnp.arange(block_k)) if causal
              else (None, None)))
        return _fold_block(carry, qh, k_blk, v_blk, mask,
                           dot_dtype=dot_dtype), None

    (m, denom, acc), _ = jax.lax.scan(
        fold, (m0, denom0, acc0),
        (jnp.arange(n_blocks), k_blocks, v_blocks))
    out = acc / jnp.maximum(denom, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def _fold_block(carry, q, k_blk, v_blk, s_mask, dot_dtype=None):
    """Online-softmax fold of one K/V block into (m, denom, acc).

    ``dot_dtype`` casts the two tile GEMMs' operands (scores stay f32
    via ``preferred_element_type``; the running statistics are always
    f32 — same convention as :func:`local_attention`)."""
    m, denom, acc = carry
    d = q.shape[-1]
    if dot_dtype is not None:
        q, k_blk = q.astype(dot_dtype), k_blk.astype(dot_dtype)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk,
                   preferred_element_type=jnp.float32) / np.sqrt(d)
    s = jnp.where(s_mask, s, _NEG_INF)
    m_blk = s.max(axis=-1)
    m_new = jnp.maximum(m, m_blk)
    # guard: rows with no visible keys anywhere yet keep m = -inf
    p = jnp.exp(s - m_new[..., None])
    p = jnp.where(s_mask, p, 0.0)
    correction = jnp.exp(m - m_new)
    if dot_dtype is not None:
        p, v_blk = p.astype(dot_dtype), v_blk.astype(dot_dtype)
    acc = acc * correction[..., None] \
        + jnp.einsum("bhqk,bkhd->bhqd", p, v_blk,
                     preferred_element_type=jnp.float32)
    denom = (denom * correction
             + p.astype(jnp.float32).sum(axis=-1))
    return m_new, denom, acc


def _ring_kernel_fold(q, k, v, offs, axis_name: str, causal: bool,
                      dot_dtype, block_q: int | None,
                      block_k: int | None, interpret: bool,
                      head_pack: int):
    """The round-6 ring fold: each hop IS one fused flash-kernel pass
    (:func:`znicz_tpu.ops.pallas_attention.ring_hop`) over the
    arriving K/V shard at its GLOBAL offset, and the hops compose
    through the same online-softmax (m, l, acc) algebra the scan fold
    carries — expressed as the numerically identical (out, lse) pair:
    ``combine((o₁, lse₁), (o₂, lse₂)) = ((o₁·w₁ + o₂·w₂)/(w₁+w₂),
    m + log(w₁+w₂))`` with ``wᵢ = exp(lseᵢ − m)``.  The backward
    differentiates through the combination and the per-hop custom_vjp
    (recompute-from-lse kernels, the lse cotangent folded into delta),
    so sequence-parallel training runs kernel-rate in BOTH directions.
    Causal hops entirely above the diagonal skip every tile via the
    kernel's offset-aware ``pl.when`` (they contribute lse ≈ −1e30 and
    weight 0 here).

    Operands stay head-major (and head-packed) around the whole ring —
    K/V rotate in kernel layout, so the per-hop cost is exactly one
    kernel dispatch, no re-transposes.

    ``offs`` is this device's (1, 1) int32 global row offset, handed
    in as a SEQUENCE-SHARDED OPERAND (not ``axis_index``), and the
    arriving block's offset ROTATES with K/V via ``ppermute``.  This
    is load-bearing, not style: the offsets become custom_vjp
    residuals, i.e. shard_map OUTPUTS of the forward — and the GSPMD
    partitioner refuses a partition-id-derived value crossing that
    boundary ("PartitionId instruction is not supported for SPMD
    partitioning … ambiguous").  Deriving them from a sharded operand
    keeps the whole fold partition-id-free."""
    from znicz_tpu.ops import pallas_attention as pa

    axis_size = jax.lax.psum(1, axis_name)
    b, tq, h, dh = q.shape
    tk = k.shape[1]
    if dot_dtype is not None:
        q, k, v = (a.astype(dot_dtype) for a in (q, k, v))
    pack = head_pack or 1
    qh, kh, vh = (pa.pack_heads(a, pack) for a in (q, k, v))
    bq = min(block_q or pa.BLOCK_Q, tq)
    bk = min(block_k or pa.BLOCK_K, tk)
    q_off = offs[0, 0]                   # this device's global row 0
    dhp = pack * dh                      # packed head width

    def hop(k_t, v_t, k_off):
        return pa.ring_hop(qh, k_t, v_t, q_off, k_off, causal,
                           bq, bk, interpret, pack)

    def combine(state, o_h, lse_h):
        o, lse = state                   # o f32, lse f32 (B,Hp,Tq,pack)
        m = jnp.maximum(lse, lse_h)
        w1, w2 = jnp.exp(lse - m), jnp.exp(lse_h - m)
        l = w1 + w2
        o = o * jnp.repeat(w1 / l, dh, axis=-1) \
            + o_h.astype(jnp.float32) * jnp.repeat(w2 / l, dh, axis=-1)
        return o, m + jnp.log(l)

    # fold the local block first (it holds the causal diagonal, so
    # lse starts finite), then rotate-then-fold — the final iteration
    # folds without a trailing (wasted) ppermute
    o0, lse0 = hop(kh, vh, q_off)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(i, loop_state):
        o, lse, k_cur, v_cur, off_cur = loop_state
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        # the arriving block's global offset travels WITH the block
        off_cur = jax.lax.ppermute(off_cur, axis_name, perm)
        o, lse = combine((o, lse), *hop(k_cur, v_cur, off_cur[0, 0]))
        return o, lse, k_cur, v_cur, off_cur

    o, _, _, _, _ = jax.lax.fori_loop(
        1, axis_size, step, (o0.astype(jnp.float32), lse0, kh, vh,
                             offs))
    assert o.shape == (b, h // pack, tq, dhp)
    return pa.unpack_heads(o.astype(q.dtype), pack, h)  # (B,Tq,H,Dh)


def ring_attention_block(q, k, v, seq_offsets=None,
                         axis_name: str = SEQ_AXIS,
                         causal: bool = False, dot_dtype=None,
                         block_k: int | None = None,
                         pallas_fold: bool = False,
                         pallas_interpret: bool = False,
                         pallas_block_q: int | None = None,
                         head_pack: int = 1):
    """The per-device body (call under ``shard_map``): q/k/v are THIS
    device's sequence shards; K/V rotate the full ring.

    ``pallas_fold`` makes each hop a fused flash-kernel pass (the
    round-6 production TPU path — see :func:`_ring_kernel_fold`);
    ``pallas_interpret`` runs those kernels in interpret mode (the
    virtual-CPU-mesh testing lever), ``pallas_block_q`` overrides the
    kernel's q tile and ``head_pack`` is the lane-packing factor
    resolved by the unit gate.  Legality (tiling/dh) is the CALLER's
    job — :func:`sequence_sharded_attention` gates on the per-shard
    shapes and falls back to the scan fold.

    ``block_k`` composes the flash-style K/V-block fold INTO each ring
    step of the SCAN fold: the arriving (tq × tk_local) tile is folded
    sub-block by sub-block under ``jax.checkpoint``, so a device never
    materializes even its per-step local score tile — the single-chip
    ``local_attention_blocked`` memory behavior, per ring hop.
    Without it, large per-device T_local hits the same (tq, tk) HBM
    wall on every hop that the blocked form was built to remove
    (round-4 verdict item 6).  On the kernel fold, ``block_k`` is the
    kernel's K tile instead.  The scan fold remains the portable
    fallback (non-TPU backends, kernel-illegal shapes).

    ``seq_offsets`` (kernel fold only): this device's (1, 1) int32
    global row offset as a sequence-sharded operand — see
    :func:`_ring_kernel_fold` for why it cannot be ``axis_index``."""
    if pallas_fold:
        if seq_offsets is None:
            raise ValueError("the kernel fold needs the sharded "
                             "seq_offsets operand (see "
                             "sequence_sharded_attention)")
        return _ring_kernel_fold(q, k, v, seq_offsets, axis_name,
                                 causal, dot_dtype, pallas_block_q,
                                 block_k, pallas_interpret, head_pack)
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, tq, h, dim = q.shape
    tk = k.shape[1]
    # block_k >= T_local degrades to the whole-tile fold below — only
    # a PARTIAL blocking that doesn't tile evenly is an error
    if block_k is not None and block_k < tk and tk % block_k:
        raise ValueError(f"T_local {tk} not divisible by "
                         f"block_k {block_k}")
    q_pos = my_idx * tq + jnp.arange(tq)            # global positions

    def fold_tile(state, k_t, v_t, src):
        """Fold the whole K/V tile that originated on device ``src``
        — one `_fold_block` when ``block_k`` is off, a checkpointed
        sub-block scan when on."""
        k_pos0 = src * tk
        if block_k is None or block_k >= tk:
            mask = _visibility(
                tq, tk,
                *((q_pos, k_pos0 + jnp.arange(tk)) if causal
                  else (None, None)))
            return _fold_block(state, q, k_t, v_t, mask,
                               dot_dtype=dot_dtype)
        nb = tk // block_k
        k_sub = jnp.moveaxis(
            k_t.reshape(b, nb, block_k, h, dim), 1, 0)
        v_sub = jnp.moveaxis(
            v_t.reshape(b, nb, block_k, h, dim), 1, 0)

        @functools.partial(jax.checkpoint, prevent_cse=False)
        def sub_fold(carry, blk):
            i, kk, vv = blk
            mask = _visibility(
                tq, block_k,
                *((q_pos, k_pos0 + i * block_k + jnp.arange(block_k))
                  if causal else (None, None)))
            return _fold_block(carry, q, kk, vv, mask,
                               dot_dtype=dot_dtype), None

        state, _ = jax.lax.scan(sub_fold, state,
                                (jnp.arange(nb), k_sub, v_sub))
        return state

    # accumulators: derived from q so they carry its sharded/varying
    # type under shard_map, but cast to f32 — attention statistics
    # accumulate across the whole ring in f32 even with bf16 q/k/v
    # (the repo-wide bf16-inputs/f32-accumulation convention)
    zero4 = (jnp.swapaxes(q, 1, 2) * 0.0).astype(jnp.float32)
    state = (zero4[..., 0] + _NEG_INF, zero4[..., 0], zero4)
    # fold the local block first, then rotate-then-fold — the final
    # iteration folds without a trailing (wasted) ppermute
    state = fold_tile(state, k, v, my_idx)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(i, loop_state):
        m, denom, acc, k_cur, v_cur = loop_state
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        src = (my_idx - i) % axis_size   # origin of the arriving block
        m, denom, acc = fold_tile((m, denom, acc), k_cur, v_cur, src)
        return m, denom, acc, k_cur, v_cur

    m, denom, acc, _, _ = jax.lax.fori_loop(
        1, axis_size, step, (*state, k, v))
    denom = jnp.where(denom == 0.0, 1.0, denom)  # fully masked rows
    out = (acc / denom[..., None]).astype(q.dtype)
    return jnp.transpose(out, (0, 2, 1, 3))      # → (B, Tq, H, D)


def ring_fold_choice(mesh, shape, axis_name: str = SEQ_AXIS,
                     block_k: int | None = None,
                     pallas_fold: bool = False,
                     pallas_block_q: int | None = None,
                     head_pack: int = 1):
    """Resolve which fold the ring will actually run for a GLOBAL
    (B, T, H, Dh) shape: ``("pallas", bq, bk)`` when the kernel fold
    is requested AND the per-shard geometry is kernel-legal, else
    ``("scan", None, block_k)``.  One place for the unit gate, the
    entry below and the dryrun attestation to agree on."""
    from znicz_tpu.ops import pallas_attention as pa
    from znicz_tpu.parallel.mesh import kernel_shard_spec, \
        shard_shape, spec_divides

    spec, _ = kernel_shard_spec(mesh, 4, model_shard_dim=1,
                                model_axis=axis_name)
    if not pallas_fold or not spec_divides(mesh, shape, spec):
        return "scan", None, block_k
    _, t_local, h, dh = shard_shape(mesh, shape, spec)
    bq = min(pallas_block_q or pa.BLOCK_Q, t_local)
    bk = min(block_k or pa.BLOCK_K, t_local)
    pack = head_pack or 1
    if h % pack or not pa.kernel_legal(t_local, t_local, dh * pack,
                                       bq, bk):
        return "scan", None, block_k
    return "pallas", bq, bk


def sequence_sharded_attention(mesh, q, k, v, causal: bool = False,
                               axis_name: str = SEQ_AXIS,
                               dot_dtype=None,
                               block_k: int | None = None,
                               pallas_fold: bool = False,
                               pallas_interpret: bool = False,
                               pallas_block_q: int | None = None,
                               head_pack: int = 1):
    """Shard the time axis of q/k/v over ``mesh[axis_name]`` and run
    ring attention; returns output with the same sharding as q.

    ``pallas_fold=True`` requests the round-6 kernel fold (each hop a
    fused flash pass at its global offset); shapes the kernel's tiling
    cannot cover fall back to the scan fold silently — the same
    fallback philosophy as the unit gates.  ``pallas_interpret`` is
    the virtual-CPU-mesh lever (the REAL kernels, emulated).

    When the mesh also has a ``data`` axis, the BATCH dim shards over
    it — the ring runs per batch shard (the batch dim never enters the
    ring collectives), so data parallelism composes with sequence
    parallelism instead of being silently all-gathered away at the
    shard_map boundary."""
    from znicz_tpu.parallel.mesh import kernel_shard_spec, \
        shard_map_fn, shard_map_unchecked

    # one spec convention for the ring and the mesh-native Pallas
    # kernels: batch rides the data axis, time (dim 1) rides the
    # named sequence/model axis
    spec, _ = kernel_shard_spec(mesh, 4, model_shard_dim=1,
                                model_axis=axis_name)
    fold, bq, bk = ring_fold_choice(
        mesh, q.shape, axis_name=axis_name, block_k=block_k,
        pallas_fold=pallas_fold, pallas_block_q=pallas_block_q,
        head_pack=head_pack)
    body = functools.partial(ring_attention_block,
                             axis_name=axis_name, causal=causal,
                             dot_dtype=dot_dtype, block_k=bk,
                             pallas_fold=(fold == "pallas"),
                             pallas_interpret=pallas_interpret,
                             pallas_block_q=bq,
                             head_pack=head_pack if fold == "pallas"
                             else 1)
    if fold == "pallas":
        from jax.sharding import PartitionSpec as P

        # per-device global row offsets as a SEQ-SHARDED operand (each
        # shard sees its own (1, 1) scalar) — axis_index would leave a
        # partition-id in the custom_vjp residuals, which the GSPMD
        # partitioner rejects at the shard_map boundary
        n_seq = mesh.shape[axis_name]
        t_local = q.shape[1] // n_seq
        offs = (jnp.arange(n_seq, dtype=jnp.int32)
                * t_local).reshape(n_seq, 1)
        # the opaque pallas_call (and its custom_vjp) has no
        # replication rule — same unchecked wrapper as the
        # batch-sharded flash path
        fn = shard_map_unchecked(
            body, mesh,
            in_specs=(spec, spec, spec, P(axis_name, None)),
            out_specs=spec)
        return fn(q, k, v, offs)
    fn = shard_map_fn()(body, mesh=mesh,
                        in_specs=(spec, spec, spec),
                        out_specs=spec)
    return fn(q, k, v)


def make_seq_mesh(n_devices: int | None = None):
    """A 1-D ``seq`` mesh over the local devices (tests use the
    virtual 8-CPU mesh)."""
    from jax.sharding import Mesh
    devices = jax.devices()
    n = n_devices or len(devices)
    return Mesh(np.array(devices[:n]), (SEQ_AXIS,))
