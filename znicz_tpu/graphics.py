"""Graphics service: decoupled rendering + metrics event stream.

Rebuilds the reference's plotting transport (reference:
``veles/graphics_server.py`` / ``veles/graphics_client.py`` — plotter
units published pickled plot payloads over a ZeroMQ PUB socket and a
separate matplotlib process rendered them, keeping drawing off the
training hot path).

TPU-first redesign, same decoupling:

- plotter units :meth:`GraphicsServer.submit` small *payload dicts*;
- a background **render thread** draws them with matplotlib's
  thread-safe object API (``Figure`` + Agg canvas, no pyplot) into
  ``root.common.dirs.plots`` — the training loop never blocks on
  drawing;
- every payload is also appended to ``events.jsonl`` (arrays
  summarized), the structured-metrics stream SURVEY.md §5.5 calls for;
- optionally the payload is ZeroMQ-PUB-published (pickled) for a live
  :class:`GraphicsClient`, preserving the reference's remote-viewer
  topology.

Payload schema (all optional but ``kind``/``name``):
``{"kind": "curve"|"matrix"|"image"|"hist", "name": str, "step": int,
"series": {label: [[x...],[y...]]}, "data": ndarray, "labels": [...]}``
"""

from __future__ import annotations

import json
import os
import pickle
import queue
import threading

import numpy as np

from znicz_tpu.utils.config import root
from znicz_tpu.utils.logger import Logger


def _summarize(value):
    """JSON-safe summary of a payload value (arrays → stats, not bulk)."""
    if isinstance(value, np.ndarray):
        if value.size <= 64:
            return value.tolist()
        return {"shape": list(value.shape),
                "min": float(value.min()), "max": float(value.max()),
                "mean": float(value.mean())}
    if isinstance(value, dict):
        return {k: _summarize(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        if len(value) > 256:
            return {"len": len(value), "tail": _summarize(value[-4:])}
        return [_summarize(v) for v in value]
    if isinstance(value, (np.integer,)):
        return int(value)
    if isinstance(value, (np.floating,)):
        return float(value)
    return value


class GraphicsServer(Logger):
    """Collects plot payloads; renders + logs + publishes off-thread."""

    def __init__(self, out_dir: str | None = None,
                 render: bool | None = None,
                 publish_port: "int | bool | None" = None) -> None:
        # publish_port: None → config default; False → never publish
        super().__init__()
        self.out_dir = out_dir or str(root.common.dirs.plots)
        os.makedirs(self.out_dir, exist_ok=True)
        self.render = (bool(root.common.graphics.render)
                       if render is None else render)
        self._events_path = os.path.join(self.out_dir, "events.jsonl")
        self._events_lock = threading.Lock()
        self._queue: "queue.Queue[dict | None]" = queue.Queue()
        self._thread: threading.Thread | None = None
        self._pub = None
        port = (publish_port if publish_port is not None
                else root.common.graphics.publish_port)
        self.publish_port = None
        if port is not None and port is not False:
            import zmq
            self._zmq_ctx = zmq.Context.instance()
            self._pub = self._zmq_ctx.socket(zmq.PUB)
            if int(port) == 0:  # pick a free port
                self.publish_port = self._pub.bind_to_random_port(
                    "tcp://127.0.0.1")
            else:
                self.publish_port = int(port)
                self._pub.bind(f"tcp://*:{self.publish_port}")
            self.endpoint = f"tcp://127.0.0.1:{self.publish_port}"
        if self.render:
            self._thread = threading.Thread(
                target=self._render_loop, name="graphics-render",
                daemon=True)
            self._thread.start()

    # ------------------------------------------------------------------
    def submit(self, payload: dict) -> None:
        """Accept a payload from a plotter unit (cheap, non-blocking)."""
        self._log_event(payload)
        if self._pub is not None:
            topic = payload.get("name", "plot").encode()
            self._pub.send_multipart([topic, pickle.dumps(payload)])
        if self._thread is not None:
            self._queue.put(payload)

    def flush(self, timeout: float = 30.0) -> bool:
        """Block until everything submitted so far has been drawn
        (consumers like the Publisher embed the PNGs — they must not
        read files the render thread is still writing)."""
        if self._thread is None:
            return True
        event = threading.Event()
        self._queue.put({"__flush__": event})
        return event.wait(timeout)

    def stop(self) -> None:
        """Drain the render queue and join the thread."""
        if self._thread is not None:
            self._queue.put(None)
            self._thread.join(timeout=30)
            self._thread = None
        if self._pub is not None:
            self._pub.close(linger=0)
            self._pub = None

    # ------------------------------------------------------------------
    def _log_event(self, payload: dict) -> None:
        event = {k: _summarize(v) for k, v in payload.items()}
        line = json.dumps(event)
        with self._events_lock:
            with open(self._events_path, "a") as f:
                f.write(line + "\n")

    def _render_loop(self) -> None:
        while True:
            payload = self._queue.get()
            if payload is None:
                return
            # collapse bursts: only the newest payload per name is drawn
            latest: dict[str, dict] = {}
            flush_events = []
            stopping = False
            if "__flush__" in payload:
                flush_events.append(payload["__flush__"])
            else:
                latest[payload.get("name", "plot")] = payload
            try:
                while not stopping:
                    extra = self._queue.get_nowait()
                    if extra is None:
                        stopping = True
                    elif "__flush__" in extra:
                        flush_events.append(extra["__flush__"])
                    else:
                        latest[extra.get("name", "plot")] = extra
            except queue.Empty:
                pass
            for p in latest.values():
                try:
                    self._draw(p)
                except Exception as exc:  # noqa: BLE001 — keep rendering
                    self.warning("failed to draw %s: %s",
                                 p.get("name"), exc)
            for event in flush_events:
                event.set()
            if stopping:
                return

    # -- drawing (render thread only) -----------------------------------
    def _draw(self, payload: dict) -> None:
        from matplotlib.backends.backend_agg import FigureCanvasAgg
        from matplotlib.figure import Figure

        kind = payload.get("kind", "curve")
        name = payload.get("name", "plot")
        fig = Figure(figsize=(6.4, 4.8), dpi=100)
        FigureCanvasAgg(fig)
        ax = fig.add_subplot(111)
        if kind == "curve":
            for label, (xs, ys) in payload.get("series", {}).items():
                ax.plot(xs, ys, label=label)
            ax.set_xlabel(payload.get("xlabel", "epoch"))
            ax.set_ylabel(payload.get("ylabel", ""))
            if payload.get("series"):
                ax.legend(loc="best", fontsize=8)
            ax.grid(True, alpha=0.3)
        elif kind == "matrix":
            data = np.asarray(payload["data"])
            im = ax.imshow(data, cmap=payload.get("cmap", "viridis"))
            fig.colorbar(im, ax=ax)
            labels = payload.get("labels")
            if labels is not None and len(labels) <= 32:
                ax.set_xticks(range(len(labels)), labels, fontsize=6,
                              rotation=90)
                ax.set_yticks(range(len(labels)), labels, fontsize=6)
            if data.shape[0] * data.shape[1] <= 400:
                for (i, j), v in np.ndenumerate(data):
                    ax.text(j, i, f"{v:g}", ha="center", va="center",
                            fontsize=6)
        elif kind == "image":
            data = np.asarray(payload["data"])
            ax.imshow(data, cmap=payload.get("cmap", "gray"))
            ax.axis("off")
        elif kind == "hist":
            data = np.asarray(payload["data"]).ravel()
            ax.bar(np.asarray(payload.get(
                "bin_centers", np.arange(data.size))), data,
                width=payload.get("bar_width", 0.8))
            ax.set_ylabel(payload.get("ylabel", "count"))
        else:
            raise ValueError(f"unknown payload kind '{kind}'")
        title = payload.get("title", name)
        step = payload.get("step")
        if step is not None:
            title = f"{title}  [{payload.get('xlabel', 'epoch')} {step}]"
        ax.set_title(title, fontsize=10)
        fig.tight_layout()
        fig.savefig(os.path.join(self.out_dir, f"{name}.png"))


class GraphicsClient(Logger):
    """Subscribes to a :class:`GraphicsServer`'s PUB socket and renders
    received payloads locally (reference: the separate
    ``graphics_client`` matplotlib process)."""

    def __init__(self, endpoint: str, out_dir: str) -> None:
        super().__init__()
        import zmq
        self._ctx = zmq.Context.instance()
        self._sub = self._ctx.socket(zmq.SUB)
        self._sub.connect(endpoint)
        self._sub.setsockopt(zmq.SUBSCRIBE, b"")
        # publish_port=False: the internal renderer must never open its
        # own PUB socket (it would race the real server for the
        # configured port)
        self._renderer = GraphicsServer(out_dir=out_dir, render=False,
                                        publish_port=False)

    def poll_once(self, timeout_ms: int = 1000) -> bool:
        """Receive and draw one payload; False on timeout."""
        import zmq
        if not self._sub.poll(timeout_ms, zmq.POLLIN):
            return False
        _topic, blob = self._sub.recv_multipart()
        self._renderer._draw(pickle.loads(blob))
        return True

    def close(self) -> None:
        self._sub.close(linger=0)


# ----------------------------------------------------------------------
# process-global default server (reference: one GraphicsServer per run)
# ----------------------------------------------------------------------
_server: GraphicsServer | None = None
_server_lock = threading.Lock()


def get_server() -> GraphicsServer:
    global _server
    with _server_lock:
        if _server is None:
            _server = GraphicsServer()
        return _server


def flush_server() -> bool:
    """Flush the global server's render queue IF one exists (never
    creates one).  False = flush timed out, renders may be mid-write."""
    with _server_lock:
        server = _server
    if server is not None:
        return server.flush()
    return True


def reset_server() -> None:
    """Stop and drop the global server (tests / run teardown)."""
    global _server
    with _server_lock:
        if _server is not None:
            _server.stop()
            _server = None
