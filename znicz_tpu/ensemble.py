"""Ensemble: train N model instances, aggregate their evaluation.

Rebuilds the reference's ``veles/ensemble/`` — N independent trainings
of the same workflow (different seeds), followed by an aggregated
evaluation pass (averaged class probabilities) that is typically
better than any single member.

The reference trained members as separate cluster jobs; here
process-level scale-out mirrors genetics: with ``jax.distributed``,
process *p* trains members ``p::process_count`` on its local devices
(collective-free — members are independent runs), then ``evaluate``
merges the per-process probability sums and member error rates with
lockstep all-gathers, so every process returns the identical ensemble
result.  Single-process trains members sequentially with zero jax
collectives.  Tested across real OS processes in
``tests/test_distributed.py`` (``ensemble`` mode: disjoint member
sets, identical aggregated result).

The aggregated pass replays each member's validation/test minibatches
through its compiled hot chain — backward units stay gated off on
non-train classes, dropout runs in eval mode — and averages the
softmax outputs per sample.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from znicz_tpu.loader.base import TRAIN, VALID
from znicz_tpu.parallel.process_shard import (allgather_sum,
                                              broadcast_from_zero,
                                              merge_round_robin,
                                              pick_eval_device,
                                              process_info)
from znicz_tpu.utils.logger import Logger


def class_forward_pass(wf, klass: int) -> tuple[dict, dict]:
    """Replay every minibatch of ``klass`` through the (trained)
    workflow's hot chain; returns ``(outputs, labels)`` keyed by
    global sample index.  Training side effects are impossible for
    non-train classes: the GD units' ``gate_skip`` follows
    ``minibatch_class != TRAIN`` and stochastic units track
    ``forward_mode``."""
    loader = wf.loader
    outputs: dict[int, np.ndarray] = {}
    labels: dict[int, int] = {}
    out_vec = wf.forwards[-1].output
    for cursor, (cls, _lo, _hi) in enumerate(loader._schedule):
        if cls != klass:
            continue
        loader._cursor = cursor
        loader.run()
        if wf._region_unit is not None:
            wf._region_unit.run()
        else:
            for unit in wf.forwards:
                unit.run()
        out_vec.map_read()
        loader.minibatch_labels.map_read()
        idx = loader._host_indices
        for row in range(loader.minibatch_size):
            gi = int(idx[row])
            outputs[gi] = np.array(out_vec.mem[row], copy=True)
            labels[gi] = int(loader.minibatch_labels.mem[row])
    return outputs, labels


class Ensemble(Logger):
    """Train ``n_models`` instances of a sample and vote.

    Parameters
    ----------
    build_fn:
        ``callable(**overrides) -> StandardWorkflow`` (a sample's
        ``build``); the loss must be classification (softmax head).
    n_models / base_seed:
        member *i* trains with PRNG seed ``base_seed + i`` — different
        weight init and shuffle streams, same dataset split.
    backend:
        ``"process"`` (default — members train sequentially, or
        process-sharded under ``jax.distributed``) or ``"mesh"`` — all
        N members train SIMULTANEOUSLY as one stacked population in a
        single vmapped jit region (member axis sharded over ``mesh``'s
        data axis), each member bitwise-identical to the sequential
        run its seed would produce; the aggregate pass reads all N
        members' class probabilities from one stacked forward.
    """

    def __init__(self, build_fn: Callable, n_models: int = 3,
                 base_seed: int = 1234,
                 device_factory: Callable | None = None,
                 train_kwargs: dict | None = None,
                 backend: str = "process",
                 mesh=None) -> None:
        super().__init__()
        if n_models < 1:
            raise ValueError("n_models must be >= 1")
        if backend not in ("process", "mesh"):
            raise ValueError(f"unknown ensemble backend '{backend}'")
        self.build_fn = build_fn
        self.n_models = int(n_models)
        self.base_seed = int(base_seed)
        self.device_factory = device_factory
        self.train_kwargs = dict(train_kwargs or {})
        self.backend = backend
        self.mesh = mesh
        self.trainer = None                 # mesh backend's population
        self.workflows: list = []           # members trained locally
        self.member_ids: list[int] = []     # their GLOBAL member indices
        self.member_stats: list[dict] = []  # ALL members (gathered)

    # ------------------------------------------------------------------
    def _train_stacked(self) -> "Ensemble":
        """Mesh backend: one population run trains every member."""
        from znicz_tpu.population import PopulationTrainer
        trainer = PopulationTrainer(
            self.build_fn, self.n_models,
            member_seeds=[self.base_seed + i
                          for i in range(self.n_models)],
            build_kwargs=dict(self.train_kwargs),
            mesh=self.mesh, evolve=None, name="ensemble")
        trainer.initialize()
        trainer.run()
        self.trainer = trainer
        self.member_ids = list(range(self.n_models))
        self.member_stats = [
            {"seed": self.base_seed + i,
             "validation_err_pt": float(-trainer.member_best_fitness[i])}
            for i in range(self.n_models)]
        self.info("stacked ensemble trained: %s", self.member_stats)
        return self

    def train(self) -> "Ensemble":
        from znicz_tpu.utils import prng
        if self.backend == "mesh":
            return self._train_stacked()
        pidx, pcount = process_info()
        self.workflows = []
        self.member_ids = []
        local_err_pt: list[float] = []
        local_exc: "Exception | None" = None
        for i in range(self.n_models):
            if i % pcount != pidx:
                continue
            try:
                prng.seed_all(self.base_seed + i)
                wf = self.build_fn(**self.train_kwargs)
                device = pick_eval_device(self.device_factory)
                wf.initialize(device=device)
                wf.run()
            except Exception as exc:
                if pcount == 1:
                    raise
                # multi-process: a lone raise would leave the peers
                # blocked in the stats-merge collective below — record
                # the failure, gather flags, raise together
                local_exc = exc
                break
            d = wf.decision
            stats = {"seed": self.base_seed + i}
            if getattr(d, "min_validation_n_err_pt", None) is not None:
                stats["validation_err_pt"] = \
                    float(d.min_validation_n_err_pt)
            self.info("member %d/%d trained: %s", i + 1,
                      self.n_models, stats)
            self.workflows.append(wf)
            self.member_ids.append(i)
            local_err_pt.append(stats.get("validation_err_pt", np.nan))
        if pcount > 1 and allgather_sum(
                np.array([1.0 if local_exc else 0.0]))[0] > 0:
            raise RuntimeError(
                "ensemble member training failed on a process; every "
                "process aborts together") from local_exc
        self.member_stats = self._gather_member_stats(
            local_err_pt, pidx, pcount)
        return self

    def _gather_member_stats(self, local_err_pt: list[float],
                             pidx: int, pcount: int) -> list[dict]:
        """Per-member stats for ALL members, identical on every
        process.  Member *i* lives on process ``i % pcount`` at local
        slot ``i // pcount`` — the round-robin inverse."""
        if pcount == 1:
            return [{"seed": self.base_seed + i,
                     "validation_err_pt": err_pt}
                    if not np.isnan(err_pt)
                    else {"seed": self.base_seed + i}
                    for i, err_pt in enumerate(local_err_pt)]
        merged = merge_round_robin(local_err_pt, pidx, pcount,
                                   self.n_models)
        stats = []
        for i in range(self.n_models):
            entry = {"seed": self.base_seed + i}
            if not np.isnan(merged[i]):
                entry["validation_err_pt"] = float(merged[i])
            stats.append(entry)
        return stats

    # ------------------------------------------------------------------
    _SPLIT_DISAGREES = (
        "members disagree on sample labels: the loader's class split "
        "depends on the PRNG seed; give the loader a fixed split (or "
        "its own prng_name) so every member sees the same sample at "
        "the same global index")

    def _evaluate_stacked(self, klass: int) -> dict:
        """Mesh backend aggregate pass: every ``klass`` minibatch runs
        ONCE through the stacked eval-variant region and all N
        members' probabilities come back as one (N, batch, classes)
        read — the aggregate pass costs one schedule sweep, not one
        per member.  Non-train segments ride natural order identically
        across members, so labels agree by construction."""
        if self.trainer is None:
            raise RuntimeError("train() first")
        region = self.trainer.region
        wf = self.trainer.template
        loader = wf.loader
        out_vec = wf.forwards[-1].output
        sum_probs: dict[int, np.ndarray] = {}
        labels: dict[int, int] = {}
        member_err_counts = np.zeros(self.n_models, dtype=np.int64)
        for pos, (cls, lo, hi) in enumerate(loader._schedule):
            if cls != klass:
                continue
            region.run_schedule_entry(pos)
            probs = np.array(region.read_leaf(out_vec),
                             dtype=np.float64)        # (N, B, C)
            idx = np.asarray(
                region.read_leaf(loader.minibatch_indices)[0])
            labs = np.asarray(
                region.read_leaf(loader.minibatch_labels)[0])
            count = hi - lo
            pm = probs[:, :count, :]
            pred = pm.argmax(axis=2)
            member_err_counts += (
                pred != labs[None, :count]).sum(axis=1)
            for row in range(count):
                gi = int(idx[row])
                labels[gi] = int(labs[row])
                sum_probs[gi] = pm[:, row, :].sum(axis=0)
        if not sum_probs:
            raise ValueError(f"loader has no class-{klass} samples")
        ens_errs = sum(
            1 for gi, probs in sum_probs.items()
            if int(np.argmax(probs)) != labels[gi])
        result = {
            "n_samples": len(sum_probs),
            "member_err_pt": [100.0 * int(c) / len(sum_probs)
                              for c in member_err_counts],
            "ensemble_err_pt": 100.0 * ens_errs / len(sum_probs),
        }
        self.info("stacked ensemble eval: %s", result)
        return result

    def evaluate(self, klass: int = VALID) -> dict:
        """Aggregate evaluation on ``klass`` minibatches.

        Returns per-member error percentages and the ensemble's
        (averaged class probabilities → argmax).  Multi-process: every
        process contributes its local members' probability sums and
        receives the identical merged result."""
        if klass == TRAIN:
            raise ValueError("evaluate on VALID or TEST, not TRAIN")
        if self.backend == "mesh":
            return self._evaluate_stacked(klass)
        pidx, pcount = process_info()
        trained = self.workflows if pcount == 1 else self.member_stats
        if not trained:
            raise RuntimeError("train() first")
        sum_probs: dict[int, np.ndarray] = {}
        labels: dict[int, int] = {}
        member_errs: list[float] = []
        # In multi-process mode a LOCAL failure must not raise before
        # the collectives — a lone raise would leave the peers blocked
        # in _evaluate_merge's broadcasts.  Record it; the merge
        # gathers the failure flags so every process raises together.
        local_error: str | None = None
        for wf in self.workflows:
            outputs, wf_labels = class_forward_pass(wf, klass)
            if not outputs:
                local_error = f"loader has no class-{klass} samples"
                break
            errs = 0
            for gi, probs in outputs.items():
                if int(np.argmax(probs)) != wf_labels[gi]:
                    errs += 1
                if gi in sum_probs:
                    sum_probs[gi] = sum_probs[gi] + probs
                else:
                    sum_probs[gi] = probs.astype(np.float64)
                # per-index labels must agree across members — a
                # seed-dependent dataset split (e.g. a loader carving
                # validation via the global PRNG) would silently
                # average probabilities of unrelated samples
                if labels.setdefault(gi, wf_labels[gi]) != wf_labels[gi]:
                    local_error = self._SPLIT_DISAGREES
                    break
            if local_error:
                break
            member_errs.append(100.0 * errs / len(outputs))
        if pcount > 1:
            return self._evaluate_merge(sum_probs, labels, member_errs,
                                        local_error, pidx, pcount)
        if local_error:
            raise ValueError(local_error)
        ens_errs = sum(
            1 for gi, probs in sum_probs.items()
            if int(np.argmax(probs)) != labels[gi])
        result = {
            "n_samples": len(sum_probs),
            "member_err_pt": member_errs,
            "ensemble_err_pt": 100.0 * ens_errs / len(sum_probs),
        }
        self.info("ensemble eval: %s", result)
        return result

    def _evaluate_merge(self, sum_probs: dict, labels: dict,
                        member_errs: list, local_error: "str | None",
                        pidx: int, pcount: int) -> dict:
        """Lockstep cross-process merge of the aggregate pass.

        Process 0 always trained member 0 (round-robin), so its index
        set defines the reference sample order; a process with no
        members (``n_models < process_count``) contributes zeros.
        Failures (a local one recorded by ``evaluate``, or a
        cross-process split disagreement) are gathered as FLAGS before
        raising, so every process raises together — a lone raise would
        deadlock the peers in the later collectives."""
        if allgather_sum(np.array([1.0 if local_error else 0.0]))[0] > 0:
            raise ValueError(local_error or
                             "a peer process failed the ensemble "
                             "aggregate pass")
        have = bool(sum_probs)
        idxs = np.array(sorted(sum_probs), np.int64) if have \
            else np.zeros(0, np.int64)
        meta = broadcast_from_zero(
            np.array([len(idxs),
                      len(next(iter(sum_probs.values()))) if have
                      else 0], np.int64))
        n_samples, n_classes = int(meta[0]), int(meta[1])
        ref_idx = broadcast_from_zero(
            idxs if pidx == 0 else np.zeros(n_samples, np.int64))
        ref_lab = broadcast_from_zero(
            np.array([labels[g] for g in idxs], np.int64)
            if pidx == 0 else np.zeros(n_samples, np.int64))
        mismatch = 0.0
        if have:
            local_lab = np.array([labels[g] for g in idxs], np.int64)
            if (not np.array_equal(idxs, ref_idx)
                    or not np.array_equal(local_lab, ref_lab)):
                mismatch = 1.0
        if allgather_sum(np.array([mismatch]))[0] > 0:
            raise ValueError(self._SPLIT_DISAGREES)
        partial = (np.stack([sum_probs[g] for g in ref_idx]) if have
                   else np.zeros((n_samples, n_classes)))
        total = allgather_sum(partial)
        merged_errs = merge_round_robin(member_errs, pidx, pcount,
                                        self.n_models)
        ens_errs = int((total.argmax(axis=1) != ref_lab).sum())
        result = {
            "n_samples": n_samples,
            "member_err_pt": [float(e) for e in merged_errs],
            "ensemble_err_pt": 100.0 * ens_errs / n_samples,
        }
        self.info("ensemble eval (merged over %d processes): %s",
                  pcount, result)
        return result
