"""Ensemble: train N model instances, aggregate their evaluation.

Rebuilds the reference's ``veles/ensemble/`` — N independent trainings
of the same workflow (different seeds), followed by an aggregated
evaluation pass (averaged class probabilities) that is typically
better than any single member.

The reference trained members as separate cluster jobs; here members
train sequentially on the local device (process-level scale-out mirrors
genetics: with ``jax.distributed``, process *p* trains members
``p::process_count``).  The aggregated pass replays each member's
validation/test minibatches through its compiled hot chain — backward
units stay gated off on non-train classes, dropout runs in eval mode —
and averages the softmax outputs per sample.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from znicz_tpu.loader.base import TRAIN, VALID
from znicz_tpu.utils.logger import Logger


def class_forward_pass(wf, klass: int) -> tuple[dict, dict]:
    """Replay every minibatch of ``klass`` through the (trained)
    workflow's hot chain; returns ``(outputs, labels)`` keyed by
    global sample index.  Training side effects are impossible for
    non-train classes: the GD units' ``gate_skip`` follows
    ``minibatch_class != TRAIN`` and stochastic units track
    ``forward_mode``."""
    loader = wf.loader
    outputs: dict[int, np.ndarray] = {}
    labels: dict[int, int] = {}
    out_vec = wf.forwards[-1].output
    for cursor, (cls, _lo, _hi) in enumerate(loader._schedule):
        if cls != klass:
            continue
        loader._cursor = cursor
        loader.run()
        if wf._region_unit is not None:
            wf._region_unit.run()
        else:
            for unit in wf.forwards:
                unit.run()
        out_vec.map_read()
        loader.minibatch_labels.map_read()
        idx = loader._host_indices
        for row in range(loader.minibatch_size):
            gi = int(idx[row])
            outputs[gi] = np.array(out_vec.mem[row], copy=True)
            labels[gi] = int(loader.minibatch_labels.mem[row])
    return outputs, labels


class Ensemble(Logger):
    """Train ``n_models`` instances of a sample and vote.

    Parameters
    ----------
    build_fn:
        ``callable(**overrides) -> StandardWorkflow`` (a sample's
        ``build``); the loss must be classification (softmax head).
    n_models / base_seed:
        member *i* trains with PRNG seed ``base_seed + i`` — different
        weight init and shuffle streams, same dataset split.
    """

    def __init__(self, build_fn: Callable, n_models: int = 3,
                 base_seed: int = 1234,
                 device_factory: Callable | None = None,
                 train_kwargs: dict | None = None) -> None:
        super().__init__()
        if n_models < 1:
            raise ValueError("n_models must be >= 1")
        self.build_fn = build_fn
        self.n_models = int(n_models)
        self.base_seed = int(base_seed)
        self.device_factory = device_factory
        self.train_kwargs = dict(train_kwargs or {})
        self.workflows: list = []
        self.member_stats: list[dict] = []

    # ------------------------------------------------------------------
    def train(self) -> "Ensemble":
        from znicz_tpu.backends import Device
        from znicz_tpu.utils import prng
        self.workflows = []
        self.member_stats = []
        for i in range(self.n_models):
            prng.seed_all(self.base_seed + i)
            wf = self.build_fn(**self.train_kwargs)
            device = (self.device_factory() if self.device_factory
                      else Device.create())
            wf.initialize(device=device)
            wf.run()
            d = wf.decision
            stats = {"seed": self.base_seed + i}
            if getattr(d, "min_validation_n_err_pt", None) is not None:
                stats["validation_err_pt"] = \
                    float(d.min_validation_n_err_pt)
            self.info("member %d/%d trained: %s", i + 1,
                      self.n_models, stats)
            self.workflows.append(wf)
            self.member_stats.append(stats)
        return self

    # ------------------------------------------------------------------
    def evaluate(self, klass: int = VALID) -> dict:
        """Aggregate evaluation on ``klass`` minibatches.

        Returns per-member error percentages and the ensemble's
        (averaged class probabilities → argmax)."""
        if not self.workflows:
            raise RuntimeError("train() first")
        if klass == TRAIN:
            raise ValueError("evaluate on VALID or TEST, not TRAIN")
        sum_probs: dict[int, np.ndarray] = {}
        labels: dict[int, int] = {}
        member_errs: list[float] = []
        for wf in self.workflows:
            outputs, wf_labels = class_forward_pass(wf, klass)
            if not outputs:
                raise ValueError(f"loader has no class-{klass} samples")
            errs = 0
            for gi, probs in outputs.items():
                if int(np.argmax(probs)) != wf_labels[gi]:
                    errs += 1
                if gi in sum_probs:
                    sum_probs[gi] = sum_probs[gi] + probs
                else:
                    sum_probs[gi] = probs.astype(np.float64)
                # per-index labels must agree across members — a
                # seed-dependent dataset split (e.g. a loader carving
                # validation via the global PRNG) would silently
                # average probabilities of unrelated samples
                if labels.setdefault(gi, wf_labels[gi]) != wf_labels[gi]:
                    raise ValueError(
                        "members disagree on sample labels: the "
                        "loader's class split depends on the PRNG "
                        "seed; give the loader a fixed split (or its "
                        "own prng_name) so every member sees the same "
                        "sample at the same global index")
            member_errs.append(100.0 * errs / len(outputs))
        ens_errs = sum(
            1 for gi, probs in sum_probs.items()
            if int(np.argmax(probs)) != labels[gi])
        result = {
            "n_samples": len(sum_probs),
            "member_err_pt": member_errs,
            "ensemble_err_pt": 100.0 * ens_errs / len(sum_probs),
        }
        self.info("ensemble eval: %s", result)
        return result
