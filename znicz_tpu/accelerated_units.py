"""Accelerated units and the jit-region engine.

Rebuilds the reference's ``AcceleratedUnit`` (reference:
``veles/accelerated_units.py`` — base class whose ``run`` dispatches to
``ocl_run``/``cuda_run``/``numpy_run`` and which builds/caches device
kernels), redesigned for XLA's compilation model:

- every compute unit provides ``numpy_run`` (host oracle — the spec)
  and ``xla_run`` (pure jax ops over its Vectors' ``devmem``);
- there is **no kernel-build machinery** — instead, the hot
  per-minibatch chain of units is compiled into a **jit region**: one
  ``jax.jit``'ed, donated-buffer XLA program produced by tracing each
  member unit's ``xla_run`` in control order.  This replaces the
  reference's per-unit Python dispatch around kernel launches
  (SURVEY.md §3.1 "the whole minibatch step must be ONE jitted
  function").

Unit contract for region membership:

- ``xla_run`` must be *pure device compute*: read ``vector.devmem``,
  write ``vector.devmem``, no ``map_*`` calls, no host branches on
  data values (host branches on *static* flags are fine if the flag is
  part of :meth:`AcceleratedUnit.region_key` — the region recompiles
  per key, e.g. dropout train vs test);
- per-step host bookkeeping goes in ``host_run`` (runs outside the
  region, before it fires);
- random state lives in a Vector of PRNG key data so it is a region
  leaf (see :meth:`AcceleratedUnit.init_rng`).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

import jax

from znicz_tpu.backends import Device, NumpyDevice
from znicz_tpu.memory import Vector
from znicz_tpu.observe import metrics as _metrics
from znicz_tpu.observe import tracing as _tracing
from znicz_tpu.units import Unit
from znicz_tpu.utils import prng
from znicz_tpu.utils.logger import Logger
from znicz_tpu.workflow import Workflow

#: the gradient-accumulation phase active for the CURRENT region trace
#: (round 20).  ``None`` outside accumulation (the historical single
#: fused-batch step); ``("accum", M)`` while tracing a microbatch body
#: whose gradients must be summed into the micro-accumulation buffers
#: without touching parameters; ``("apply", M)`` while tracing the
#: final microbatch, whose body folds the buffered sum into one
#: optimizer step.  Tracing is synchronous and single-threaded inside
#: ``JitRegion.build_callable``, so a module global (set/reset in the
#: traced function body, i.e. AT TRACE TIME) is sufficient — the value
#: never needs to survive into the compiled program, it only steers
#: which ops get traced (``GradientDescentBase._apply_param_xla``,
#: the evaluator's flag seeding, the anomaly guard's commit).
_ACCUM_PHASE: "tuple[str, int] | None" = None


def current_accum_phase() -> "tuple[str, int] | None":
    """The accumulation phase of the region body currently being
    traced (``None`` / ``("accum", M)`` / ``("apply", M)``)."""
    return _ACCUM_PHASE


class AcceleratedUnit(Unit):
    """Base class for compute units with oracle + XLA paths."""

    def __init__(self, workflow, name: str | None = None, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.device: Device | None = None
        self._in_region = False
        self.rng_state = Vector(name=f"{self.name}.rng_state")

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def initialize(self, device: Device | None = None, **kwargs) -> None:
        if device is None and isinstance(self.workflow, AcceleratedWorkflow):
            device = self.workflow.device
        if device is None:
            raise ValueError(f"{self}: no device supplied")
        self.device = device
        super().initialize(**kwargs)

    @property
    def compute_dtype(self) -> np.dtype:
        assert self.device is not None
        return self.device.compute_dtype

    @property
    def mxu_dtype(self):
        """Matmul/conv INPUT dtype for the XLA path, from
        ``root.common.precision_type``: ``jnp.bfloat16`` in bf16 mode
        (native MXU dtype — inputs cast down, accumulation and
        parameters stay float32: standard TPU mixed precision), else
        None (full-precision math)."""
        if self.device is not None \
                and self.device.compute_dtype == np.dtype("bfloat16"):
            import jax.numpy as jnp
            return jnp.bfloat16
        return None

    @property
    def act_store_dtype(self) -> np.dtype:
        """STORAGE dtype for activation / error tensors (the big
        batch-major intermediates): ``bfloat16`` in bf16 mode on XLA
        devices, else ``float32``.

        Profiling the AlexNet step (profiles/r03_b256_xla_lrn) showed
        ~60% of device time in bandwidth-bound work over f32
        activations; storing them bf16 halves that traffic.  Math
        still runs in f32 where it matters (GEMM/conv accumulation via
        ``preferred_element_type``, LRN denominators, evaluator loss)
        — this is storage precision, not compute precision.  Params,
        weight gradients, and loss accumulators stay f32.  Opt out:
        ``root.common.engine.bf16_activations = False``.  The numpy
        oracle path (host-only devices) always stores f32.
        """
        from znicz_tpu.utils.config import root
        assert self.device is not None, \
            f"{self}: act_store_dtype before initialize resolved a device"
        if (not self.device.is_host_only
                and self.device.compute_dtype == np.dtype("bfloat16")
                and bool(root.common.engine.get("bf16_activations",
                                                True))):
            import jax.numpy as jnp
            return np.dtype(jnp.bfloat16)
        return np.dtype(np.float32)

    @property
    def fp8_dtype(self):
        """Matmul INPUT dtype under the ``engine.fp8_matmul`` lever
        (round 21, default OFF): ``jnp.float8_e4m3fn`` when the lever
        is on and this jax build carries the dtype, else ``None``.
        Accumulation stays f32 (``preferred_element_type``) and
        parameters stay f32 — fp8 is input precision only, the same
        convergence-gated shape as ``bf16_grad_comms`` (the lever
        stays off until the QUANT_BENCH fp8 A/B and the FP8_TPU chip
        arm clear it)."""
        from znicz_tpu.utils.config import root
        if not bool(root.common.engine.get("fp8_matmul", False)):
            return None
        import jax.numpy as jnp
        return getattr(jnp, "float8_e4m3fn", None)

    def mxu_dot(self, xp, a, b):
        """``a @ b`` routed through the MXU at the configured input
        precision (f32 accumulation); numpy path untouched (oracle).
        Precision ladder: fp8 (``engine.fp8_matmul``) over bf16
        (``precision_type``) over f32."""
        import jax.numpy as jnp
        if xp is jnp:
            dt = self.fp8_dtype or self.mxu_dtype
            if dt is not None:
                return jnp.dot(a.astype(dt), b.astype(dt),
                               preferred_element_type=jnp.float32)
        return xp.dot(a, b)

    def init_vectors(self, *vectors: Vector) -> None:
        """Attach vectors to the device (reference:
        ``AcceleratedUnit.init_vectors``).

        On XLA devices every Vector first BINDS against the owning
        workflow's partition-rule table (``parallel.partition``): its
        canonical ``unit.name/slot`` path resolves to a PartitionSpec
        (first match wins, unmatched = hard error) and the legacy
        slot attributes are stamped FROM that resolution, so
        ``Device.sharding_for`` becomes a table lookup."""
        assert self.device is not None
        from znicz_tpu.parallel import partition
        table = (None if self.device.is_host_only
                 else partition.table_for(self.workflow))
        framework_unit = type(self).__module__.startswith("znicz_tpu")
        for vec in vectors:
            if vec:
                if table is not None:
                    try:
                        partition.bind(table, vec, self.name,
                                       self.device)
                    except partition.UnmatchedLeafError:
                        # the hard-error contract covers the
                        # framework's slot vocabulary; user/test units
                        # with ad-hoc names keep the legacy attribute
                        # path unless they declare rules
                        if framework_unit:
                            raise
                vec.initialize(self.device)

    def partition_leaf(self, slot: str, placement, vec: Vector | None = None,
                       logical_shape=None):
        """Declare this unit's ``slot`` placement in the workflow's
        partition table (an exact-path override rule).  Under
        ``engine.partition_rules=False`` the same decision is applied
        as the legacy slot attributes instead — one call site, two
        arms, pinned bitwise-equal by the golden-table test."""
        from znicz_tpu.parallel import partition
        vec = vec if vec is not None else getattr(self, slot)
        return partition.declare(self, vec, placement, slot=slot,
                                 logical_shape=logical_shape)

    def unmap_vectors(self, *vectors: Vector) -> None:
        for vec in vectors:
            if vec:
                vec.unmap()

    def init_rng(self, gen: "prng.RandomGenerator | None" = None) -> None:
        """Give this unit a device-resident PRNG key chain (a region
        leaf, so stochastic units stay inside jit regions)."""
        gen = gen or prng.get()
        key = gen.key()
        self.rng_state.reset(np.asarray(jax.random.key_data(key)))
        self.init_vectors(self.rng_state)

    def take_key(self):
        """Inside ``xla_run``: split a fresh subkey, advancing the
        device-side chain functionally."""
        key = jax.random.wrap_key_data(self.rng_state.devmem)
        key, sub = jax.random.split(key)
        self.rng_state.devmem = jax.random.key_data(key)
        return sub

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------
    def host_run(self) -> None:
        """Per-step host bookkeeping (runs even when the device compute
        is owned by a jit region)."""

    def run(self) -> None:
        self.host_run()
        if self._in_region:
            return  # device compute happens inside the region program
        if self.device is None or self.device.is_host_only:
            self.numpy_run()
        else:
            self.xla_run()

    def numpy_run(self) -> None:
        raise NotImplementedError(f"{type(self).__name__}.numpy_run")

    def xla_run(self) -> None:
        raise NotImplementedError(f"{type(self).__name__}.xla_run")

    # ------------------------------------------------------------------
    # region protocol
    # ------------------------------------------------------------------
    def region_vectors(self) -> list[Vector]:
        """Vectors this unit touches in ``xla_run`` — region leaves.

        Default: every Vector in ``__dict__`` (own state) plus every
        linked attribute resolving to a Vector (inputs from other
        units).  Deterministic order by attribute name.
        """
        found: dict[int, Vector] = {}
        for name in sorted(self.__dict__):
            val = self.__dict__[name]
            if isinstance(val, Vector) and val:
                found.setdefault(id(val), val)
        for name in sorted(self._linked_attrs):
            val = self._linked_attrs[name].get()
            if isinstance(val, Vector) and val:
                found.setdefault(id(val), val)
        return list(found.values())

    def region_key(self) -> tuple:
        """Hashable static flags; region recompiles when they change."""
        return ()


class JitRegion(Logger):
    """Compiles an ordered chain of AcceleratedUnits into one donated
    XLA program per static-key combination."""

    def __init__(self, name: str, units: Sequence[AcceleratedUnit],
                 device: Device) -> None:
        super().__init__()
        self.name = name
        self.units = list(units)
        self.device = device
        for unit in self.units:
            unit._in_region = True
        self._vectors: list[Vector] | None = None
        self._cache: dict[tuple, object] = {}

    def _collect_vectors(self) -> list[Vector]:
        seen: dict[int, Vector] = {}
        for unit in self.units:
            for vec in unit.region_vectors():
                seen.setdefault(id(vec), vec)
        return list(seen.values())

    @property
    def debug_checks(self) -> bool:
        """``root.common.engine.debug_checks``: compile the region
        through ``checkify`` (NaN / inf / div-by-zero / OOB-index
        checks on every primitive) and raise a located error from
        ``run`` — the debug-mode equivalent of the Vector state
        machine for *inside*-the-program faults (SURVEY.md §5.2).
        Costs a host sync per step and disables buffer donation; for
        debugging, not production."""
        from znicz_tpu.utils.config import root
        return bool(root.common.engine.get("debug_checks", False))

    def run(self) -> None:
        if self._vectors is None:
            self._vectors = self._collect_vectors()
        vectors = self._vectors
        for vec in vectors:
            vec.unmap()
        skips = tuple(bool(unit.gate_skip) for unit in self.units)
        checks = self.debug_checks
        key = tuple(unit.region_key() for unit in self.units) \
            + (skips, checks)
        fn = self._cache.get(key)
        leaves = [vec._devmem for vec in vectors]
        if fn is None:
            self.debug("region '%s': compiling for key %s "
                       "(%d units, %d leaves)", self.name, key,
                       len(self.units), len(vectors))
            if not checks:  # checkify programs are never persisted
                fn = self._persisted_program(
                    ("step",) + key, self.build_callable(skips),
                    leaves, donate=True)
            if fn is not None:
                self._cache[key] = fn
                out = fn(*leaves)
            else:
                # compile/retrace counter: the steady-state retrace
                # guard asserts this stays flat once every variant is
                # warmed.  jit compiles lazily, so the first dispatch
                # rides inside the compile span — that is where the
                # trace+compile cost actually lands.
                _metrics.xla_compiles(f"region:{self.name}").inc()
                with _tracing.TRACER.span(f"compile:{self.name}",
                                          cat="compile"):
                    fn = self._cache[key] = self._build(skips, checks)
                    if checks:
                        err, out = fn(*leaves)
                        err.throw()
                    else:
                        out = fn(*leaves)
        elif checks:
            err, out = fn(*leaves)
            err.throw()  # located NaN/inf/OOB report, e.g. "nan
            #              generated by primitive: log" + traceback
        else:
            out = fn(*leaves)
        _metrics.region_steps(self.name).inc()
        for vec, leaf in zip(vectors, out):
            vec.devmem = leaf

    def build_callable(self, skips: tuple[bool, ...],
                       accum_phase: "tuple[str, int] | None" = None):
        """The pure (un-jitted) region function ``leaves -> leaves``,
        wrapping member ``xla_run``s in the Vector tracing harness.
        Single home of the tracing invariant — external jittable entry
        points (``__graft_entry__.entry``) reuse it instead of
        re-threading ``Vector._tracing`` by hand.

        ``accum_phase`` (round 20) selects the gradient-accumulation
        variant of the body: ``("accum", M)`` traces a
        buffer-the-gradients microbatch, ``("apply", M)`` the final
        microbatch that commits one optimizer step from the buffered
        sum.  The phase is installed while the body traces (see
        :data:`_ACCUM_PHASE`), so phase-aware units branch statically
        — each phase is its own compiled program variant."""
        if self._vectors is None:
            self._vectors = self._collect_vectors()
        vectors = self._vectors
        units = self.units
        precision = getattr(self.device, "matmul_precision", "default")
        # telemetry: trace each member under jax.named_scope so the
        # compiled program's op names (and thus trace_top's fusion
        # rows) carry unit attribution; resolved at trace time so a
        # cached program keeps whatever naming it compiled with
        named = _metrics.enabled()

        def fn(*leaves):
            global _ACCUM_PHASE
            prev_phase = _ACCUM_PHASE
            _ACCUM_PHASE = accum_phase
            for vec, leaf in zip(vectors, leaves):
                vec._tracing = True
                vec._devmem = leaf
            try:
                with jax.default_matmul_precision(precision):
                    for unit, skip in zip(units, skips):
                        if skip:
                            continue
                        if named:
                            with jax.named_scope(unit.name):
                                unit.xla_run()
                        else:
                            unit.xla_run()
                return tuple(vec._devmem for vec in vectors)
            finally:
                _ACCUM_PHASE = prev_phase
                for vec in vectors:
                    vec._tracing = False
                for unit in units:
                    # drop any intra-trace pullback stash a forward
                    # left for a (possibly gate-skipped) GD pair —
                    # escaped tracers must not outlive the trace
                    if getattr(unit, "_traced_vjp", None) is not None:
                        unit._traced_vjp = None

        return fn

    def run_chunk(self, n_steps: int) -> None:
        """Execute ``n_steps`` region steps in ONE dispatch:
        ``lax.scan`` over the region body (the idiomatic JAX training
        loop).  Amortizes per-step dispatch/RPC cost — the difference
        between one host round trip per minibatch and one per chunk.

        Caller contract: every per-step input the device program needs
        must be device-resident and self-advancing across the chunk —
        i.e. the loader runs a device schedule
        (``FullBatchLoader.device_schedule``), PRNG chains / LR state /
        error accumulators are already region leaves — and the static
        key (gate skips, unit modes) must not change within the chunk.
        The caller advances host-side bookkeeping (epoch counters)
        separately; ``StandardWorkflow.run_chunked`` does both.
        """
        if n_steps == 1:
            return self.run()
        if self._vectors is None:
            self._vectors = self._collect_vectors()
        vectors = self._vectors
        for vec in vectors:
            vec.unmap()
        skips = tuple(bool(unit.gate_skip) for unit in self.units)
        if self.debug_checks:
            # checkify's error pytree doesn't thread through this scan
            # harness; debug runs take the per-step path
            for _ in range(n_steps):
                self.run()
            return
        key = tuple(unit.region_key() for unit in self.units) \
            + (skips, "chunk", n_steps)
        fn = self._cache.get(key)
        leaves = [vec._devmem for vec in vectors]
        if fn is None:
            self.debug("region '%s': compiling %d-step scan chunk",
                       self.name, n_steps)
            body, invariant = self._analyzed_body(
                self.build_callable(skips), leaves)

            def chunk_fn(*leaves):
                scanned = self._scan_body(body, invariant, leaves,
                                          n_steps)
                return tuple(scanned)

            fn = self._persisted_program(("chunk", n_steps) + key,
                                         chunk_fn, leaves, donate=True)
            if fn is not None:
                self._cache[key] = fn
                out = fn(*leaves)
            else:
                _metrics.xla_compiles(f"region:{self.name}").inc()
                fn = self._cache[key] = jax.jit(
                    chunk_fn,
                    donate_argnums=tuple(range(len(vectors))))
                with _tracing.TRACER.span(f"compile:{self.name}",
                                          cat="compile",
                                          chunk=n_steps):
                    out = fn(*leaves)  # first dispatch = trace+compile
        else:
            # chunked dispatches bypass RegionUnit._fire (bench /
            # run_chunked drive this directly), so the dispatch gets
            # its own span — one per chunk, not per step
            with _tracing.TRACER.span(f"chunk:{self.name}",
                                      cat="region", steps=n_steps):
                out = fn(*leaves)
        _metrics.region_steps(self.name).inc(n_steps)
        for vec, leaf in zip(vectors, out):
            vec.devmem = leaf

    # -- shared scan machinery (run_chunk / run_accum) ------------------
    def _analyzed_body(self, body, leaves):
        """Trace ``body`` once and split its leaves into loop-carried
        vs loop-invariant.

        Loop-invariant analysis: leaves the body never writes
        (datasets, schedule tables) must NOT ride a scan carry — XLA
        copies carries it cannot alias across iterations, which for a
        device-resident dataset means re-copying the whole table every
        step (measured 3.1 ms/step on a 1 GB table — PERF.md round 5).
        A jaxpr outvar that IS the corresponding invar was passed
        through untouched; such leaves become closed-over scan-body
        inputs instead.  Returns ``(body_fn, invariant)`` where the
        probe jaxpr IS the step body — the region is traced once, not
        once per analysis + once per jit."""
        jaxpr = jax.make_jaxpr(body)(*leaves)
        invariant = tuple(
            ov is iv for ov, iv in zip(jaxpr.jaxpr.outvars,
                                       jaxpr.jaxpr.invars))
        from jax.extend import core as jex_core
        return jex_core.jaxpr_as_fun(jaxpr), invariant

    @staticmethod
    def _scan_body(body, invariant, leaves, length: int) -> list:
        """``lax.scan`` the analyzed ``body`` ``length`` times over
        ``leaves``: invariant leaves close over the scan, the rest ride
        the carry; returns the full merged leaf list."""
        ro = [l for l, inv in zip(leaves, invariant) if inv]

        def step(carry, _):
            full, it_c, it_r = [], iter(carry), iter(ro)
            for inv in invariant:
                full.append(next(it_r) if inv else next(it_c))
            out = body(*full)
            return tuple(o for o, inv in zip(out, invariant)
                         if not inv), None

        carry0 = tuple(l for l, inv in zip(leaves, invariant)
                       if not inv)
        out_rw, _ = jax.lax.scan(step, carry0, xs=None, length=length)
        merged, it_w, it_r = [], iter(out_rw), iter(ro)
        for inv in invariant:
            merged.append(next(it_r) if inv else next(it_w))
        return merged

    def run_accum(self, n_micro: int) -> None:
        """One ACCUMULATED optimizer step in ONE dispatch (round 20):
        ``n_micro`` consecutive microbatches from the device-resident
        loader schedule run accumulate-then-apply —

        - microbatches ``0 .. n_micro-2`` trace in ``("accum", M)``
          phase: forwards + backward gradients only, each weighted
          GD summing its gradient into a float32 micro-accumulation
          buffer (``acc_micro_*``) while parameters, momentum and the
          anomaly/SDC state stay untouched;
        - microbatch ``n_micro-1`` traces in ``("apply", M)`` phase:
          its gradient joins the buffered sum, the mean
          ``(Σ grads)/M`` flows through the UNCHANGED update path
          (ZeRO-1, bf16 opt-state, anomaly gate, SDC fingerprints all
          compose), and the buffers are zeroed for the next step.

        The accum microbatches ride a ``lax.scan`` with the same
        loop-invariance analysis as :meth:`run_chunk` (weights,
        momentum and dataset tables close over the scan — they are
        read-only in accum phase), so the whole accumulated step is
        one donated-buffer program: per-chip batch/activation memory
        stays at MICRObatch scale while the effective (optimizer)
        batch is ``n_micro`` times larger.

        Caller contract matches :meth:`run_chunk`, plus: all
        ``n_micro`` schedule entries must be same-class (TRAIN) FULL
        minibatches — ``StandardWorkflow.run_accumulated`` validates
        divisibility and advances the host-side loader mirror.
        """
        if n_micro == 1:
            return self.run()
        if self._vectors is None:
            self._vectors = self._collect_vectors()
        vectors = self._vectors
        for vec in vectors:
            vec.unmap()
        skips = tuple(bool(unit.gate_skip) for unit in self.units)
        if self.debug_checks:
            raise NotImplementedError(
                "engine.debug_checks does not compose with "
                "run_accum (checkify cannot thread the accumulation "
                "scan); disable one of them")
        key = tuple(unit.region_key() for unit in self.units) \
            + (skips, "accum", n_micro)
        fn = self._cache.get(key)
        leaves = [vec._devmem for vec in vectors]
        if fn is None:
            self.debug("region '%s': compiling %d-microbatch "
                       "accumulate-then-apply step", self.name, n_micro)
            accum_body, invariant = self._analyzed_body(
                self.build_callable(skips,
                                    accum_phase=("accum", n_micro)),
                leaves)
            apply_body = self.build_callable(
                skips, accum_phase=("apply", n_micro))

            def accum_fn(*leaves):
                merged = self._scan_body(accum_body, invariant, leaves,
                                         n_micro - 1)
                return apply_body(*merged)

            # the persisted key hashes the jaxpr of the FULL composed
            # accum+apply function — the accum body alone is blind to
            # apply-only constants (lr, momentum), which would let a
            # wrong optimizer step load
            fn = self._persisted_program(("accum", n_micro) + key,
                                         accum_fn, leaves, donate=True)
            if fn is not None:
                self._cache[key] = fn
                out = fn(*leaves)
            else:
                _metrics.xla_compiles(f"region:{self.name}").inc()
                fn = self._cache[key] = jax.jit(
                    accum_fn,
                    donate_argnums=tuple(range(len(vectors))))
                with _tracing.TRACER.span(f"compile:{self.name}",
                                          cat="compile", accum=n_micro):
                    out = fn(*leaves)  # first dispatch = trace+compile
        else:
            with _tracing.TRACER.span(f"accum:{self.name}",
                                      cat="region", micro=n_micro):
                out = fn(*leaves)
        _metrics.region_steps(self.name).inc(n_micro)
        for vec, leaf in zip(vectors, out):
            vec.devmem = leaf

    def run_undonated(self,
                      accum_phase: "tuple[str, int] | None" = None,
                      ) -> None:
        """One region step compiled WITHOUT buffer donation, optionally
        in a gradient-accumulation phase — the pipeline executor's
        dispatch primitive (``parallel.pipeline``): its per-microbatch
        activation store holds references to leaf buffers across
        dispatches, which donation would invalidate.  Programs cache
        alongside the donated variants under a distinct key."""
        if self._vectors is None:
            self._vectors = self._collect_vectors()
        vectors = self._vectors
        for vec in vectors:
            vec.unmap()
        skips = tuple(bool(unit.gate_skip) for unit in self.units)
        key = tuple(unit.region_key() for unit in self.units) \
            + (skips, "nodonate", accum_phase)
        fn = self._cache.get(key)
        leaves = [vec._devmem for vec in vectors]
        if fn is None:
            self.debug("region '%s': compiling undonated variant "
                       "(phase=%s)", self.name, accum_phase)
            fn = self._persisted_program(
                ("nodonate", accum_phase) + key,
                self.build_callable(skips, accum_phase=accum_phase),
                leaves, donate=False)
            if fn is not None:
                self._cache[key] = fn
                out = fn(*leaves)
            else:
                _metrics.xla_compiles(f"region:{self.name}").inc()
                with _tracing.TRACER.span(f"compile:{self.name}",
                                          cat="compile"):
                    fn = self._cache[key] = jax.jit(
                        self.build_callable(skips,
                                            accum_phase=accum_phase))
                    out = fn(*leaves)
        else:
            out = fn(*leaves)
        _metrics.region_steps(self.name).inc()
        for vec, leaf in zip(vectors, out):
            vec.devmem = leaf

    def _persisted_program(self, variant: tuple, fn, leaves,
                           donate: bool):
        """Resolve one region program variant through the persisted
        AOT cache (round 23): a deserialized executable on a hit, an
        eagerly-compiled-and-stored one on a miss.  Returns ``None``
        when the cache is disabled or the program is not safely
        keyable — the caller then takes the lazy ``jax.jit`` path,
        bit-identical to the pre-cache behavior.

        Region bodies bake unit hyperparameters into the trace, so
        the key is the **jaxpr hash** of the exact function being
        jitted (plus operand avals, donation, platform, build): the
        hit path still traces — that is what computes the key — but
        skips the XLA compile, which is where nearly all cold-start
        wall-clock lives.  A deserialized load never touches the
        ``region:<name>`` compile counter."""
        from znicz_tpu.serving import aot_cache as _aot
        cache = _aot.active_cache()
        if cache is None:
            return None
        site = f"region:{self.name}"
        key = _aot.jaxpr_key(fn, leaves,
                             extra=(site, donate) + tuple(variant))
        if key is None:
            return None
        donate_argnums = tuple(range(len(leaves))) if donate else ()
        prog = cache.get(key, site)
        if prog is not None:
            prog = _aot.guard_donated(prog, donate_argnums)
        else:
            _metrics.xla_compiles(site).inc()
            with _tracing.TRACER.span(f"compile:{self.name}",
                                      cat="compile"):
                prog = jax.jit(fn, donate_argnums=donate_argnums).lower(
                    *leaves).compile()
            cache.put(key, prog, site,
                      meta={"family": site,
                            "variant": [str(v) for v in variant[:2]]})
        return self._respecialize_guard(prog, fn, donate_argnums, site)

    @staticmethod
    def _respecialize_guard(prog, fn, donate_argnums, site):
        """An AOT ``Compiled`` is pinned to the exact input shardings
        and devices it was lowered with; lazy ``jax.jit`` transparently
        respecializes when they change between fires (on a mesh the
        compiler assigns shardings to a step's outputs, which become
        the next fire's inputs).  Dispatch the fixed program until it
        rejects its operands, then hand the variant to a lazy jit —
        bit-identical to the pre-cache behavior, and counted as a real
        compile."""
        fallback = None

        def call(*leaves):
            nonlocal fallback
            if fallback is None:
                try:
                    return prog(*leaves)
                except ValueError:
                    _metrics.xla_compiles(site).inc()
                    fallback = jax.jit(fn,
                                       donate_argnums=donate_argnums)
            return fallback(*leaves)

        return call

    def _build(self, skips: tuple[bool, ...], checks: bool = False):
        assert self._vectors is not None
        fn = self.build_callable(skips)
        if checks:
            from jax.experimental import checkify
            # no donation: checkify threads an error-state pytree
            # through the program, which breaks input→output aliasing
            return jax.jit(checkify.checkify(
                fn, errors=checkify.all_checks))
        return jax.jit(fn,
                       donate_argnums=tuple(range(len(self._vectors))))


class RegionUnit(AcceleratedUnit):
    """Workflow node that fires a :class:`JitRegion` as one step.

    Wiring pattern (see ``StandardWorkflow``): member units keep their
    ``host_run`` in the control graph *before* this unit; their device
    compute runs here, fused.
    """

    def __init__(self, workflow, units: Sequence[AcceleratedUnit],
                 name: str | None = None, **kwargs) -> None:
        super().__init__(workflow, name=name or "jit_region", **kwargs)
        self._member_units = list(units)
        self.region: JitRegion | None = None

    def initialize(self, device: Device | None = None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if isinstance(self.device, NumpyDevice):
            # Oracle backend: no compilation; members run themselves.
            for unit in self._member_units:
                unit._in_region = False
            self.gate_skip.value = True
            return
        for unit in self._member_units:
            if not unit.is_initialized:
                raise AttributeError(f"region member {unit} not initialized")
        assert self.device is not None
        self.region = JitRegion(self.name, self._member_units, self.device)

    def run(self) -> None:
        assert self.region is not None
        self.region.run()


class AcceleratedWorkflow(Workflow):
    """Workflow owning a device (reference:
    ``veles/accelerated_units.py`` ``AcceleratedWorkflow``)."""

    def __init__(self, workflow=None, name: str | None = None,
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.device: Device | None = None

    def initialize(self, device: Device | None = None, **kwargs) -> None:
        self.device = device if device is not None else Device.create()
        super().initialize(device=self.device, **kwargs)
