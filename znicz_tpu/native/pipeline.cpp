// znicz_tpu native image pipeline.
//
// TPU-native replacement for the reference's host-side image decode
// path (reference: veles/loader/image.py + PIL, which capped ImageNet
// throughput).  The north-star AlexNet config needs ~8k img/s of
// decoded 227x227x3 across a v4-32 (~1.9 GB/s decoded); Python/PIL
// cannot feed that, so decode + augment runs here: a C++ worker pool
// doing JPEG (libjpeg) / PNG (libpng) decode, bilinear resize, crop,
// horizontal flip and affine normalization straight into the loader's
// pinned minibatch buffer (float32 NHWC).
//
// Exposed as a plain C ABI consumed via ctypes
// (znicz_tpu/native/__init__.py) — one asynchronous batch in flight
// per pool, which is exactly the double-buffering the loader needs:
// submit batch N+1, let the TPU chew batch N, wait, swap.

#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <csetjmp>
#include <jpeglib.h>
#include <png.h>

namespace {

// ---------------------------------------------------------------- rng
// splitmix64: cheap, seedable per-sample stream for crop/flip draws
static inline uint64_t splitmix64(uint64_t &state) {
  uint64_t z = (state += 0x9e3779b97f4a7c15ULL);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

// ------------------------------------------------------------- decode
struct Image {
  std::vector<uint8_t> px;  // RGB interleaved
  int w = 0, h = 0;
};

struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jb;
};

static void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr *err = reinterpret_cast<JpegErr *>(cinfo->err);
  longjmp(err->jb, 1);
}

static bool decode_jpeg(const uint8_t *buf, size_t len, Image &out) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t *>(buf),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  out.w = static_cast<int>(cinfo.output_width);
  out.h = static_cast<int>(cinfo.output_height);
  out.px.resize(static_cast<size_t>(out.w) * out.h * 3);
  const size_t stride = static_cast<size_t>(out.w) * 3;
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t *row = out.px.data() + cinfo.output_scanline * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

static bool decode_png(const uint8_t *buf, size_t len, Image &out) {
  png_image img;
  std::memset(&img, 0, sizeof(img));
  img.version = PNG_IMAGE_VERSION;
  if (!png_image_begin_read_from_memory(&img, buf, len)) return false;
  img.format = PNG_FORMAT_RGB;
  out.w = static_cast<int>(img.width);
  out.h = static_cast<int>(img.height);
  out.px.resize(PNG_IMAGE_SIZE(img));
  if (!png_image_finish_read(&img, nullptr, out.px.data(), 0, nullptr)) {
    png_image_free(&img);
    return false;
  }
  return true;
}

static bool read_file(const char *path, std::vector<uint8_t> &buf) {
  FILE *f = std::fopen(path, "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  if (size <= 0) {
    std::fclose(f);
    return false;
  }
  std::fseek(f, 0, SEEK_SET);
  buf.resize(static_cast<size_t>(size));
  size_t got = std::fread(buf.data(), 1, buf.size(), f);
  std::fclose(f);
  return got == buf.size();
}

static bool decode_any(const char *path, Image &out) {
  std::vector<uint8_t> buf;
  if (!read_file(path, buf) || buf.size() < 8) return false;
  if (buf[0] == 0xFF && buf[1] == 0xD8) return decode_jpeg(buf.data(), buf.size(), out);
  if (buf[0] == 0x89 && buf[1] == 'P' && buf[2] == 'N' && buf[3] == 'G')
    return decode_png(buf.data(), buf.size(), out);
  return false;
}

// ------------------------------------------------------- resize (u8)
// Bilinear, pixel-center convention: src = (dst + 0.5) * scale - 0.5
// (matches numpy/OpenCV INTER_LINEAR and the Python oracle in tests).
static void resize_bilinear(const Image &src, int dst_w, int dst_h,
                            std::vector<uint8_t> &dst) {
  dst.resize(static_cast<size_t>(dst_w) * dst_h * 3);
  if (src.w == dst_w && src.h == dst_h) {
    std::memcpy(dst.data(), src.px.data(), dst.size());
    return;
  }
  const float sx = static_cast<float>(src.w) / dst_w;
  const float sy = static_cast<float>(src.h) / dst_h;
  for (int y = 0; y < dst_h; ++y) {
    float fy = (y + 0.5f) * sy - 0.5f;
    if (fy < 0) fy = 0;
    int y0 = static_cast<int>(fy);
    if (y0 > src.h - 2) y0 = src.h - 2;
    if (y0 < 0) y0 = 0;
    float wy = fy - y0;
    if (src.h == 1) { y0 = 0; wy = 0; }
    for (int x = 0; x < dst_w; ++x) {
      float fx = (x + 0.5f) * sx - 0.5f;
      if (fx < 0) fx = 0;
      int x0 = static_cast<int>(fx);
      if (x0 > src.w - 2) x0 = src.w - 2;
      if (x0 < 0) x0 = 0;
      float wx = fx - x0;
      if (src.w == 1) { x0 = 0; wx = 0; }
      const uint8_t *p00 = &src.px[(static_cast<size_t>(y0) * src.w + x0) * 3];
      const uint8_t *p01 = p00 + (src.w > 1 ? 3 : 0);
      const uint8_t *p10 = p00 + (src.h > 1 ? static_cast<size_t>(src.w) * 3 : 0);
      const uint8_t *p11 = p10 + (src.w > 1 ? 3 : 0);
      uint8_t *d = &dst[(static_cast<size_t>(y) * dst_w + x) * 3];
      for (int c = 0; c < 3; ++c) {
        float top = p00[c] + (p01[c] - p00[c]) * wx;
        float bot = p10[c] + (p11[c] - p10[c]) * wx;
        float v = top + (bot - top) * wy;
        d[c] = static_cast<uint8_t>(v + 0.5f);
      }
    }
  }
}

// ------------------------------------------------------------ job/pool
struct Job {
  const char *const *paths = nullptr;
  int n = 0;
  int resize_h = 0, resize_w = 0;  // 0 → keep decoded size
  int out_h = 0, out_w = 0;
  int channels = 3;    // 3 = RGB, 1 = luma
  int random_crop = 0; // 0 = center crop
  int random_flip = 0; // 1 = coin-flip horizontal mirror (train aug)
  float scale = 1.0f, bias = 0.0f;
  uint64_t seed = 0;
  void *out = nullptr;
  int out_u8 = 0;  // 1 → raw uint8 output, scale/bias ignored
                   // (device-side normalize: 4× smaller upload)
};

struct Pool {
  std::vector<std::thread> workers;
  std::mutex mu;
  std::condition_variable cv_work, cv_done;
  Job job;                      // stable while busy (claims imply busy)
  uint64_t generation = 0;      // bumps per submitted batch
  int next_i = 0;               // sample cursor (guarded by mu)
  std::atomic<int> done{0};
  std::atomic<int> failed{0};
  bool busy = false;
  bool stopping = false;

  explicit Pool(int n_threads) {
    if (n_threads <= 0) {
      n_threads = static_cast<int>(std::thread::hardware_concurrency());
      if (n_threads <= 0) n_threads = 1;
    }
    for (int i = 0; i < n_threads; ++i)
      workers.emplace_back([this] { worker_loop(); });
  }

  ~Pool() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stopping = true;
    }
    cv_work.notify_all();
    for (auto &t : workers) t.join();
  }

  void process(int i) {
    const Job &j = job;
    const size_t sample_sz =
        static_cast<size_t>(j.out_h) * j.out_w * j.channels;
    const size_t elem = j.out_u8 ? sizeof(uint8_t) : sizeof(float);
    uint8_t *dst_raw = static_cast<uint8_t *>(j.out) +
                       sample_sz * elem * i;
    float *dst = reinterpret_cast<float *>(dst_raw);
    uint8_t *dst8 = dst_raw;
    Image img;
    if (!decode_any(j.paths[i], img) || img.w < 1 || img.h < 1) {
      std::memset(dst_raw, 0, sample_sz * elem);
      failed.fetch_add(1);
      return;
    }
    std::vector<uint8_t> resized;
    const uint8_t *base;
    int bw, bh;
    int rh = j.resize_h > 0 ? j.resize_h : img.h;
    int rw = j.resize_w > 0 ? j.resize_w : img.w;
    if (rh != img.h || rw != img.w) {
      resize_bilinear(img, rw, rh, resized);
      base = resized.data();
      bw = rw;
      bh = rh;
    } else {
      base = img.px.data();
      bw = img.w;
      bh = img.h;
    }
    // crop window
    int max_dx = bw - j.out_w, max_dy = bh - j.out_h;
    if (max_dx < 0 || max_dy < 0) {  // undersized source: refuse
      std::memset(dst_raw, 0, sample_sz * elem);
      failed.fetch_add(1);
      return;
    }
    uint64_t rng = j.seed ^ (0x5851f42d4c957f2dULL * (i + 1));
    int dx, dy;
    bool flip = false;
    if (j.random_crop) {
      dx = max_dx ? static_cast<int>(splitmix64(rng) % (max_dx + 1)) : 0;
      dy = max_dy ? static_cast<int>(splitmix64(rng) % (max_dy + 1)) : 0;
    } else {
      dx = max_dx / 2;
      dy = max_dy / 2;
    }
    if (j.random_flip) flip = (splitmix64(rng) & 1) != 0;
    // crop + (flip) + store: normalized float32 NHWC, or raw uint8
    // NHWC when out_u8 (the normalize then happens on-device)
    for (int y = 0; y < j.out_h; ++y) {
      const uint8_t *row =
          base + (static_cast<size_t>(dy + y) * bw + dx) * 3;
      const size_t row_off = static_cast<size_t>(y) * j.out_w * j.channels;
      float *drow = dst + row_off;
      uint8_t *drow8 = dst8 + row_off;
      for (int x = 0; x < j.out_w; ++x) {
        int sxp = flip ? (j.out_w - 1 - x) : x;
        const uint8_t *p = row + static_cast<size_t>(sxp) * 3;
        if (j.channels == 1) {
          float luma = 0.299f * p[0] + 0.587f * p[1] + 0.114f * p[2];
          if (j.out_u8)
            // round-half-to-even to match the PIL oracle's np.rint —
            // truncating luma+0.5 disagreed by one level at .5 ties
            drow8[x] = static_cast<uint8_t>(std::lrintf(luma));
          else
            drow[x] = luma * j.scale + j.bias;
        } else if (j.out_u8) {
          uint8_t *d = drow8 + static_cast<size_t>(x) * 3;
          d[0] = p[0];
          d[1] = p[1];
          d[2] = p[2];
        } else {
          float *d = drow + static_cast<size_t>(x) * 3;
          d[0] = p[0] * j.scale + j.bias;
          d[1] = p[1] * j.scale + j.bias;
          d[2] = p[2] * j.scale + j.bias;
        }
      }
    }
  }

  void worker_loop() {
    uint64_t seen = 0;
    for (;;) {
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_work.wait(lk, [&] { return stopping || generation != seen; });
        if (stopping) return;
        seen = generation;
      }
      for (;;) {
        int i, n;
        {
          // claim under the lock: a straggler waking after its batch
          // completed (or after a NEW batch was submitted) must not
          // touch the possibly-mid-assignment job fields
          std::lock_guard<std::mutex> lk(mu);
          if (generation != seen || !busy || next_i >= job.n) break;
          i = next_i++;
          n = job.n;
        }
        // job is stable here: busy stays true until done == n, which
        // cannot happen before this claimed item is processed
        process(i);
        if (done.fetch_add(1) + 1 == n) {
          std::lock_guard<std::mutex> lk(mu);
          busy = false;
          cv_done.notify_all();
        }
      }
    }
  }

  int submit(const Job &j) {
    std::unique_lock<std::mutex> lk(mu);
    cv_done.wait(lk, [&] { return !busy; });  // one batch in flight
    job = j;
    next_i = 0;
    done.store(0);
    failed.store(0);
    if (j.n == 0) return 0;
    busy = true;
    ++generation;
    cv_work.notify_all();
    return 0;
  }

  int wait() {
    std::unique_lock<std::mutex> lk(mu);
    cv_done.wait(lk, [&] { return !busy; });
    return failed.load();
  }
};

}  // namespace

extern "C" {

void *zp_create(int n_threads) { return new Pool(n_threads); }

void zp_destroy(void *pool) { delete static_cast<Pool *>(pool); }

int zp_submit(void *pool, const char *const *paths, int n, int resize_h,
              int resize_w, int out_h, int out_w, int channels,
              int random_crop, int random_flip, float scale, float bias,
              uint64_t seed, void *out, int out_u8) {
  if (!pool || n < 0 || out_h <= 0 || out_w <= 0 ||
      (channels != 1 && channels != 3))
    return -1;
  Job j;
  j.paths = paths;
  j.n = n;
  j.resize_h = resize_h;
  j.resize_w = resize_w;
  j.out_h = out_h;
  j.out_w = out_w;
  j.channels = channels;
  j.random_crop = random_crop;
  j.random_flip = random_flip;
  j.scale = scale;
  j.bias = bias;
  j.seed = seed;
  j.out = out;
  j.out_u8 = out_u8;
  return static_cast<Pool *>(pool)->submit(j);
}

int zp_wait(void *pool) {
  if (!pool) return -1;
  return static_cast<Pool *>(pool)->wait();
}

}  // extern "C"
