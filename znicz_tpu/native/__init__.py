"""Native host-side components, built on demand with the system
toolchain.

The reference shipped native code beside Python (OpenCL/CUDA kernel
corpus, FFI runtimes — SURVEY.md §2.3); the TPU rebuild's device
compute is XLA/Pallas, so the native layer moves to where TPU runs
actually hurt: the **host input pipeline**.  :class:`ImagePipeline`
wraps ``pipeline.cpp`` — a libjpeg/libpng decode + augment worker pool
writing float32 NHWC minibatches — compiled at first use with g++ into
the user cache dir (no pip installs in this environment; ctypes, not
pybind11, per the same constraint).

Falls back cleanly: ``ImagePipeline.available()`` is False when the
toolchain or image libraries are missing, and the Python loaders use a
PIL path instead.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "pipeline.cpp")
_LIBS = ("-ljpeg", "-lpng")

_lock = threading.Lock()
_lib: "ctypes.CDLL | None" = None
_build_error: str | None = None


def _cache_dir() -> str:
    from znicz_tpu.utils.config import root
    d = os.path.join(str(root.common.dirs.cache), "native")
    os.makedirs(d, exist_ok=True)
    return d


def _build() -> ctypes.CDLL:
    """Compile (once per source+host fingerprint) and load the shared
    library.  The fingerprint includes the CPU feature flags because
    the build uses ``-march=native`` — a cache dir shared across
    heterogeneous hosts must not hand an AVX-512 binary to an older
    core."""
    h = hashlib.sha256()
    with open(_SRC, "rb") as f:
        h.update(f.read())
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    h.update(line.encode())
                    break
    except OSError:
        import platform
        h.update(platform.processor().encode())
    tag = h.hexdigest()[:16]
    so_path = os.path.join(_cache_dir(), f"znicz_pipeline_{tag}.so")
    if not os.path.exists(so_path):
        # per-process tmp: concurrent cold-cache builds (multi-process
        # jax, pytest-xdist) must not interleave into one file
        tmp = f"{so_path}.{os.getpid()}.tmp"
        cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC",
               "-std=c++17", _SRC, "-o", tmp, "-pthread", *_LIBS]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"native build failed: {' '.join(cmd)}\n{proc.stderr}")
        os.replace(tmp, so_path)
    lib = ctypes.CDLL(so_path)
    lib.zp_create.restype = ctypes.c_void_p
    lib.zp_create.argtypes = [ctypes.c_int]
    lib.zp_destroy.argtypes = [ctypes.c_void_p]
    lib.zp_submit.restype = ctypes.c_int
    lib.zp_submit.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_float, ctypes.c_float, ctypes.c_uint64,
        ctypes.c_void_p, ctypes.c_int]
    lib.zp_wait.restype = ctypes.c_int
    lib.zp_wait.argtypes = [ctypes.c_void_p]
    return lib


def _get_lib() -> "ctypes.CDLL | None":
    global _lib, _build_error
    with _lock:
        if _lib is None and _build_error is None:
            try:
                _lib = _build()
            except Exception as exc:  # toolchain/libs missing
                _build_error = str(exc)
        return _lib


class ImagePipeline:
    """Asynchronous decode+augment batches (one in flight per pool).

    Usage::

        pipe = ImagePipeline(n_threads=8)
        pipe.submit(paths, out, out_hw=(227, 227), resize_hw=(256, 256),
                    random_crop=True, random_flip=True,
                    scale=1/127.5, bias=-1.0, seed=step)
        ...                      # TPU works on the previous batch here
        n_failed = pipe.wait()   # out is now filled (failed → zeros)
    """

    def __init__(self, n_threads: int = 0) -> None:
        lib = _get_lib()
        if lib is None:
            raise RuntimeError(f"native pipeline unavailable: "
                               f"{_build_error}")
        self._lib = lib
        self._pool = lib.zp_create(int(n_threads))
        self._keepalive: tuple | None = None  # paths array + out buffer

    @staticmethod
    def available() -> bool:
        return _get_lib() is not None

    @staticmethod
    def build_error() -> str | None:
        _get_lib()
        return _build_error

    def submit(self, paths: list[str], out: np.ndarray,
               out_hw: tuple[int, int],
               resize_hw: tuple[int, int] | None = None,
               channels: int = 3, random_crop: bool = False,
               random_flip: bool = False, scale: float = 1.0,
               bias: float = 0.0, seed: int = 0) -> None:
        if self._pool is None:
            raise RuntimeError("pipeline destroyed")
        n = len(paths)
        out_h, out_w = out_hw
        expected = (n, out_h, out_w, channels) if channels == 3 \
            else (n, out_h, out_w)
        if out.dtype not in (np.float32, np.uint8) \
                or not out.flags.c_contiguous:
            raise ValueError("out must be C-contiguous float32 or uint8")
        out_u8 = out.dtype == np.uint8
        if out_u8 and (scale != 1.0 or bias != 0.0):
            raise ValueError("uint8 output is raw pixels — normalize "
                             "on-device (scale/bias must be 1/0)")
        if out.size != n * out_h * out_w * channels:
            raise ValueError(f"out size {out.shape} != {expected}")
        arr = (ctypes.c_char_p * n)(
            *[p.encode() for p in paths])
        rh, rw = resize_hw if resize_hw is not None else (0, 0)
        rc = self._lib.zp_submit(
            self._pool, arr, n, rh, rw, out_h, out_w, channels,
            int(random_crop), int(random_flip),
            ctypes.c_float(scale), ctypes.c_float(bias),
            ctypes.c_uint64(seed & (2 ** 64 - 1)),
            ctypes.c_void_p(out.ctypes.data), ctypes.c_int(out_u8))
        if rc != 0:
            raise RuntimeError(f"zp_submit failed (rc={rc})")
        # paths array and out buffer must outlive the async batch
        self._keepalive = (arr, out)

    def wait(self) -> int:
        """Block until the in-flight batch completes; returns the
        number of failed decodes (their slots are zero-filled)."""
        if self._pool is None:
            raise RuntimeError("pipeline destroyed")
        n_failed = self._lib.zp_wait(self._pool)
        self._keepalive = None
        return int(n_failed)

    def close(self) -> None:
        if self._pool is not None:
            self._lib.zp_wait(self._pool)
            self._lib.zp_destroy(self._pool)
            self._pool = None

    def __del__(self) -> None:  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass
