"""Dataflow units: nodes of the workflow graph.

Rebuilds the reference's unit model (reference: ``veles/units.py``):

- **control links** (``b.link_from(a)``): b becomes runnable when a
  finishes; a unit with several incoming links waits for *all* of them
  (:class:`Repeater` waits for *any* — that is what closes training
  loops);
- **attribute links** (``b.link_attrs(a, ("input", "output"))``):
  ``b.input`` is a live alias of ``a.output`` — the data plane;
- **gates**: ``gate_block`` (don't run, don't propagate — control flow
  stops here while the gate holds) and ``gate_skip`` (don't run, but
  propagate), both :class:`~znicz_tpu.mutable.Bool` so other units flip
  them live.

TPU-first note: this graph is the *host control plane* executed between
device steps.  The per-minibatch compute chain is compiled out of the
graph into a single XLA program by the jit-region engine
(:mod:`znicz_tpu.accelerated_units`); gates that flip per-epoch stay
here, gates that flip per-minibatch become static region keys.
"""

from __future__ import annotations

import time
from typing import TYPE_CHECKING, Iterable

from znicz_tpu.mutable import Bool, LinkableAttribute
from znicz_tpu.observe import metrics as _metrics
from znicz_tpu.observe import tracing as _tracing
from znicz_tpu.utils.logger import Logger

if TYPE_CHECKING:  # pragma: no cover
    from znicz_tpu.workflow import Workflow


class Unit(Logger):
    """A node in the dataflow graph.

    Subclasses override :meth:`initialize` (allocate state once the
    graph is wired) and :meth:`run` (one firing).  ``initialize`` may
    raise :class:`AttributeError` if a linked attribute is not yet
    available; the workflow retries in dependency order
    (reference behavior: ``veles/workflow.py`` multi-pass initialize).
    """

    def __init__(self, workflow: "Workflow | None", name: str | None = None,
                 **kwargs) -> None:
        # _linked_attrs must exist before any attribute writes resolve.
        object.__setattr__(self, "_linked_attrs", {})
        super().__init__(**kwargs)
        self.name = name or type(self).__name__
        self.links_from: dict[Unit, bool] = {}
        self.links_to: dict[Unit, bool] = {}
        self.gate_block = Bool(False)
        self.gate_skip = Bool(False)
        self._initialized = False
        self.run_count = 0
        self.run_time_total = 0.0
        self._workflow: "Workflow | None" = None
        if workflow is not None:
            workflow.add_ref(self)

    # ------------------------------------------------------------------
    # attribute linking (data plane)
    # ------------------------------------------------------------------
    def __setattr__(self, name: str, value) -> None:
        link = self._linked_attrs.get(name)
        if link is not None:
            link.set(value)
            return
        object.__setattr__(self, name, value)

    def __getattr__(self, name: str):
        # Called only when normal lookup fails.
        if name == "_linked_attrs":
            raise AttributeError(name)
        link = self._linked_attrs.get(name)
        if link is not None:
            return link.get()
        raise AttributeError(
            f"{type(self).__name__} '{self.__dict__.get('name', '?')}' "
            f"has no attribute '{name}'")

    def link_attrs(self, other: "Unit",
                   *pairs: "str | tuple[str, str]",
                   two_way: bool = True) -> "Unit":
        """Alias attributes of ``other`` into this unit.

        Each pair is either a name (same on both sides) or
        ``(dst_name, src_name)``: ``self.dst_name`` aliases
        ``other.src_name``.
        """
        for pair in pairs:
            dst, src = (pair, pair) if isinstance(pair, str) else pair
            self.__dict__.pop(dst, None)  # the alias must win lookups
            self._linked_attrs[dst] = LinkableAttribute(other, src, two_way)
        return self

    def unlink_attrs(self, *names: str) -> None:
        for name in names:
            self._linked_attrs.pop(name, None)

    # ------------------------------------------------------------------
    # control linking
    # ------------------------------------------------------------------
    def link_from(self, *units: "Unit") -> "Unit":
        for unit in units:
            self.links_from[unit] = False
            unit.links_to[self] = False
        return self

    def unlink_from(self, *units: "Unit") -> None:
        for unit in units:
            self.links_from.pop(unit, None)
            unit.links_to.pop(self, None)

    def unlink_all(self) -> None:
        for unit in list(self.links_from):
            self.unlink_from(unit)
        for unit in list(self.links_to):
            unit.unlink_from(self)

    def open_gate(self, src: "Unit") -> bool:
        """Record that ``src`` finished; True when this unit may fire.

        Default: all incoming links must have fired (barrier join).
        """
        if src in self.links_from:
            self.links_from[src] = True
        return all(self.links_from.values())

    def reset_links(self) -> None:
        for unit in self.links_from:
            self.links_from[unit] = False

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    @property
    def workflow(self) -> "Workflow | None":
        return self._workflow

    @property
    def is_initialized(self) -> bool:
        return self._initialized

    def initialize(self, **kwargs) -> None:
        """Allocate state.  May raise AttributeError to defer."""
        self._initialized = True

    def run(self) -> None:
        """One firing of the unit."""

    def stop(self) -> None:
        """Called when the workflow is stopping; release resources."""

    # ------------------------------------------------------------------
    # snapshot protocol (reference: whole-graph pickle in
    # ``veles/snapshotter.py``; here state is a pure data tree split
    # from code — SURVEY.md §5.4)
    # ------------------------------------------------------------------
    #: extra scalar/ndarray attributes to persist beside owned Vectors
    SNAPSHOT_ATTRS: tuple = ()
    #: owned Vectors that must NOT be snapshotted (e.g. the loader's
    #: device-resident dataset — large, immutable, rebuilt on resume)
    SNAPSHOT_EXCLUDE: tuple = ()

    def state_dict(self, allow_collective: bool = False) -> dict:
        """``allow_collective=True`` when EVERY process reaches this
        call in lockstep (the in-graph Snapshotter unit: SPMD runs it
        on all processes) — model-sharded persistent state is then
        gathered via the collective read.  Solo callers (the master's
        emergency snapshot) must leave it False."""
        from znicz_tpu.memory import Vector  # local: avoid import cycle
        import numpy as _np
        out: dict = {}
        for name, val in self.__dict__.items():
            if name in self.SNAPSHOT_EXCLUDE:
                continue
            if isinstance(val, Vector) and val:
                if val.needs_collective_read:
                    if val.batch_major:
                        # Batch-sharded buffers are per-minibatch
                        # transients (loader/forward/err chains refill
                        # them before any consumer on resume); never
                        # worth a cross-process all-gather.
                        continue
                    if not allow_collective:
                        # Persistent sharded state (tensor-parallel
                        # weights/momentum) cannot be silently skipped
                        # — resuming would restore fresh random init
                        # for just these layers.  Reading it here
                        # would all-gather, which deadlocks on a solo
                        # snapshot path, so fail loudly instead.
                        raise NotImplementedError(
                            f"{self}: snapshotting model-sharded "
                            f"Vector '{val.name}' outside a lockstep "
                            f"snapshot point — use the Snapshotter "
                            f"unit (all processes) for tensor-"
                            f"parallel state")
                    # lockstep: map_read → device.get →
                    # process_allgather reassembles the full array
                val.map_read()
                # ZeRO-1 state is stored data-axis-sharded and possibly
                # zero-padded; the read above gathered the full array —
                # slice the padding so the checkpoint holds the LOGICAL
                # tensor, independent of the mesh size that wrote it
                out[name] = _np.array(val.strip_data_pad(val.mem),
                                      copy=True)
        for name in self.SNAPSHOT_ATTRS:
            out[name] = getattr(self, name)
        return out

    def load_state(self, state: dict) -> None:
        from znicz_tpu.memory import Vector
        import numpy as _np
        for name, val in state.items():
            cur = self.__dict__.get(name)
            if isinstance(cur, Vector):
                arr = _np.array(val, copy=True)
                if cur and cur.data_shard_dim is not None:
                    # re-shard for the CURRENT mesh: the live Vector's
                    # padding (computed at initialize for this run's
                    # data-axis size) may differ from the writer's
                    arr = cur.apply_data_pad(arr)
                cur.reset(arr)
            else:
                setattr(self, name, val)

    # engine hook — called by the workflow scheduler
    def _fire(self) -> None:
        start = time.perf_counter()
        if _metrics.enabled():
            # telemetry on: the fire becomes a host span (lined up
            # with XLA device lanes when a profiler window is open)
            # and a sample in the per-unit run-time histogram
            with _tracing.TRACER.span(self.name, cat="unit",
                                      kind=type(self).__name__):
                self.run()
            elapsed = time.perf_counter() - start
            _metrics.unit_run_seconds(self.name).observe(elapsed)
        else:
            self.run()
            elapsed = time.perf_counter() - start
        self.run_time_total += elapsed
        self.run_count += 1

    def __repr__(self) -> str:
        return f"<{type(self).__name__} '{self.name}'>"


class TrivialUnit(Unit):
    """A no-op unit (useful as a join/fan-out point)."""

    def initialize(self, **kwargs) -> None:
        super().initialize(**kwargs)


class Repeater(TrivialUnit):
    """Opens its gate on ANY incoming link — the loop-closing unit.

    Reference: ``veles/workflow.py`` ``Repeater``; without any-semantics
    a training loop (start_point → repeater ← last backward unit) would
    deadlock waiting for both predecessors every iteration.
    """

    def open_gate(self, src: Unit) -> bool:
        if src in self.links_from:
            self.links_from[src] = True
        return any(self.links_from.values())


class StartPoint(TrivialUnit):
    """The workflow's entry node (reference: ``veles/workflow.py``)."""


class EndPoint(TrivialUnit):
    """The workflow's exit node; firing it completes the run."""

    def run(self) -> None:
        wf = self.workflow
        if wf is not None:
            wf.on_end_point()


class Container(Unit):
    """A unit that owns other units (reference: ``veles/units.py``)."""

    def __init__(self, workflow: "Workflow | None", name: str | None = None,
                 **kwargs) -> None:
        # before super().__init__: _linked_attrs does not exist yet
        object.__setattr__(self, "units", [])
        super().__init__(workflow, name=name, **kwargs)

    def add_ref(self, unit: Unit) -> None:
        if unit is self:
            raise ValueError("a container cannot contain itself")
        taken = {u.name for u in self.units}
        if unit.name in taken:  # unique names (snapshot state keys)
            i = 2
            while f"{unit.name}_{i}" in taken:
                i += 1
            unit.name = f"{unit.name}_{i}"
        self.units.append(unit)
        unit._workflow = self  # type: ignore[assignment]

    def del_ref(self, unit: Unit) -> None:
        self.units.remove(unit)
        unit._workflow = None

    def __iter__(self) -> "Iterable[Unit]":
        return iter(self.units)

    def __len__(self) -> int:
        return len(self.units)
