"""Web status: a live dashboard of running workflows.

Rebuilds the reference's ``veles/web_status.py`` + ``veles/web/``
(a Tornado UI where the master reported running workflows, slaves and
progress).  TPU-first deltas: there is no master–slave topology to
display — the cluster is an SPMD mesh — so the dashboard shows the
process's registered workflows: epoch/minibatch progress, best
metrics, device, mesh shape, per-unit timing.  Implementation is
stdlib ``http.server`` in a daemon thread (no tornado in this
environment): ``/`` is a self-refreshing HTML page, ``/status.json``
the machine-readable feed, ``/metrics`` the Prometheus text
exposition of the process-global :mod:`znicz_tpu.observe` registry
(compile counts, per-unit run-time histograms, transfer bytes,
serving latency — everything train + serve register; since round 16
one process typically hosts a FLEET, so ``/metrics`` aggregates N
serving/decode engines under per-engine labels plus the per-tenant
fleet series), and ``/trace.json`` a live Chrome-trace/Perfetto dump
of the host-span ring buffer (open it in ``ui.perfetto.dev``), and —
round 11 — ``/healthz`` (liveness, always 200) + ``/readyz``
(readiness fed from the registry: circuit-breaker state per engine,
serving queue age, last-step staleness; 503 while any ENGINE sheds
load — a fleet tenant's own breaker opening is NOT an engine outage:
it sheds exactly that tenant and is reported per tenant, never
flipping the process probe) so external supervisors can probe
training and every resident serving engine at once.  Round 18:
``/readyz`` on process 0 additionally folds per-process heartbeat
ages from ``znicz_heartbeat_age_seconds`` (aggregate pod health —
a stale peer makes the pod not ready past
``engine.ready_max_heartbeat_s``, unset = report-only).  Round 24:
``/flightrecord`` serves the ops flight recorder's journal
(``?since=<seq>&kind=<k1,k2>`` filters), and ``/readyz`` folds the
federation view — each :class:`~znicz_tpu.observe.federation.
Federator` source's scrape staleness, bounded by
``engine.ready_max_fed_age_s`` (unset = report-only).
"""

from __future__ import annotations

import html
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from znicz_tpu.utils.logger import Logger


def gather_status(workflow) -> dict:
    """One workflow's live status snapshot (scalars only — safe to
    read from the serving thread while training runs).  Anything with
    a ``serving_status`` hook — a
    :class:`znicz_tpu.serving.ServingEngine`, a
    :class:`~znicz_tpu.serving.DecodeEngine`, or a whole
    :class:`~znicz_tpu.serving.FleetEngine` (per-tenant SLO state,
    models, replica groups) — reports its own snapshot through the
    same feed."""
    if hasattr(workflow, "serving_status"):
        return workflow.serving_status()
    from znicz_tpu.utils.introspect import (slowest_units,
                                            validation_metrics)
    out: dict = {"name": workflow.name,
                 "initialized": workflow.is_initialized,
                 "stopped": bool(workflow.stopped)}
    loader = getattr(workflow, "loader", None)
    if loader is not None and loader.is_initialized:
        out["epoch"] = int(loader.epoch_number)
        out["total_samples"] = int(loader.total_samples)
        schedule_len = len(loader._schedule)
        if schedule_len:
            out["epoch_progress_pt"] = round(
                100.0 * min(loader._cursor, schedule_len) / schedule_len,
                1)
    out.update(validation_metrics(workflow))
    decision = getattr(workflow, "decision", None)
    if decision is not None:
        out["complete"] = bool(getattr(decision, "complete", False))
    device = getattr(workflow, "device", None)
    if device is not None:
        out["backend"] = device.backend
        mesh = getattr(device, "mesh", None)
        if mesh is not None:
            out["mesh"] = {ax: int(n) for ax, n
                           in zip(mesh.axis_names, mesh.devices.shape)}
    out["slowest_units"] = slowest_units(workflow, n=5)
    return out


class WebStatusServer(Logger):
    """Serves ``/`` (HTML) and ``/status.json`` for every registered
    workflow.  ``port=0`` picks a free port (see :attr:`port`)."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1") -> None:
        super().__init__()
        self._workflows: list = []
        self._lock = threading.Lock()
        self._started = time.time()
        status_server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route into our logger
                status_server.debug("http: " + fmt, *args)

            def do_GET(self):
                if self.path.startswith("/status.json"):
                    body = json.dumps(status_server.status()).encode()
                    ctype = "application/json"
                elif self.path.startswith("/healthz"):
                    # liveness: the process answers — always 200
                    body = json.dumps(status_server.health()).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                elif self.path.startswith("/readyz"):
                    # readiness: fed from the observe registry (breaker
                    # state, queue age, last-step staleness) — 503
                    # tells an external supervisor to stop routing here
                    report = status_server.readiness()
                    body = json.dumps(report).encode()
                    self.send_response(200 if report["ready"] else 503)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                elif self.path.startswith("/metrics"):
                    from znicz_tpu.observe import metrics
                    body = metrics.REGISTRY.to_prometheus().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path.startswith("/trace.json"):
                    from znicz_tpu.observe import tracing
                    body = json.dumps(
                        tracing.TRACER.to_chrome_trace()).encode()
                    ctype = "application/json"
                elif self.path.startswith("/flightrecord"):
                    # round 24: the ops flight recorder's journal —
                    # ?since=<seq> and ?kind=<k1,k2> filter; newest
                    # 256 events by default so the page stays bounded
                    from urllib.parse import parse_qs, urlparse
                    from znicz_tpu.observe import recorder
                    q = parse_qs(urlparse(self.path).query)
                    since = int(q.get("since", ["0"])[0] or 0)
                    kinds = None
                    if q.get("kind"):
                        kinds = [k for k in
                                 q["kind"][0].split(",") if k]
                    rec = recorder.get_recorder()
                    if rec is None:
                        payload = {"events": [], "status": None}
                    else:
                        events = rec.dump_since(since, kinds=kinds)
                        payload = {"events": events[-256:],
                                   "status": rec.status()}
                    body = json.dumps(payload).encode()
                    ctype = "application/json"
                elif self.path == "/" or self.path.startswith("/index"):
                    body = status_server.render_html().encode()
                    ctype = "text/html; charset=utf-8"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        # round-19 satellite: every /metrics endpoint exports
        # znicz_build_info (fleet debugging must tell which build a
        # scrape came from).  Fallback registration only — device
        # creation refreshes with platform/mesh/process labels; no
        # backend query here (the TPU tunnel can wedge on one).
        try:
            from znicz_tpu.observe import metrics as _metrics
            _metrics.set_build_info(fallback=True)
        except Exception:  # noqa: BLE001 — never block the dashboard
            pass
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="web-status",
            daemon=True)
        self._thread.start()
        self.info("web status @ http://%s:%d/", self.host, self.port)

    # ------------------------------------------------------------------
    def register(self, workflow) -> None:
        with self._lock:
            if workflow not in self._workflows:
                self._workflows.append(workflow)

    def unregister(self, workflow) -> None:
        with self._lock:
            if workflow in self._workflows:
                self._workflows.remove(workflow)

    def status(self) -> dict:
        with self._lock:
            workflows = list(self._workflows)
        return {
            "uptime_s": round(time.time() - self._started, 1),
            "workflows": [gather_status(wf) for wf in workflows],
        }

    # -- supervisor probes (round 11) ----------------------------------
    def health(self) -> dict:
        """/healthz body: liveness only — the process is up and the
        status thread answers."""
        with self._lock:
            n = len(self._workflows)
        return {"status": "ok",
                "uptime_s": round(time.time() - self._started, 1),
                "workflows": n}

    def readiness(self) -> dict:
        """/readyz body, fed from the observe REGISTRY (so it reflects
        exactly what ``/metrics`` exports, not object state):

        - ``znicz_serving_breaker_state`` — any ENGINE with an OPEN
          breaker (2) makes the process not-ready (it is shedding
          every caller);
        - ``znicz_fleet_breaker_state`` (round 16) — per-TENANT fleet
          breakers are reported under ``tenants`` but are
          REPORT-ONLY: an open tenant breaker sheds exactly that
          tenant while every other tenant is served normally, so it
          must not flip a supervisor's routing decision;
        - ``znicz_serving_queue_age_seconds`` — reported per engine;
          not-ready when it exceeds ``engine.ready_max_queue_age_s``
          (default unset = report-only);
        - ``znicz_last_step_timestamp_seconds`` — per-workflow step
          staleness; not-ready when older than
          ``engine.ready_max_staleness_s`` (default unset =
          report-only, so a finished training run does not flip a
          serving process to 503);
        - ``znicz_model_version`` (round 13) — the live published
          model version per serving engine, reported so a supervisor
          can confirm which weights a replica is actually running;
        - ``znicz_snapshot_age_seconds`` (round 13) — time since each
          source (snapshotter prefix / publish directory) last wrote a
          GOOD artifact; not-ready when it exceeds
          ``engine.ready_max_snapshot_age_s`` (default unset =
          report-only), so a stalled trainer that stopped publishing
          shows up on the serving probe;
        - ``znicz_loader_rows_quarantined_total`` (round 19) — rows a
          quarantined shard delivered as zeros, per loader.
          REPORT-ONLY: quarantine-and-continue is degraded, not dead
          — restarting would lose more progress than the zeros cost.
        """
        from znicz_tpu.observe import metrics
        from znicz_tpu.utils.config import root
        now = time.time()
        out: dict = {"ready": True, "reasons": [],
                     "engines": {}, "workflows": {}}

        def not_ready(reason: str) -> None:
            out["ready"] = False
            out["reasons"].append(reason)

        fam = metrics.REGISTRY.get("znicz_serving_breaker_state")
        if fam is not None:
            for key, child in fam.items():
                (engine,) = key
                state = {0: "closed", 1: "half_open",
                         2: "open"}.get(int(child.value), "?")
                out["engines"].setdefault(engine, {})["breaker"] = state
                if state == "open":
                    not_ready(f"breaker open on engine {engine}")
        fam = metrics.REGISTRY.get("znicz_fleet_breaker_state")
        if fam is not None:
            out["tenants"] = {}
            for key, child in fam.items():
                fleet, tenant = key
                state = {0: "closed", 1: "half_open",
                         2: "open"}.get(int(child.value), "?")
                out["tenants"][f"{fleet}/{tenant}"] = state
        fam = metrics.REGISTRY.get("znicz_serving_queue_age_seconds")
        max_age = root.common.engine.get("ready_max_queue_age_s", None)
        if fam is not None:
            for key, child in fam.items():
                # ("engine",) pre-round-22 children, ("engine","pool")
                # after — /readyz watches the WORST pool per engine
                engine = key[0]
                age = round(float(child.value), 3)
                prior = out["engines"].setdefault(engine, {})
                age = max(age, prior.get("queue_age_s", 0.0))
                prior["queue_age_s"] = age
                if max_age is not None and age > float(max_age):
                    not_ready(f"queue age {age:.1f}s on engine "
                              f"{engine}")
        fam = metrics.REGISTRY.get("znicz_last_step_timestamp_seconds")
        max_stale = root.common.engine.get("ready_max_staleness_s", None)
        if fam is not None:
            for key, child in fam.items():
                (workflow,) = key
                stale = round(max(0.0, now - float(child.value)), 3)
                out["workflows"][workflow] = {"last_step_age_s": stale}
                if max_stale is not None and stale > float(max_stale):
                    not_ready(f"workflow {workflow} last step "
                              f"{stale:.0f}s ago")
        # round 18: aggregate pod health — per-process heartbeat ages
        # (fed by the coordinator-side HeartbeatMonitor from the
        # shared channel).  A stale peer makes the POD not ready when
        # engine.ready_max_heartbeat_s is set (unset = report-only:
        # single-host runs and gang supervisors that own restarts
        # themselves must not flip this process's probe).
        fam = metrics.REGISTRY.get("znicz_heartbeat_age_seconds")
        max_hb = root.common.engine.get("ready_max_heartbeat_s", None)
        if fam is not None:
            out["processes"] = {}
            for key, child in fam.items():
                (process,) = key
                age = float(child.value)
                out["processes"][process] = {
                    "heartbeat_age_s": (None if age == float("inf")
                                        else round(age, 3))}
                if max_hb is not None and age > float(max_hb):
                    not_ready(f"process {process} heartbeat "
                              f"{age:.0f}s stale")
        # round 19: silent data loss made loud — rows a quarantined
        # shard delivered as ZEROS.  REPORT-ONLY by design: a run that
        # chose quarantine-and-continue is degraded, not dead, and an
        # external supervisor restarting it would lose MORE progress;
        # the row count here (and on /metrics) is the operator signal.
        fam = metrics.REGISTRY.get("znicz_loader_rows_quarantined_total")
        if fam is not None:
            out["loaders"] = {}
            for key, child in fam.items():
                (loader,) = key
                out["loaders"][loader] = {
                    "rows_quarantined": int(child.value)}
        fam = metrics.REGISTRY.get("znicz_model_version")
        if fam is not None:
            for key, child in fam.items():
                (engine,) = key
                out["engines"].setdefault(engine, {})[
                    "model_version"] = int(child.value)
        fam = metrics.REGISTRY.get("znicz_snapshot_age_seconds")
        max_snap = root.common.engine.get("ready_max_snapshot_age_s",
                                          None)
        if fam is not None:
            out["artifacts"] = {}
            for key, child in fam.items():
                (source,) = key
                age = round(float(child.value), 3)
                out["artifacts"][source] = {"age_s": age}
                if max_snap is not None and age > float(max_snap):
                    not_ready(f"no good artifact from {source} for "
                              f"{age:.0f}s")
        # round 24: the federated view — when this process folds a
        # gang's children (supervisor/fleet/disagg federators), report
        # each source's scrape staleness; not-ready only when
        # engine.ready_max_fed_age_s is set AND a source is staler
        # (unset = report-only: a paused fold must not 503 a healthy
        # serving process)
        try:
            from znicz_tpu.observe import federation
            feds = federation.status()
        except Exception:  # noqa: BLE001 — probe must answer anyway
            feds = []
        if feds:
            out["federation"] = feds
            max_fed = root.common.engine.get("ready_max_fed_age_s",
                                             None)
            if max_fed is not None:
                worst = federation.max_age_s()
                if worst > float(max_fed):
                    not_ready(f"federated scrape {worst:.1f}s stale")
        return out

    # ------------------------------------------------------------------
    def render_html(self) -> str:
        status = self.status()
        rows = []
        for wf in status["workflows"]:
            metrics = {k: v for k, v in wf.items()
                       if k not in ("name", "slowest_units")}
            timing = "".join(
                f"<li>{html.escape(t['unit'])}: {t['total_s']}s / "
                f"{t['runs']}x</li>" for t in wf.get("slowest_units", []))
            rows.append(
                f"<div class='wf'><h2>{html.escape(wf['name'])}</h2>"
                f"<pre>{html.escape(json.dumps(metrics, indent=2))}"
                f"</pre><ul>{timing}</ul></div>")
        body = "\n".join(rows) or "<p>No workflows registered.</p>"
        return (
            "<!DOCTYPE html><html><head><meta charset='utf-8'>"
            "<meta http-equiv='refresh' content='2'>"
            "<title>znicz_tpu status</title>"
            "<style>body{font-family:monospace;margin:2em}"
            ".wf{border:1px solid #999;padding:1em;margin:1em 0}"
            "</style></head><body><h1>znicz_tpu</h1>"
            f"<p>uptime {status['uptime_s']}s</p>{body}</body></html>")

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=10)
