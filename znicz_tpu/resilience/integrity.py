"""Silent-data-corruption sentinel: fingerprints, audits, quarantine.

Every defense the resilience stack shipped before this round catches
*loud* failures — non-finite losses (guard), crashed processes
(elastic supervisor), corrupt files (sha256 sidecars).  A defective
accelerator that computes *plausible-but-wrong* values trips none of
them: the numbers are finite, the process is alive, the bytes on disk
digest cleanly — and the bad chip silently poisons weights or logits.
Fleet experience (Hochschild et al., "Cores that don't count",
HotOS'21; Dixit et al., "Silent Data Corruptions at Scale", 2021) puts
such chips at ~1/1000 machines.  This module is the detection layer:

- **Step fingerprints** — every weighted GD unit folds a cheap
  sub-sampled checksum of its post-update parameters (and its folded
  gradient) into a shared device vector hosted by the
  :class:`~znicz_tpu.resilience.guard.AnomalyGuard`
  (``sdc_fingerprint``, seeded by the evaluator each train step).
  The fold rides the existing ``_apply_param_xla`` path inside the
  SAME jit region — zero extra compiles, zero extra per-step d2h
  (the fingerprint is read at the sentinel's vote cadence, like the
  guard's anomaly state).

- **Cross-replica vote** — post-update parameters are definitionally
  identical across data replicas, so per-replica fingerprints must
  agree.  At ``engine.sdc_vote_interval`` the sentinel all-gathers
  ``(claimed device fp, host-recomputed fp, sticky self-check)``
  triples and :func:`vote_verdict` localizes a diverging chip/host.
  The HOST recompute (this process's local param copy) is the replica
  comparison — an in-program fold can be GSPMD-homogenized (sharded
  reduction reads each row from its owner) and must not be trusted
  for cross-host divergence.  Localization is self-evident either
  way GSPMD compiles the fold: a homogenized claimed fp disagrees
  with the corrupt host's local recompute, while per-device folds
  trip the guard's sticky temporal self-check — so even a 2-process
  gang names the culprit; ≥3 processes also majority-vote on the
  host fingerprints.  Scope: the vote sees divergence in state that
  replicas maintain INDEPENDENTLY (pure-DP parameters).  Under
  ZeRO-1 the per-step reduce-scatter/all-gather re-derives params
  from shared collectives, so per-host corruption becomes globally
  CONSISTENT corruption within one step — invisible to any replica
  compare and exactly what the redundant-compute audit exists for.

- **Redundant-compute audit** — at ``engine.sdc_audit_interval`` the
  last microbatch's step is replayed on a SHADOW oracle (the numpy
  backend — a genuinely different compute substrate on CPU meshes,
  and always a different chip than the suspect accelerator): the
  sentinel captures pre-step state, lets the device run the step, then
  replays it through a numpy-backend clone of the workflow and
  compares per-tensor post-update parameter fingerprints within
  ``engine.sdc_audit_rtol``.  A confirmed mismatch attributes
  ``znicz_sdc_suspect_total{process,device}`` and escalates.

- **Quarantine** — under an elastic gang (round 18), a confirmed
  culprit annotates the heartbeat channel (culprit ids + the
  last-known-good PRE-divergence snapshot recorded at the last clean
  vote) and exits :data:`~znicz_tpu.resilience.supervisor.EXIT_SDC`;
  healthy peers exit ``EXIT_PEER_LOST`` after annotating, and the
  :class:`~znicz_tpu.resilience.supervisor.ElasticSupervisor`
  restarts the survivors from the pre-divergence snapshot with the
  culprit blocklisted (``znicz_host_losses_total{kind=sdc}``).
  Unsupervised runs roll back to the last-known-good snapshot
  in-process.  Serving-side quarantine lives in
  :mod:`znicz_tpu.serving.engine` (sampled shadow audit) +
  :class:`~znicz_tpu.serving.fleet.ReplicaGroup` (replica removal).

Drillable fault sites: ``sdc.flip_param`` / ``sdc.flip_grad`` (an
exponent-scale multiplier applied to one element on one process —
rides a device leaf like the guard's NaN injection, so injecting never
recompiles) and ``sdc.serving_bitflip`` (a serving replica's replies
corrupted post-program).

Gate: ``root.common.engine.sdc_fingerprints`` (default on whenever the
anomaly guard is on).  Knobs: ``sdc_vote_interval`` (50),
``sdc_audit_interval`` (0 = off), ``sdc_suspect_threshold`` (1),
``sdc_fp_rtol`` / ``sdc_audit_rtol`` (1e-3).
"""

from __future__ import annotations

import os

import numpy as np

from znicz_tpu.observe import metrics as _metrics
from znicz_tpu.resilience import faults as _faults
from znicz_tpu.utils.config import root
from znicz_tpu.utils.logger import Logger

#: the sdc injection sites the guard's device leaf hosts
SDC_TRAIN_SITES = ("sdc.flip_param", "sdc.flip_grad")

#: elements sampled per tensor by the fingerprint (static stride from
#: the static shape — the fold compiles into the existing region)
FP_SAMPLES = 64


def enabled() -> bool:
    """The sentinel gate: ``engine.sdc_fingerprints`` (default on).
    The fold itself only engages where the guard wired the fingerprint
    vector, so this is a build-time decision like the guard's."""
    return bool(root.common.engine.get("sdc_fingerprints", True))


def tensor_fingerprint(xp, arr):
    """Position-weighted sub-sampled checksum of one tensor.

    Samples ``~FP_SAMPLES`` elements at a static stride (element 0
    always included — deterministic coverage of the drill's flip
    target) and folds them with position weights so swapped values
    cannot cancel.  Works identically for ``xp`` = numpy (host/oracle
    recompute) and jax.numpy (the in-region fold); all math in f32 so
    a healthy device fold and the same fold re-traced later are
    bitwise-stable.
    """
    flat = xp.ravel(arr).astype(xp.float32)
    n = int(flat.shape[0])
    stride = max(1, n // FP_SAMPLES)
    sample = flat[::stride]
    weights = 1.0 + (xp.arange(sample.shape[0], dtype=xp.float32)
                     % 31.0)
    return xp.sum(sample * weights)


def host_param_fingerprint(workflow) -> float:
    """Recompute the parameter fingerprint ON THE HOST from the same
    tensors the device fold covered (each GD unit records the exact
    Vector set it folded — see ``GradientDescentBase._fp_folded``),
    in the same order.  f64 accumulation: the comparison against the
    device's f32 fold is tolerance-based (``engine.sdc_fp_rtol``)."""
    total = 0.0
    for gd_unit in getattr(workflow, "gds", ()):
        for vec in getattr(gd_unit, "_fp_folded", {}).values():
            vec.map_read()
            total += float(tensor_fingerprint(np, np.asarray(vec.mem)))
    return total


def audit_fingerprints(workflow) -> list[tuple[str, float]]:
    """Per-tensor host fingerprints ``[(vector name, fp)]`` over every
    parameter the device fold covers — the audit compares these
    between the device run and the shadow oracle so a mismatch is
    attributable to a named tensor."""
    out = []
    for gd_unit in getattr(workflow, "gds", ()):
        for vec in getattr(gd_unit, "_fp_folded", {}).values():
            vec.map_read()
            out.append((vec.name,
                        float(tensor_fingerprint(np,
                                                 np.asarray(vec.mem)))))
    return out


def _rel_diff(a: float, b: float) -> float:
    return abs(a - b) / max(abs(a), abs(b), 1e-12)


def vote_verdict(device_fps, host_fps, rtol: float,
                 self_flags=None) -> dict:
    """Pure verdict over the all-gathered fingerprint evidence.

    ``device_fps[p]`` is process p's on-device claimed param
    fingerprint; ``host_fps[p]`` its host recompute over the same
    buffers; ``self_flags[p]`` its guard's sticky self-check mismatch
    count (a param that mutated between one step's post-update fold
    and the next step's pre-update refold).  Returns
    ``{"divergent": bool, "culprits": [p...], "self_bad": [p...]}``:

    - all device fingerprints agree (within ``rtol``) and nothing
      self-flagged → clean;
    - a process that self-flagged (sticky on-device check) or whose
      claimed fold disagrees with its own host recompute saw its
      parameters mutate outside any computation — a self-evident
      culprit, localizable even in a 2-process gang
      (``sdc.flip_param``'s exact signature);
    - otherwise the minority cluster of device fingerprints is the
      culprit set (majority vote, needs ≥3 voters); a tie (2-process
      gang, divergence through the compute path) marks every
      divergent member suspect — the redundant-compute audit is the
      tiebreaker.
    """
    device_fps = [float(v) for v in device_fps]
    host_fps = [float(v) for v in host_fps]
    n = len(device_fps)
    flags = ([float(v) for v in self_flags]
             if self_flags is not None else [0.0] * n)
    # the HOST fingerprints are the replica-state comparison: each is
    # computed from that process's LOCAL copy of the parameters, which
    # GSPMD cannot homogenize (an in-program fold is free to be
    # computed as a sharded reduction + all-reduce, which reads each
    # row from its OWNER's copy and hides per-host divergence)
    divergent = any(_rel_diff(host_fps[0], v) > rtol
                    for v in host_fps[1:])
    self_bad = [p for p in range(n)
                if flags[p] > 0.0
                or _rel_diff(device_fps[p], host_fps[p]) > rtol]
    if not divergent and not self_bad:
        return {"divergent": False, "culprits": [], "self_bad": []}
    if self_bad:
        return {"divergent": True, "culprits": sorted(self_bad),
                "self_bad": sorted(self_bad)}
    # cluster host fingerprints; minority cluster(s) are the culprits
    clusters: list[list[int]] = []
    for p, v in enumerate(host_fps):
        for cluster in clusters:
            if _rel_diff(host_fps[cluster[0]], v) <= rtol:
                cluster.append(p)
                break
        else:
            clusters.append([p])
    biggest = max(len(c) for c in clusters)
    majority = [c for c in clusters if len(c) == biggest]
    if len(majority) == 1 and biggest > n - biggest:
        culprits = sorted(p for c in clusters if c is not majority[0]
                          for p in c)
    else:  # tie: every divergent member is suspect
        culprits = list(range(n))
    return {"divergent": True, "culprits": culprits, "self_bad": []}


class IntegritySentinel(Logger):
    """Host-side driver of the SDC detectors for one training
    workflow.  Ticked by the Decision unit every step boundary
    (:meth:`on_step`) — all processes tick in lockstep, so the vote's
    all-gather is a legal collective."""

    def __init__(self, workflow, **overrides) -> None:
        super().__init__()
        engine = root.common.engine
        self.workflow = workflow
        self.vote_interval = int(overrides.get(
            "vote_interval", engine.get("sdc_vote_interval", 50)))
        self.audit_interval = int(overrides.get(
            "audit_interval", engine.get("sdc_audit_interval", 0)))
        self.suspect_threshold = int(overrides.get(
            "suspect_threshold", engine.get("sdc_suspect_threshold", 1)))
        self.fp_rtol = float(overrides.get(
            "fp_rtol", engine.get("sdc_fp_rtol", 1e-3)))
        self.audit_rtol = float(overrides.get(
            "audit_rtol", engine.get("sdc_audit_rtol", 1e-3)))
        self._tick = 0
        self._suspect_streak: dict[int, int] = {}
        self._audit_streak = 0
        #: newest snapshot known to PREDATE any divergence — recorded
        #: at every clean vote; the quarantine resume target
        self.last_good_snapshot: str | None = None
        self._pending_audit_state: dict | None = None
        self._shadow = None
        self.quarantined = False

    # ------------------------------------------------------------------
    def read_device_fingerprint(self) -> np.ndarray | None:
        """The guard-hosted f32[5] fingerprint state (one tiny d2h at
        vote/audit cadence only) — [claimed param fp, grad fp,
        pre-update refold, sticky self-check mismatches, previous
        claimed fp]; None when absent (guard off, population-stacked
        state)."""
        guard = getattr(self.workflow, "anomaly_guard", None)
        if guard is None:
            return None
        return guard.read_sdc_fingerprint()

    # ------------------------------------------------------------------
    # the per-step tick (Decision._resilience_tick)
    # ------------------------------------------------------------------
    def on_step(self) -> None:
        if self.quarantined:
            return
        self._tick += 1
        if self.audit_interval > 0:
            if self._pending_audit_state is not None:
                self._run_audit()
            elif (self._tick + 1) % self.audit_interval == 0:
                # the NEXT step is the audit target: capture its
                # pre-state now (we are at the boundary before it)
                self._capture_audit_state()
        if self.vote_interval > 0 and self._tick % self.vote_interval == 0:
            self._vote()

    # ------------------------------------------------------------------
    # cross-replica vote
    # ------------------------------------------------------------------
    def _vote(self) -> None:
        wf = self.workflow
        fp = self.read_device_fingerprint()
        if fp is None or fp[0] == 0.0:
            return  # no train step folded yet (or stacked state)
        from znicz_tpu.parallel.process_shard import (_exact_allgather,
                                                      process_info)
        pidx, pcount = process_info()
        host_fp = host_param_fingerprint(wf)
        triple = [fp[0], host_fp, fp[3]]  # claimed, recomputed, sticky
        if pcount == 1:
            # single process: the self-checks alone (sticky on-device
            # count + claimed-vs-host-recompute) — catch a post-fold
            # buffer mutation without any peer to compare against
            verdict = vote_verdict([triple[0]], [triple[1]],
                                   self.fp_rtol,
                                   self_flags=[triple[2]])
        else:
            gathered = _exact_allgather(
                np.asarray(triple, dtype=np.float64))  # (P, 3)
            verdict = vote_verdict(gathered[:, 0], gathered[:, 1],
                                   self.fp_rtol,
                                   self_flags=gathered[:, 2])
        if not verdict["divergent"]:
            _metrics.sdc_votes(wf.name, "clean").inc()
            self._suspect_streak.clear()
            snap = getattr(wf, "snapshotter", None)
            dest = getattr(snap, "destination", None)
            if dest and os.path.exists(dest):
                self.last_good_snapshot = dest
            return
        _metrics.sdc_votes(wf.name, "divergent").inc()
        _metrics.sdc_detected("vote").inc()
        for p in verdict["culprits"]:
            _metrics.sdc_suspects(p, "-").inc()
            self._suspect_streak[p] = self._suspect_streak.get(p, 0) + 1
        self.warning(
            "SDC vote DIVERGENT at tick %d: culprits=%s (self-evident="
            "%s, last_good=%s)", self._tick, verdict["culprits"],
            verdict["self_bad"], self.last_good_snapshot)
        confirmed = [p for p, s in self._suspect_streak.items()
                     if s >= self.suspect_threshold]
        if confirmed:
            self._quarantine(confirmed, detector="vote")

    # ------------------------------------------------------------------
    # redundant-compute audit
    # ------------------------------------------------------------------
    def _shadow_workflow(self):
        if self._shadow is None:
            self._shadow = self.workflow.build_shadow()
        return self._shadow

    def _capture_audit_state(self) -> None:
        wf = self.workflow
        from znicz_tpu.parallel.process_shard import process_info
        if process_info()[1] > 1:
            # multi-process audits would need per-process 1/N replay;
            # the cross-replica vote is the multi-host detector
            return
        try:
            self._pending_audit_state = wf.state_dict()
        except Exception as exc:  # noqa: BLE001 — audit must not kill
            self.warning("audit state capture failed: %s", exc)
            self._pending_audit_state = None

    def _run_audit(self) -> None:
        """Replay the step that JUST ran on the device (pre-state was
        captured at the previous boundary) through the numpy-backend
        shadow and compare per-tensor post-update fingerprints."""
        wf = self.workflow
        state = self._pending_audit_state
        self._pending_audit_state = None
        from znicz_tpu.utils import prng as _prng
        saved_prng = _prng.get().get_state()
        try:
            # the shadow's load_state/step must not perturb the LIVE
            # process's global PRNG stream (bit-identical trajectory
            # with and without audits — test-pinned)
            shadow = self._shadow_workflow()
            shadow.load_state(state)
            shadow.loader.run()
            for unit in shadow.hot_chain_units()[1:]:
                if not unit.gate_block and not unit.gate_skip:
                    unit.run()
            # same declarative config → same construction order →
            # identical unit/vector names, so names key the comparison
            shadow_fps = dict(audit_fingerprints(shadow))
        except Exception as exc:  # noqa: BLE001 — audit must not kill
            self.warning("shadow audit replay failed: %s", exc)
            return
        finally:
            _prng.get().set_state(saved_prng)
        device_fps = audit_fingerprints(wf)
        mismatched = []
        for name, dev_fp in device_fps:
            ref = shadow_fps.get(name)
            if ref is None:
                continue
            if _rel_diff(dev_fp, ref) > self.audit_rtol:
                mismatched.append((name, dev_fp, ref))
        if not mismatched:
            _metrics.sdc_audits(wf.name, "match").inc()
            self._audit_streak = 0
            return
        _metrics.sdc_audits(wf.name, "mismatch").inc()
        _metrics.sdc_detected("audit").inc()
        from znicz_tpu.parallel.process_shard import process_info
        pidx = process_info()[0]
        _metrics.sdc_suspects(pidx, "-").inc()
        self._audit_streak += 1
        self.warning(
            "SDC audit MISMATCH at tick %d: device step diverged from "
            "the shadow oracle on %s", self._tick,
            [(n, f"{d:.6g}!={r:.6g}") for n, d, r in mismatched])
        if self._audit_streak >= self.suspect_threshold:
            self._quarantine([pidx], detector="audit")

    # ------------------------------------------------------------------
    # quarantine
    # ------------------------------------------------------------------
    def _quarantine(self, culprits: list[int], detector: str) -> None:
        """Confirmed-corrupt escalation.  Supervised gang: annotate
        the heartbeat channel (culprits + pre-divergence snapshot +
        detection attestations) and exit — the culprit with EXIT_SDC
        (blocklist me), the healthy peers with EXIT_PEER_LOST (their
        next collective can never complete anyway); the
        ElasticSupervisor restarts the survivors from the
        pre-divergence snapshot.  Unsupervised: roll back to the
        last-known-good snapshot in-process and keep going — weights
        poisoned after the divergence are discarded either way."""
        from znicz_tpu.parallel.process_shard import process_info
        from znicz_tpu.resilience import supervisor as _sup
        wf = self.workflow
        pidx = process_info()[0]
        self.quarantined = True
        sup = getattr(wf, "_worker_supervisor", None)
        from znicz_tpu.observe import recorder as _recorder
        _recorder.record("sdc_quarantine", detector=detector,
                         culprits=",".join(str(c) for c in culprits),
                         process=pidx,
                         last_good=self.last_good_snapshot)
        self.warning("SDC quarantine (%s): culprits=%s, self=%d, "
                     "last_good=%s", detector, culprits, pidx,
                     self.last_good_snapshot)
        if sup is not None and getattr(sup, "writer", None) is not None:
            plan = _faults.active()
            sup.writer.annotate(
                sdc_culprits=list(culprits),
                sdc_last_good=self.last_good_snapshot,
                sdc_detected={detector: 1},
                faults_injected=(plan.counts() if plan else {}))
            if pidx in culprits:
                os._exit(_sup.EXIT_SDC)
            os._exit(_sup.EXIT_PEER_LOST)
        _metrics.sdc_quarantined("host").inc()
        path = self.last_good_snapshot
        if path and os.path.exists(path):
            from znicz_tpu.utils.snapshotter import Snapshotter
            wf.load_state(Snapshotter.load(path))
            guard = getattr(wf, "anomaly_guard", None)
            if guard is not None:
                guard.reset_sdc_fingerprint()
            _metrics.recoveries("sdc_rollback").inc()
            self.warning("rolled back to pre-divergence snapshot %s",
                         path)
            self.quarantined = False  # state is clean again
            self._audit_streak = 0
            self._suspect_streak.clear()
        else:
            self.warning("no pre-divergence snapshot recorded — "
                         "sentinel stands down (suspect state kept)")
