"""Resilience: deterministic fault injection + the recovery machinery.

Veles's defining production trait (PAPER.md SURVEY §0) was surviving
partial failure — slaves could drop, stall, or send garbage and the
master kept training and serving.  This package is the rebuild's
equivalent substrate, wired through every layer of the modern stack:

- :mod:`znicz_tpu.resilience.faults` — a seeded, deterministic
  fault-injection harness: every injection point in the framework is a
  *named site* gated on ``root.common.engine.faults`` (default off,
  one dict lookup when off), so a chaos run is a config recipe, not a
  code fork, and replays bit-for-bit from its seed;
- :mod:`znicz_tpu.resilience.guard` — the training anomaly guard: an
  on-device finite check folded into the existing jit region that
  skips the optimizer update on a non-finite loss/grad step, counts
  anomalies, and (via the Decision unit) rolls back to the last good
  snapshot after K consecutive anomalous steps;
- the streaming loader's shard-CRC/retry/quarantine and
  producer-death propagation live in :mod:`znicz_tpu.loader.streaming`;
- the serving deadline/retry/circuit-breaker path lives in
  :mod:`znicz_tpu.serving`;
- snapshot retention + digest-verified load lives in
  :mod:`znicz_tpu.utils.snapshotter`;
- :mod:`znicz_tpu.resilience.supervisor` — round 18: elastic
  multi-host supervision — per-process heartbeats into a
  coordinator-visible channel, the coordinator-side liveness monitor,
  SIGTERM/preemption → barriered checkpoint-on-signal (master writes
  the sha256-sidecar snapshot, peers fence on the sidecar), the
  collective-hang self-watchdog, and the
  :class:`~znicz_tpu.resilience.supervisor.ElasticSupervisor` gang
  owner that restarts training on the surviving mesh from the newest
  digest-verified snapshot;
- :mod:`znicz_tpu.resilience.publisher` — round 13: the train-to-serve
  handoff control plane: digest-sidecar bundle publication, the
  serving-side :class:`~znicz_tpu.resilience.publisher.PublicationWatcher`
  (loads only digest-verified bundles, falls back on corruption), and
  the :class:`~znicz_tpu.resilience.publisher.SwapController`
  canary-gate → promote → probation → automatic-rollback state machine
  over the engines' recompile-free ``swap_weights``.

Every fault, retry, skip, quarantine, rollback and breaker transition
is a canonical :mod:`znicz_tpu.observe` registry series scraped by
``/metrics`` (``znicz_faults_injected_total``,
``znicz_recoveries_total``, ``znicz_step_anomalies_total``, …) and
attested by the chaos dryrun (``GRAFT_CHAOS=1 __graft_entry__.py``).
"""

from znicz_tpu.resilience.faults import (  # noqa: F401
    FaultInjected,
    FaultPlan,
    SITES,
    fire,
)
from znicz_tpu.resilience.publisher import (  # noqa: F401
    PublicationWatcher,
    SwapController,
    WeightPublisher,
    classifier_score,
    publish_bundle,
)
from znicz_tpu.resilience.supervisor import (  # noqa: F401
    EXIT_PEER_LOST,
    EXIT_PREEMPTED,
    ElasticSupervisor,
    HeartbeatMonitor,
    HeartbeatWriter,
    PeerLost,
    Preempted,
    WorkerSupervisor,
    newest_good_snapshot,
)
