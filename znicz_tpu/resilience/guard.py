"""Training anomaly guard: on-device finite checks, skip-don't-poison.

The failure mode this kills: one non-finite training step (bad batch,
overflowing logits, a flipped bit on the wire) used to poison the
weights forever — every later step multiplies NaN by something and the
run is dead long before a human reads the loss curve.

Design (all inside the EXISTING jit region — zero new compiles on
warmed paths, ``tests/test_retrace_guard.py``):

- the evaluator seeds a device-resident ``step_flags`` f32[2] vector
  each step: ``[running_ok, loss_ok]``, both = isfinite(step loss);
- every weighted GD unit folds ``isfinite(‖grad‖²)`` into
  ``running_ok`` and applies its parameter update through
  ``where(ok, new, old)`` — a non-finite step leaves weights AND
  momentum untouched (see ``GradientDescentBase._apply_param_xla``);
- this unit runs LAST in the region and maintains
  ``anomaly_state`` int32[3] = ``[consecutive_streak,
  loss_anomalies_total, grad_anomalies_total]`` on device;
- the Decision unit reads the state each fire (host control plane),
  translates the totals into ``znicz_step_anomalies_total{kind}`` /
  ``znicz_recoveries_total{kind=anomaly_step}`` registry deltas, and
  after K consecutive anomalies (``engine.anomaly_rollback_k``,
  default 5) asks the workflow to roll back to the Snapshotter's last
  good checkpoint (the round-10 mid-epoch resume path) and continue.

Fault injection (``train.nonfinite_loss`` / ``train.nonfinite_grad``):
when the active fault plan configures either site, the guard allocates
a ``fault_inject`` f32[2] leaf the evaluator adds into the step loss /
the err_output seed — the NaN rides a leaf VALUE, so injecting never
recompiles, and the poisoned numbers flow through the real data path
(the gradients genuinely go non-finite).  Without a plan the leaf is
never allocated and the traced program is byte-identical to a
guard-only build.

Gate: ``root.common.engine.anomaly_guard`` (default on) — built by
``StandardWorkflow``; the measured warmed-step overhead is within
noise (PERF.md round 11).
"""

from __future__ import annotations

import numpy as np

from znicz_tpu.accelerated_units import AcceleratedUnit
from znicz_tpu.loader.base import TRAIN
from znicz_tpu.memory import Vector
from znicz_tpu.resilience import faults as _faults

#: the two training injection sites this unit hosts
TRAIN_SITES = ("train.nonfinite_loss", "train.nonfinite_grad")


class AnomalyGuard(AcceleratedUnit):
    """Region member that finalizes the per-step anomaly verdict.

    Trace order: loader → forwards → evaluator → backwards → **guard**
    — by the time this unit runs, ``step_flags[0]`` has been ANDed by
    the evaluator (loss finite) and every weighted GD (grad finite).
    """

    # per-step transients + process-lifetime totals: neither belongs in
    # a checkpoint (restoring old totals would run the host-side metric
    # deltas backwards)
    SNAPSHOT_EXCLUDE = ("step_flags", "anomaly_state", "fault_inject")

    def __init__(self, workflow, name: str | None = None, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        #: [running_ok, loss_ok] — seeded by the evaluator each step,
        #: ANDed by each GD unit, read+committed here
        self.step_flags = Vector(name=f"{self.name}.step_flags")
        #: [consecutive_streak, loss_total, grad_total]
        self.anomaly_state = Vector(name=f"{self.name}.anomaly_state")
        #: [loss_add, grad_add] — 0.0 normally, NaN on injected steps;
        #: allocated ONLY when a fault plan configures a train site
        self.fault_inject: Vector | None = (
            Vector(name=f"{self.name}.fault_inject")
            if _faults.site_configured(*TRAIN_SITES) else None)
        #: host mirror of the last totals the Decision translated into
        #: registry counters (delta base)
        self._metric_base = (0, 0)
        self._last_inject = (False, False)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        self.step_flags.reset(np.ones(2, dtype=np.float32))
        self.anomaly_state.reset(np.zeros(3, dtype=np.int32))
        self.init_vectors(self.step_flags, self.anomaly_state)
        if self.fault_inject is not None:
            self.fault_inject.reset(np.zeros(2, dtype=np.float32))
            self.init_vectors(self.fault_inject)
        self._metric_base = (0, 0)
        self._last_inject = (False, False)

    # ------------------------------------------------------------------
    # host control plane: arm/disarm the injection leaf per step
    # ------------------------------------------------------------------
    def host_run(self) -> None:
        inj = self.fault_inject
        if inj is None or not inj:
            return
        loader = getattr(self.workflow, "loader", None)
        on_train = (loader is None
                    or loader.minibatch_class == TRAIN)
        want = ((bool(_faults.fire("train.nonfinite_loss")),
                 bool(_faults.fire("train.nonfinite_grad")))
                if on_train else (False, False))
        if want == self._last_inject:
            return  # leaf value unchanged: no host write, no upload
        self._last_inject = want
        inj.map_invalidate()
        inj.mem[...] = [np.nan if want[0] else 0.0,
                        np.nan if want[1] else 0.0]
        if self.device is not None and not self.device.is_host_only:
            inj.unmap()

    # ------------------------------------------------------------------
    # the per-step commit (inside the region on XLA; eager on numpy)
    # ------------------------------------------------------------------
    def xla_run(self) -> None:
        import jax.numpy as jnp
        flags = self.step_flags.devmem
        ok = flags[0] > 0.5
        loss_ok = flags[1] > 0.5
        st = self.anomaly_state.devmem
        one = jnp.ones((), dtype=st.dtype)
        zero = jnp.zeros((), dtype=st.dtype)
        self.anomaly_state.devmem = jnp.stack([
            jnp.where(ok, zero, st[0] + 1),
            st[1] + jnp.where(loss_ok, zero, one),
            st[2] + jnp.where(loss_ok & ~ok, one, zero)])

    def numpy_run(self) -> None:
        flags = self.step_flags.mem
        ok = bool(flags[0] > 0.5)
        loss_ok = bool(flags[1] > 0.5)
        st = self.anomaly_state.mem
        st[0] = 0 if ok else st[0] + 1
        if not loss_ok:
            st[1] += 1
        elif not ok:
            st[2] += 1

    # ------------------------------------------------------------------
    # host-side readers (Decision unit / rollback)
    # ------------------------------------------------------------------
    def read_state(self) -> tuple[int, int, int]:
        """(streak, loss_total, grad_total) — one tiny d2h read."""
        self.anomaly_state.map_read()
        s = self.anomaly_state.mem
        return int(s[0]), int(s[1]), int(s[2])

    def reset_streak(self) -> None:
        """Zero the consecutive-anomaly streak (post-rollback), keeping
        the monotone totals the metric deltas ride on."""
        self.anomaly_state.map_write()
        self.anomaly_state.mem[0] = 0
