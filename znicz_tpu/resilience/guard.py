"""Training anomaly guard: on-device finite checks, skip-don't-poison.

The failure mode this kills: one non-finite training step (bad batch,
overflowing logits, a flipped bit on the wire) used to poison the
weights forever — every later step multiplies NaN by something and the
run is dead long before a human reads the loss curve.

Design (all inside the EXISTING jit region — zero new compiles on
warmed paths, ``tests/test_retrace_guard.py``):

- the evaluator seeds a device-resident ``step_flags`` f32[2] vector
  each step: ``[running_ok, loss_ok]``, both = isfinite(step loss);
- every weighted GD unit folds ``isfinite(‖grad‖²)`` into
  ``running_ok`` and applies its parameter update through
  ``where(ok, new, old)`` — a non-finite step leaves weights AND
  momentum untouched (see ``GradientDescentBase._apply_param_xla``);
- this unit runs LAST in the region and maintains
  ``anomaly_state`` int32[3] = ``[consecutive_streak,
  loss_anomalies_total, grad_anomalies_total]`` on device;
- the Decision unit reads the state each fire (host control plane),
  translates the totals into ``znicz_step_anomalies_total{kind}`` /
  ``znicz_recoveries_total{kind=anomaly_step}`` registry deltas, and
  after K consecutive anomalies (``engine.anomaly_rollback_k``,
  default 5) asks the workflow to roll back to the Snapshotter's last
  good checkpoint (the round-10 mid-epoch resume path) and continue.

Fault injection (``train.nonfinite_loss`` / ``train.nonfinite_grad``):
when the active fault plan configures either site, the guard allocates
a ``fault_inject`` f32[2] leaf the evaluator adds into the step loss /
the err_output seed — the NaN rides a leaf VALUE, so injecting never
recompiles, and the poisoned numbers flow through the real data path
(the gradients genuinely go non-finite).  Without a plan the leaf is
never allocated and the traced program is byte-identical to a
guard-only build.

Gate: ``root.common.engine.anomaly_guard`` (default on) — built by
``StandardWorkflow``; the measured warmed-step overhead is within
noise (PERF.md round 11).
"""

from __future__ import annotations

import numpy as np

from znicz_tpu.accelerated_units import AcceleratedUnit
from znicz_tpu.loader.base import TRAIN
from znicz_tpu.memory import Vector
from znicz_tpu.resilience import faults as _faults

#: the two training injection sites this unit hosts
TRAIN_SITES = ("train.nonfinite_loss", "train.nonfinite_grad")


class AnomalyGuard(AcceleratedUnit):
    """Region member that finalizes the per-step anomaly verdict.

    Trace order: loader → forwards → evaluator → backwards → **guard**
    — by the time this unit runs, ``step_flags[0]`` has been ANDed by
    the evaluator (loss finite) and every weighted GD (grad finite).
    """

    # per-step transients + process-lifetime totals: neither belongs in
    # a checkpoint (restoring old totals would run the host-side metric
    # deltas backwards)
    SNAPSHOT_EXCLUDE = ("step_flags", "anomaly_state", "fault_inject",
                        "sdc_fingerprint", "sdc_inject")

    def __init__(self, workflow, name: str | None = None, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        from znicz_tpu.resilience import integrity as _integrity
        #: [running_ok, loss_ok] — seeded by the evaluator each step,
        #: ANDed by each GD unit, read+committed here
        self.step_flags = Vector(name=f"{self.name}.step_flags")
        #: [consecutive_streak, loss_total, grad_total]
        self.anomaly_state = Vector(name=f"{self.name}.anomaly_state")
        #: [loss_add, grad_add] — 0.0 normally, NaN on injected steps;
        #: allocated ONLY when a fault plan configures a train site
        self.fault_inject: Vector | None = (
            Vector(name=f"{self.name}.fault_inject")
            if _faults.site_configured(*TRAIN_SITES) else None)
        #: round 19 SDC fingerprint state, committed by this unit —
        #: f32[5]: [0]=param fp claimed this step (each weighted GD
        #: folds its POST-update checksum in), [1]=gradient fp,
        #: [2]=pre-update refold of the STORED params, [3]=sticky
        #: self-check mismatch count (a param that mutated BETWEEN
        #: step k's post-update fold and step k+1's pre-update refold
        #: was corrupted by THIS chip's memory — the flip_param
        #: signature, detectable at any later vote), [4]=previous
        #: step's claimed fp (the self-check's reference).  Slots
        #: 0..2 are zero-seeded by the evaluator per train step; see
        #: resilience.integrity.
        self.sdc_fingerprint: Vector | None = (
            Vector(name=f"{self.name}.sdc_fingerprint")
            if _integrity.enabled() else None)
        #: [param_flip_scale, grad_flip_scale] — 0.0 normally; on an
        #: injected step a large multiplier delta the GD units apply
        #: to one element (``value * (1 + scale)`` — exact identity at
        #: scale 0, an exponent-scale corruption when armed).  Only
        #: allocated when a fault plan configures an sdc train site.
        self.sdc_inject: Vector | None = (
            Vector(name=f"{self.name}.sdc_inject")
            if _faults.site_configured(*_integrity.SDC_TRAIN_SITES)
            else None)
        #: host mirror of the last totals the Decision translated into
        #: registry counters (delta base)
        self._metric_base = (0, 0)
        self._last_inject = (False, False)
        self._last_sdc = (False, False)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        self.step_flags.reset(np.ones(2, dtype=np.float32))
        self.anomaly_state.reset(np.zeros(3, dtype=np.int32))
        self.init_vectors(self.step_flags, self.anomaly_state)
        if self.fault_inject is not None:
            self.fault_inject.reset(np.zeros(2, dtype=np.float32))
            self.init_vectors(self.fault_inject)
        if self.sdc_fingerprint is not None:
            self.sdc_fingerprint.reset(np.zeros(5, dtype=np.float32))
            self.init_vectors(self.sdc_fingerprint)
        if self.sdc_inject is not None:
            self.sdc_inject.reset(np.zeros(2, dtype=np.float32))
            self.init_vectors(self.sdc_inject)
        self._metric_base = (0, 0)
        self._last_inject = (False, False)
        self._last_sdc = (False, False)

    # ------------------------------------------------------------------
    # host control plane: arm/disarm the injection leaf per step
    # ------------------------------------------------------------------
    def host_run(self) -> None:
        loader = getattr(self.workflow, "loader", None)
        on_train = (loader is None
                    or loader.minibatch_class == TRAIN)
        inj = self.fault_inject
        if inj is not None and inj:
            want = ((bool(_faults.fire("train.nonfinite_loss")),
                     bool(_faults.fire("train.nonfinite_grad")))
                    if on_train else (False, False))
            if want != self._last_inject:
                # leaf value unchanged: no host write, no upload
                self._last_inject = want
                inj.map_invalidate()
                inj.mem[...] = [np.nan if want[0] else 0.0,
                                np.nan if want[1] else 0.0]
                if self.device is not None \
                        and not self.device.is_host_only:
                    inj.unmap()
        sdc = self.sdc_inject
        if sdc is not None and sdc:
            from znicz_tpu.parallel.process_shard import process_info
            pidx = process_info()[0]
            pay_p = (_faults.fire("sdc.flip_param", process=pidx)
                     if on_train else None)
            if pay_p is not None:
                # the param flip happens HOST-SIDE between dispatches:
                # a partition-proof, strictly process-local mutation of
                # the stored buffer (an in-program scatter would be
                # re-sharded by GSPMD onto the element's OWNER device,
                # silently no-opping a flip targeted at any other
                # process).  Landing between step k's post-update fold
                # and step k+1's pre-update refold is exactly the
                # memory-corruption signature the sticky self-check
                # localizes.
                self._host_flip_param(
                    float(pay_p.get("factor", 2.0 ** 16)))
            pay_g = (_faults.fire("sdc.flip_grad", process=pidx)
                     if on_train else None)
            want_sdc = (False, pay_g is not None)
            if want_sdc != self._last_sdc:
                self._last_sdc = want_sdc
                sdc.map_invalidate()
                # ``value * (1 + scale)``: an exponent-scale flip when
                # armed, an exact float identity (×1.0) when not
                sdc.mem[...] = [
                    0.0,
                    float(pay_g.get("factor", 2.0 ** 16)) - 1.0
                    if pay_g is not None else 0.0]
                if self.device is not None \
                        and not self.device.is_host_only:
                    sdc.unmap()

    def _host_flip_param(self, factor: float) -> None:
        """Multiply element 0 of the first weighted GD's parameter
        tensor in THIS process's stored copy (d2h of the local shard,
        host mutate, per-process re-upload — no collective, no
        recompile: the leaf keeps its shape/sharding)."""
        for gd_unit in getattr(self.workflow, "gds", []):
            vec = getattr(gd_unit, "weights", None)
            if vec is None or not vec:
                continue
            vec.map_write()
            flat = vec.mem.reshape(-1)
            flat[0] = flat[0] * factor
            if self.device is not None \
                    and not self.device.is_host_only:
                vec.unmap()
            self.warning("sdc.flip_param injected: %s[0] ×%g "
                         "(process-local memory corruption)",
                         vec.name, factor)
            return

    # ------------------------------------------------------------------
    # the per-step commit (inside the region on XLA; eager on numpy)
    # ------------------------------------------------------------------
    def region_key(self) -> tuple:
        # the SDC self-check only runs on TRAIN steps (eval steps skip
        # the GD folds, so the per-step slots are stale there); the
        # evaluator already keys the region on minibatch_class, so
        # this adds zero NEW program variants
        loader = getattr(self.workflow, "loader", None)
        return (int(loader.minibatch_class)
                if loader is not None else -1,)

    def _on_train(self) -> bool:
        loader = getattr(self.workflow, "loader", None)
        return loader is None or loader.minibatch_class == TRAIN

    def xla_run(self) -> None:
        import jax.numpy as jnp
        from znicz_tpu.accelerated_units import current_accum_phase
        phase = current_accum_phase()
        if phase is not None and phase[0] != "apply":
            # accumulation microbatch (round 20): no parameter was
            # touched and no fingerprint folded — the verdict for the
            # whole accumulated step commits once, in the apply-phase
            # body (the flags keep ANDing across microbatches)
            return
        flags = self.step_flags.devmem
        ok = flags[0] > 0.5
        loss_ok = flags[1] > 0.5
        st = self.anomaly_state.devmem
        one = jnp.ones((), dtype=st.dtype)
        zero = jnp.zeros((), dtype=st.dtype)
        self.anomaly_state.devmem = jnp.stack([
            jnp.where(ok, zero, st[0] + 1),
            st[1] + jnp.where(loss_ok, zero, one),
            st[2] + jnp.where(loss_ok & ~ok, one, zero)])
        fpv = self.sdc_fingerprint
        if fpv is not None and fpv and self._on_train():
            # self-check: last step's POST-update claimed fp vs this
            # step's PRE-update refold of the stored params — a
            # mutation between the two happened in THIS chip's memory
            # outside any computation (sdc.flip_param's signature);
            # the sticky count localizes the culprit at any later vote
            fp = fpv.devmem
            prev, pre = fp[4], fp[2]
            bad = (prev != 0.0) & (jnp.abs(pre - prev)
                                   > 1e-5 * jnp.maximum(jnp.abs(prev),
                                                        1.0))
            fpv.devmem = jnp.stack([
                fp[0], fp[1], fp[2],
                fp[3] + jnp.where(bad, 1.0, 0.0), fp[0]])
        if phase is not None:
            # apply phase: the accumulated step is committed — reset
            # the flags so the NEXT step's first accumulation
            # microbatch ANDs into a clean [1, 1] (the non-accum path
            # keeps the historical evaluator overwrite instead)
            self.step_flags.devmem = jnp.ones(2, dtype=jnp.float32)

    def numpy_run(self) -> None:
        flags = self.step_flags.mem
        ok = bool(flags[0] > 0.5)
        loss_ok = bool(flags[1] > 0.5)
        st = self.anomaly_state.mem
        st[0] = 0 if ok else st[0] + 1
        if not loss_ok:
            st[1] += 1
        elif not ok:
            st[2] += 1
        fpv = self.sdc_fingerprint
        if fpv is not None and fpv and self._on_train():
            fp = fpv.mem
            prev, pre = float(fp[4]), float(fp[2])
            if prev != 0.0 and abs(pre - prev) \
                    > 1e-5 * max(abs(prev), 1.0):
                fp[3] += 1.0
            fp[4] = fp[0]

    # ------------------------------------------------------------------
    # host-side readers (Decision unit / rollback)
    # ------------------------------------------------------------------
    def read_state(self) -> tuple[int, int, int]:
        """(streak, loss_total, grad_total) — one tiny d2h read."""
        self.anomaly_state.map_read()
        s = self.anomaly_state.mem
        return int(s[0]), int(s[1]), int(s[2])

    def reset_streak(self) -> None:
        """Zero the consecutive-anomaly streak (post-rollback), keeping
        the monotone totals the metric deltas ride on."""
        self.anomaly_state.map_write()
        self.anomaly_state.mem[0] = 0

    def read_sdc_fingerprint(self) -> np.ndarray | None:
        """Host copy of the f32[5] fingerprint state (one tiny d2h at
        the sentinel's vote/audit cadence); None when absent or not
        the expected shape (population-stacked state)."""
        fpv = self.sdc_fingerprint
        if fpv is None or not fpv:
            return None
        fpv.map_read()
        arr = np.asarray(fpv.mem, dtype=np.float64).ravel()
        return arr if arr.size == 5 else None

    def reset_sdc_fingerprint(self) -> None:
        """Zero the fingerprint state after ANY in-process restore of
        older weights (anomaly rollback, SDC rollback): the previous
        claimed fp no longer describes the live buffers, so the next
        self-check must start from scratch instead of false-alarming."""
        fpv = self.sdc_fingerprint
        if fpv is None or not fpv:
            return
        fpv.map_write()
        fpv.mem[...] = 0.0
        if self.device is not None and not self.device.is_host_only:
            fpv.unmap()
