"""Train-to-serve publication: snapshot watcher, canary gate, rollback.

Veles's defining trick (PAPER.md SURVEY §0) was asynchronous
master–slave learning — the master kept *serving* the current model
while slaves streamed updates in.  This module is the modern rebuild's
control plane for that loop, round 13's glue between the round-11
digest machinery and the round-13 ``swap_weights`` engines:

- **publication** (training side) — :func:`publish_bundle` /
  :class:`WeightPublisher`: the trained forward chain is exported to a
  handoff directory as ``<prefix>_v<version>.npz`` with a ``.sha256``
  sidecar (round-11 snapshot discipline applied to serving bundles),
  versions strictly monotonic, writes atomic (tmp + rename) so a
  reader never sees a torn file;
- **watching** (serving side) — :class:`PublicationWatcher`: polls the
  directory, loads ONLY digest-verified bundles, falls back to the
  newest older good version when the latest is corrupt (the corrupt
  file is remembered and never retried), and tracks the monotonic
  version it has surfaced;
- **canary gating + automatic rollback** — :class:`SwapController`:
  before promotion a candidate is scored by a shadow evaluator
  (:func:`classifier_score` runs the compile-free numpy oracle on a
  held-out stream, so canarying never touches the serving AOT
  programs or the compile counters); a candidate whose score regresses
  beyond ``engine.swap_guard_margin`` is **rejected** and the
  incumbent keeps serving.  A promoted model is on *probation* for
  ``engine.swap_probation_steps`` served requests: if the engine turns
  unhealthy (breaker open, or the ``swap.probation_fail`` chaos site
  fires) the controller swaps straight back to the prior version —
  **rolled_back** — and quarantines the bad candidate.

Every verdict is a registry series (``znicz_swaps_total{outcome=
promoted|rejected|rolled_back}``, ``znicz_model_version``,
``znicz_swap_duration_seconds``, ``znicz_publishes_total``,
``znicz_snapshot_age_seconds``) so the soak harness and the chaos
dryrun attest the whole pipeline from the same ``/metrics`` feed
Prometheus scrapes.  Chaos sites: ``publish.corrupt`` (bundle bytes
flipped after the digest → the watcher must reject) and the two swap
sites above.
"""

from __future__ import annotations

import os
import re
import time

import numpy as np

from znicz_tpu.observe import metrics as _metrics
from znicz_tpu.observe import tracing as _tracing
from znicz_tpu.resilience import faults as _faults
from znicz_tpu.units import Unit
from znicz_tpu.utils.config import root
from znicz_tpu.utils.logger import Logger

# NOTE: znicz_tpu.utils.snapshotter imports this package (faults) at
# module level, so its SnapshotCorrupt/_sha256_file are imported
# lazily inside functions here to keep the cycle one-directional at
# import time.

__all__ = ["publish_bundle", "published_versions", "PublicationWatcher",
           "SwapController", "WeightPublisher", "classifier_score",
           "mark_artifact_written"]

#: ``<prefix>_v<version>.npz`` — the publication naming contract
_VERSION_RE = re.compile(r"_v(\d+)\.npz$")

#: last-good artifact timestamps feeding znicz_snapshot_age_seconds
#: (a live callback gauge: /readyz sees a stalled trainer as growing
#: age without any writer-side heartbeat)
_last_written: dict[str, float] = {}


def mark_artifact_written(source: str) -> None:
    """Record a good artifact write for ``source`` and keep its
    ``znicz_snapshot_age_seconds`` child live (the Snapshotter and the
    publisher both report through this)."""
    _last_written[source] = time.time()
    _metrics.snapshot_age_seconds(source).set_function(
        lambda s=source: time.time() - _last_written[s])


def published_versions(directory: str,
                       prefix: str = "model") -> list[tuple[int, str]]:
    """All published ``(version, path)`` pairs in ``directory``,
    ascending — including files that may fail digest verification
    (version allocation must see them, the watcher filters them)."""
    out = []
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    for name in names:
        if not name.startswith(f"{prefix}_v"):
            continue
        m = _VERSION_RE.search(name)
        if m:
            out.append((int(m.group(1)),
                        os.path.join(directory, name)))
    out.sort()
    return out


def _fence_publish(directory: str, prefix: str,
                   entry: list[tuple[int, str]]) -> tuple[int, str]:
    """Non-master half of a lockstep multi-process publish: wait for
    process 0's new version to land COMPLETE (its ``.sha256`` sidecar
    exists — the sidecar is written strictly after the atomic data
    replace).  ``entry`` is the version listing at call entry; the
    fence is satisfied by any complete version newer than the newest
    complete one at entry, or by the entry-newest itself when its
    sidecar is FRESH (process 0 finished before this process arrived
    at the lockstep site).  Bounded by
    ``engine.publish_fence_timeout_s`` (default 60 s); on timeout the
    newest complete version is returned with a warning rather than
    stranding the gang."""
    import logging
    import time as _time

    from znicz_tpu.utils.config import root

    log = logging.getLogger("publisher")
    entry_wall = _time.time()
    entry_complete = max(
        (v for v, p in entry if os.path.exists(f"{p}.sha256")),
        default=0)
    timeout = float(root.common.engine.get("publish_fence_timeout_s",
                                           60.0))
    deadline = _time.monotonic() + timeout

    def newest_complete() -> tuple[int, str] | None:
        done = [(v, p) for v, p in published_versions(directory, prefix)
                if os.path.exists(f"{p}.sha256")]
        return done[-1] if done else None

    while True:
        got = newest_complete()
        if got is not None:
            version, path = got
            try:
                side_mtime = os.path.getmtime(f"{path}.sha256")
            except OSError:
                side_mtime = 0.0
            if version > entry_complete \
                    or side_mtime >= entry_wall - 2.0:
                return version, path
        if _time.monotonic() >= deadline:
            if got is not None:
                log.warning(
                    "publish fence in %s timed out after %.0fs — "
                    "returning the newest complete version v%d",
                    directory, timeout, got[0])
                return got
            raise OSError(
                f"publish fence in {directory} timed out after "
                f"{timeout:.0f}s with no complete version — process 0 "
                f"never published (shared filesystem not mounted on "
                f"every host, or the master publish failed)")
        _time.sleep(0.02)


def _quantize_staged(tmp: str, mode: str, calib) -> dict:
    """Round 21: rewrite the staged (not yet visible) bundle as its
    int8 twin.  The accuracy gate runs HERE, before any bytes are
    published: when the calibration stream shows the quantized numpy
    oracle regressing past ``engine.swap_guard_margin``, the f32
    bundle ships instead and the gate verdict is logged.  The
    ``quant.calib_corrupt`` chaos site fires inside
    :func:`~znicz_tpu.serving.quantize.quantize_bundle` AFTER the
    gate — a mis-scaled bundle then publishes cleanly and the
    downstream canary is the only defense left, which is exactly what
    the chaos drill proves."""
    import io
    import json
    import logging

    from znicz_tpu.export import read_bundle
    from znicz_tpu.serving import quantize as _quant
    if mode != "int8":
        raise ValueError(f"unsupported quantize mode {mode!r}")
    manifest, params = read_bundle(tmp)
    qman, qparams, info = _quant.quantize_bundle(manifest, params,
                                                 calib=calib)
    if not info.get("quantized"):
        return info
    margin = float(root.common.engine.get("swap_guard_margin", 0.02))
    delta = info.get("acc_delta")
    if delta is not None and delta > margin \
            and not info.get("corrupted"):
        logging.getLogger("publisher").warning(
            "int8 calibration regressed %.4f > guard margin %.4f — "
            "publishing the f32 bundle instead", delta, margin)
        info["gated"] = True
        return info
    arrays = {k: np.asarray(v) for k, v in qparams.items()}
    arrays["manifest"] = np.frombuffer(
        json.dumps(qman).encode(), dtype=np.uint8)
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    with open(tmp, "wb") as f:
        f.write(buf.getvalue())
    info["gated"] = False
    return info


def publish_bundle(workflow, directory: str,
                   prefix: str = "model", *,
                   quantize: str | None = None,
                   calib: tuple | None = None) -> tuple[int, str]:
    """Export ``workflow``'s trained forward chain into the handoff
    directory as the next monotonic version, with a sha256 sidecar.

    Write order is crash-safe and reader-safe: the bundle is exported
    to a temp name, its digest computed, then atomically renamed into
    place BEFORE the sidecar lands — a watcher polling mid-publish
    sees either nothing or a complete file (a missing sidecar just
    defers pickup to the next poll).  The ``publish.corrupt`` chaos
    site flips bytes AFTER the digest is computed, producing exactly
    the torn-publish failure the watcher must reject.

    ``quantize="int8"`` (round 21) rewrites the staged bundle as its
    per-channel int8 twin before the digest; ``calib=(x, y)`` is the
    canary/shadow stream the accuracy gate scores both arms on — a
    regression past ``engine.swap_guard_margin`` ships the f32 bundle
    instead."""
    from znicz_tpu.export import export_forward
    from znicz_tpu.parallel.process_shard import process_info
    from znicz_tpu.utils.snapshotter import _sha256_file
    os.makedirs(directory, exist_ok=True)
    existing = published_versions(directory, prefix)
    pidx, pcount = process_info()
    if pcount > 1 and pidx != 0:
        # round 18: only process 0 writes shared publish artifacts —
        # the rest fence on the new version's digest sidecar appearing
        return _fence_publish(directory, prefix, existing)
    version = (existing[-1][0] + 1) if existing else 1
    final = os.path.join(directory, f"{prefix}_v{version:06d}.npz")
    tmp = f"{final}.{os.getpid()}.staging"
    with _tracing.TRACER.span("publish_bundle", cat="snapshot",
                              version=version):
        export_forward(workflow, tmp)
        if quantize is not None:
            _quantize_staged(tmp, quantize, calib)
        digest = _sha256_file(tmp)
        if _faults.fire("publish.corrupt") is not None:
            with open(tmp, "r+b") as f:  # digest now lies about this
                f.seek(max(0, os.path.getsize(tmp) // 2))
                f.write(b"\xde\xad\xbe\xef")
        os.replace(tmp, final)
        side_tmp = f"{final}.sha256.{os.getpid()}.tmp"
        with open(side_tmp, "w") as f:
            f.write(digest + "\n")
        os.replace(side_tmp, f"{final}.sha256")
    source = f"publish:{prefix}"
    _metrics.publishes_total(source).inc()
    mark_artifact_written(source)
    # round 23: pack the trainer's persisted AOT programs for this
    # architecture beside the weights (best-effort, after the weights
    # + sidecar are fully durable) — a scale-out replica or hot-swap
    # candidate imports them and comes up compile-free
    from znicz_tpu.serving import aot_cache as _aot
    _aot.publish_programs(directory, prefix, version, final)
    return version, final


class PublicationWatcher(Logger):
    """Serving-side poller over a publication directory.

    :meth:`poll` surfaces the newest digest-verified bundle whose
    version exceeds everything seen so far, as ``(version, path,
    manifest, params)`` — or ``None`` when nothing new verifies.  A
    corrupt newest falls back to the newest OLDER good version
    (counted as ``znicz_snapshot_failures_total{op=publish}`` +
    ``znicz_recoveries_total{kind=publish_fallback}``); corrupt or
    rejected versions are quarantined and never retried."""

    def __init__(self, directory: str, prefix: str = "model") -> None:
        super().__init__()
        self.directory = directory
        self.prefix = prefix
        self.version = 0      # newest version surfaced so far
        self._bad: set[int] = set()

    def mark_bad(self, version: int) -> None:
        """Quarantine a version (the controller calls this for canary
        rejections and probation rollbacks so a bad model is never
        re-promoted)."""
        self._bad.add(int(version))

    def _verify(self, path: str) -> None:
        from znicz_tpu.utils.snapshotter import (SnapshotCorrupt,
                                                 _sha256_file)
        sidecar = f"{path}.sha256"
        if not os.path.exists(sidecar):
            raise SnapshotCorrupt(
                f"{path}: published bundle has no sha256 sidecar "
                f"(incomplete publish?)")
        with open(sidecar) as f:
            want = f.read().strip()
        got = _sha256_file(path)
        if got != want:
            raise SnapshotCorrupt(
                f"{path}: sha256 {got[:12]}… != sidecar {want[:12]}…")

    def poll(self):
        """Newest unseen good bundle, or ``None``."""
        from znicz_tpu.export import read_bundle
        fell_back = False
        for version, path in sorted(
                published_versions(self.directory, self.prefix),
                reverse=True):
            if version <= self.version:
                break  # older than what we already surfaced
            if version in self._bad:
                continue  # quarantined; an older unseen may still do
            try:
                self._verify(path)
                manifest, params = read_bundle(path)
            except Exception as exc:  # noqa: BLE001 — corrupt publish
                _metrics.snapshot_failures("publish").inc()
                self._bad.add(version)
                fell_back = True
                self.warning("published bundle rejected: %s", exc)
                continue  # fall back to the next older version
            self.version = version
            if fell_back:
                _metrics.recoveries("publish_fallback").inc()
            # round 23: import the programs pack published beside the
            # weights into the local AOT cache BEFORE surfacing the
            # bundle, so warmup() deserializes instead of compiling.
            # A corrupt pack is rejected inside import_programs (the
            # fallback counted); the verified WEIGHTS still surface —
            # programs are an accelerant, never a gate.
            from znicz_tpu.serving import aot_cache as _aot
            imported = _aot.import_programs(path)
            if imported:
                self.debug("imported %d published program(s) for v%d",
                           imported, version)
            return version, path, manifest, params
        return None


def classifier_score(x, y):
    """A shadow-evaluator ``score_fn(manifest, params) -> accuracy``
    over a held-out stream, running the COMPILE-FREE numpy oracle —
    canary scoring must never add a serving-AOT compile, so the
    candidate is rebuilt on the host path, not the XLA path.  Works
    for one-shot classifiers and next-token LM bundles alike (the
    export chain ends in a softmax head either way)."""
    x = np.asarray(x)
    y = np.asarray(y)

    def score(manifest: dict, params: dict) -> float:
        from znicz_tpu.backends import NumpyDevice
        from znicz_tpu.export import ExportedModel
        model = ExportedModel(dict(manifest), dict(params),
                              device=NumpyDevice())
        return float((model.predict_classes(x) == y).mean())

    return score


class SwapController(Logger):
    """The promote/reject/rollback state machine over one engine.

    Drive it with :meth:`tick` from any host loop (the soak bench
    ticks between replay submits; the dryrun ticks between waves).
    Each tick first settles probation, then — when no probation is
    active — polls the watcher and runs one candidate through
    canary → promote.

    ``score_fn(manifest, params) -> float`` (higher is better) is the
    shadow evaluator; ``None`` disables the canary gate (every good
    publish promotes).  ``guard_margin`` / ``probation_steps`` default
    to ``engine.swap_guard_margin`` (0.02) /
    ``engine.swap_probation_steps`` (50 served requests)."""

    def __init__(self, engine, watcher: PublicationWatcher,
                 score_fn=None, *, guard_margin: float | None = None,
                 probation_steps: int | None = None) -> None:
        super().__init__()
        self.engine = engine
        self.watcher = watcher
        self.score_fn = score_fn
        self.guard_margin = float(
            root.common.engine.get("swap_guard_margin", 0.02)
            if guard_margin is None else guard_margin)
        self.probation_steps = int(
            root.common.engine.get("swap_probation_steps", 50)
            if probation_steps is None else probation_steps)
        #: the serving truth: what the engine is running right now
        self._incumbent: dict | None = None
        self._probation: dict | None = None

    # ------------------------------------------------------------------
    def _served(self) -> int:
        return int(self.engine.stats()["served"])

    def _ensure_incumbent(self) -> dict:
        if self._incumbent is None:
            manifest, params = self.engine.current_bundle()
            self._incumbent = {"version": self.engine.model_version,
                               "manifest": manifest, "params": params,
                               "score": None}
        return self._incumbent

    def _score(self, manifest, params) -> float | None:
        if self.score_fn is None:
            return None
        return float(self.score_fn(manifest, params))

    @property
    def on_probation(self) -> bool:
        return self._probation is not None

    def _quant_outcome(self, manifest, outcome: str) -> None:
        """Quantized candidates get their own canary ledger
        (``znicz_quant_canary_total{outcome}``, round 21) — the quant
        dryrun and the fleet dashboards watch the int8 promote/reject
        ratio separately from ordinary weight refreshes."""
        if manifest and manifest.get("quant"):
            _metrics.quant_canary(
                getattr(self.engine, "_obs_id", "engine"),
                outcome).inc()

    # ------------------------------------------------------------------
    def tick(self) -> list[str]:
        """One control-plane step; returns human-readable events."""
        events: list[str] = []
        self._check_probation(events)
        if self._probation is None:
            got = self.watcher.poll()
            if got is not None:
                self._consider(*got, events=events)
        return events

    def _consider(self, version: int, path: str, manifest: dict,
                  params: dict, events: list[str]) -> None:
        from znicz_tpu.export import SwapIncompatible
        incumbent = self._ensure_incumbent()
        cand_score = self._score(manifest, params)
        if cand_score is not None:
            payload = _faults.fire("swap.canary_regress")
            if payload is not None:
                cand_score -= float(payload.get("penalty", 1.0))
            if incumbent["score"] is None:
                incumbent["score"] = self._score(
                    incumbent["manifest"], incumbent["params"])
            if cand_score < incumbent["score"] - self.guard_margin:
                self.engine.record_swap_outcome("rejected")
                self._quant_outcome(manifest, "rejected")
                self.watcher.mark_bad(version)
                msg = (f"rejected v{version}: canary "
                       f"{cand_score:.4f} < incumbent "
                       f"{incumbent['score']:.4f} − margin "
                       f"{self.guard_margin}")
                self.warning(msg)
                events.append(msg)
                return
        try:
            self.engine.swap_weights((manifest, params),
                                     version=version)
        except SwapIncompatible as exc:
            self.engine.record_swap_outcome("rejected")
            self._quant_outcome(manifest, "rejected")
            self.watcher.mark_bad(version)
            msg = f"rejected v{version}: {exc}"
            self.warning(msg)
            events.append(msg)
            return
        self._quant_outcome(manifest, "promoted")
        self._incumbent = {"version": version, "manifest": manifest,
                           "params": params, "score": cand_score}
        self._probation = {"prior": incumbent, "version": version,
                           "until": self._served()
                           + self.probation_steps,
                           "t0": time.monotonic()}
        events.append(f"promoted v{version} (probation for "
                      f"{self.probation_steps} served requests)")

    def _check_probation(self, events: list[str]) -> None:
        p = self._probation
        if p is None:
            return
        unhealthy = _faults.fire("swap.probation_fail") is not None
        if not unhealthy:
            # the breaker IS the health signal: a model whose
            # dispatches fail (or stall the queue) opens it within
            # the probation window
            unhealthy = getattr(self.engine, "breaker_state",
                                "closed") == "open" \
                or not self.engine.ready()
        if unhealthy:
            prior = p["prior"]
            self.engine.swap_weights(
                (prior["manifest"], prior["params"]),
                version=prior["version"], outcome="rolled_back")
            self.watcher.mark_bad(p["version"])
            if self._incumbent is not None:
                self._quant_outcome(self._incumbent["manifest"],
                                    "rolled_back")
            self._incumbent = prior
            self._probation = None
            msg = (f"rolled back v{p['version']} → "
                   f"v{prior['version']} (probation tripped)")
            self.warning(msg)
            events.append(msg)
            return
        if self._served() >= p["until"]:
            self._probation = None
            events.append(f"v{p['version']} passed probation")


class WeightPublisher(Unit):
    """Epoch side-chain unit: publish the forward chain every N epochs
    (wire with ``StandardWorkflow.link_weight_publisher`` — it rides
    the decision's ``epoch_ended`` gate exactly like the snapshotter
    rides ``improved``).  This is the training half of the continuous
    soak loop: train → publish → the serving process's watcher picks
    it up → canary → hot swap, all while requests keep flowing."""

    def __init__(self, workflow, name: str | None = None,
                 directory: str | None = None, prefix: str = "model",
                 every_n_epochs: int = 1,
                 quantize: str | None = None,
                 calib: tuple | None = None, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.directory = directory or os.path.join(
            str(root.common.dirs.snapshots), "published")
        self.prefix = prefix
        self.every = max(1, int(every_n_epochs))
        self.quantize = quantize
        self.calib = calib
        self._epochs = 0
        self.published: list[tuple[int, str]] = []

    def run(self) -> None:
        self._epochs += 1
        if self._epochs % self.every:
            return
        import jax
        if jax.process_count() > 1 and jax.process_index() != 0:
            # single-writer discipline (the handoff directory is
            # shared); parameter reads here are replicated leaves, so
            # non-master processes can simply skip
            return
        version, path = publish_bundle(self.workflow, self.directory,
                                       self.prefix,
                                       quantize=self.quantize,
                                       calib=self.calib)
        self.published.append((version, path))
        self.info("published model v%d → %s", version, path)
