"""Deterministic fault injection: named sites, seeded recipes.

The chaos contract: a fault recipe is **configuration**
(``root.common.engine.faults``), every injection point in the
framework is a **named site**, and a given ``(recipe, seed)`` replays
the identical fault sequence — so a chaos soak is as reproducible as
the counter-based shuffle made the data plane.

Usage (the injecting side)::

    from znicz_tpu.resilience import faults as _faults
    payload = _faults.fire("loader.corrupt_shard", shard=3)
    if payload is not None:
        raise ShardReadError(3, "injected corrupt shard")

``fire`` returns ``None`` in one dict lookup when no plan is
configured — the zero-overhead-when-off guarantee every hot path
relies on.  When a plan is active, each call counts one *arrival* at
the site (optionally filtered by keyword context, e.g. only arrivals
for ``shard=3``) and the site's spec decides whether this arrival
fires.

Recipe forms (``root.common.engine.faults = {...}``), per site:

- ``3`` or ``[3, 7]`` — fire on exactly those arrival ordinals
  (1-based); each listed arrival is one counted fault event;
- ``{"at": [3]}`` — same, dict form (extra keys become the payload
  and double as context filters);
- ``{"after": 1}`` — fire on every arrival from that ordinal on — a
  *persistent* fault (a corrupt shard stays corrupt); counted as ONE
  fault event no matter how many reads hit it;
- ``{"p": 0.05}`` — fire each arrival with probability p from the
  plan's Philox stream (deterministic per seed); each fire is one
  event;
- ``True`` — shorthand for ``{"after": 1}``.

The reserved recipe key ``"_seed"`` (default 0) seeds the
probabilistic streams.  Any other spec key that also appears in the
``fire`` call's context must match for the arrival to count — e.g.
``{"shard": 1, "after": 1}`` only ever fires for ``fire(site,
shard=1)``.

Every fired event increments ``znicz_faults_injected_total{site}`` so
the dryrun tail and the tests attest injection counts from the same
series ``/metrics`` exposes.
"""

from __future__ import annotations

import threading
import zlib

import numpy as np

from znicz_tpu.observe import metrics as _metrics
from znicz_tpu.utils.config import root

#: the framework's named injection sites (the docstring of record —
#: greppable, and the recipe validator rejects unknown names so a typo
#: fails loudly instead of silently injecting nothing)
SITES = {
    "train.nonfinite_loss":
        "NaN added to the evaluator's per-step loss (rides the guard's "
        "device-resident inject leaf — no recompile)",
    "train.nonfinite_grad":
        "NaN added to the evaluator's err_output seed — every weight "
        "gradient of the step goes non-finite while the loss stays "
        "clean",
    "loader.reader_death":
        "streaming producer thread raises mid-epoch (exercises the "
        "poison-pill propagation + bounded pipeline restart)",
    "loader.corrupt_shard":
        "a shard read raises as if its CRC failed; with {'after': n} "
        "the shard is persistently bad and must be quarantined",
    "loader.short_read":
        "a shard read raises as a transient short read (retry path)",
    "serving.program_error":
        "the serving dispatch raises before touching the AOT program "
        "(exercises the retry budget / breaker)",
    "serving.latency_spike":
        "the serving dispatch sleeps payload 'ms' (default 50) before "
        "running (exercises deadlines + queue-age shedding)",
    "snapshot.write_fail":
        "Snapshotter.write raises OSError mid-write (exercises "
        "tolerate-and-continue + retention of the last good snapshot)",
    "publish.corrupt":
        "publish_bundle corrupts the bundle bytes AFTER computing the "
        "sidecar digest — the serving-side watcher must reject the "
        "file on digest verification and keep the incumbent serving",
    "swap.canary_regress":
        "the candidate's canary score is penalized by payload "
        "'penalty' (default 1.0) so the swap gate must reject the "
        "publish (exercises guard-margin rejection)",
    "swap.probation_fail":
        "the post-promotion probation check reports the freshly "
        "promoted model unhealthy, forcing an automatic rollback to "
        "the prior version",
    "fleet.tenant_flood":
        "FleetEngine.tick injects a burst of payload 'n' (default 32) "
        "synthetic requests for payload 'tenant' (default the lowest-"
        "priority tenant) — admission must shed the flood inside that "
        "tenant's class without moving any other tenant's SLO",
    "fleet.model_corrupt":
        "ForgeRegistry.fetch treats the fetched bundle as failing its "
        "sha256 digest — the registry must QUARANTINE it and fall "
        "back to the newest older good version instead of handing "
        "corrupt bytes to a loader",
    "host.loss":
        "a training step boundary hard-kills this process (os._exit, "
        "no drain, no snapshot) as if the host vanished — filter with "
        "{'process': i}; the elastic supervisor must detect the loss "
        "(child exit / heartbeat timeout), reap the stranded gang, and "
        "restart on the surviving mesh from the newest good snapshot",
    "host.preempt":
        "a step boundary receives a simulated preemption notice "
        "(SIGTERM semantics): the worker supervisor requests the "
        "barriered checkpoint-on-signal and the whole gang exits "
        "EXIT_PREEMPTED after process 0's sha256 sidecar lands — "
        "filter with {'process': i}",
    "heartbeat.stall":
        "the heartbeat writer freezes its step counter while "
        "wall-clock beats continue and the step blocks for payload "
        "'sleep_s' (default 3600) — a hung collective's exact "
        "signature; the monitor must declare the process stalled "
        "within the stall timeout",
    "checkpoint.signal_corrupt":
        "the checkpoint-on-signal bytes are corrupted AFTER the "
        "sidecar digest is computed — resume must reject the file on "
        "digest verification and fall back to the newest older good "
        "snapshot",
    "fleet.replica_loss":
        "FleetEngine.tick kills one live replica of payload 'model' "
        "(default the first model) mid-traffic — routing must steer "
        "around the loss and the autoscaler must repair the group "
        "with zero high-priority request failures",
    "sdc.flip_param":
        "one element of a parameter tensor is silently multiplied by "
        "payload 'factor' (default 2^16) in THIS process's stored "
        "copy, HOST-SIDE between dispatches (an in-program scatter "
        "would be re-sharded by GSPMD onto the element's owner device "
        "and silently no-op on other processes) — the mutation lands "
        "between one step's post-update fingerprint fold and the next "
        "step's pre-update refold, exactly the memory-corruption "
        "signature the guard's sticky self-check localizes; filter "
        "with {'process': i} so ONE gang member diverges and the "
        "cross-replica vote must quarantine it",
    "sdc.flip_grad":
        "one element of the folded weight gradient is multiplied by "
        "payload 'factor' (default 2^16) BEFORE the update (rides the "
        "guard's sdc_inject device leaf — no recompile) — finite, "
        "plausible, wrong: the isfinite guard passes while the "
        "device's update diverges from the shadow oracle; the "
        "redundant-compute audit must catch the mismatch.  Drill "
        "single-process: under multi-process ZeRO-1 GSPMD may assign "
        "the scatter to the element's owner device",
    "sdc.serving_bitflip":
        "a serving replica's reply rows are corrupted post-program "
        "(column 0 scaled by payload 'factor') — plausible-but-wrong "
        "scores; the sampled shadow audit must re-score against the "
        "compile-free numpy oracle, correct the reply, and remove the "
        "replica via the ReplicaGroup repair path; filter with "
        "{'replica': id}",
    "quant.calib_corrupt":
        "publish-time int8 quantization mis-scales every per-channel "
        "weight scale by payload 'factor' (default 64) AFTER the "
        "calibration accuracy gate passed — a calibration bug that "
        "slips publication; the SwapController's canary must reject "
        "the bundle at the guard margin with the f32 incumbent still "
        "serving",
    "disagg.handoff_drop":
        "a prefill→decode page-table handoff is dropped in flight (the "
        "cross-pool transfer fails after the prefill pool already "
        "released its pages) — the DisaggEngine must retry the request "
        "on a fresh prefill pass with its token-budget reservation "
        "kept, reject it only past the retry budget, and leave the "
        "budget balanced() with every page reclaimed",
    "aotcache.corrupt":
        "a persisted AOT executable's payload bytes rot between the "
        "sha256 sidecar write and the next cold-start read (torn "
        "write, bit rot, truncated copy) — the cache's digest gate "
        "must quarantine the entry (renamed aside, never retried), "
        "count recoveries{aotcache_fallback}, and fall back to "
        "tracing with outputs bitwise-equal to the traced arm; a "
        "wrong program must never load",
    "observe.recorder_stall":
        "a flight-recorder journal write stalls/fails as if the disk "
        "filled or the device tore — the recorder must DROP the event "
        "(counting znicz_flightrecord_dropped_total) and return "
        "immediately: no dispatch, swap or restart may ever block on "
        "or fail from ops journaling",
}

#: spec keys that steer firing rather than ride the payload
_CONTROL_KEYS = ("at", "after", "p")


class FaultInjected(RuntimeError):
    """The exception injected faults raise where a real fault would."""


def _normalize(site: str, spec) -> dict:
    if spec is True:
        spec = {"after": 1}
    elif isinstance(spec, (int, np.integer)) and not isinstance(spec, bool):
        spec = {"at": [int(spec)]}
    elif isinstance(spec, (list, tuple)):
        spec = {"at": [int(a) for a in spec]}
    if not isinstance(spec, dict):
        raise ValueError(f"fault site '{site}': bad spec {spec!r}")
    if not any(k in spec for k in _CONTROL_KEYS):
        raise ValueError(
            f"fault site '{site}': spec needs one of {_CONTROL_KEYS}")
    return dict(spec)


class FaultPlan:
    """One chaos recipe: per-site firing specs + deterministic state.

    Thread-safe — loader reader pools, the serving scheduler thread
    and the training control plane all call :meth:`fire` concurrently.
    """

    def __init__(self, recipe: dict, seed: int | None = None) -> None:
        recipe = dict(recipe)
        self.seed = int(recipe.pop("_seed", 0) if seed is None else seed)
        unknown = sorted(set(recipe) - set(SITES))
        if unknown:
            raise ValueError(
                f"unknown fault site(s) {unknown} — see "
                f"znicz_tpu.resilience.faults.SITES")
        self._specs = {site: _normalize(site, spec)
                       for site, spec in recipe.items()}
        self._lock = threading.Lock()
        self._arrivals: dict[str, int] = {}
        self._events: dict[str, int] = {}
        self._rngs: dict[str, np.random.Generator] = {}

    # ------------------------------------------------------------------
    def _rng(self, site: str) -> np.random.Generator:
        gen = self._rngs.get(site)
        if gen is None:
            key = np.array([self.seed & ((1 << 64) - 1),
                            zlib.crc32(site.encode())], dtype=np.uint64)
            gen = self._rngs[site] = np.random.Generator(
                np.random.Philox(key=key))
        return gen

    def fire(self, site: str, **ctx):
        """One arrival at ``site``: the payload dict when the plan says
        this arrival faults, else ``None``."""
        spec = self._specs.get(site)
        if spec is None:
            return None
        with self._lock:
            for key, want in spec.items():
                if key in _CONTROL_KEYS:
                    continue
                if key in ctx and ctx[key] != want:
                    return None  # context mismatch: not our arrival
            n = self._arrivals.get(site, 0) + 1
            self._arrivals[site] = n
            fired = event = False
            if "at" in spec:
                fired = event = n in set(int(a) for a in spec["at"])
            elif "after" in spec:
                fired = n >= int(spec["after"])
                # a persistent fault is ONE event however often it is
                # observed (one corrupt shard, many reads of it)
                event = fired and not self._events.get(site)
            elif "p" in spec:
                fired = event = bool(
                    self._rng(site).random() < float(spec["p"]))
            if not fired:
                return None
            if event:
                self._events[site] = self._events.get(site, 0) + 1
                _metrics.faults_injected(site).inc()
        payload = {k: v for k, v in spec.items() if k not in _CONTROL_KEYS}
        payload.update(ctx)
        payload["site"] = site
        payload["arrival"] = n
        return payload

    # ------------------------------------------------------------------
    @property
    def events_fired(self) -> int:
        """Distinct fault events fired so far (what the dryrun tail
        attests as ``faults_injected``)."""
        with self._lock:
            return sum(self._events.values())

    def counts(self) -> dict:
        with self._lock:
            return dict(self._events)

    def configured_sites(self) -> set:
        return set(self._specs)

    def __repr__(self) -> str:
        return f"FaultPlan(seed={self.seed}, sites={sorted(self._specs)})"


# ----------------------------------------------------------------------
# the module-level gate every injection point calls
# ----------------------------------------------------------------------
def active() -> FaultPlan | None:
    """The configured plan, or None (the fast path: one dict lookup).
    A plain dict recipe in ``root.common.engine.faults`` is wrapped
    into a :class:`FaultPlan` on first touch and stored back, so its
    arrival counters persist for the run."""
    plan = root.common.engine.get("faults", None)
    if plan is None or plan is False:
        return None
    if not isinstance(plan, FaultPlan):
        if hasattr(plan, "as_dict"):  # the config tree nodified the
            plan = plan.as_dict()     # recipe dict on assignment
        plan = FaultPlan(plan)
        root.common.engine.faults = plan
    return plan


def fire(site: str, **ctx):
    """Arrival at a named site: payload dict when it faults, else
    None.  Zero work when no plan is configured."""
    plan = active()
    if plan is None:
        return None
    return plan.fire(site, **ctx)


def site_configured(*sites: str) -> bool:
    """True when the active plan injects at ANY of the given sites —
    lets initialize-time code (the guard's inject leaf) avoid touching
    the traced program when no training fault can ever fire."""
    plan = active()
    return plan is not None and bool(
        plan.configured_sites() & set(sites))
