"""Elastic multi-host supervision: heartbeats, preemption-safe
checkpoint-on-signal, automatic reshard-resume onto the surviving mesh.

Veles's master↔slave runtime tracked slave liveness over ZeroMQ and
redistributed work when a node vanished (reference:
``apply_data_from_slave``; SURVEY "master↔slave").  The pod-scale SPMD
replacement (round 17, ``jax.distributed``) was gang-scheduled and
*brittle*: one SIGTERM'd or hung process killed the whole job with no
detection, no drain and no restart.  This module is the supervisor
layer that composes the existing recovery prerequisites — partition
tables re-resolve onto any mesh, ZeRO-1 snapshots restore bitwise
across mesh sizes, the streaming loader re-slices its per-process 1/N
reads at the restored cursor — into preemption-proof elastic training:

- :class:`HeartbeatWriter` — every process beats ``(step counter,
  wall-clock)`` into a coordinator-visible channel (one atomic JSON
  file per process in ``ZNICZ_HEARTBEAT_DIR`` — a shared filesystem on
  real pods) and the observe registry
  (``znicz_heartbeat_age_seconds{process}``);
- :class:`HeartbeatMonitor` — the coordinator-side reader: a process
  is declared dead after ``engine.heartbeat_timeout_s`` of missed
  beats ("the host vanished") or a *stalled step counter* with fresh
  wall-clock beats ("the host is up but hung in a collective");
- :class:`WorkerSupervisor` — the in-process glue the Launcher
  attaches: per-step-boundary heartbeats, the ``host.loss`` /
  ``host.preempt`` / ``heartbeat.stall`` chaos sites, SIGTERM →
  *barriered checkpoint-on-signal* (every process checkpoints at the
  same step boundary; process 0 writes the sha256-sidecar snapshot,
  the rest fence on the sidecar appearing) and a self-watchdog that
  bounds time-in-step so a dead peer surfaces as a logged
  :class:`PeerLost` + prompt exit instead of an infinite gloo/ICI
  hang;
- :class:`ElasticSupervisor` — the gang owner: spawns one worker
  process per host, watches child exits + heartbeats, classifies
  failures (``znicz_host_losses_total{kind}``), kills the stranded
  gang, and relaunches on the *surviving* host set from the newest
  digest-verified snapshot (``znicz_elastic_restarts_total``) — the
  relaunched workers re-invoke
  :func:`znicz_tpu.parallel.distributed.ensure_initialized` with the
  reduced process count, the partition table re-resolves every
  placement onto the smaller mesh, and training continues.

Preemption contract: SIGTERM (or the ``host.preempt`` site) requests a
checkpoint at a near step boundary — the barrier step is the
requester's current step plus ``engine.preempt_barrier_steps`` so
every gang member reaches it in lockstep — then the whole gang exits
with :data:`EXIT_PREEMPTED`.  A TPU preemption therefore costs at most
the one in-flight step plus the checkpoint write.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Sequence

from znicz_tpu.observe import metrics as _metrics
from znicz_tpu.observe import recorder as _recorder
from znicz_tpu.resilience import faults as _faults
from znicz_tpu.utils.config import root
from znicz_tpu.utils.logger import Logger

#: gang exit code after a successful checkpoint-on-signal (EX_TEMPFAIL:
#: "resumable — relaunch me on the surviving host set")
EXIT_PREEMPTED = 75
#: self-watchdog exit code: this process's step stopped making progress
#: past ``engine.collective_timeout_s`` — a peer is gone and the
#: in-flight collective will never complete
EXIT_PEER_LOST = 113
#: round 19: the SDC sentinel confirmed THIS process's chip computes
#: wrong values — the gang supervisor must blocklist this host and
#: restart the survivors from the PRE-divergence snapshot (the
#: sentinel annotated its path into the heartbeat channel)
EXIT_SDC = 97

#: env channel shared by Launcher / workers / gang supervisor
ENV_HEARTBEAT_DIR = "ZNICZ_HEARTBEAT_DIR"
ENV_RESUME_SNAPSHOT = "ZNICZ_RESUME_SNAPSHOT"
ENV_ELASTIC_ATTEMPT = "ZNICZ_ELASTIC_ATTEMPT"

_PREEMPT_FLAG = "preempt.json"


class PeerLost(RuntimeError):
    """A peer process died and the in-flight collective can never
    complete (surfaced by the watchdog instead of an infinite hang)."""


class Preempted(SystemExit):
    """Raised after a successful checkpoint-on-signal; subclasses
    ``SystemExit`` so the Launcher's crash-retry loop never swallows it
    and an unhandled instance exits the process with
    :data:`EXIT_PREEMPTED` (the gang supervisor's "resumable" code)."""

    def __init__(self, snapshot_path: str | None = None) -> None:
        super().__init__(EXIT_PREEMPTED)
        self.snapshot_path = snapshot_path


def _atomic_write_json(path: str, payload: dict) -> None:
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as fh:
        json.dump(payload, fh)
    os.replace(tmp, path)


def _read_json(path: str) -> dict | None:
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError):  # missing / mid-replace torn read
        return None


def heartbeat_path(directory: str, process_index: int) -> str:
    return os.path.join(directory, f"hb_{process_index:04d}.json")


# ----------------------------------------------------------------------
# per-process heartbeat writer
# ----------------------------------------------------------------------
class HeartbeatWriter(Logger):
    """Beats ``{process, step, time, ...}`` into the channel file.

    A daemon thread refreshes the wall-clock every ``interval_s`` even
    while the step counter is frozen — that is what lets the monitor
    tell "host vanished" (stale time) from "host up, step hung in a
    collective" (fresh time, stale step).  :meth:`beat` is the
    step-boundary update; :meth:`annotate` rides extra fields (resume
    position, checkpoint counts) the gang supervisor folds into its
    own registry."""

    def __init__(self, directory: str, process_index: int,
                 interval_s: float = 1.0, **kwargs) -> None:
        super().__init__(**kwargs)
        self.directory = directory
        self.process_index = int(process_index)
        self.interval_s = max(0.05, float(interval_s))
        self.path = heartbeat_path(directory, self.process_index)
        self._lock = threading.Lock()
        self._step = 0
        self._extra: dict = {}
        self._frozen = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        os.makedirs(directory, exist_ok=True)

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "HeartbeatWriter":
        if self._thread is None:
            self._write()
            self._thread = threading.Thread(
                target=self._loop, name=f"heartbeat-{self.process_index}",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self._write()  # final state (exit annotations) lands

    # -- updates --------------------------------------------------------
    def beat(self, step: int) -> None:
        """Step-boundary beat: record progress and persist now (the
        interval thread only keeps wall-clock fresh between steps)."""
        with self._lock:
            if not self._frozen:
                self._step = int(step)
        self._write()

    def annotate(self, **fields) -> None:
        with self._lock:
            self._extra.update(fields)
        self._write()

    def freeze(self) -> None:
        """Chaos hook (``heartbeat.stall``): keep wall-clock beats
        flowing but never advance the step counter again — the exact
        signature of a process hung inside a collective."""
        with self._lock:
            self._frozen = True

    @property
    def step(self) -> int:
        return self._step

    # -- plumbing -------------------------------------------------------
    def _payload(self) -> dict:
        with self._lock:
            payload = {"process": self.process_index, "step": self._step,
                       "time": time.time(), "pid": os.getpid()}
            payload.update(self._extra)
        return payload

    def _write(self) -> None:
        try:
            _atomic_write_json(self.path, self._payload())
        except OSError as exc:  # channel fs hiccup: beat again next tick
            self.warning("heartbeat write failed: %s", exc)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._write()


# ----------------------------------------------------------------------
# coordinator-side monitor
# ----------------------------------------------------------------------
class HeartbeatMonitor(Logger):
    """Reads every process's channel file and classifies liveness.

    ``poll()`` returns ``{process: {"status", "age_s", "step",
    "step_age_s"}}`` where status is ``ok`` / ``starting`` (never
    beaten, within the bring-up grace) / ``missing`` / ``stale`` (no
    beat for ``timeout_s``) / ``stalled`` (beats flow, step frozen for
    ``stall_timeout_s``).  ``dead()`` lists the processes a supervisor
    must act on.  ``register_gauges()`` feeds the canonical
    ``znicz_heartbeat_age_seconds{process}`` callback gauges so
    ``/metrics`` and ``/readyz`` expose peer ages from the same
    channel."""

    def __init__(self, directory: str, n_processes: int,
                 timeout_s: float = 30.0,
                 stall_timeout_s: float | None = None,
                 start_grace_s: float | None = None, **kwargs) -> None:
        super().__init__(**kwargs)
        self.directory = directory
        self.n_processes = int(n_processes)
        self.timeout_s = float(timeout_s)
        self.stall_timeout_s = float(
            stall_timeout_s if stall_timeout_s is not None
            else max(timeout_s, 2.0))
        #: jax bring-up (imports + distributed init + first compile) can
        #: dwarf the steady-state timeout; a process that has NEVER
        #: beaten only counts dead after this grace
        self.start_grace_s = float(
            start_grace_s if start_grace_s is not None
            else max(4 * self.timeout_s, 60.0))
        self._t0 = time.time()
        #: per-process (step, first-seen-at-this-step) for stall detect
        self._step_seen: dict[int, tuple[int, float]] = {}

    def read(self, process_index: int) -> dict | None:
        return _read_json(heartbeat_path(self.directory, process_index))

    def age_of(self, process_index: int) -> float:
        """Seconds since the process last beat (inf when never seen) —
        the ``znicz_heartbeat_age_seconds`` gauge body."""
        hb = self.read(process_index)
        if hb is None:
            return float("inf")
        return max(0.0, time.time() - float(hb.get("time", 0.0)))

    def poll(self, now: float | None = None) -> dict[int, dict]:
        now = time.time() if now is None else now
        out: dict[int, dict] = {}
        for i in range(self.n_processes):
            hb = self.read(i)
            if hb is None:
                grace_left = self.start_grace_s - (now - self._t0)
                out[i] = {"status": "starting" if grace_left > 0
                          else "missing",
                          "age_s": float("inf"), "step": None,
                          "step_age_s": float("inf")}
                continue
            age = max(0.0, now - float(hb.get("time", 0.0)))
            step = int(hb.get("step", 0))
            seen = self._step_seen.get(i)
            if seen is None or seen[0] != step:
                self._step_seen[i] = (step, now)
                step_age = 0.0
            else:
                step_age = now - seen[1]
            if age > self.timeout_s:
                status = "stale"
            elif step_age > self.stall_timeout_s and step > 0:
                status = "stalled"
            else:
                status = "ok"
            out[i] = {"status": status, "age_s": age, "step": step,
                      "step_age_s": step_age, "hb": hb}
        return out

    def dead(self, now: float | None = None) -> list[tuple[int, str]]:
        """``[(process, kind)]`` needing supervisor action — kind is
        ``loss`` (missing/stale) or ``stall`` (frozen step counter)."""
        out = []
        for i, st in self.poll(now).items():
            if st["status"] in ("missing", "stale"):
                out.append((i, "loss"))
            elif st["status"] == "stalled":
                out.append((i, "stall"))
        return out

    def register_gauges(self) -> None:
        for i in range(self.n_processes):
            _metrics.heartbeat_age_seconds(i).set_function(
                lambda i=i: self.age_of(i))


# ----------------------------------------------------------------------
# preemption flag (the cross-process checkpoint barrier request)
# ----------------------------------------------------------------------
def request_preempt_flag(directory: str, barrier_step: int,
                         requested_by: int, reason: str) -> str:
    """Publish the gang-wide checkpoint request.  First writer wins —
    a flag already on disk (another host was preempted in the same
    window) is left untouched so every process agrees on ONE barrier
    step."""
    path = os.path.join(directory, _PREEMPT_FLAG)
    if not os.path.exists(path):
        _atomic_write_json(path, {
            "barrier_step": int(barrier_step),
            "requested_by": int(requested_by),
            "reason": reason, "time": time.time()})
    return path


def preempt_flag(directory: str) -> dict | None:
    return _read_json(os.path.join(directory, _PREEMPT_FLAG))


# ----------------------------------------------------------------------
# in-process supervision (attached by the Launcher)
# ----------------------------------------------------------------------
def worker_config() -> dict | None:
    """The Launcher's attach decision: the env channel
    (``ZNICZ_HEARTBEAT_DIR``) or ``engine.heartbeat_dir`` turns
    supervision on; returns the ctor kwargs or None."""
    directory = os.environ.get(ENV_HEARTBEAT_DIR) \
        or root.common.engine.get("heartbeat_dir", None)
    if not directory:
        return None
    return {"directory": str(directory)}


class WorkerSupervisor(Logger):
    """One workflow run's in-process supervision.

    ``attach()`` hooks the workflow's step boundary (fired by the
    Decision unit every step / chunk): each boundary beats the
    heartbeat, fires the elastic chaos sites, polls the preempt flag
    and — once a preemption is pending and the barrier step is reached
    — executes the checkpoint-on-signal and raises
    :class:`Preempted`.  A watchdog thread bounds the time between
    step boundaries (``engine.collective_timeout_s``, unset = off):
    when a peer dies mid-collective this process logs
    :class:`PeerLost` and exits :data:`EXIT_PEER_LOST` promptly
    instead of hanging in gloo/ICI forever."""

    def __init__(self, workflow, directory: str | None = None,
                 process_index: int | None = None,
                 process_count: int | None = None,
                 is_master: bool | None = None,
                 heartbeat_interval_s: float | None = None,
                 collective_timeout_s: float | None = None,
                 **kwargs) -> None:
        super().__init__(**kwargs)
        from znicz_tpu.parallel.process_shard import process_info
        pidx, pcount = process_info()
        self.workflow = workflow
        self.directory = directory
        self.process_index = pidx if process_index is None \
            else int(process_index)
        self.process_count = pcount if process_count is None \
            else int(process_count)
        self.is_master = (self.process_index == 0) if is_master is None \
            else bool(is_master)
        engine = root.common.engine
        interval = heartbeat_interval_s if heartbeat_interval_s is not None \
            else engine.get("heartbeat_interval_s", 1.0)
        self.collective_timeout_s = collective_timeout_s \
            if collective_timeout_s is not None \
            else engine.get("collective_timeout_s", None)
        self.preempt_barrier_steps = int(
            engine.get("preempt_barrier_steps", 4))
        self.step = 0
        self.writer: HeartbeatWriter | None = None
        self.monitor: HeartbeatMonitor | None = None
        if directory:
            self.writer = HeartbeatWriter(
                directory, self.process_index, interval_s=float(interval))
            attempt = os.environ.get(ENV_ELASTIC_ATTEMPT)
            if attempt is not None:
                self.writer.annotate(attempt=int(attempt))
            if self.is_master:
                # coordinator-side monitor: REPORT-ONLY in-worker (the
                # gang supervisor owns restarts) — feeds the per-peer
                # age gauges /metrics + /readyz expose
                self.monitor = HeartbeatMonitor(
                    directory, self.process_count,
                    timeout_s=float(engine.get("heartbeat_timeout_s",
                                               30.0)),
                    stall_timeout_s=engine.get(
                        "heartbeat_stall_timeout_s", None))
                self.monitor.register_gauges()
        self._preempt: dict | None = None
        self._preempt_lock = threading.Lock()
        self._attached = False
        self._last_boundary = time.time()
        self._watchdog_stop = threading.Event()
        self._watchdog: threading.Thread | None = None

    # -- lifecycle ------------------------------------------------------
    def attach(self) -> "WorkerSupervisor":
        if self._attached:
            return self
        self.workflow.add_step_hook(self.on_step)
        # the SDC sentinel reaches the heartbeat channel through this
        # back-reference (quarantine annotations + EXIT_SDC)
        self.workflow._worker_supervisor = self
        if self.writer is not None:
            # resume-position attestation: attach runs after any
            # snapshot restore, so the loader's position IS where this
            # attempt resumed — the gang supervisor folds it into its
            # registry as the drill's `resumed_step`
            loader = getattr(self.workflow, "loader", None)
            schedule = getattr(loader, "_schedule", None)
            if loader is not None and schedule is not None:
                try:
                    self.writer.annotate(
                        resumed_step=(int(loader.epoch_number)
                                      * len(schedule)
                                      + int(loader._cursor)),
                        start_epoch=int(loader.epoch_number),
                        start_cursor=int(loader._cursor))
                except (TypeError, ValueError):  # uninitialized loader
                    pass
            self.writer.start()
        if self.collective_timeout_s:
            self._watchdog = threading.Thread(
                target=self._watchdog_loop, name="collective-watchdog",
                daemon=True)
            self._watchdog.start()
        self._attached = True
        return self

    def detach(self) -> None:
        if not self._attached:
            return
        self.workflow.remove_step_hook(self.on_step)
        if getattr(self.workflow, "_worker_supervisor", None) is self:
            self.workflow._worker_supervisor = None
        self._watchdog_stop.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout=5)
            self._watchdog = None
        if self.writer is not None:
            self.writer.stop()
        self._attached = False

    # -- the step boundary ---------------------------------------------
    def on_step(self) -> None:
        self.step += 1
        self._last_boundary = time.time()
        if self.writer is not None:
            self.writer.beat(self.step)
        if _faults.active() is not None:
            if _faults.fire("host.loss",
                            process=self.process_index) is not None:
                # "the host vanished": no drain, no snapshot, no exit
                # handlers — exactly what a real loss looks like to the
                # survivors and the gang supervisor
                self.error("host.loss injected at step %d — dying hard",
                           self.step)
                os._exit(1)
            if _faults.fire("host.preempt",
                            process=self.process_index) is not None:
                self.request_preempt("host.preempt fault")
            payload = _faults.fire("heartbeat.stall",
                                   process=self.process_index)
            if payload is not None:
                sleep_s = float(payload.get("sleep_s", 3600.0))
                self.warning("heartbeat.stall injected at step %d — "
                             "freezing step counter and blocking %gs",
                             self.step, sleep_s)
                if self.writer is not None:
                    self.writer.freeze()
                time.sleep(sleep_s)
        self._poll_preempt()
        pre = self._preempt
        if pre is not None and self.step >= int(pre["barrier_step"]):
            self.checkpoint_on_signal()

    def _poll_preempt(self) -> None:
        if self._preempt is not None or self.directory is None:
            return
        flag = preempt_flag(self.directory)
        if flag is None:
            return
        with self._preempt_lock:
            self._preempt = flag
        barrier = int(flag["barrier_step"])
        if self.step > barrier and self.process_count > 1:
            # lockstep was violated (flag observed too late) — a
            # mismatched collective checkpoint would deadlock the
            # gang; die loudly and let the supervisor restart from the
            # last periodic snapshot instead
            self.error("preempt barrier step %d already passed at "
                       "step %d — exiting without checkpoint",
                       barrier, self.step)
            os._exit(EXIT_PEER_LOST)

    # -- preemption -----------------------------------------------------
    def request_preempt(self, reason: str) -> None:
        """SIGTERM / ``host.preempt`` entry: announce the gang-wide
        barrier step (this process's current step + margin, so every
        lockstep peer reaches it) and join it ourselves.  Signal-safe:
        no jax, one tiny file write."""
        with self._preempt_lock:
            if self._preempt is not None:
                return
            margin = 1 if self.process_count == 1 \
                else self.preempt_barrier_steps
            flag = {"barrier_step": self.step + margin,
                    "requested_by": self.process_index,
                    "reason": reason, "time": time.time()}
            if self.directory is not None:
                request_preempt_flag(self.directory, flag["barrier_step"],
                                     self.process_index, reason)
                # first writer wins: re-read so a concurrent request
                # from another host leaves ONE agreed barrier
                flag = preempt_flag(self.directory) or flag
            self._preempt = flag
        self.warning("preemption requested (%s): checkpoint-on-signal "
                     "at step boundary >= %d", reason,
                     self._preempt["barrier_step"])

    def checkpoint_on_signal(self) -> None:
        """The barriered checkpoint: every process gathers state at the
        SAME step boundary (collective reads are legal — the gang is in
        lockstep), process 0 writes the sha256-sidecar snapshot, the
        rest fence on the sidecar appearing, and everyone exits
        :data:`EXIT_PREEMPTED` via :class:`Preempted`."""
        from znicz_tpu.utils.snapshotter import Snapshotter
        wf = self.workflow
        pre = self._preempt or {}
        snap = getattr(wf, "snapshotter", None)
        directory = snap.directory if snap is not None \
            else str(root.common.dirs.snapshots)
        prefix = snap.prefix if snap is not None else wf.name
        suffix = f"preempt_s{int(pre.get('barrier_step', self.step))}"
        state = wf.state_dict(allow_collective=True)
        path = Snapshotter.write(state, directory, prefix, suffix)
        if self.is_master \
                and _faults.fire("checkpoint.signal_corrupt") is not None:
            with open(path, "r+b") as fh:  # digest now lies about this
                fh.seek(max(0, os.path.getsize(path) // 2))
                fh.write(b"\xde\xad\xbe\xef")
            self.warning("checkpoint.signal_corrupt injected on %s",
                         path)
        _metrics.checkpoint_on_signal().inc()
        if self.writer is not None:
            self.writer.annotate(
                checkpoint_on_signal=1, checkpoint_path=path,
                checkpoint_step=self.step)
        self.warning("checkpoint-on-signal complete at step %d → %s — "
                     "exiting %d", self.step, path, EXIT_PREEMPTED)
        wf.stop()
        raise Preempted(path)

    # -- watchdog -------------------------------------------------------
    def _watchdog_loop(self) -> None:
        timeout = float(self.collective_timeout_s)
        while not self._watchdog_stop.wait(min(1.0, timeout / 4)):
            if self.step == 0:
                continue  # bring-up / first compile: unbounded
            stall = time.time() - self._last_boundary
            if stall > timeout:
                self.error(
                    "PeerLost: no step boundary for %.1fs (> "
                    "collective_timeout_s=%.1fs) — a peer is gone and "
                    "the in-flight collective cannot complete; exiting "
                    "%d for the elastic supervisor", stall, timeout,
                    EXIT_PEER_LOST)
                if self.writer is not None:
                    self.writer.annotate(peer_lost=True)
                # a thread cannot interrupt a blocked gloo/ICI call —
                # prompt suicide IS the detectable surfacing
                os._exit(EXIT_PEER_LOST)


# ----------------------------------------------------------------------
# gang supervisor (the elastic restart owner)
# ----------------------------------------------------------------------
def newest_good_snapshot(directory: str, prefix: str | None = None
                         ) -> str | None:
    """Newest ``*.pickle.gz`` whose sha256 sidecar verifies (sidecarless
    files — the crash window — count good, matching
    ``Snapshotter._load_verified``); None when nothing qualifies."""
    import glob as _glob

    from znicz_tpu.utils.snapshotter import _sha256_file
    pattern = f"{prefix}_*.pickle.gz" if prefix else "*.pickle.gz"
    files = _glob.glob(os.path.join(directory, pattern))
    files.sort(key=os.path.getmtime, reverse=True)
    for path in files:
        sidecar = f"{path}.sha256"
        try:
            if os.path.exists(sidecar):
                with open(sidecar) as fh:
                    if _sha256_file(path) != fh.read().strip():
                        continue
            return path
        except OSError:
            continue
    return None


def _free_port() -> int:
    import socket
    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        return sock.getsockname()[1]


class ElasticSupervisor(Logger):
    """Owns the worker gang: spawn → monitor → classify → restart.

    ``argv_for(process_id, n_processes, attempt)`` builds each worker's
    command line; the supervisor provides the env contract
    (``ZNICZ_COORDINATOR`` on a fresh port per attempt,
    ``ZNICZ_NUM_PROCESSES`` / ``ZNICZ_PROCESS_ID``,
    ``ZNICZ_HEARTBEAT_DIR`` per attempt, ``ZNICZ_ELASTIC_ATTEMPT`` and
    — after the first attempt — ``ZNICZ_RESUME_SNAPSHOT`` pointing at
    the newest digest-verified snapshot).  ``fault_env`` is applied to
    attempt 0 only, so a seeded chaos recipe injects exactly once and
    the restarted gang runs clean.

    Failure classification (counted as
    ``znicz_host_losses_total{kind}``):

    - ``preempt`` — a child exited :data:`EXIT_PREEMPTED` after the
      barriered checkpoint; the gang drains on its own;
    - ``stall`` — heartbeats flow but a step counter froze past the
      stall timeout (hung collective / seized host);
    - ``loss`` — a child died (any other nonzero exit) or its
      heartbeat went stale/missing;
    - ``sdc`` (round 19) — a child exited :data:`EXIT_SDC` after the
      integrity sentinel confirmed its chip computes wrong values:
      the culprit is BLOCKLISTED and the restart resumes from the
      gang-attested PRE-divergence snapshot (heartbeat annotation
      ``sdc_last_good``), not the newest one — snapshots written
      after the divergence may already carry the corruption.

    Every restart shrinks the gang by the lost processes and relaunches
    on the surviving host set (``znicz_elastic_restarts_total``)."""

    def __init__(self, argv_for: Callable[[int, int, int], Sequence[str]],
                 n_processes: int, work_dir: str,
                 snapshot_dir: str, snapshot_prefix: str | None = None,
                 heartbeat_timeout_s: float = 10.0,
                 stall_timeout_s: float | None = None,
                 start_grace_s: float | None = None,
                 poll_interval_s: float = 0.25,
                 drain_s: float = 30.0,
                 max_restarts: int = 3,
                 env: dict | None = None,
                 fault_env: dict | None = None,
                 initial_snapshot: str | None = None, **kwargs) -> None:
        super().__init__(**kwargs)
        #: snapshot handed to attempt 0 (restart attempts always pick
        #: the newest good one from snapshot_dir) — the parity drill's
        #: reference arm resumes a 1-process gang from a pinned file
        self.initial_snapshot = initial_snapshot
        self.argv_for = argv_for
        self.n_processes = int(n_processes)
        self.work_dir = work_dir
        self.snapshot_dir = snapshot_dir
        self.snapshot_prefix = snapshot_prefix
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.stall_timeout_s = stall_timeout_s
        self.start_grace_s = start_grace_s
        self.poll_interval_s = float(poll_interval_s)
        self.drain_s = float(drain_s)
        self.max_restarts = int(max_restarts)
        self.env = dict(env or {})
        self.fault_env = dict(fault_env or {})
        self.monitor: HeartbeatMonitor | None = None
        #: run() summary (also returned): attempts, restarts, losses by
        #: kind, resume snapshots, checkpoint-on-signal folds, ...
        self.summary: dict = {}
        #: round 19: process indices confirmed corrupt by the SDC
        #: sentinel — never relaunched (the "corrupt-chip quarantine")
        self.blocklist: set[int] = set()
        #: pre-divergence snapshot annotated by the gang at an SDC
        #: quarantine — overrides newest_good_snapshot for the restart
        self._sdc_resume: str | None = None
        os.makedirs(work_dir, exist_ok=True)

    # -- one attempt ----------------------------------------------------
    def _spawn(self, attempt: int, n: int, hb_dir: str,
               resume: str | None) -> list[subprocess.Popen]:
        port = _free_port()
        base = dict(os.environ)
        for key, val in self.env.items():
            if val is None:  # None = scrub from the inherited env
                base.pop(key, None)
            else:
                base[key] = str(val)
        if attempt == 0:
            base.update(self.fault_env)
        base["ZNICZ_COORDINATOR"] = f"127.0.0.1:{port}"
        base["ZNICZ_NUM_PROCESSES"] = str(n)
        base[ENV_HEARTBEAT_DIR] = hb_dir
        base[ENV_ELASTIC_ATTEMPT] = str(attempt)
        if resume:
            base[ENV_RESUME_SNAPSHOT] = resume
        else:
            base.pop(ENV_RESUME_SNAPSHOT, None)
        procs = []
        for pid in range(n):
            env = dict(base)
            env["ZNICZ_PROCESS_ID"] = str(pid)
            log_path = os.path.join(
                self.work_dir, f"worker_a{attempt}_p{pid}.log")
            log_fh = open(log_path, "w")
            proc = subprocess.Popen(
                list(self.argv_for(pid, n, attempt)),
                env=env, stdout=log_fh, stderr=subprocess.STDOUT)
            proc._znicz_log = log_path  # type: ignore[attr-defined]
            proc._znicz_log_fh = log_fh  # type: ignore[attr-defined]
            procs.append(proc)
        self.info("attempt %d: spawned %d worker(s) @ port %d "
                  "(resume=%s)", attempt, n, port, resume or "fresh")
        return procs

    @staticmethod
    def _kill(procs: list[subprocess.Popen]) -> None:
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        deadline = time.time() + 5.0
        for proc in procs:
            if proc.poll() is None:
                try:
                    proc.wait(timeout=max(0.1, deadline - time.time()))
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=10)

    @staticmethod
    def _close_logs(procs: list[subprocess.Popen]) -> None:
        for proc in procs:
            fh = getattr(proc, "_znicz_log_fh", None)
            if fh is not None:
                fh.close()

    def _fold_heartbeats(self, hb_dir: str, n: int) -> None:
        """Worker-side attestations ride the heartbeat channel; fold
        them into THIS process's registry so the dryrun scrape sees one
        coherent story (checkpoint-on-signal counts, resume steps,
        SDC verdicts)."""
        sdc_detected: dict[str, float] = {}
        sdc_injected: dict[str, float] = {}
        for i in range(n):
            hb = _read_json(heartbeat_path(hb_dir, i))
            if not hb:
                continue
            if hb.get("checkpoint_on_signal"):
                _metrics.checkpoint_on_signal().inc(
                    float(hb["checkpoint_on_signal"]))
            if hb.get("resumed_step") is not None:
                self.summary["resumed_step"] = int(hb["resumed_step"])
            # round 19: SDC quarantine attestations — every gang
            # member annotates the SAME verdict (the vote is
            # symmetric), so detection counts fold as a MAX across
            # members while injected-fault counts (which fired only on
            # the culprit) fold as written
            if hb.get("sdc_last_good"):
                self._sdc_resume = str(hb["sdc_last_good"])
            if hb.get("sdc_culprits"):
                self.summary.setdefault("sdc_culprits", sorted(
                    int(p) for p in hb["sdc_culprits"]))
            for kind, count in (hb.get("sdc_detected") or {}).items():
                sdc_detected[kind] = max(sdc_detected.get(kind, 0.0),
                                         float(count))
            for site, count in (hb.get("faults_injected")
                                or {}).items():
                sdc_injected[site] = sdc_injected.get(site, 0.0) \
                    + float(count)
        for kind, count in sdc_detected.items():
            _metrics.sdc_detected(kind).inc(count)
        for site, count in sdc_injected.items():
            _metrics.faults_injected(site).inc(count)

    def _tail(self, proc: subprocess.Popen, n: int = 2000) -> str:
        path = getattr(proc, "_znicz_log", None)
        if not path or not os.path.exists(path):
            return ""
        with open(path, errors="replace") as fh:
            return fh.read()[-n:]

    # -- the elastic loop -----------------------------------------------
    def run(self) -> dict:
        n = self.n_processes
        attempt = 0
        restarts = 0
        losses: dict[str, int] = {}
        resume_snapshots: list[str | None] = []
        while True:
            hb_dir = os.path.join(self.work_dir, f"hb_a{attempt}")
            os.makedirs(hb_dir, exist_ok=True)
            resume = self.initial_snapshot
            if attempt > 0:
                if self._sdc_resume:
                    # SDC restart: snapshots written AFTER the
                    # divergence may already carry the corruption —
                    # resume from the gang-attested PRE-divergence one
                    resume = self._sdc_resume
                    self._sdc_resume = None
                    self.summary["resumed"] = "pre-divergence"
                else:
                    resume = newest_good_snapshot(self.snapshot_dir,
                                                  self.snapshot_prefix)
            resume_snapshots.append(resume)
            self.monitor = HeartbeatMonitor(
                hb_dir, n, timeout_s=self.heartbeat_timeout_s,
                stall_timeout_s=self.stall_timeout_s,
                start_grace_s=self.start_grace_s)
            self.monitor.register_gauges()
            fed = None
            if _metrics.enabled():
                # the supervisor IS the gang's metrics folder: every
                # poll folds the heartbeat channel into znicz_fed_*
                # children (per-member step + staleness), so one
                # scrape of this process answers "which worker is
                # behind" (round 24)
                from znicz_tpu.observe.federation import Federator
                fed = Federator("elastic")
                fed.add_heartbeats(hb_dir, n)
            procs = self._spawn(attempt, n, hb_dir, resume)
            dead: dict[int, str] = {}
            try:
                while True:
                    time.sleep(self.poll_interval_s)
                    if fed is not None:
                        fed.scrape()
                    rcs = [proc.poll() for proc in procs]
                    if all(rc == 0 for rc in rcs):
                        self.summary.update({
                            "attempts": attempt + 1,
                            "restarts": restarts, "losses": losses,
                            "final_processes": n,
                            "resume_snapshots": resume_snapshots,
                            "ok": True})
                        self.info("gang complete on attempt %d "
                                  "(%d process(es))", attempt, n)
                        return self.summary
                    for i, rc in enumerate(rcs):
                        if rc is not None and rc != 0 and i not in dead:
                            dead[i] = ("preempt" if rc == EXIT_PREEMPTED
                                       else "sdc" if rc == EXIT_SDC
                                       else "loss")
                            self.warning(
                                "worker %d exited rc=%d (%s)\n%s", i,
                                rc, dead[i], self._tail(procs[i]))
                    if any(k == "preempt" for k in dead.values()):
                        # the gang is draining through its own
                        # checkpoint barrier: give every member
                        # drain_s to land its fence + exit 75
                        deadline = time.time() + self.drain_s
                        while time.time() < deadline and any(
                                p.poll() is None for p in procs):
                            time.sleep(self.poll_interval_s)
                        for i, proc in enumerate(procs):
                            rc = proc.poll()
                            if rc == EXIT_PREEMPTED:
                                dead.setdefault(i, "preempt")
                            elif rc not in (None, 0):
                                dead.setdefault(i, "loss")
                        break
                    if dead:
                        # a hard loss strands every survivor inside the
                        # dead peer's collective — no point waiting for
                        # heartbeats to confirm what the exit code said
                        break
                    for i, kind in self.monitor.dead():
                        dead.setdefault(i, kind)
                    if dead:
                        break
                # 113-only observation: every exit seen so far is a
                # watchdog/SDC-peer victim — the ROOT CAUSE (a dead
                # host, or an EXIT_SDC culprit racing its peers to the
                # exit) may surface within a short settle window
                if dead and all(
                        k == "loss"
                        and procs[i].poll() == EXIT_PEER_LOST
                        for i, k in dead.items()):
                    deadline = time.time() + min(self.drain_s, 5.0)
                    while time.time() < deadline:
                        found_root = False
                        for i, proc in enumerate(procs):
                            rc = proc.poll()
                            if rc is not None and rc != 0 \
                                    and i not in dead:
                                dead[i] = (
                                    "preempt" if rc == EXIT_PREEMPTED
                                    else "sdc" if rc == EXIT_SDC
                                    else "loss")
                                if rc != EXIT_PEER_LOST:
                                    found_root = True
                        if found_root:
                            break
                        time.sleep(self.poll_interval_s)
                # a stall needs a settle window to tell culprit from
                # victim: the hung peer's watchdog exits it
                # EXIT_PEER_LOST while the seized host stays alive
                if any(k == "stall" for k in dead.values()):
                    settle = min(self.drain_s, max(
                        5.0, 1.5 * float(root.common.engine.get(
                            "collective_timeout_s") or 0)))
                    deadline = time.time() + settle
                    while time.time() < deadline and any(
                            procs[i].poll() is None for i in dead):
                        time.sleep(self.poll_interval_s)
            finally:
                self._fold_heartbeats(hb_dir, n)
                if fed is not None:
                    fed.scrape()  # final fold before the dir goes cold
                    fed.close()
                self._kill(procs)
                self._close_logs(procs)
            # Only ROOT-CAUSE hosts are gone; everyone else rejoins:
            # - preempt: the flag names the requester — peers that
            #   drained through the barrier and exited 75 are healthy;
            # - stall: the culprit is the stalled process still ALIVE
            #   at the settle deadline (victims self-exited 113);
            # - loss: the dead children themselves, minus watchdog
            #   victims (rc EXIT_PEER_LOST follows a peer's death).
            preempted: set[int] = set()
            if any(k == "preempt" for k in dead.values()):
                flag = preempt_flag(hb_dir)
                preempted = {int(flag["requested_by"])} if flag else {
                    min(i for i, k in dead.items() if k == "preempt")}
            stalled = {i for i, k in dead.items() if k == "stall"}
            if len(stalled) > 1:
                alive_stalled = {i for i in stalled
                                 if procs[i].poll() in (None, -15, -9)}
                if alive_stalled and alive_stalled != stalled:
                    stalled = alive_stalled
            # round 19: an EXIT_SDC child is a sentinel-confirmed
            # corrupt chip — quarantined (blocklisted), never a victim
            sdc_hosts = {i for i, k in dead.items() if k == "sdc"}
            hard_lost = {i for i, k in dead.items()
                         if k == "loss"
                         and procs[i].poll() != EXIT_PEER_LOST} | stalled
            n_lost = max(1, len(hard_lost) + len(preempted)
                         + len(sdc_hosts))
            if not hard_lost and not preempted and not sdc_hosts:
                # every observed exit was a watchdog victim — the root
                # cause never even reached the channel; one host is
                # gone all the same
                losses["loss"] = losses.get("loss", 0) + 1
                _metrics.host_losses("loss").inc()
            for i in sorted(hard_lost):
                kind = dead.get(i, "loss")
                losses[kind] = losses.get(kind, 0) + 1
                _metrics.host_losses(kind).inc()
                _recorder.record("host_loss", process=i, cause=kind,
                                 attempt=attempt)
            for i in sorted(preempted):
                losses["preempt"] = losses.get("preempt", 0) + 1
                _metrics.host_losses("preempt").inc()
                _recorder.record("host_loss", process=i,
                                 cause="preempt", attempt=attempt)
            for i in sorted(sdc_hosts):
                losses["sdc"] = losses.get("sdc", 0) + 1
                self.blocklist.add(i)
                _metrics.host_losses("sdc").inc()
                _metrics.sdc_quarantined("host").inc()
                _recorder.record("sdc_quarantine", process=i,
                                 scope="host", attempt=attempt)
            if self.blocklist:
                self.summary["blocklisted"] = sorted(self.blocklist)
            survivors = n - n_lost
            if survivors < 1:
                # preemption of the LAST host: the checkpoint survives,
                # a later scheduling round resumes it — report, don't
                # spin
                self.summary.update({
                    "attempts": attempt + 1, "restarts": restarts,
                    "losses": losses, "final_processes": 0,
                    "resume_snapshots": resume_snapshots, "ok": False,
                    "reason": "no surviving hosts"})
                return self.summary
            if restarts >= self.max_restarts:
                raise RuntimeError(
                    f"elastic supervisor exceeded max_restarts="
                    f"{self.max_restarts} (losses={losses})")
            restarts += 1
            attempt += 1
            n = survivors
            _metrics.elastic_restarts().inc()
            _recorder.record("elastic_restart", attempt=attempt,
                             processes=n,
                             losses=",".join(
                                 f"{k}:{v}" for k, v in
                                 sorted(losses.items())))
            self.warning("restarting on the surviving mesh: %d → %d "
                         "process(es) (losses=%s)", n + n_lost, n,
                         losses)
