"""Elastic drill worker: one gang member of a supervised streaming run.

Runnable as ``python -m znicz_tpu.resilience.elastic_worker <out.json>
<shard_dir>`` under the :class:`~znicz_tpu.resilience.supervisor.
ElasticSupervisor` env contract (``ZNICZ_COORDINATOR`` /
``ZNICZ_NUM_PROCESSES`` / ``ZNICZ_PROCESS_ID`` /
``ZNICZ_HEARTBEAT_DIR`` / ``ZNICZ_RESUME_SNAPSHOT`` /
``ZNICZ_ELASTIC_ATTEMPT``).  Each process:

1. pins its platform + per-process device count (CPU drills; on a pod
   leave ``ZNICZ_ELASTIC_PLATFORM`` empty and the ambient TPU runtime
   wins),
2. boots the Launcher — the env contract performs the
   ``jax.distributed`` bring-up (bounded by
   ``engine.dist_init_timeout_s``) and attaches the
   :class:`~znicz_tpu.resilience.supervisor.WorkerSupervisor`
   (heartbeats, preemption, watchdog),
3. trains a small streaming-loader MLP (per-process 1/N reads,
   ZeRO-1 on the data axis, a lockstep Snapshotter every epoch), and
4. writes a JSON digest: bitwise weight sha256, resume position,
   warmed-step compile delta, the partition table's bound mesh — what
   the elastic tests and the dryrun attest parity and reshard from.

Chaos rides the normal seeded recipe: the supervisor exports
``ZNICZ_ELASTIC_FAULTS`` (a JSON recipe over the ``host.loss`` /
``host.preempt`` / ``heartbeat.stall`` / ``checkpoint.signal_corrupt``
sites) on attempt 0 only, so the restarted gang runs clean.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys


def build_workflow(shard_dir: str, snapshot_dir: str,
                   minibatch_size: int = 16, max_epochs: int = 6):
    """Streaming 2-layer MLP over the drill shard set — small enough
    for a sub-minute CPU gang, real enough to exercise ZeRO-1 (data
    axis > 1), the counter-based shuffle and mid-epoch resume."""
    from znicz_tpu.loader.streaming import StreamingLoader
    from znicz_tpu.models.standard_workflow import StandardWorkflow

    wf = StandardWorkflow(
        name="elastic_mlp",
        loader_factory=lambda w: StreamingLoader(
            w, shard_dir, minibatch_size=minibatch_size,
            prefetch_depth=2,
            normalization_scale=2.0 / 255.0, normalization_bias=-1.0),
        layers=[
            {"type": "all2all_tanh",
             "->": {"output_sample_shape": 16, "weights_filling": "he"},
             "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
            {"type": "softmax",
             "->": {"output_sample_shape": 4, "weights_filling": "he"},
             "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
        ],
        decision_config={"max_epochs": max_epochs,
                         "fail_iterations": 10 ** 6},
        snapshotter_config={"prefix": "elastic",
                            "directory": snapshot_dir,
                            "keep_last": 10})
    wf._max_fires = 10 ** 6
    # the drill needs a deterministic checkpoint cadence: snapshot at
    # EVERY epoch boundary, not only on validation improvement — and
    # with a UNIQUE suffix per write (the default best-error suffix
    # overwrites same-error epochs, which would mutate the very file a
    # parity reference must later restore from)
    wf.snapshotter.gate_skip = ~wf.decision.epoch_ended
    snap = wf.snapshotter
    snap.snapshot_suffix = (
        lambda: f"ep{int(wf.loader.epoch_number):03d}")
    return wf


def main() -> None:
    out_path = sys.argv[1]
    shard_dir = sys.argv[2]
    snapshot_dir = os.environ.get(
        "ZNICZ_ELASTIC_SNAPSHOT_DIR",
        os.path.join(os.path.dirname(out_path), "snapshots"))
    minibatch = int(os.environ.get("ZNICZ_ELASTIC_BATCH", "16"))
    max_epochs = int(os.environ.get("ZNICZ_ELASTIC_EPOCHS", "6"))
    devices_per_proc = int(os.environ.get("ZNICZ_ELASTIC_DEVICES", "2"))
    platform = os.environ.get("ZNICZ_ELASTIC_PLATFORM", "cpu")

    if platform == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                        f"{devices_per_proc}").strip()
    import jax
    if platform:
        jax.config.update("jax_platforms", platform)
    if platform == "cpu":
        try:
            jax.config.update("jax_num_cpu_devices", devices_per_proc)
        except AttributeError:  # older jax: XLA_FLAGS above covers it
            pass

    from znicz_tpu.launcher import Launcher
    from znicz_tpu.observe import metrics as obs_metrics
    from znicz_tpu.utils import prng
    from znicz_tpu.utils.config import root

    faults_json = os.environ.get("ZNICZ_ELASTIC_FAULTS")
    if faults_json:
        root.common.engine.faults = json.loads(faults_json)
    for env, knob, cast in (
            ("ZNICZ_COLLECTIVE_TIMEOUT_S", "collective_timeout_s", float),
            ("ZNICZ_HEARTBEAT_INTERVAL_S", "heartbeat_interval_s", float),
            ("ZNICZ_HEARTBEAT_TIMEOUT_S", "heartbeat_timeout_s", float),
            ("ZNICZ_DIST_INIT_TIMEOUT_S", "dist_init_timeout_s", float),
            ("ZNICZ_PREEMPT_BARRIER_STEPS", "preempt_barrier_steps",
             int),
            # round 19: the SDC sentinel's drill knobs.  The vote
            # drill turns ZeRO-1 OFF: pure-DP replicas maintain params
            # independently, so a flipped copy STAYS divergent (ZeRO-1
            # re-derives params from shared collectives every step,
            # healing per-host divergence into globally-consistent
            # poison — the audit's territory, not the vote's)
            ("ZNICZ_SDC_VOTE_INTERVAL", "sdc_vote_interval", int),
            ("ZNICZ_SDC_SUSPECT_THRESHOLD", "sdc_suspect_threshold",
             int),
            ("ZNICZ_ZERO1", "zero1", lambda v: bool(int(v)))):
        val = os.environ.get(env)
        if val:
            setattr(root.common.engine, knob, cast(val))

    # the env contract drives the distributed bring-up + the resume
    # snapshot + the WorkerSupervisor attach — nothing per-host here.
    # NOTE: bring-up must precede ANY jax computation (seeding included)
    launcher = Launcher()
    prng.seed_all(1234)

    def run(load, main_fn):  # reference sample protocol
        load(build_workflow, shard_dir=shard_dir,
             snapshot_dir=snapshot_dir, minibatch_size=minibatch,
             max_epochs=max_epochs)
        main_fn()

    wf = launcher.boot(run)  # Preempted (SystemExit 75) propagates

    # -- digest: what the parity drill compares bitwise -----------------
    loader = wf.loader
    region_unit = wf._region_unit
    warmed_delta = -1
    if region_unit is not None:
        compiles = obs_metrics.xla_compiles(f"region:{region_unit.name}")
        before = compiles.value
        loader.run()          # lockstep on every process: one warmed
        region_unit.run()     # extra step must compile NOTHING
        warmed_delta = int(compiles.value - before)
    loader.stop()

    sha = hashlib.sha256()
    sums = []
    import numpy as np
    for fwd in wf.forwards:
        for vec in (fwd.weights, fwd.bias):
            if vec is None or not vec:
                continue
            vec.map_read()
            arr = np.ascontiguousarray(vec.mem)
            sha.update(arr.tobytes())
            sums.append(float(np.asarray(arr, dtype=np.float64).sum()))

    from znicz_tpu.resilience import faults as _faults
    plan = _faults.active()
    digest = {
        "process_id": int(jax.process_index()),
        "n_processes": int(jax.process_count()),
        "faults_injected": dict(plan.counts()) if plan else {},
        "sdc_fingerprint": (
            None if wf.anomaly_guard is None
            or wf.anomaly_guard.read_sdc_fingerprint() is None
            else [float(v) for v in
                  wf.anomaly_guard.read_sdc_fingerprint()]),
        "n_global_devices": len(jax.devices()),
        "attempt": int(os.environ.get("ZNICZ_ELASTIC_ATTEMPT", "0")),
        "resumed_from": os.environ.get("ZNICZ_RESUME_SNAPSHOT") or None,
        "weights_sha256": sha.hexdigest(),
        "weight_sums": sums,
        "min_validation_n_err": int(wf.decision.min_validation_n_err),
        "epochs_done": int(loader.epoch_number),
        "warmed_step_compiles": warmed_delta,
        "local_batch": int(loader.local_batch),
        "bound_mesh": wf.partition.bound_mesh,
        "snapshot_destination": (wf.snapshotter.destination
                                 if wf.snapshotter else None),
    }
    with open(out_path, "w") as fh:
        json.dump(digest, fh)
    print(f"elastic worker {digest['process_id']}: OK "
          f"(mesh={digest['bound_mesh']}, "
          f"sha={digest['weights_sha256'][:12]})", flush=True)


if __name__ == "__main__":
    main()
