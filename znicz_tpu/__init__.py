"""znicz_tpu — a TPU-native dataflow deep-learning framework.

A ground-up rebuild of the capabilities of Samsung VELES / Znicz
(reference: ``Samsung/veles.znicz``; structural blueprint in
``SURVEY.md`` at the repo root) designed for TPUs from the start:

- a model is a **Workflow**: a directed graph of **Unit** objects joined
  by control links (run ordering + Bool gates) and attribute links
  (data aliasing) — the Veles dataflow model
  (reference: ``veles/units.py``, ``veles/workflow.py``);
- compute units derive from **AcceleratedUnit** and provide a
  ``numpy_run`` oracle plus an ``xla_run`` path of pure jax/jnp ops
  (replacing the reference's ``ocl_run``/``cuda_run`` OpenCL/CUDA
  kernels, reference: ``veles/accelerated_units.py``);
- the per-minibatch hot chain is **not** Python-dispatched per unit:
  the engine partitions the unit graph into *jit regions* that compile
  to single donated-buffer XLA programs (see
  :mod:`znicz_tpu.accelerated_units`);
- buffers are **Vector** objects: a ``jax.Array`` in HBM with an
  optional host mirror preserving the reference's
  ``map_read``/``map_write``/``unmap`` discipline
  (reference: ``veles/memory.py``);
- distribution is synchronous SPMD data parallelism over a
  ``jax.sharding.Mesh`` with XLA collectives over ICI, replacing the
  reference's asynchronous ZeroMQ master–slave parameter server
  (reference: ``veles/server.py``/``veles/client.py`` →
  :mod:`znicz_tpu.parallel`).

Note: the reference mount was empty at build time; all reference
citations are upstream-repo-relative paths per SURVEY.md's provenance
notice, not verified file:line.
"""

__version__ = "0.1.0"

from znicz_tpu.utils.config import root  # noqa: F401
from znicz_tpu.mutable import Bool  # noqa: F401
from znicz_tpu.units import Unit, Container  # noqa: F401
from znicz_tpu.workflow import Workflow  # noqa: F401
from znicz_tpu.memory import Vector  # noqa: F401
from znicz_tpu.backends import Device, NumpyDevice, XLADevice, TPUDevice  # noqa: F401
from znicz_tpu.accelerated_units import (  # noqa: F401
    AcceleratedUnit,
    AcceleratedWorkflow,
)
