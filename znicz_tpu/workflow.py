"""Workflow: a container of units with a scheduler and lifecycle.

Rebuilds the reference's workflow engine (reference:
``veles/workflow.py``).  Differences that are deliberate TPU-first
design, not omissions:

- The reference scheduled unit callbacks on a thread pool
  (``veles/thread_pool.py``) because GPU kernel launches overlapped
  under the GIL.  On TPU the device pipeline parallelism comes from
  XLA's async dispatch and from jit regions compiling whole chains into
  one program, so the host scheduler is a deterministic worklist — no
  threads, no races, reproducible unit ordering.
- ``generate_graph`` emits Graphviz DOT like the reference.
"""

from __future__ import annotations

from collections import deque

from znicz_tpu.mutable import Bool
from znicz_tpu.observe import metrics as _metrics
from znicz_tpu.observe import tracing as _tracing
from znicz_tpu.units import Container, EndPoint, StartPoint, Unit


class Workflow(Container):
    """A directed graph of units executed from ``start_point``.

    Lifecycle: construct units and wire links in ``__init__`` (or
    after), then :meth:`initialize` (multi-pass, resolves deferred
    attribute links), then :meth:`run` — the scheduler fires units
    until :attr:`end_point` runs or :meth:`stop` is called.
    """

    def __init__(self, workflow: "Workflow | None" = None,
                 name: str | None = None, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.start_point = StartPoint(self, name="start_point")
        self.end_point = EndPoint(self, name="end_point")
        from znicz_tpu.parallel.partition import PartitionTable
        #: the workflow's ONE ordered partition-rule table — units
        #: declare placement overrides into it (TP, ring, ZeRO-1,
        #: population member axis) and every Vector binds against it
        #: at init_vectors time (parallel.partition)
        self.partition = PartitionTable(name=self.name)
        self.stopped = Bool(False)
        self._finished = False
        self._max_fires: int | None = None  # safety valve for tests
        #: step-boundary hooks (round 18): fired by the Decision unit
        #: once per training step (per chunk under run_chunked) — the
        #: elastic WorkerSupervisor beats its heartbeat and services
        #: preemption requests here.  Exceptions propagate (Preempted
        #: is a SystemExit and must unwind the run loop).
        self._step_hooks: list = []

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def initialize(self, **kwargs) -> None:
        """Initialize all units, retrying ones whose linked attributes
        are produced by units initialized later (reference behavior:
        multi-pass dependency resolution)."""
        pending = list(self.units)
        passes = 0
        while pending:
            passes += 1
            deferred: list[tuple[Unit, AttributeError]] = []
            progress = False
            for unit in pending:
                if unit.is_initialized:
                    continue
                try:
                    unit.initialize(**kwargs)
                    unit._initialized = True
                    progress = True
                except AttributeError as exc:
                    # a base-class initialize may have set the flag
                    # before the subclass raised — the workflow loop is
                    # authoritative about who still needs a pass
                    unit._initialized = False
                    deferred.append((unit, exc))
            if not deferred:
                break
            if not progress:
                unit, exc = deferred[0]
                raise RuntimeError(
                    f"workflow '{self.name}' initialize deadlock after "
                    f"{passes} passes; first stuck unit: {unit} "
                    f"({exc})") from exc
            pending = [u for u, _ in deferred]
        self._initialized = True

    def run(self) -> None:
        """Fire units from ``start_point`` until completion.

        Deterministic worklist scheduler: a unit is enqueued when its
        gate opens; ``gate_block`` drops the control signal,
        ``gate_skip`` propagates without running.
        """
        if not self.is_initialized:
            raise RuntimeError(f"workflow '{self.name}' not initialized")
        import time as _time
        self.run_started_at = _time.time()  # consumers (Publisher)
        #                       use it to tell this run's artifacts apart
        if _metrics.enabled():
            _metrics.REGISTRY.counter(
                "znicz_workflow_runs_total", "Workflow.run invocations",
                labels=("workflow",)).labels(workflow=self.name).inc()
        self._finished = False
        self.stopped.value = False
        queue: deque[Unit] = deque([self.start_point])
        self.start_point.reset_links()
        fires = 0
        with _tracing.TRACER.span(f"workflow:{self.name}",
                                  cat="workflow"):
            while queue and not self._finished and not self.stopped:
                unit = queue.popleft()
                if unit.gate_block:
                    continue
                if not unit.gate_skip:
                    unit._fire()
                    if self._finished or self.stopped:
                        break
                for dst in list(unit.links_to):
                    if dst.open_gate(unit):
                        dst.reset_links()
                        queue.append(dst)
                fires += 1
                if self._max_fires is not None and fires > self._max_fires:
                    raise RuntimeError(
                        f"workflow '{self.name}' exceeded max_fires="
                        f"{self._max_fires} (runaway loop?)")
        self.on_workflow_finished()

    def on_end_point(self) -> None:
        self._finished = True

    def stop(self) -> None:
        self.stopped.value = True
        for unit in self.units:
            unit.stop()

    # ------------------------------------------------------------------
    # step-boundary hooks (round 18: elastic supervision)
    # ------------------------------------------------------------------
    def add_step_hook(self, fn) -> None:
        if fn not in self._step_hooks:
            self._step_hooks.append(fn)

    def remove_step_hook(self, fn) -> None:
        if fn in self._step_hooks:
            self._step_hooks.remove(fn)

    def on_step_boundary(self) -> None:
        """Called by the Decision unit after every step's bookkeeping —
        the one safe point to heartbeat, poll preemption flags and
        take a barriered checkpoint (the whole gang reaches the same
        boundary in lockstep)."""
        for fn in list(self._step_hooks):
            fn()

    def on_workflow_finished(self) -> None:
        """Hook: after the scheduler drains.  Logs the slowest units
        (reference behavior: per-unit timing table at workflow end)."""
        rows = sorted((u for u in self.units if u.run_count),
                      key=lambda u: u.run_time_total, reverse=True)[:5]
        if rows:
            table = ", ".join(
                f"{u.name}: {u.run_time_total:.3f}s/{u.run_count}x"
                for u in rows)
            self.debug("slowest units: %s", table)

    # ------------------------------------------------------------------
    # snapshot protocol
    # ------------------------------------------------------------------
    def state_dict(self, allow_collective: bool = False) -> dict:
        """Pure-data state tree: per-unit Vectors + declared scalars +
        the PRNG streams (so resume continues the exact trajectory).

        ``allow_collective``: see :meth:`Unit.state_dict` — True only
        from lockstep snapshot points (the Snapshotter unit)."""
        from znicz_tpu.utils import prng
        state: dict = {"__units__": {}, "__prng__": prng.get().get_state()}
        for unit in self.units:
            unit_state = unit.state_dict(allow_collective=allow_collective)
            if unit_state:
                state["__units__"][unit.name] = unit_state
        return state

    def load_state(self, state: dict) -> None:
        from znicz_tpu.utils import prng
        by_name = state.get("__units__", {})
        for unit in self.units:
            unit_state = by_name.get(unit.name)
            if unit_state:
                unit.load_state(unit_state)
        if "__prng__" in state:
            prng.get().set_state(state["__prng__"])

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    def generate_graph(self) -> str:
        """Graphviz DOT of the control-flow graph (reference:
        ``veles/workflow.py`` ``generate_graph``)."""
        lines = [f'digraph "{self.name}" {{', "  rankdir=TB;"]
        ids = {unit: f"u{i}" for i, unit in enumerate(self.units)}
        for unit, uid in ids.items():
            lines.append(
                f'  {uid} [label="{unit.name}\\n{type(unit).__name__}"];')
        for unit, uid in ids.items():
            for dst in unit.links_to:
                if dst in ids:
                    lines.append(f"  {uid} -> {ids[dst]};")
        lines.append("}")
        return "\n".join(lines)
