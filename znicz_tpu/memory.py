"""Vector: the framework's buffer type.

Rebuilds the reference's host↔device buffer pair (reference:
``veles/memory.py`` — ``Vector`` with ``mem``/``devmem`` and the
``map_read`` / ``map_write`` / ``map_invalidate`` / ``unmap`` lazy-sync
protocol), re-based on ``jax.Array``:

- ``devmem`` is a ``jax.Array`` living in HBM (or a tracer while a jit
  region is being traced);
- ``mem`` is a lazily-materialized host ``numpy`` mirror;
- the map/unmap state machine is preserved because it is the
  reference's central correctness invariant (SURVEY.md §3.2) and it
  keeps host↔HBM traffic explicit: ``map_read`` = device→host fetch,
  ``unmap`` = host→device upload, ``map_invalidate`` = "host will
  overwrite everything, skip the fetch".

Invalid transitions raise — the reference enforced the same assertions
as its substitute for a race detector (SURVEY.md §5.2).
"""

from __future__ import annotations

import enum
from typing import TYPE_CHECKING

import numpy as np

from znicz_tpu.observe import metrics as _metrics

if TYPE_CHECKING:  # pragma: no cover
    from znicz_tpu.backends import Device


def _count_transfer(direction: str, nbytes: int) -> None:
    """Telemetry: host<->device traffic through the map/unmap
    protocol (the explicit-transfer invariant makes this THE place
    transfer volume is knowable).  Gated — disabled telemetry costs
    one dict lookup on an already-transferring path."""
    if _metrics.enabled():
        _metrics.transfer_bytes(direction).inc(nbytes)


def _is_float_dtype(dt: np.dtype) -> bool:
    """True for any float dtype incl. the ml_dtypes ones (bfloat16
    reports numpy kind 'V' and ``np.finfo`` rejects it, so probe with
    ``ml_dtypes.finfo``, which covers the numpy floats too)."""
    if np.issubdtype(dt, np.floating):
        return True
    try:
        import ml_dtypes
        ml_dtypes.finfo(dt)
        return True
    except ValueError:
        return False


class _State(enum.Enum):
    EMPTY = 0     #: no storage yet
    HOST = 1      #: host copy authoritative; device copy stale/absent
    DEVICE = 2    #: device copy authoritative; host copy stale
    SYNCED = 3    #: both copies valid; host reads need no transfer


class Vector:
    """Host-mirrored device buffer with explicit sync points."""

    __slots__ = ("_mem", "_devmem", "_state", "_device", "_tracing", "name",
                 "batch_major", "model_shard_dim", "data_shard_dim",
                 "data_shard_pad", "member_axis", "model_shard_axis",
                 "_partition")

    def __init__(self, mem: np.ndarray | None = None,
                 name: str = "", batch_major: bool = False,
                 model_shard_dim: int | None = None,
                 data_shard_dim: int | None = None,
                 member_axis: bool = False) -> None:
        self._mem: np.ndarray | None = None
        self._devmem = None
        self._state = _State.EMPTY
        self._device: "Device | None" = None
        self._tracing = False
        self.name = name
        #: first dim is the minibatch — shard it over the mesh's data
        #: axis when the device carries one (SPMD data parallelism)
        self.batch_major = batch_major
        #: dim sharded over the mesh's MODEL axis (tensor parallelism:
        #: column/row-parallel weights and feature-sharded activations);
        #: None = replicated over model.  Set before ``initialize`` —
        #: the device reads it when placing the buffer
        self.model_shard_dim = model_shard_dim
        #: dim sharded over the mesh's DATA axis for NON-batch-major
        #: persistent state (ZeRO-1 optimizer sharding: each chip owns
        #: 1/N of the momentum accumulators).  Composes with
        #: ``model_shard_dim`` (a different dim) so bf16 optimizer
        #: state + TP weights + data-sharded momentum all stack.
        self.data_shard_dim = data_shard_dim
        #: True when dim 0 is a POPULATION axis (K stacked model
        #: replicas — the population engine's member-major buffers,
        #: one slice per member of a K-replica training run).  Member
        #: buffers shard dim 0 over the mesh's DATA axis, the same
        #: axis batch-major buffers ride in ordinary data-parallel
        #: training: in population mode the members *are* the data
        #: parallelism (small nets train K-per-chip; a K that does not
        #: divide the axis stays replicated and XLA time-slices).
        #: ``model_shard_dim`` composes (a member's TP dim, already
        #: shifted by the leading member axis).  Mutually exclusive
        #: with ``batch_major``/``data_shard_dim``.
        self.member_axis = member_axis
        #: rows of zero padding appended along ``data_shard_dim`` when
        #: the logical dim does not divide the data-axis size (jax
        #: shardings must divide evenly).  Snapshots slice the padding
        #: off on save and re-pad on load, so checkpoints stay
        #: layout-independent (``Unit.state_dict``/``load_state``).
        self.data_shard_pad = 0
        #: mesh axis ``model_shard_dim`` rides — MODEL by default; the
        #: ring sets SEQ on a 3-D (data × model × seq) mesh so DP × TP
        #: × SP compose without overloading the model axis
        self.model_shard_axis = "model"
        #: resolved placement from the workflow's declarative
        #: partition-rule table (parallel.partition) — when set,
        #: ``backends.sharding_for`` is a pure lookup and the slot
        #: attributes above are a compatibility layer populated FROM
        #: this resolution, not hand-set by units
        self._partition = None
        if mem is not None:
            self.reset(mem)

    # ------------------------------------------------------------------
    # allocation
    # ------------------------------------------------------------------
    def reset(self, mem: np.ndarray | None) -> None:
        """(Re)bind host contents; device copy becomes stale."""
        self._check_not_tracing("reset")
        if mem is None:
            self._mem = None
            self._devmem = None
            self._state = _State.EMPTY
            return
        arr = np.asarray(mem)
        if arr.ndim and not arr.flags.c_contiguous:
            arr = np.ascontiguousarray(arr)  # NB: would promote 0-d to 1-d
        self._mem = arr
        self._state = _State.HOST

    def initialize(self, device: "Device") -> None:
        """Attach to a device; upload if the host copy is authoritative.

        Reference: ``Vector.initialize`` in ``veles/memory.py`` — called
        from ``AcceleratedUnit.init_vectors``.
        """
        self._check_not_tracing("initialize")
        self._device = device
        if device.is_host_only:
            return
        if self._state == _State.HOST:
            self._devmem = device.put(self._mem, vector=self)
            _count_transfer("h2d", self._mem.nbytes)
            self._state = _State.SYNCED

    @property
    def needs_collective_read(self) -> bool:
        """True when reading this Vector back to the host requires a
        cross-process collective (multi-process SPMD, batch-sharded
        buffer).  Such reads are only safe in lockstep — master-only
        paths (snapshots) must skip these Vectors or they deadlock."""
        dev = self._devmem
        return (self._state == _State.DEVICE
                and hasattr(dev, "is_fully_addressable")
                and not dev.is_fully_addressable
                and not dev.sharding.is_fully_replicated)

    # ------------------------------------------------------------------
    # the map/unmap protocol
    # ------------------------------------------------------------------
    def map_read(self) -> None:
        """Make the host copy current for reading."""
        self._check_not_tracing("map_read")
        if self._state == _State.EMPTY:
            raise ValueError(f"Vector '{self.name}': map_read on empty buffer")
        if self._state == _State.DEVICE:
            assert self._device is not None
            self._mem = self._device.get(self._devmem)
            _count_transfer("d2h", self._mem.nbytes)
            self._state = _State.SYNCED

    def map_write(self) -> None:
        """Make the host copy current and mark it authoritative."""
        self.map_read()
        if self._mem is not None and not self._mem.flags.writeable:
            # device.get may hand back a zero-copy read-only view
            self._mem = np.array(self._mem, copy=True)
        self._state = _State.HOST

    def map_invalidate(self) -> None:
        """Host will fully overwrite; skip the device→host fetch."""
        self._check_not_tracing("map_invalidate")
        if self._state == _State.EMPTY:
            raise ValueError(
                f"Vector '{self.name}': map_invalidate on empty buffer")
        if self._mem is None:
            assert self._devmem is not None
            self._mem = np.empty(self._devmem.shape,
                                 dtype=np.dtype(self._devmem.dtype))
        elif not self._mem.flags.writeable:
            self._mem = np.empty_like(self._mem)
        self._state = _State.HOST

    def unmap(self) -> None:
        """Make the device copy current (upload if host was written)."""
        self._check_not_tracing("unmap")
        if self._state == _State.EMPTY:
            raise ValueError(f"Vector '{self.name}': unmap on empty buffer")
        if self._device is None or self._device.is_host_only:
            return
        if self._state == _State.HOST:
            self._devmem = self._device.put(self._mem, vector=self)
            _count_transfer("h2d", self._mem.nbytes)
        self._state = _State.DEVICE

    # ------------------------------------------------------------------
    # storage access
    # ------------------------------------------------------------------
    @property
    def mem(self) -> np.ndarray:
        """The host ndarray.  Caller must hold a map_read/map_write."""
        if self._state == _State.DEVICE:
            raise ValueError(
                f"Vector '{self.name}': host access while device copy is "
                f"authoritative — call map_read()/map_write() first")
        if self._mem is None:
            raise ValueError(f"Vector '{self.name}': no storage")
        return self._mem

    @mem.setter
    def mem(self, value: np.ndarray) -> None:
        self.reset(value)

    @property
    def devmem(self):
        """The device ``jax.Array`` (or tracer inside a jit region)."""
        if self._tracing:
            return self._devmem
        if self._device is None or self._device.is_host_only:
            # Host-only backend: the ndarray *is* the device buffer.
            return self.mem
        if self._state == _State.HOST:
            raise ValueError(
                f"Vector '{self.name}': device access while host copy is "
                f"authoritative — call unmap() first")
        if self._devmem is None:
            raise ValueError(f"Vector '{self.name}': not initialized "
                             f"on a device")
        return self._devmem

    @devmem.setter
    def devmem(self, value) -> None:
        """Functional update from device compute (eager xla_run or the
        region builder writing traced results back).

        FLOAT writes are cast to the DECLARED dtype (the host
        mirror's, set at allocation) when they disagree — the
        storage-precision contract: a bf16-declared activation vector
        stores bf16 no matter what precision the producing math ran
        in, and scan carries (``JitRegion.run_chunk``) stay
        dtype-stable across steps.  Matching writes are untouched, and
        non-float mismatches (e.g. an int64 write into an int32 index
        vector) are NOT silently coerced — those are unit bugs that
        should stay visible.
        """
        if (self._mem is not None and hasattr(value, "dtype")
                and value.dtype != self._mem.dtype
                and hasattr(value, "astype")
                and _is_float_dtype(np.dtype(value.dtype))
                and _is_float_dtype(self._mem.dtype)):
            value = value.astype(self._mem.dtype)
        self._devmem = value
        if not self._tracing:
            self._state = _State.DEVICE

    def accept_device(self, devarr) -> None:
        """Adopt an ALREADY-uploaded device array as the authoritative
        copy — the streaming data plane's delivery handoff: an uploader
        thread ``device_put`` the staged batch while the previous step
        computed, and delivery is this pointer swap (zero host work on
        the step's critical path).  Shape/dtype must match the declared
        storage so consumers (jit regions) never see a new signature —
        the zero-recompile contract.  (Multi-process arrays are
        globally shaped while the host mirror holds only the local
        shard; those skip the host-shape check.)"""
        self._check_not_tracing("accept_device")
        if self._state == _State.EMPTY:
            raise ValueError(
                f"Vector '{self.name}': accept_device on empty buffer")
        addressable = getattr(devarr, "is_fully_addressable", True)
        if self._mem is not None and addressable:
            if (tuple(devarr.shape) != tuple(self._mem.shape)
                    or np.dtype(devarr.dtype) != self._mem.dtype):
                raise ValueError(
                    f"Vector '{self.name}': accept_device "
                    f"{devarr.shape}/{devarr.dtype} does not match the "
                    f"declared {self._mem.shape}/{self._mem.dtype}")
        self._devmem = devarr
        self._state = _State.DEVICE

    @property
    def state_name(self) -> str:
        return self._state.name

    # ------------------------------------------------------------------
    # conveniences
    # ------------------------------------------------------------------
    @property
    def shape(self) -> tuple[int, ...]:
        if self._mem is not None:
            return tuple(self._mem.shape)
        if self._devmem is not None:
            return tuple(self._devmem.shape)
        raise ValueError(f"Vector '{self.name}': no storage")

    @property
    def dtype(self) -> np.dtype:
        if self._mem is not None and self._state != _State.DEVICE:
            return self._mem.dtype
        if self._devmem is not None:
            return np.dtype(self._devmem.dtype)
        if self._mem is not None:
            return self._mem.dtype
        raise ValueError(f"Vector '{self.name}': no storage")

    @property
    def size(self) -> int:
        return int(np.prod(self.shape)) if self else 0

    @property
    def sample_size(self) -> int:
        """Elements per sample (all dims but the first — the reference's
        frequent ``size // shape[0]`` idiom)."""
        shape = self.shape
        return int(np.prod(shape[1:])) if len(shape) > 1 else 1

    # -- ZeRO-1 padding helpers (snapshot layout independence) ---------
    def strip_data_pad(self, arr: np.ndarray) -> np.ndarray:
        """Remove the ``data_shard_pad`` zero rows — the LOGICAL
        content a snapshot stores, independent of the mesh size the
        padding was computed for."""
        if not self.data_shard_pad or self.data_shard_dim is None:
            return arr
        dim = self.data_shard_dim
        idx = [slice(None)] * arr.ndim
        idx[dim] = slice(0, arr.shape[dim] - self.data_shard_pad)
        return arr[tuple(idx)]

    def apply_data_pad(self, arr: np.ndarray) -> np.ndarray:
        """Re-pad a logical (snapshot) array to THIS Vector's padded
        storage shape — the inverse of :meth:`strip_data_pad` under the
        CURRENT mesh (a restore may re-shard onto a different mesh size
        than the one that saved)."""
        if not self.data_shard_pad or self.data_shard_dim is None:
            return arr
        dim = self.data_shard_dim
        want = self.shape[dim]
        have = arr.shape[dim]
        if have == want:
            return arr
        widths = [(0, 0)] * arr.ndim
        widths[dim] = (0, want - have)
        return np.pad(arr, widths)

    def __bool__(self) -> bool:
        return self._state != _State.EMPTY

    def __len__(self) -> int:
        return self.shape[0] if self else 0

    def __array__(self, dtype=None, copy=None):
        self.map_read()
        arr = self.mem
        return arr.astype(dtype) if dtype is not None else arr

    def __getitem__(self, idx):
        return self.mem[idx]

    def __setitem__(self, idx, value) -> None:
        self.mem[idx] = value

    def __repr__(self) -> str:
        if not self:
            return f"Vector('{self.name}', empty)"
        return (f"Vector('{self.name}', {self.shape}, {self.dtype}, "
                f"{self._state.name})")

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------
    def _check_not_tracing(self, op: str) -> None:
        if self._tracing:
            raise RuntimeError(
                f"Vector '{self.name}': {op}() inside a jit region — "
                f"host sync is not allowed in traced code; move this "
                f"unit out of the region or use device-side state")


class StagingRing:
    """Bounded ring of reusable host staging buffers — the streaming
    data plane's slot pool.

    Producers :meth:`acquire` a free slot, fill it (shard reads /
    decode), and hand the index downstream; whoever finishes with the
    contents (the uploader after ``device_put``, or the consumer on
    host-only backends) :meth:`release`\\ s it.  The bound is the
    backpressure mechanism: a stalled consumer blocks the producers at
    ``acquire`` instead of growing host memory — total staging
    footprint is pinned at ``n_slots × batch_bytes`` no matter how
    large the dataset is.

    Thread-safe; allocation happens once, up front (steady state does
    zero allocations on the step path).
    """

    def __init__(self, n_slots: int, shape: tuple[int, ...],
                 dtype) -> None:
        import queue
        if n_slots < 1:
            raise ValueError(f"StagingRing needs >= 1 slot, got {n_slots}")
        self._bufs = [np.zeros(shape, dtype=dtype) for _ in range(n_slots)]
        self._free: "queue.Queue[int]" = queue.Queue()
        for i in range(n_slots):
            self._free.put(i)

    @property
    def n_slots(self) -> int:
        return len(self._bufs)

    @property
    def n_free(self) -> int:
        return self._free.qsize()

    @property
    def nbytes(self) -> int:
        """Total host bytes pinned by the ring."""
        return sum(b.nbytes for b in self._bufs)

    def buffer(self, slot: int) -> np.ndarray:
        return self._bufs[slot]

    def acquire(self, timeout: float | None = None) -> int | None:
        """Next free slot index; blocks (bounded by ``timeout``) when
        the ring is full downstream.  ``None`` on timeout so pipeline
        threads can re-check their stop flag instead of hanging."""
        import queue
        try:
            return self._free.get(timeout=timeout)
        except queue.Empty:
            return None

    def release(self, slot: int) -> None:
        self._free.put(slot)


class PageStager:
    """Pinned staging rings + ONE uploader thread for KV-page h2d
    traffic (round 22) — the :class:`StagingRing` machinery the
    streaming loader runs for training batches, specialized to the
    serving data plane's unit of transfer: one KV-cache *page* per
    pool array (a spill restore promoting a cold prefix block back to
    HBM, or a prefill→decode handoff landing a prompt's pages in the
    decode pool's cache).

    One ring per page-pool spec (K and V pools have the same page
    shape but int8-quantized caches add f32 scale pools with their
    own), so a staged page is a *set* of per-pool buffers travelling
    together under one slot index tuple.  :meth:`upload` is
    synchronous for the caller — stage (memcpy into the pinned slot)
    → enqueue → the uploader thread ``device_put``\\ s and fences —
    because the caller's very next dispatch consumes the arrays; the
    ring bound is still load-bearing: concurrent uploaders (several
    decode-pool replicas accepting handoffs) backpressure at
    ``acquire`` instead of growing host memory.
    """

    def __init__(self, shapes_dtypes: list[tuple[tuple, object]],
                 n_slots: int = 2) -> None:
        import queue
        import threading
        self._rings = [StagingRing(n_slots, tuple(shape), dtype)
                       for shape, dtype in shapes_dtypes]
        self._work: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(
            target=self._upload_loop, name="page-uploader", daemon=True)
        self._thread.start()

    @property
    def nbytes(self) -> int:
        return sum(r.nbytes for r in self._rings)

    def _upload_loop(self) -> None:
        import jax
        while True:
            item = self._work.get()
            if item is None:
                return
            slots, fut = item
            try:
                out = []
                for ring, slot in zip(self._rings, slots):
                    out.append(jax.device_put(ring.buffer(slot)))
                for arr in out:  # fence: the slot is reusable only
                    arr.block_until_ready()  # once the copy landed
                fut.set_result(out)
            except Exception as exc:  # noqa: BLE001 — caller's error
                fut.set_exception(exc)
            finally:
                for ring, slot in zip(self._rings, slots):
                    ring.release(slot)

    def upload(self, pages: list[np.ndarray],
               timeout: float = 30.0) -> list:
        """Stage one page set and return its device arrays (blocks
        until the uploader fenced the copies)."""
        from concurrent.futures import Future
        if len(pages) != len(self._rings):
            raise ValueError(f"page set has {len(pages)} arrays, "
                             f"stager expects {len(self._rings)}")
        slots: list[int] = []
        for ring, page in zip(self._rings, pages):
            slot = ring.acquire(timeout=timeout)
            if slot is None:
                for r, s in zip(self._rings, slots):
                    r.release(s)
                raise TimeoutError(
                    "page staging ring full — uploader stalled past "
                    f"{timeout}s")
            np.copyto(ring.buffer(slot), page)
            slots.append(slot)
        fut: Future = Future()
        self._work.put((slots, fut))
        out = fut.result(timeout=timeout)
        if _metrics.enabled():
            _metrics.transfer_bytes("h2d").inc(
                sum(int(p.nbytes) for p in pages))
        return out

    def shutdown(self) -> None:
        self._work.put(None)
        self._thread.join(timeout=5.0)


class HostPageTier:
    """Host-DRAM tier for cold KV pages (round 22) — the capacity
    layer under the HBM page pool that lets a prefix working set
    survive past ``pool_pages``.

    Frames are preallocated numpy buffers (one ``(capacity, ...page
    shape)`` block per pool spec, allocation-free steady state); the
    free list hands out frame ids with the same exactly-once
    discipline as :class:`~znicz_tpu.serving.decode.PagedKVCache`
    page ids — a frame id is held by AT MOST ONE trie node, and a
    spilled block lives in exactly one tier at a time (HBM page XOR
    host frame; the accounting invariant
    tests/test_disagg.py pins).  Restores travel through the
    :class:`PageStager` ring + uploader thread.
    """

    def __init__(self, shapes_dtypes: list[tuple[tuple, object]],
                 capacity_pages: int, stager: PageStager | None = None,
                 ring_slots: int = 2) -> None:
        self.capacity = int(capacity_pages)
        if self.capacity < 1:
            raise ValueError(
                f"host tier needs >= 1 page, got {capacity_pages}")
        self._frames = [np.zeros((self.capacity,) + tuple(shape),
                                 dtype)
                        for shape, dtype in shapes_dtypes]
        self._free = list(range(self.capacity - 1, -1, -1))
        self._own_stager = stager is None
        self.stager = (stager if stager is not None
                       else PageStager(shapes_dtypes,
                                       n_slots=ring_slots))

    @property
    def used(self) -> int:
        return self.capacity - len(self._free)

    @property
    def full(self) -> bool:
        return not self._free

    @property
    def nbytes(self) -> int:
        return sum(f.nbytes for f in self._frames)

    def store(self, pages: list[np.ndarray]) -> int | None:
        """Land one exported page set in a free frame; ``None`` when
        the tier is full (caller falls back to eviction)."""
        if not self._free:
            return None
        hid = self._free.pop()
        for frame, page in zip(self._frames, pages):
            np.copyto(frame[hid], page)
        if _metrics.enabled():
            _metrics.transfer_bytes("d2h").inc(
                sum(int(p.nbytes) for p in pages))
        return hid

    def read(self, hid: int) -> list[np.ndarray]:
        return [frame[hid] for frame in self._frames]

    def upload(self, hid: int) -> list:
        """Device arrays for one stored frame, via the staging ring +
        uploader thread (the restore h2d path)."""
        return self.stager.upload(self.read(hid))

    def free(self, hid: int) -> None:
        self._free.append(hid)

    def shutdown(self) -> None:
        if self._own_stager:
            self.stager.shutdown()
