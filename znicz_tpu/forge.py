"""Forge: model-zoo packaging, publishing and fetching.

Rebuilds the reference's ``veles/forge/`` (VelesForge — the service
the reference used to package trained workflows, upload them to a
registry and fetch/run other people's; tornado server + client).

Here a **forge bundle** is one ``.forge.tar.gz`` holding:

- ``manifest.json`` — name/version/author/description + the training
  metrics snapshot;
- ``model.npz`` — the servable forward chain
  (:mod:`znicz_tpu.export` bundle; reload with ``ExportedModel``);
- optionally the post-training report (``report.json``).

:class:`ForgeRegistry` is the store (a directory, versioned);
:class:`ForgeServer`/:class:`ForgeClient` wrap it over HTTP (stdlib
``http.server``/``urllib`` — no tornado in this environment) so one
host can publish models to the rest of a site, exactly the VelesForge
workflow.

Integrity (round 16): every upload writes a ``.sha256`` sidecar (the
:mod:`znicz_tpu.resilience.publisher` convention) and every
:meth:`ForgeRegistry.fetch` verifies it BEFORE the bundle reaches a
loader — a corrupt bundle is moved to the ``quarantine/`` subdirectory
(counted on ``znicz_snapshot_failures_total{op=forge}``) and, when no
explicit version was requested, the fetch falls back to the newest
older good version (``znicz_recoveries_total{kind=forge_fallback}``).
Pre-round-16 bundles without a sidecar get one on first verified read
(trust-on-first-fetch, then pinned).  The ``fleet.model_corrupt``
chaos site injects exactly this failure."""

from __future__ import annotations

import json
import os
import re
import shutil
import tarfile
import tempfile
import threading
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from znicz_tpu.utils.logger import Logger

_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.-]*$")


def _check_name(name: str, what: str) -> str:
    if not _NAME_RE.match(name):
        raise ValueError(f"invalid {what} '{name}' (letters, digits, "
                         f"'._-' only)")
    return name


def package(workflow, path: str, name: str | None = None,
            version: str = "1.0.0", author: str = "",
            description: str = "") -> str:
    """Package a trained workflow into a forge bundle at ``path``."""
    from znicz_tpu.export import export_forward
    from znicz_tpu.publishing import gather_report
    name = _check_name(name or workflow.name, "model name")
    _check_name(version, "version")
    report = gather_report(workflow)
    manifest = {
        "format": "znicz-tpu-forge",
        "name": name,
        "version": version,
        "author": author,
        "description": description,
        "workflow": workflow.name,
        "metrics": report.get("metrics", {}),
    }
    with tempfile.TemporaryDirectory() as tmp:
        model_path = os.path.join(tmp, "model.npz")
        export_forward(workflow, model_path)
        with open(os.path.join(tmp, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=2)
        with open(os.path.join(tmp, "report.json"), "w") as f:
            json.dump(report, f, indent=2, default=str)
        tmp_tar = f"{path}.{os.getpid()}.tmp"
        with tarfile.open(tmp_tar, "w:gz") as tar:
            for fname in ("manifest.json", "model.npz", "report.json"):
                tar.add(os.path.join(tmp, fname), arcname=fname)
        os.replace(tmp_tar, path)
    return path


def _tar_member(tar: tarfile.TarFile, name: str, bundle_path: str):
    try:  # extractfile raises KeyError for a missing member
        member = tar.extractfile(name)
    except KeyError:
        member = None
    if member is None:
        raise ValueError(f"{bundle_path}: no {name} "
                         f"(not a forge bundle)")
    return member


def read_manifest(bundle_path: str) -> dict:
    with tarfile.open(bundle_path, "r:gz") as tar:
        manifest = json.load(
            _tar_member(tar, "manifest.json", bundle_path))
    if manifest.get("format") != "znicz-tpu-forge":
        raise ValueError(f"{bundle_path}: not a forge bundle")
    return manifest


def extract_model(bundle_path: str, directory: str) -> str:
    """Extract the servable ``model.npz``; returns its path (load with
    :class:`znicz_tpu.export.ExportedModel`)."""
    os.makedirs(directory, exist_ok=True)
    with tarfile.open(bundle_path, "r:gz") as tar:
        member = _tar_member(tar, "model.npz", bundle_path)
        out = os.path.join(directory, "model.npz")
        with open(out, "wb") as f:
            shutil.copyfileobj(member, f)
    return out


class ForgeRegistry(Logger):
    """A versioned bundle store: ``<dir>/<name>/<version>.forge.tar.gz``."""

    def __init__(self, directory: str) -> None:
        super().__init__()
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _bundle_path(self, name: str, version: str) -> str:
        return os.path.join(self.directory, _check_name(name, "name"),
                            f"{_check_name(version, 'version')}"
                            f".forge.tar.gz")

    def upload(self, bundle_path: str) -> dict:
        from znicz_tpu.utils.snapshotter import _sha256_file
        manifest = read_manifest(bundle_path)
        dest = self._bundle_path(manifest["name"], manifest["version"])
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        # atomic + exclusive publish: copy to tmp, then hard-link into
        # place — link fails if dest exists, closing the concurrent-
        # upload race that a check-then-replace would leave open
        tmp = f"{dest}.{os.getpid()}.tmp"
        shutil.copyfile(bundle_path, tmp)
        digest = _sha256_file(tmp)
        try:
            os.link(tmp, dest)
        except FileExistsError:
            raise FileExistsError(
                f"{manifest['name']} {manifest['version']} already "
                f"published (versions are immutable)") from None
        finally:
            os.unlink(tmp)
        # digest sidecar AFTER the bundle lands (publisher.py write
        # order): a concurrent fetch sees either a complete pair or a
        # sidecar-less file it will TOFU-verify
        side_tmp = f"{dest}.sha256.{os.getpid()}.tmp"
        with open(side_tmp, "w") as f:
            f.write(digest + "\n")
        os.replace(side_tmp, f"{dest}.sha256")
        self.info("published %s %s (sha256 %s…)", manifest["name"],
                  manifest["version"], digest[:12])
        return manifest

    def list(self) -> dict[str, list[str]]:
        out: dict[str, list[str]] = {}
        for name in sorted(os.listdir(self.directory)):
            full = os.path.join(self.directory, name)
            if not os.path.isdir(full):
                continue
            versions = sorted(
                f[:-len(".forge.tar.gz")] for f in os.listdir(full)
                if f.endswith(".forge.tar.gz"))
            if versions:
                out[name] = versions
        return out

    def latest_version(self, name: str) -> str:
        versions = self.list().get(name)
        if not versions:
            raise KeyError(f"no published model '{name}'")
        # semver-flavored ordering: 1.10.0 > 1.9.0 (numeric-aware),
        # 2.0.0 > 2.0.0-rc1 (a release outranks its pre-release tags),
        # 2.0.1 > 2.0.0.  Segment ranks: string(0) < absent(1) <
        # numeric(2); versions are padded to equal length with the
        # 'absent' sentinel.
        split = {v: re.split(r"[._-]", v) for v in versions}
        width = max(len(parts) for parts in split.values())

        def key(v: str):
            parts = [(2, int(p), "") if p.isdigit() else (0, 0, p)
                     for p in split[v]]
            return parts + [(1, 0, "")] * (width - len(parts))
        return sorted(versions, key=key)[-1]

    def _verify(self, name: str, version: str, path: str) -> None:
        """Digest-check one bundle; raises ``SnapshotCorrupt`` on a
        mismatch (or when the ``fleet.model_corrupt`` chaos site says
        so).  A sidecar-less legacy bundle is hashed and pinned on
        first read (trust-on-first-fetch)."""
        from znicz_tpu.resilience import faults as _faults
        from znicz_tpu.utils.snapshotter import (SnapshotCorrupt,
                                                 _sha256_file)
        if _faults.fire("fleet.model_corrupt", name=name,
                        version=version) is not None:
            raise SnapshotCorrupt(
                f"{path}: injected digest corruption "
                f"(fleet.model_corrupt)")
        sidecar = f"{path}.sha256"
        got = _sha256_file(path)
        if not os.path.exists(sidecar):
            side_tmp = f"{sidecar}.{os.getpid()}.tmp"
            with open(side_tmp, "w") as f:
                f.write(got + "\n")
            os.replace(side_tmp, sidecar)
            self.info("pinned legacy bundle %s %s on first fetch "
                      "(sha256 %s…)", name, version, got[:12])
            return
        with open(sidecar) as f:
            want = f.read().strip()
        if got != want:
            raise SnapshotCorrupt(
                f"{path}: sha256 {got[:12]}… != sidecar {want[:12]}…")

    def _quarantine(self, name: str, path: str) -> str:
        """Move a corrupt bundle (+ sidecar) out of the serving set so
        no later fetch or latest_version can ever surface it again."""
        qdir = os.path.join(self.directory, name, "quarantine")
        os.makedirs(qdir, exist_ok=True)
        dest = os.path.join(qdir, os.path.basename(path))
        os.replace(path, dest)
        sidecar = f"{path}.sha256"
        if os.path.exists(sidecar):
            os.replace(sidecar, f"{dest}.sha256")
        from znicz_tpu.observe import recorder as _recorder
        _recorder.record("bundle_quarantine", model=name,
                         bundle=os.path.basename(path))
        return dest

    def fetch(self, name: str, version: str | None = None) -> str:
        """The digest-VERIFIED bundle path for ``name`` (newest
        version when ``version`` is None).  A bundle failing
        verification is quarantined; with no explicit version the
        fetch falls back to the newest older good version, raising
        ``SnapshotCorrupt`` only when nothing verifies."""
        from znicz_tpu.observe import metrics as _metrics
        from znicz_tpu.utils.snapshotter import SnapshotCorrupt
        explicit = version is not None
        fell_back = False
        last_exc: Exception | None = None
        while True:
            version = version if explicit else self.latest_version(name)
            path = self._bundle_path(name, version)
            if not os.path.exists(path):
                raise KeyError(f"no bundle {name} {version}")
            try:
                self._verify(name, version, path)
            except SnapshotCorrupt as exc:
                _metrics.snapshot_failures("forge").inc()
                quarantined = self._quarantine(name, path)
                self.warning("quarantined %s %s → %s: %s", name,
                             version, quarantined, exc)
                last_exc = exc
                if explicit:
                    raise
                if not self.list().get(name):
                    raise SnapshotCorrupt(
                        f"no version of '{name}' verifies "
                        f"(last: {last_exc})") from exc
                fell_back = True
                version = None
                continue
            if fell_back:
                _metrics.recoveries("forge_fallback").inc()
                self.info("fetch fell back to %s %s after "
                          "quarantining newer corrupt version(s)",
                          name, version)
            return path

    def manifest(self, name: str, version: str | None = None) -> dict:
        return read_manifest(self.fetch(name, version))


class ForgeServer(Logger):
    """HTTP front for a registry: ``GET /list``, ``GET
    /fetch?name=&version=``, ``POST /upload`` (bundle body)."""

    def __init__(self, registry: ForgeRegistry, port: int = 0,
                 host: str = "127.0.0.1") -> None:
        super().__init__()
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                server.debug("http: " + fmt, *args)

            def _send(self, code: int, body: bytes,
                      ctype: str = "application/json"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                parsed = urllib.parse.urlparse(self.path)
                if parsed.path == "/list":
                    self._send(200, json.dumps(
                        registry.list()).encode())
                    return
                if parsed.path == "/fetch":
                    from znicz_tpu.utils.snapshotter import \
                        SnapshotCorrupt
                    q = urllib.parse.parse_qs(parsed.query)
                    try:
                        path = registry.fetch(
                            q["name"][0],
                            q.get("version", [None])[0])
                    except SnapshotCorrupt as exc:
                        # never stream corrupt bytes to a client: the
                        # bundle was quarantined, nothing verifies
                        self._send(410, json.dumps(
                            {"error": str(exc)}).encode())
                        return
                    except (KeyError, ValueError) as exc:
                        self._send(404, json.dumps(
                            {"error": str(exc)}).encode())
                        return
                    # stream: bundles carry full weight dumps
                    size = os.path.getsize(path)
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/gzip")
                    self.send_header("Content-Length", str(size))
                    self.end_headers()
                    with open(path, "rb") as f:
                        shutil.copyfileobj(f, self.wfile)
                    return
                self._send(404, b'{"error": "unknown path"}')

            def do_POST(self):
                if self.path != "/upload":
                    self._send(404, b'{"error": "unknown path"}')
                    return
                length = int(self.headers.get("Content-Length", 0))
                tmp = tempfile.NamedTemporaryFile(
                    suffix=".forge.tar.gz", delete=False)
                try:
                    # chunked spool to disk, not a whole-blob buffer
                    remaining = length
                    while remaining > 0:
                        chunk = self.rfile.read(min(remaining, 1 << 20))
                        if not chunk:
                            break
                        tmp.write(chunk)
                        remaining -= len(chunk)
                    tmp.close()
                    manifest = registry.upload(tmp.name)
                    self._send(200, json.dumps(manifest).encode())
                except (ValueError, FileExistsError,
                        tarfile.TarError) as exc:
                    self._send(400, json.dumps(
                        {"error": str(exc)}).encode())
                finally:
                    os.unlink(tmp.name)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="forge", daemon=True)
        self._thread.start()
        self.info("forge @ http://%s:%d/", self.host, self.port)

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=10)


class ForgeClient(Logger):
    """Talk to a remote :class:`ForgeServer`."""

    def __init__(self, base_url: str, timeout: float = 30.0) -> None:
        super().__init__()
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def list(self) -> dict[str, list[str]]:
        with urllib.request.urlopen(f"{self.base_url}/list",
                                    timeout=self.timeout) as resp:
            return json.load(resp)

    def fetch(self, name: str, directory: str,
              version: str | None = None) -> str:
        query = {"name": name}
        if version:
            query["version"] = version
        url = (f"{self.base_url}/fetch?"
               f"{urllib.parse.urlencode(query)}")
        os.makedirs(directory, exist_ok=True)
        dest = os.path.join(
            directory, f"{name}-{version or 'latest'}.forge.tar.gz")
        with urllib.request.urlopen(url, timeout=self.timeout) as resp:
            with open(dest, "wb") as f:
                shutil.copyfileobj(resp, f)
        return dest

    def upload(self, bundle_path: str) -> dict:
        size = os.path.getsize(bundle_path)
        with open(bundle_path, "rb") as f:  # streamed request body
            req = urllib.request.Request(
                f"{self.base_url}/upload", data=f,
                headers={"Content-Type": "application/gzip",
                         "Content-Length": str(size)})
            try:
                with urllib.request.urlopen(
                        req, timeout=self.timeout) as resp:
                    return json.load(resp)
            except urllib.error.HTTPError as exc:
                detail = exc.read().decode(errors="replace")
                raise RuntimeError(
                    f"upload rejected ({exc.code}): {detail}") from exc
