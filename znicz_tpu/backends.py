"""Device backends.

Rebuilds the reference's backend abstraction (reference:
``veles/backends.py`` — ``Device``/``OpenCLDevice``/``CUDADevice``/
``NumpyDevice`` selected by ``root.common.engine.backend``) for TPU:

- :class:`XLADevice` is the accelerator backend: jax/XLA over PJRT.
  It works on any jax platform (``tpu`` in production, ``cpu`` in unit
  tests with a virtual multi-device mesh) because the compute path is
  pure jax — this mirrors how the reference's units ran unchanged on
  OpenCL *or* CUDA.
- :class:`TPUDevice` is the TPU-pinned convenience subclass.
- :class:`NumpyDevice` is the host oracle backend: every unit's
  ``numpy_run`` is the spec that ``xla_run`` is tested against
  (reference test strategy, SURVEY.md §4).

There is no kernel build/autotune machinery here on purpose: XLA owns
tiling and fusion; the reference's per-device BLOCK_SIZE autotuning
(``veles/backends.py``) has no TPU analogue.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.observe import metrics as _metrics
from znicz_tpu.utils.config import root
from znicz_tpu.utils.logger import Logger


_PRECISION_BY_LEVEL = {0: "default", 1: "float32", 2: "highest"}


class Device(Logger):
    """Backend base class."""

    backend = "abstract"
    #: True when there is no separate device memory (numpy oracle).
    is_host_only = False

    def __init__(self, **kwargs) -> None:
        super().__init__(**kwargs)
        self.compute_dtype = np.dtype(
            root.common.get("precision_type", "float32"))

    @staticmethod
    def create(backend: str | None = None) -> "Device":
        """Factory honoring ``root.common.engine.backend``."""
        backend = backend or root.common.engine.backend
        if backend == "numpy":
            return NumpyDevice()
        if backend == "tpu":
            return TPUDevice()
        if backend == "xla":
            return XLADevice()
        raise ValueError(f"unknown backend '{backend}' "
                         f"(expected xla | tpu | numpy)")

    # transfer API used by Vector -------------------------------------
    def put(self, arr: np.ndarray, vector=None):
        raise NotImplementedError

    def put_local_batch(self, arr: np.ndarray, vector=None):
        """Place a host-staged batch-major buffer.  Single-process
        backends: identical to :meth:`put`.  Multi-process SPMD
        overrides assemble the GLOBAL batch from this process's 1/N of
        the rows — the placement half of the streaming data plane's
        per-host sharded reads."""
        return self.put(arr, vector=vector)

    def get(self, devarr) -> np.ndarray:
        raise NotImplementedError

    def sync(self) -> None:
        """Block until queued device work completes."""

    @property
    def supports_donation(self) -> bool:
        """True when XLA implements input-buffer donation on this
        platform (TPU/GPU).  The serving engine's AOT programs donate
        the request buffer when they can — CPU only warns."""
        return False


class NumpyDevice(Device):
    """Host-only oracle backend (reference: ``NumpyDevice``)."""

    backend = "numpy"
    is_host_only = True

    def __init__(self, **kwargs) -> None:
        super().__init__(**kwargs)
        if _metrics.enabled():
            _metrics.backend_info(self.backend, "host").set(1)

    def put(self, arr: np.ndarray, vector=None) -> np.ndarray:
        return arr

    def get(self, devarr) -> np.ndarray:
        return np.asarray(devarr)


class XLADevice(Device):
    """jax/XLA backend over PJRT — the ``xla_run`` target.

    ``precision_type``/``precision_level`` from the config tree map to
    the matmul input dtype and ``jax.lax.Precision``:

    - level 0 (fast): inputs in ``precision_type`` (bf16 recommended on
      TPU — native MXU dtype), default XLA precision;
    - level 1: f32 matmul precision (deterministic accumulation);
    - level 2: ``highest`` (f32 data passes through MXU in multiple
      passes).
    """

    backend = "xla"
    platform: str | None = None  # subclass pin; None = jax default

    def __init__(self, device: "jax.Device | None" = None,
                 mesh: "jax.sharding.Mesh | None" = None, **kwargs) -> None:
        super().__init__(**kwargs)
        #: when set, this device is SPMD over the mesh: batch-major
        #: Vectors are sharded over the 'data' axis, everything else is
        #: replicated, and XLA inserts the ICI collectives — the TPU
        #: replacement for the reference's master–slave cluster
        #: (reference: veles/server.py / veles/client.py; SURVEY.md §2.5)
        self.mesh = mesh
        if device is None:
            if mesh is not None:
                device = mesh.devices.flat[0]
            else:
                devices = (jax.devices(self.platform) if self.platform
                           else jax.devices())
                device = devices[0]
        self.jax_device = device
        self.compute_dtype = np.dtype(
            root.common.get("precision_type", "float32"))
        level = int(root.common.get("precision_level", 0))
        self.matmul_precision = _PRECISION_BY_LEVEL.get(level, "default")
        self.debug("XLA device %s (platform=%s, dtype=%s, precision=%s, "
                   "mesh=%s)", device, device.platform, self.compute_dtype,
                   self.matmul_precision,
                   None if mesh is None else dict(mesh.shape))
        if _metrics.enabled():
            _metrics.backend_info(self.backend, device.platform).set(1)
            # round 19: build-identity gauge with the full label set
            # (the backend is necessarily initialized here, so the
            # platform/process queries cannot wedge a cold tunnel)
            try:
                _metrics.set_build_info(
                    platform=device.platform,
                    mesh=("-" if mesh is None else "x".join(
                        str(n) for n in mesh.devices.shape)),
                    processes=jax.process_count())
            except Exception:  # noqa: BLE001 — telemetry only
                pass

    @property
    def supports_donation(self) -> bool:
        return self.jax_device.platform in ("tpu", "gpu", "cuda", "rocm")

    @property
    def n_data_shards(self) -> int:
        from znicz_tpu.parallel.axis import DATA_AXIS
        return 1 if self.mesh is None else self.mesh.shape[DATA_AXIS]

    def sharding_for(self, vector) -> "jax.sharding.Sharding | None":
        """Placement for one Vector on this device's mesh.

        Table-bound Vectors (allocated through a workflow that owns a
        ``parallel.partition.PartitionTable``) are a pure LOOKUP: the
        spec was resolved once from the workflow's ordered rule table
        at bind time.  The attribute-derived branch below survives as
        the compatibility layer for bare Vectors (tests, serving
        staging buffers) and for the ``engine.partition_rules=False``
        A/B arm — the golden-table test pins the two paths
        bitwise-equal on the default tables.
        """
        if self.mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec
        from znicz_tpu.parallel import replicated_sharding
        from znicz_tpu.parallel.axis import DATA_AXIS, MODEL_AXIS
        if vector is None:
            return replicated_sharding(self.mesh)
        resolved = getattr(vector, "_partition", None)
        if resolved is not None:
            from znicz_tpu.parallel.partition import sharding_of
            return sharding_of(self.mesh, resolved)
        model_dim = getattr(vector, "model_shard_dim", None)
        model_axis = getattr(vector, "model_shard_axis", MODEL_AXIS)
        data_dim = getattr(vector, "data_shard_dim", None)
        member = getattr(vector, "member_axis", False)
        if member:
            # population-stacked buffer: dim 0 is the member axis and
            # rides the mesh's data axis — in population mode the K
            # model replicas ARE the data parallelism.  A member count
            # that does not divide the axis stays replicated (XLA
            # time-slices the members instead of sharding them).
            if vector.batch_major or data_dim is not None:
                raise ValueError(
                    f"Vector '{vector.name}': member_axis buffers "
                    f"cannot also be batch_major / ZeRO-1 data-sharded"
                    f" — the member axis owns the data axis")
            if model_dim == 0:
                raise ValueError(
                    f"Vector '{vector.name}': dim 0 is the member "
                    f"axis — it cannot also carry the model axis")
            ndim = len(vector.shape)
            spec = [None] * ndim
            if ndim and vector.shape[0] % self.n_data_shards == 0:
                spec[0] = DATA_AXIS
            if model_dim is not None:
                spec[model_dim] = model_axis
            return NamedSharding(self.mesh, PartitionSpec(*spec))
        if not vector.batch_major and model_dim is None \
                and data_dim is None:
            return replicated_sharding(self.mesh)
        ndim = len(vector.shape)
        spec: list = [None] * ndim
        if vector.batch_major and ndim:
            if data_dim is not None:
                raise ValueError(
                    f"Vector '{vector.name}': batch-major buffers "
                    f"already ride the data axis on dim 0 — "
                    f"data_shard_dim is for persistent (ZeRO-1) state")
            spec[0] = DATA_AXIS
        if data_dim is not None:
            # ZeRO-1 optimizer state: each chip stores 1/N of the
            # accumulator along this dim (nn_units pads the dim to a
            # multiple of the data-axis size at allocation)
            if data_dim == model_dim:
                raise ValueError(
                    f"Vector '{vector.name}': dim {data_dim} cannot "
                    f"carry both the data and the model axis")
            spec[data_dim] = DATA_AXIS
        if model_dim is not None:
            if model_dim == 0 and vector.batch_major:
                raise ValueError(
                    f"Vector '{vector.name}': dim 0 is the batch (data"
                    f"-sharded) — it cannot also carry the model axis")
            spec[model_dim] = model_axis
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def put(self, arr: np.ndarray, vector=None):
        if self.jax_device.platform == "cpu":
            # On the CPU backend device_put is ZERO-COPY for aligned
            # numpy arrays: the "device" buffer aliases the host array,
            # and a later host write (map_invalidate → mem[...] = …)
            # would corrupt async in-flight computation.  Detach.
            # (TPU/GPU transfers always copy; no cost there.)
            arr = np.array(arr, copy=True)
        sharding = self.sharding_for(vector)
        if sharding is None:
            return jax.device_put(arr, self.jax_device)
        if self.mesh is not None and jax.process_count() > 1 \
                and not sharding.is_fully_addressable:
            # Multi-process upload WITHOUT the hidden collective:
            # ``jax.device_put`` onto a non-addressable sharding runs
            # a host-side ``assert_equal`` broadcast, which executes
            # immediately on this thread while previously dispatched
            # step programs (and their in-program collectives) are
            # still in flight asynchronously — on the CPU/Gloo backend
            # the two interleave in different orders per process and
            # cross lanes (corrupt data or a gloo size-mismatch
            # abort).  Every host mirror is GLOBAL bookkeeping (the
            # per-host slice path is ``put_local_batch``), so each
            # addressable device's shard is a local slice of ``arr``
            # and no cross-process traffic is needed at all.
            return jax.make_array_from_callback(
                arr.shape, sharding, lambda idx: arr[idx])
        return jax.device_put(arr, sharding)

    def put_local_batch(self, arr: np.ndarray, vector=None):
        """Multi-process meshes: ``arr`` holds only THIS process's
        rows of the (batch-major) buffer; assemble the global sharded
        array without any cross-host gather.  Single-process falls
        through to :meth:`put` (arr already is the whole batch)."""
        if self.mesh is not None and jax.process_count() > 1:
            if self.jax_device.platform == "cpu":
                # same zero-copy hazard as :meth:`put`: on CPU the
                # local shards ALIAS the host array — a staging-ring
                # slot reused by the producer after upload would
                # silently rewrite the device batch (half the global
                # rows become the NEXT batch's data).  Detach.
                arr = np.array(arr, copy=True)
            sharding = self.sharding_for(vector)
            assert sharding is not None
            return jax.make_array_from_process_local_data(sharding, arr)
        return self.put(arr, vector=vector)

    def get(self, devarr) -> np.ndarray:
        if isinstance(devarr, jax.Array) and not devarr.is_fully_addressable:
            # Multi-process SPMD: this process holds only its shards.
            # Replicated arrays (params, scalars) read locally; sharded
            # ones all-gather — safe because every process runs the
            # same program and reaches this read in lockstep.
            if devarr.sharding.is_fully_replicated:
                return np.asarray(devarr.addressable_data(0))
            from jax.experimental import multihost_utils
            return np.asarray(
                multihost_utils.process_allgather(devarr, tiled=True))
        return np.asarray(jax.device_get(devarr))

    def sync(self) -> None:
        # Block on a trivial computation queued after outstanding work.
        jnp.zeros((), device=self.jax_device).block_until_ready()


class TPUDevice(XLADevice):
    """XLA backend pinned to the TPU platform (reference analogue:
    ``CUDADevice`` — the production accelerator backend)."""

    backend = "tpu"
    platform = "tpu"
