"""Population engine: K-replica evolution as a first-class mesh
workload (ROADMAP item 5).

- :class:`~znicz_tpu.population.engine.PopulationTrainer` — build K
  members of one sample architecture, train them simultaneously in one
  vmapped jit region (member axis sharded over the mesh's data axis),
  evolve at epoch boundaries;
- :class:`~znicz_tpu.population.engine.PopulationRegion` — the
  stacked-leaf step engine itself;
- :mod:`~znicz_tpu.population.evolution` — the deterministic on-device
  selection/crossover/mutation/truncation operators.
"""

from znicz_tpu.population.engine import (PopulationRegion,  # noqa: F401
                                         PopulationTrainer,
                                         harvest_state, leaf_keys,
                                         train_drafter)
from znicz_tpu.population import evolution  # noqa: F401

__all__ = ["PopulationRegion", "PopulationTrainer", "evolution",
           "harvest_state", "leaf_keys", "train_drafter"]
