"""Deterministic on-device evolution ops over the stacked population.

Every operator here is a pure function ``(fitness, key, *stacked_leaves)
-> stacked_leaves`` built once per population geometry and jitted by the
engine: selection, crossover and truncation are expressed as gathers and
blends along the leading member axis of the stacked parameter/optimizer
tree — NOT host loops over member checkpoints (the shape Veles's
genetics plugin had, one genome per cluster node).  When the member axis
is sharded over the mesh's data axis, a ``jnp.take`` along it lowers to
the cross-chip collective that moves a winner's weights to a loser's
shard — selection and crossover literally run on the interconnect.

Determinism: ``jnp.argsort`` is stable (ties resolve by member index)
and all randomness flows from one explicit PRNG key the engine derives
as ``fold_in(base_key, generation)``, so a rerun with the same seed
replays the identical evolutionary trajectory.

Two strategies:

- :func:`build_pbt_step` — PBT-style truncation (Jaderberg et al.,
  2017): the bottom ``truncation`` fraction *exploits* (copies a
  uniformly-drawn top member's weights, optimizer state and
  hyperparameters, bitwise) then *explores* (perturbs its learning
  rate by a factor drawn from ``factors``);
- :func:`build_ga_step` — GA-style refill (the reference's
  ``veles/genetics/`` shape, moved on device): non-elite slots are
  refilled by size-2 tournament parents, float leaves arithmetically
  blended (``β·a + (1−β)·b``), int leaves inherited from parent A,
  learning rates log-normally mutated.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp


def tournament(key, fitness, n_draws: int):
    """``(n_draws,)`` member indices via size-2 tournament selection:
    draw two members uniformly, keep the fitter (ties → the first)."""
    k = fitness.shape[0]
    pairs = jax.random.randint(key, (2, n_draws), 0, k)
    a, b = pairs[0], pairs[1]
    return jnp.where(fitness[a] >= fitness[b], a, b)


def truncation_count(n_members: int, truncation: float) -> int:
    """Members replaced per PBT generation: ``round(K·truncation)``,
    at least 1, never more than half the population (winners and
    losers must not overlap)."""
    if n_members < 2:
        return 0
    n_cut = int(round(n_members * truncation)) or 1
    return max(1, min(n_members // 2, n_cut))


def _bshape(leaf, k: int) -> tuple:
    return (k,) + (1,) * (leaf.ndim - 1)


def build_pbt_step(n_members: int, lr_slots: Sequence[int],
                   truncation: float = 0.25,
                   factors: tuple[float, float] = (0.8, 1.25),
                   lr_bounds: tuple[float, float] | None = None):
    """PBT truncation step over the stacked tree.

    Returns ``(fn, n_cut)`` where ``fn(fitness, key, *leaves)``
    replaces the ``n_cut`` worst members' leaves with a uniformly
    chosen top-``n_cut`` member's (exploit — an exact on-device copy:
    weights, momentum, hyperparameters all move together, so the
    copied member resumes the winner's trajectory bitwise) and then
    multiplies each replaced member's leaves at ``lr_slots`` (the
    stacked ``lr_state`` hyperparameters) by a coin-flip factor from
    ``factors`` (explore), clipped to ``lr_bounds`` when given.
    """
    k = n_members
    n_cut = truncation_count(k, truncation)
    lr_slots = frozenset(lr_slots)

    def fn(fitness, key, *leaves):
        order = jnp.argsort(-fitness)          # best first, stable
        winners = order[:n_cut]
        losers = order[k - n_cut:]
        kd, kf = jax.random.split(key)
        donors = winners[jax.random.randint(kd, (n_cut,), 0, n_cut)]
        src = jnp.arange(k).at[losers].set(donors)
        explored = jnp.zeros((k,), bool).at[losers].set(True)
        fac = jnp.where(jax.random.bernoulli(kf, 0.5, (k,)),
                        jnp.float32(factors[1]), jnp.float32(factors[0]))
        out = []
        for i, leaf in enumerate(leaves):
            new = jnp.take(leaf, src, axis=0)
            if i in lr_slots:
                mutated = (new.astype(jnp.float32)
                           * fac.reshape(_bshape(new, k)))
                if lr_bounds is not None:
                    mutated = jnp.clip(mutated, lr_bounds[0],
                                       lr_bounds[1])
                new = jnp.where(explored.reshape(_bshape(new, k)),
                                mutated.astype(leaf.dtype), new)
            out.append(new)
        return tuple(out)

    return fn, n_cut


def build_ga_step(n_members: int, blendable: Sequence[bool],
                  lr_slots: Sequence[int], elite: int = 1,
                  mutation_sigma: float = 0.2,
                  lr_bounds: tuple[float, float] | None = None):
    """GA refill step: every non-elite slot is replaced by a child of
    two tournament-selected parents — float leaves (``blendable[i]``)
    arithmetically blended per member (``β·a + (1−β)·b``, one β per
    child shared across its whole tree so weights and their momentum
    blend consistently), non-float leaves inherited from parent A —
    and the child's learning rate is log-normally mutated.  Elite
    slots (the current top ``elite`` members) pass through untouched.

    Returns ``(fn, n_elite)``.
    """
    k = n_members
    n_elite = max(0, min(int(elite), k - 1))
    blendable = tuple(bool(b) for b in blendable)
    lr_slots = frozenset(lr_slots)

    def fn(fitness, key, *leaves):
        order = jnp.argsort(-fitness)
        keep = jnp.zeros((k,), bool)
        if n_elite:
            keep = keep.at[order[:n_elite]].set(True)
        ka, kb, kw, km = jax.random.split(key, 4)
        src_a = tournament(ka, fitness, k)
        src_b = tournament(kb, fitness, k)
        beta = jax.random.uniform(kw, (k,), dtype=jnp.float32)
        noise = jnp.exp(mutation_sigma
                        * jax.random.normal(km, (k,), dtype=jnp.float32))
        out = []
        for i, leaf in enumerate(leaves):
            bshape = _bshape(leaf, k)
            child = jnp.take(leaf, src_a, axis=0)
            if blendable[i]:
                pb = jnp.take(leaf, src_b, axis=0)
                b = beta.reshape(bshape)
                child = (b * child.astype(jnp.float32)
                         + (1.0 - b) * pb.astype(jnp.float32)
                         ).astype(leaf.dtype)
            if i in lr_slots:
                child = child.astype(jnp.float32) * noise.reshape(bshape)
                if lr_bounds is not None:
                    child = jnp.clip(child, lr_bounds[0], lr_bounds[1])
                child = child.astype(leaf.dtype)
            out.append(jnp.where(keep.reshape(bshape), leaf, child))
        return tuple(out)

    return fn, n_elite
