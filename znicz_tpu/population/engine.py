"""Population engine: K model replicas trained in ONE jit region.

Rebuilds ROADMAP item 5 — Veles's genetics/ensemble plugins trained one
candidate per cluster node; here a *population axis* of K replicas of
one :class:`~znicz_tpu.models.standard_workflow.StandardWorkflow`
architecture trains simultaneously on the mesh:

- the template workflow's hot chain (loader gather → forwards →
  evaluator → backwards → anomaly guard) is extracted through the SAME
  :meth:`JitRegion.build_callable` tracing harness the per-step and
  scan-chunk paths compile — then ``jax.vmap``'ed over a leading
  member axis and jitted once per static region key (zero compiles per
  warmed step; pinned by the retrace guard's population case);
- region leaves split into **shared** (the dataset tables and the
  minibatch schedule — read-only inside a step, decided by the same
  jaxpr ``outvar is invar`` invariance analysis ``run_chunk`` uses)
  and **member-stacked** (parameters, momentum, activations, PRNG key
  chains, each member's epoch shuffle order, each member's
  ``lr_state`` hyperparameters) — stacked leaves live in
  ``member_axis`` Vectors sharded over the mesh's DATA axis, so small
  nets train K-per-chip while an indivisible K stays replicated and
  XLA time-slices;
- every member reproduces its independent sequential run BITWISE: the
  member axis carries each member's own weight init, its own device
  PRNG chain (dropout/stochastic pooling), and its own counter-based
  epoch permutation (``loader.base.epoch_permutation`` over the
  member's snapshotted shuffle seed), so the vmapped step is the K
  sequential trajectories, not an approximation of them
  (``tests/test_population.py`` pins it);
- evolution (tournament selection, arithmetic weight crossover,
  hyperparameter mutation, PBT exploit/explore truncation) runs at
  epoch boundaries as jitted gathers/blends over the stacked tree
  (:mod:`znicz_tpu.population.evolution`) — when the member axis is
  sharded those gathers ARE the cross-chip collectives.

Notes vs the ordinary training stack: ZeRO-1 stays disengaged here by
construction (the template initializes on a mesh-free device — the
member axis owns the data axis, and member-sharding already stores
each member's optimizer state on 1/K of the chips, which is the same
HBM effect); ``engine.debug_checks`` (checkify) is not supported
inside the vmapped program.
"""

from __future__ import annotations

from typing import Callable, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.backends import NumpyDevice, XLADevice
from znicz_tpu.loader.base import TRAIN, VALID, epoch_permutation
from znicz_tpu.memory import Vector
from znicz_tpu.observe import metrics as _metrics
from znicz_tpu.observe import tracing as _tracing
from znicz_tpu.population import evolution as _evo
from znicz_tpu.utils import prng
from znicz_tpu.utils.logger import Logger


def leaf_keys(units) -> dict[int, tuple[str, str]]:
    """Stable identity for every Vector a unit chain owns:
    ``id(vector) -> (unit_name, attribute)``, first owner wins in
    deterministic (unit order, sorted attr) order.  Two workflows
    built from the same layers list produce the same key set, which is
    what lets per-member harvested state line up with the template's
    region leaves."""
    out: dict[int, tuple[str, str]] = {}
    for unit in units:
        for attr in sorted(unit.__dict__):
            val = unit.__dict__[attr]
            if isinstance(val, Vector) and val:
                out.setdefault(id(val), (unit.name, attr))
    return out


def harvest_state(workflow) -> dict:
    """Snapshot one freshly-initialized member's population-relevant
    state: every owned Vector's host value by ``(unit, attr)`` key
    plus the loader's counter-based shuffle seed.  Called on throwaway
    builds (one per distinct member seed) — the host PRNG stream is
    device-independent, so a NumpyDevice build harvests the exact
    init an XLA run would start from."""
    out = {}
    for unit in workflow.hot_chain_units():
        for attr in sorted(unit.__dict__):
            val = unit.__dict__[attr]
            if isinstance(val, Vector) and val:
                out.setdefault((unit.name, attr),
                               np.array(np.asarray(val), copy=True))
    return {"vectors": out,
            "shuffle_seed": int(workflow.loader._shuffle_seed)}


class PopulationRegion(Logger):
    """The vmapped K-member step over a template workflow's hot chain.

    Owns the stacked leaves (``member_axis`` Vectors placed through
    ``Device.sharding_for``), the per-static-key program cache, and
    the per-member schedule synchronization.  Drive it like a
    JitRegion: :meth:`step` per minibatch (host bookkeeping rides the
    template loader), read/write leaves via :meth:`read_leaf` /
    :meth:`write_leaf`.
    """

    def __init__(self, template, member_states: Sequence[dict],
                 pop_device=None, name: str = "population") -> None:
        super().__init__()
        self.name = name
        self.template = template
        self.n_members = len(member_states)
        if self.n_members < 1:
            raise ValueError("population needs at least 1 member")
        if template._region_unit is None:
            raise ValueError(
                "population needs an XLA-initialized template "
                "(numpy backend has no jit region to vmap)")
        self.device = template.device
        self.pop_device = pop_device if pop_device is not None \
            else template.device
        self.loader = template.loader
        self.region = template._region_unit.region
        self.units = self.region.units
        self._shuffle_seeds = [int(s["shuffle_seed"])
                               for s in member_states]
        self._programs: dict[tuple, object] = {}
        self._synced_epoch = 0
        self._keyof = leaf_keys(self.units)
        self._lr_vecs = [g.lr_state for g in template.gds
                         if g.lr_state]
        self._build(member_states)

    # ------------------------------------------------------------------
    # construction
    # ------------------------------------------------------------------
    def _train_skips(self) -> tuple:
        """The train-variant gate skips without touching the schedule:
        gate_skip Bools derive from ``loader.minibatch_class``, so
        flipping it to TRAIN momentarily selects the full
        fwd+bwd+update variant (the superset of every variant's
        writes — the right one for invariance analysis)."""
        loader = self.loader
        saved = loader.minibatch_class
        loader.minibatch_class = TRAIN
        try:
            return tuple(bool(u.gate_skip) for u in self.units)
        finally:
            loader.minibatch_class = saved

    def _build(self, member_states: Sequence[dict]) -> None:
        region = self.region
        loader = self.loader
        body = region.build_callable(self._train_skips())
        vectors = region._vectors
        assert vectors is not None
        self.vectors = vectors
        self._index = {id(v): i for i, v in enumerate(vectors)}
        for vec in vectors:
            vec.unmap()
        leaves0 = [vec._devmem for vec in vectors]
        # which leaves does a step WRITE?  (same outvar-is-invar
        # analysis run_chunk uses to keep the dataset off the carry)
        jaxpr = jax.make_jaxpr(body)(*leaves0)
        invariant = [ov is iv for ov, iv in zip(jaxpr.jaxpr.outvars,
                                                jaxpr.jaxpr.invars)]
        for vec, leaf in zip(vectors, leaves0):
            vec._devmem = leaf  # tracing left tracers behind
        lr_ids = {id(v) for v in self._lr_vecs}
        sched_perm = getattr(loader, "sched_perm", None)
        self.member_mask = [
            (not inv) or (vec is sched_perm) or (id(vec) in lr_ids)
            for vec, inv in zip(vectors, invariant)]
        self.in_axes = tuple(0 if m else None for m in self.member_mask)
        # leaves evolution may touch: member state minus each member's
        # identity (its PRNG chain and its own shuffle stream)
        rng_ids = {id(u.rng_state) for u in self.units
                   if getattr(u, "rng_state", None) is not None
                   and u.rng_state}
        self.evolvable = [
            m and vec is not sched_perm and id(vec) not in rng_ids
            for vec, m in zip(vectors, self.member_mask)]

        mesh = getattr(self.pop_device, "mesh", None)
        n_data = getattr(self.pop_device, "n_data_shards", 1)
        if mesh is not None and self.n_members % n_data:
            self.warning(
                "population of %d does not divide the %d-way data "
                "axis — member axis stays replicated (time-sliced)",
                self.n_members, n_data)

        # stack: one member_axis Vector per region leaf.  Placement is
        # DECLARATIVE: each stacked leaf gets a Member rule in the
        # template workflow's partition table (member-axis placement
        # and its divisibility fallback are rule consequences), shared
        # leaves get an explicit replicated rule; the rules-off arm
        # applies the equivalent legacy attributes.
        from znicz_tpu.parallel import partition
        table = partition.table_for(self.template)
        self.svecs: list[Vector] = []
        for vec, member in zip(vectors, self.member_mask):
            key = self._keyof.get(id(vec), (vec.name, ""))
            sname = f"{self.name}.{key[0]}.{key[1] or vec.name}"
            if not member:
                svec = Vector(name=sname)
                placement = partition.REPLICATED
                svec.reset(np.asarray(vec))
            else:
                svec = Vector(name=sname, member_axis=True)
                md = (vec.model_shard_dim + 1
                      if vec.model_shard_dim is not None else None)
                placement = partition.Member(md)
                svec.reset(self._stacked_init(vec, member_states))
            path = partition.path_of(svec)
            if table is not None:
                table.declare_leaf(path, placement)
                table.bind(svec, path, self.pop_device)
            else:
                partition.apply_legacy(svec, partition.materialize(
                    placement, path, tuple(svec.shape),
                    getattr(self.pop_device, "n_data_shards", 1)))
            svec.initialize(self.pop_device)
            self.svecs.append(svec)
        # template device copies are dead weight now — the stacked
        # leaves are the live state; keep only the host mirrors (the
        # export path and schedule bookkeeping read those)
        for vec in vectors:
            vec.map_read()
            vec.reset(vec.mem)
        # pin in/out shardings so host re-uploads (schedule sync,
        # accumulator zeroing) and compiler-chosen output layouts can
        # never disagree — the zero-recompile contract on a mesh
        if mesh is not None:
            self._shardings = tuple(
                self.pop_device.sharding_for(sv) for sv in self.svecs)
        else:
            self._shardings = None
        _metrics.population_members(self.name).set(self.n_members)

    def _stacked_init(self, vec: Vector,
                      member_states: Sequence[dict]) -> np.ndarray:
        loader = self.loader
        if vec is getattr(loader, "sched_perm", None):
            return self.stacked_epoch_orders(0)
        key = self._keyof.get(id(vec))
        base = np.asarray(vec)
        vals = [np.asarray(s["vectors"].get(key, base))
                for s in member_states]
        return np.stack(vals)

    # ------------------------------------------------------------------
    # per-member schedule
    # ------------------------------------------------------------------
    def stacked_epoch_orders(self, epoch: int) -> np.ndarray:
        """(K, total_samples) — every member's sample order for
        ``epoch``, each from its own counter-based shuffle stream
        (test/validation segments ride natural order, identical
        across members; the TRAIN segment is each member's own Philox
        permutation — exactly what K independent loaders would use)."""
        loader = self.loader
        total = loader.total_samples
        lo, hi = loader.class_index_range(TRAIN)
        out = np.tile(np.arange(total, dtype=np.int32),
                      (self.n_members, 1))
        n = hi - lo
        if n > 0 and loader.shuffle_limit > 0:
            eff = min(int(epoch), int(loader.shuffle_limit) - 1)
            for i, seed in enumerate(self._shuffle_seeds):
                out[i, lo:hi] = lo + epoch_permutation(seed, eff, n)
        return out

    def _sync_schedule(self) -> None:
        epoch = int(self.loader.epoch_number)
        if epoch == self._synced_epoch:
            return
        self._synced_epoch = epoch
        sched_perm = getattr(self.loader, "sched_perm", None)
        if sched_perm is None:
            return
        sv = self.svec(sched_perm)
        sv.map_invalidate()
        sv.mem[...] = self.stacked_epoch_orders(epoch)
        # the upload rides the next dispatch's unmap sweep

    # ------------------------------------------------------------------
    # leaf access
    # ------------------------------------------------------------------
    def svec(self, vec: Vector) -> Vector:
        return self.svecs[self._index[id(vec)]]

    def is_member(self, vec: Vector) -> bool:
        return self.member_mask[self._index[id(vec)]]

    def read_leaf(self, vec: Vector) -> np.ndarray:
        """Host copy of a leaf's current value ((K, ...) when
        member-stacked)."""
        sv = self.svec(vec)
        sv.map_read()
        return sv.mem

    def write_leaf(self, vec: Vector, arr: np.ndarray) -> None:
        sv = self.svec(vec)
        sv.map_invalidate()
        sv.mem[...] = arr

    def set_member_lrs(self, lrs: Sequence[float]) -> None:
        """Assign each member its own learning rate (both the weight
        and bias slots — the ``build(learning_rate=…)`` semantic every
        sample uses).  Members then train — and evolution mutates —
        K different rates inside the one compiled program."""
        if len(lrs) != self.n_members:
            raise ValueError(f"{len(lrs)} rates for "
                             f"{self.n_members} members")
        if not self._lr_vecs:
            raise ValueError(
                "template has no promoted lr leaves — call "
                "StandardWorkflow.promote_lr_leaves() before building "
                "the population")
        stacked = np.asarray([[lr, lr] for lr in lrs], dtype=np.float32)
        for vec in self._lr_vecs:
            self.write_leaf(vec, stacked)

    def member_lrs(self) -> np.ndarray:
        """(K,) current per-member learning rates (first promoted GD
        unit's weight-lr slot)."""
        if not self._lr_vecs:
            raise ValueError("no promoted lr leaves")
        return np.array(self.read_leaf(self._lr_vecs[0])[:, 0],
                        dtype=np.float64)

    # ------------------------------------------------------------------
    # dispatch
    # ------------------------------------------------------------------
    def _program(self, key: tuple, skips: tuple):
        fn = self._programs.get(key)
        if fn is None:
            self.debug("population '%s': compiling for key %s "
                       "(%d members, %d leaves)", self.name, key,
                       self.n_members, len(self.svecs))
            _metrics.xla_compiles(f"population:{self.name}").inc()
            body = self.region.build_callable(skips)
            vfn = jax.vmap(body, in_axes=self.in_axes,
                           out_axes=self.in_axes)
            donate = tuple(range(len(self.svecs)))
            if self._shardings is not None:
                fn = jax.jit(vfn, donate_argnums=donate,
                             in_shardings=self._shardings,
                             out_shardings=self._shardings)
            else:
                fn = jax.jit(vfn, donate_argnums=donate)
            self._programs[key] = fn
        return fn

    def _dispatch(self) -> None:
        skips = tuple(bool(u.gate_skip) for u in self.units)
        key = tuple(u.region_key() for u in self.units) + (skips,)
        fn = self._program(key, skips)
        for sv in self.svecs:
            sv.unmap()
        leaves = [sv._devmem for sv in self.svecs]
        with _tracing.TRACER.span(f"population:{self.name}",
                                  cat="region"):
            out = fn(*leaves)
        for sv, leaf in zip(self.svecs, out):
            sv.devmem = leaf
        _metrics.region_steps(f"population:{self.name}").inc()

    def step(self) -> None:
        """One population minibatch step: template-loader host
        bookkeeping (cursor/epoch/flags — shared across members by
        construction: every member has the same schedule geometry),
        per-member schedule sync at epoch boundaries, then ONE device
        dispatch training all K members."""
        self.loader.run()
        self._sync_schedule()
        self._dispatch()

    def run_schedule_entry(self, position: int) -> None:
        """Dispatch the step for one explicit schedule entry (the
        stacked-ensemble aggregate pass): points the device cursor —
        and the template loader's host state — at ``position`` and
        fires the matching variant.  Leaves the training cursor moved;
        use after training only."""
        loader = self.loader
        cls, lo, hi = loader._schedule[position]
        loader.minibatch_class = cls
        loader.minibatch_size = hi - lo
        loader.minibatch_offset = lo
        cursor = getattr(loader, "sched_cursor", None)
        if cursor is None or not cursor:
            raise ValueError("population eval pass needs the "
                             "device-resident schedule")
        self.write_leaf(cursor, np.full((self.n_members,), position,
                                        dtype=np.int32))
        self._dispatch()

    # ------------------------------------------------------------------
    # member readout / install
    # ------------------------------------------------------------------
    def install_member(self, member: int) -> None:
        """Copy member ``member``'s slice of every stacked leaf back
        into the template workflow's Vectors, making the template THE
        member — the bridge to every single-model surface (export,
        ``publish_bundle``, the serving canary/promote pipeline)."""
        if not 0 <= member < self.n_members:
            raise ValueError(f"member {member} out of range")
        for vec, sv, m in zip(self.vectors, self.svecs,
                              self.member_mask):
            if not m:
                continue
            sv.map_read()
            vec.reset(np.array(sv.mem[member], copy=True))

    # ------------------------------------------------------------------
    # evolution
    # ------------------------------------------------------------------
    def evolvable_slots(self) -> list[int]:
        return [i for i, e in enumerate(self.evolvable) if e]

    def lr_slots_within(self, slots: Sequence[int]) -> list[int]:
        lr_ids = {id(v) for v in self._lr_vecs}
        return [j for j, i in enumerate(slots)
                if id(self.vectors[i]) in lr_ids]

    def apply_evolution(self, fn, fitness: np.ndarray, key) -> None:
        """Run a jitted evolution step over the evolvable stacked
        leaves in place."""
        slots = self.evolvable_slots()
        for i in slots:
            self.svecs[i].unmap()
        leaves = [self.svecs[i]._devmem for i in slots]
        out = fn(jnp.asarray(fitness, dtype=jnp.float32), key, *leaves)
        for i, leaf in zip(slots, out):
            self.svecs[i].devmem = leaf


class PopulationTrainer(Logger):
    """High-level driver: build K members of one sample architecture,
    train them simultaneously through a :class:`PopulationRegion`,
    evolve at epoch boundaries, track per-member fitness.

    Parameters
    ----------
    build_fn:
        ``callable(**build_kwargs) -> StandardWorkflow`` (a sample's
        ``build``).
    n_members / base_seed / member_seeds:
        member *i* is the workflow ``build_fn`` produces after
        ``prng.seed_all(member_seeds[i])`` (default
        ``base_seed + i``) — its weight init, device PRNG chain and
        epoch shuffle stream all follow that seed, exactly as an
        independent run's would.  Repeated seeds share one harvest
        (the genetics mesh path seeds every member identically and
        varies only the learning rate).
    mesh:
        optional ``(data, model)`` mesh; the member axis shards over
        its data axis.  ``None`` = single device.
    member_lrs / lr_bounds:
        optional per-member learning rates (requires promoted lr
        leaves, done automatically) and the clip range evolution
        respects.
    evolve:
        ``"pbt"`` (exploit/explore truncation), ``"ga"`` (tournament +
        arithmetic crossover + lr mutation) or ``None`` (pure stacked
        training — the ensemble/genetics evaluation mode).
    """

    def __init__(self, build_fn: Callable, n_members: int,
                 base_seed: int | None = None,
                 member_seeds: Sequence[int] | None = None,
                 build_kwargs: dict | None = None,
                 mesh=None,
                 member_lrs: Sequence[float] | None = None,
                 lr_bounds: tuple[float, float] | None = None,
                 evolve: str | None = "pbt",
                 evolve_every: int = 1,
                 truncation: float = 0.25,
                 elite: int = 1,
                 mutation_sigma: float = 0.2,
                 explore_factors: tuple[float, float] = (0.8, 1.25),
                 seed: int = 777,
                 name: str = "population") -> None:
        super().__init__()
        from znicz_tpu.utils.config import root
        if n_members < 1:
            raise ValueError("n_members must be >= 1")
        if evolve not in (None, "pbt", "ga"):
            raise ValueError(f"unknown evolve strategy '{evolve}'")
        self.build_fn = build_fn
        self.n_members = int(n_members)
        if member_seeds is not None:
            if len(member_seeds) != n_members:
                raise ValueError("member_seeds length mismatch")
            self.member_seeds = [int(s) for s in member_seeds]
        else:
            base = int(root.common.seed if base_seed is None
                       else base_seed)
            self.member_seeds = [base + i for i in range(n_members)]
        self.build_kwargs = dict(build_kwargs or {})
        self.mesh = mesh
        self.member_lrs = (None if member_lrs is None
                           else [float(x) for x in member_lrs])
        self.lr_bounds = lr_bounds
        self.evolve = evolve
        self.evolve_every = max(1, int(evolve_every))
        self.truncation = float(truncation)
        self.elite = int(elite)
        self.mutation_sigma = float(mutation_sigma)
        self.explore_factors = explore_factors
        self.seed = int(seed)
        self.name = name
        self.template = None
        self.region: PopulationRegion | None = None
        self.history: list[dict] = []
        self.generations = 0
        #: best fitness each member has reached so far (the
        #: min-validation-error tracking a Decision unit would do)
        self.member_best_fitness = np.full(n_members, -np.inf)
        self.best_fitness = -np.inf
        self.best_member: int | None = None
        self._evolve_fn = None
        self._evolve_meta = (None, 0)
        self._base_key = None

    # ------------------------------------------------------------------
    def initialize(self) -> "PopulationTrainer":
        if self.region is not None:
            return self
        if self.mesh is None:
            template_device = XLADevice()
            pop_device = template_device
        else:
            # template traces mesh-free (per-member semantics); the
            # stacked leaves place over the mesh
            template_device = XLADevice(
                device=self.mesh.devices.flat[0])
            pop_device = XLADevice(mesh=self.mesh)
        states: list[dict] = []
        by_seed: dict[int, dict] = {}
        for i, s in enumerate(self.member_seeds):
            if i > 0 and s in by_seed:
                states.append(by_seed[s])
                continue
            prng.seed_all(s)
            wf = self.build_fn(**self.build_kwargs)
            wf._max_fires = None
            if i == 0:
                wf.initialize(device=template_device)
                wf.promote_lr_leaves()
                self.template = wf
            else:
                wf.initialize(device=NumpyDevice())
            state = harvest_state(wf)
            by_seed[s] = state
            states.append(state)
        self.region = PopulationRegion(self.template, states,
                                       pop_device=pop_device,
                                       name=self.name)
        if self.member_lrs is not None:
            self.region.set_member_lrs(self.member_lrs)
        self._base_key = jax.random.key(self.seed)
        return self

    # ------------------------------------------------------------------
    # fitness
    # ------------------------------------------------------------------
    @property
    def _metric_class(self) -> int:
        loader = self.template.loader
        return VALID if loader.class_lengths[VALID] > 0 else TRAIN

    def _read_epoch_fitness(self) -> np.ndarray:
        """(K,) fitness of the epoch that just ended (higher=better):
        ``-validation_err_pt`` for classification,
        ``-validation_mse`` for regression — read from the stacked
        evaluator accumulators, then zeroed exactly as a Decision
        unit zeroes its per-epoch device accumulators."""
        region = self.region
        wf = self.template
        ev = wf.evaluator
        loader = wf.loader
        cls = self._metric_class
        length = max(1, loader.class_lengths[cls])
        if wf.loss == "softmax":
            errs = np.array(region.read_leaf(ev.epoch_n_err),
                            dtype=np.int64)          # (K, 3)
            fitness = -100.0 * errs[:, cls] / length
            region.write_leaf(ev.epoch_n_err, 0)
            if ev.epoch_loss:
                region.write_leaf(ev.epoch_loss, 0.0)
            if getattr(ev, "compute_confusion", False) \
                    and ev.confusion_matrix:
                region.write_leaf(ev.confusion_matrix, 0)
        else:
            sse = np.array(region.read_leaf(ev.epoch_sse),
                           dtype=np.float64)
            fitness = -sse[:, cls] / length
            region.write_leaf(ev.epoch_sse, 0.0)
        return fitness

    def _record_fitness(self, fitness: np.ndarray) -> None:
        self.member_best_fitness = np.maximum(
            self.member_best_fitness, fitness)
        best = int(np.argmax(fitness))
        if fitness[best] > self.best_fitness:
            self.best_fitness = float(fitness[best])
        self.best_member = best
        if _metrics.enabled():
            for i, f in enumerate(fitness):
                _metrics.population_fitness(self.name, i).set(float(f))
            _metrics.population_best_fitness(self.name).set(
                self.best_fitness)

    # ------------------------------------------------------------------
    # evolution
    # ------------------------------------------------------------------
    def _evolution_program(self):
        if self._evolve_fn is not None:
            return self._evolve_fn
        region = self.region
        slots = region.evolvable_slots()
        lr_slots = region.lr_slots_within(slots)
        if self.evolve == "pbt":
            fn, n_cut = _evo.build_pbt_step(
                self.n_members, lr_slots, truncation=self.truncation,
                factors=self.explore_factors, lr_bounds=self.lr_bounds)
            self._evolve_meta = ("pbt", n_cut)
        else:
            blendable = [
                np.issubdtype(region.svecs[i].dtype, np.floating)
                for i in slots]
            fn, n_elite = _evo.build_ga_step(
                self.n_members, blendable, lr_slots, elite=self.elite,
                mutation_sigma=self.mutation_sigma,
                lr_bounds=self.lr_bounds)
            self._evolve_meta = ("ga", n_elite)
        _metrics.xla_compiles(f"population-evolve:{self.name}").inc()
        donate = tuple(range(2, 2 + len(slots)))
        if region._shardings is not None:
            # pin leaf shardings through the evolution program too —
            # a compiler-chosen (replicated) output here would break
            # the step program's pinned input shardings next dispatch
            from znicz_tpu.parallel import replicated_sharding
            rep = replicated_sharding(self.mesh)
            leaf_sh = tuple(region._shardings[i] for i in slots)
            self._evolve_fn = jax.jit(
                fn, donate_argnums=donate,
                in_shardings=(rep, rep) + leaf_sh,
                out_shardings=leaf_sh)
        else:
            self._evolve_fn = jax.jit(fn, donate_argnums=donate)
        return self._evolve_fn

    def evolve_generation(self, fitness: np.ndarray) -> None:
        """Apply one evolution generation to the stacked tree (called
        at epoch boundaries by :meth:`run`; callable directly)."""
        if self.evolve is None or self.n_members < 2:
            return
        fn = self._evolution_program()
        key = jax.random.fold_in(self._base_key, self.generations)
        self.region.apply_evolution(fn, fitness, key)
        self.generations += 1
        strategy, n = self._evolve_meta
        if _metrics.enabled():
            _metrics.population_generations(self.name).inc()
            if strategy == "pbt":
                _metrics.population_evolution(self.name,
                                              "exploit").inc(n)
                _metrics.population_evolution(self.name,
                                              "explore").inc(n)
            else:
                refilled = self.n_members - n
                _metrics.population_evolution(self.name,
                                              "crossover").inc(refilled)
                _metrics.population_evolution(self.name,
                                              "mutate").inc(refilled)

    # ------------------------------------------------------------------
    # driving
    # ------------------------------------------------------------------
    def run_epoch(self) -> np.ndarray:
        """One full epoch over the schedule for all K members; returns
        the (K,) epoch fitness."""
        region = self.region
        loader = self.template.loader
        while True:
            region.step()
            if loader.epoch_ended:
                break
        fitness = self._read_epoch_fitness()
        self._record_fitness(fitness)
        return fitness

    def run(self, max_epochs: int | None = None) -> list[dict]:
        """Train the population for ``max_epochs`` (default: the
        template Decision's budget), evolving every ``evolve_every``
        epochs (never after the final one — there is nothing left to
        train the mutated members on)."""
        if self.region is None:
            self.initialize()
        if max_epochs is None:
            max_epochs = self.template.decision.max_epochs
        if not max_epochs:
            raise ValueError("max_epochs undecided: pass it here or "
                             "in the template's decision_config")
        for epoch in range(int(max_epochs)):
            fitness = self.run_epoch()
            entry = {
                "epoch": epoch,
                "fitness": [float(f) for f in fitness],
                "best": float(np.max(fitness)),
                "mean": float(np.mean(fitness)),
                "best_member": int(np.argmax(fitness)),
            }
            if self.region._lr_vecs:
                entry["lrs"] = [float(x)
                                for x in self.region.member_lrs()]
            self.history.append(entry)
            self.info("epoch %d: best %.4f mean %.4f (member %d)",
                      epoch, entry["best"], entry["mean"],
                      entry["best_member"])
            if epoch + 1 < max_epochs \
                    and (epoch + 1) % self.evolve_every == 0:
                self.evolve_generation(fitness)
        return self.history

    # ------------------------------------------------------------------
    # best-member egress (the PBT -> serving loop)
    # ------------------------------------------------------------------
    def install_best(self) -> int:
        """Write the current best member's state into the template
        workflow; returns the member index."""
        if self.best_member is None:
            raise RuntimeError("run() first")
        self.region.install_member(self.best_member)
        return self.best_member

    def export_best(self, path: str) -> str:
        self.install_best()
        return self.template.export_forward(path)

    def publish_best(self, directory: str,
                     prefix: str = "model") -> tuple[int, str]:
        """Publish the best member as the next monotonic
        sha256-sidecar bundle in ``directory`` — the handoff the
        round-13 canary/promote pipeline picks up, closing the
        PBT→serving loop."""
        from znicz_tpu.resilience.publisher import publish_bundle
        self.install_best()
        return publish_bundle(self.template, directory, prefix=prefix)


def train_drafter(build_fn: Callable, n_members: int = 4, *,
                  publish_dir: str, prefix: str = "drafter",
                  mesh=None, base_seed: int = 211,
                  lr_bounds: tuple[float, float] = (0.01, 0.4),
                  evolve: str = "pbt", evolve_every: int = 2,
                  seed: int = 97, name: str = "drafter",
                  **trainer_kwargs) -> tuple[int, str,
                                             "PopulationTrainer"]:
    """The speculative-decoding drafter hook (round 15): train a
    SMALL causal-LM population with the round-14 engine, publish the
    best member through the round-13 pipeline, and hand the bundle
    path to the decode engine's draft/verify loop.

    ``build_fn`` must produce the drafter architecture (a tiny
    token-first chain — embedding → causal attention → last_token →
    softmax); the population varies seeds and learning rates, trains
    every member in ONE vmapped jit region, and the fittest member
    becomes the drafter.  A drafter is pure throughput machinery —
    the big model's verification forward decides every token, so a
    mediocre drafter costs acceptance rate, never correctness.

    Returns ``(version, bundle_path, trainer)`` — the bundle carries
    the usual sha256 sidecar, so a
    :class:`~znicz_tpu.resilience.publisher.PublicationWatcher` can
    also hot-refresh drafters later."""
    trainer = PopulationTrainer(
        build_fn, n_members, base_seed=base_seed, mesh=mesh,
        lr_bounds=lr_bounds, evolve=evolve,
        evolve_every=evolve_every, seed=seed, name=name,
        **trainer_kwargs)
    trainer.initialize()
    trainer.run()
    version, path = trainer.publish_best(publish_dir, prefix=prefix)
    return version, path, trainer
