"""Workflow introspection shared by the reporting surfaces (the
Publisher's reports and the web-status dashboard must never disagree
about the same workflow)."""

from __future__ import annotations


def validation_metrics(workflow) -> dict[str, float]:
    """Real validation metrics only: the decision's
    ``min_validation_*`` fields are untouched initial values when the
    loader has no validation split — reporting those would fabricate
    a result."""
    from znicz_tpu.loader.base import VALID
    decision = getattr(workflow, "decision", None)
    loader = getattr(workflow, "loader", None)
    if decision is None or loader is None or not loader.is_initialized \
            or not loader.class_lengths[VALID]:
        return {}
    out: dict[str, float] = {}
    for attr in ("min_validation_n_err_pt", "min_validation_mse"):
        value = getattr(decision, attr, None)
        if value is not None:
            out[attr] = float(value)
    return out


def slowest_units(workflow, n: int = 5) -> list[dict]:
    """Top-n units by cumulative host time (the reference's
    slowest-units table)."""
    rows = sorted(
        (u for u in workflow.units if u.run_count),
        key=lambda u: u.run_time_total, reverse=True)[:n]
    return [{"unit": u.name, "runs": u.run_count,
             "total_s": round(u.run_time_total, 4)} for u in rows]
