"""Snapshotter: periodic training-state checkpoints + resume.

Reference: ``veles/snapshotter.py`` — the reference pickled the whole
workflow object graph (code + state together), gzip'd, named by the
best validation error, and could resume from the file.  Known weakness
(SURVEY.md §5.4): snapshots tied to code versions.

Rebuild: state is a **pure data tree** (per-unit Vectors, counters and
the PRNG streams — see ``Unit.state_dict``) serialized with
``pickle``+gzip of plain numpy/python data.  Resume = build the same
workflow from code, then :meth:`Workflow.load_state` — trajectory
fidelity (epoch counters, best-error, RNG streams) is covered by
tests.

Checkpoints are **layout-independent**: sharded leaves are gathered on
save (model-axis/TP shards via the lockstep collective read;
data-axis/ZeRO-1 optimizer shards via the same read, with their
divisibility zero-padding sliced off — ``Vector.strip_data_pad``) and
re-sharded on load for whatever mesh the restoring run uses
(``Unit.load_state`` re-pads to the live Vector's annotations, then
the next upload re-places per ``XLADevice.sharding_for``).  A snapshot
written by an 8-way ZeRO-1 run restores bitwise onto a 2-way mesh or
a single device — ``tests/test_zero1.py`` pins this.

Trigger semantics preserved: fires when the Decision unit raises
``improved`` (best-on-validation naming via ``snapshot_suffix``).
"""

from __future__ import annotations

import gzip
import os
import pickle
import time

from znicz_tpu.observe import metrics as _metrics
from znicz_tpu.observe import tracing as _tracing
from znicz_tpu.units import Unit
from znicz_tpu.utils.config import root


class Snapshotter(Unit):
    """Writes ``<prefix>_<suffix>.pickle.gz`` on validation improvement.

    Wire with ``snapshotter.link_from(decision)`` and let
    :attr:`gate_skip` follow ``~decision.improved`` (done by
    ``StandardWorkflow.link_snapshotter``).
    """

    def __init__(self, workflow, name: str | None = None,
                 prefix: str = "snapshot",
                 directory: str | None = None,
                 interval: int = 1,
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.prefix = prefix
        self.directory = directory or str(root.common.dirs.snapshots)
        self.interval = max(1, int(interval))
        self.decision = None  # linked by workflow builder
        self.destination: str | None = None  # last written file
        self._fire_count = 0

    def snapshot_suffix(self) -> str:
        d = self.decision
        if d is not None and getattr(d, "min_validation_n_err_pt", None) \
                is not None and getattr(d, "loader", None) is not None:
            return f"{d.min_validation_n_err_pt:.2f}pt"
        if d is not None and getattr(d, "min_validation_mse", None) \
                is not None:
            return f"{d.min_validation_mse:.6f}mse"
        return f"e{self._fire_count}"

    def run(self) -> None:
        self._fire_count += 1
        if self._fire_count % self.interval:
            return
        # all processes execute this unit in lockstep under SPMD, so
        # collective reads of model-sharded state are safe here; the
        # gather must run on EVERY process (it's a collective), but
        # only process 0 writes the file (a shared snapshot directory
        # must not see concurrent writers).  The path is deterministic
        # (lockstep decision state), so every process records the SAME
        # destination — crash auto-resume must load one snapshot on
        # all processes, not master-only.
        # MULTI-HOST REQUIREMENT: the snapshot directory must be a
        # SHARED filesystem (NFS/GCS-fuse/...) — process 0 is the only
        # writer, but every process records `destination` and crash
        # auto-resume loads it on all processes.  On per-host local
        # disks the non-master hosts would resume from a path that
        # does not exist; the barrier+existence check below turns that
        # silent failure into a loud warning at write time.
        import jax
        state = self.workflow.state_dict(allow_collective=True)
        suffix = self.snapshot_suffix()
        path = os.path.join(self.directory,
                            f"{self.prefix}_{suffix}.pickle.gz")
        multi = jax.process_count() > 1
        write_exc: "Exception | None" = None
        if jax.process_index() == 0:
            try:
                written = self.write(state, self.directory, self.prefix,
                                     suffix)
                assert written == path
                self.info("snapshot → %s", path)
            except Exception as exc:
                if not multi:
                    raise
                # a lone raise here would strand the peers in the
                # barrier below — gather the failure, raise together
                write_exc = exc
        if multi:
            import numpy as np

            from znicz_tpu.parallel.process_shard import allgather_sum
            # doubles as the write barrier for the existence check
            if allgather_sum(
                    np.array([1.0 if write_exc else 0.0]))[0] > 0:
                raise RuntimeError(
                    "snapshot write failed on process 0; every "
                    "process aborts together") from write_exc
            if jax.process_index() != 0 and not os.path.exists(path):
                self.warning(
                    "snapshot %s is not visible on process %d — the "
                    "snapshot directory is NOT a shared filesystem; "
                    "auto-resume will fail on this host.  Point "
                    "`directory` (or root.common.dirs.snapshots) at "
                    "storage mounted on every host.", path,
                    jax.process_index())
        self.destination = path

    @staticmethod
    def write(state: dict, directory: str, prefix: str,
              suffix: str) -> str:
        """Atomic ``<prefix>_<suffix>.pickle.gz`` state write — the one
        serialization point (the launcher's emergency snapshots and the
        periodic unit both use it)."""
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, f"{prefix}_{suffix}.pickle.gz")
        # per-process tmp: concurrent writers on a shared fs (defense
        # in depth — run() already single-writes) must not truncate
        # each other's in-progress stream before the atomic replace
        tmp = f"{path}.{os.getpid()}.tmp"
        start = time.perf_counter()
        with _tracing.TRACER.span("snapshot_save", cat="snapshot"):
            with gzip.open(tmp, "wb") as f:
                pickle.dump(state, f, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, path)
        _metrics.snapshot_seconds("save").observe(
            time.perf_counter() - start)
        return path

    @staticmethod
    def load(path: str) -> dict:
        start = time.perf_counter()
        with _tracing.TRACER.span("snapshot_load", cat="snapshot"):
            with gzip.open(path, "rb") as f:
                state = pickle.load(f)
        _metrics.snapshot_seconds("load").observe(
            time.perf_counter() - start)
        return state
