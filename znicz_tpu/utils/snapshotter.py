"""Snapshotter: periodic training-state checkpoints + resume.

Reference: ``veles/snapshotter.py`` — the reference pickled the whole
workflow object graph (code + state together), gzip'd, named by the
best validation error, and could resume from the file.  Known weakness
(SURVEY.md §5.4): snapshots tied to code versions.

Rebuild: state is a **pure data tree** (per-unit Vectors, counters and
the PRNG streams — see ``Unit.state_dict``) serialized with
``pickle``+gzip of plain numpy/python data.  Resume = build the same
workflow from code, then :meth:`Workflow.load_state` — trajectory
fidelity (epoch counters, best-error, RNG streams) is covered by
tests.

Checkpoints are **layout-independent**: sharded leaves are gathered on
save (model-axis/TP shards via the lockstep collective read;
data-axis/ZeRO-1 optimizer shards via the same read, with their
divisibility zero-padding sliced off — ``Vector.strip_data_pad``) and
re-sharded on load for whatever mesh the restoring run uses
(``Unit.load_state`` re-pads to the live Vector's annotations, then
the next upload re-places per ``XLADevice.sharding_for``).  A snapshot
written by an 8-way ZeRO-1 run restores bitwise onto a 2-way mesh or
a single device — ``tests/test_zero1.py`` pins this.

Trigger semantics preserved: fires when the Decision unit raises
``improved`` (best-on-validation naming via ``snapshot_suffix``).

Round-11 resilience (this file is the rollback substrate the anomaly
guard and crash auto-resume both stand on, so it must survive its own
faults):

- every write leaves a ``<file>.sha256`` sidecar; :meth:`load`
  verifies it (and the gzip/pickle stream) and **falls back to the
  previous good snapshot** on corruption instead of raising into the
  resume path;
- ``keep_last`` (default 5) retains a ladder of recent snapshots —
  the fallback has somewhere to land and the directory stays bounded;
- a failed write (disk full, injected ``snapshot.write_fail``) is
  absorbed by default: the unit warns, counts
  ``znicz_snapshot_failures_total{op=write}``, keeps ``destination``
  pointing at the last GOOD snapshot and training continues
  (``engine.snapshot_tolerate_failures = False`` restores
  raise-on-failure).
"""

from __future__ import annotations

import glob
import gzip
import hashlib
import logging
import os
import pickle
import time

from znicz_tpu.observe import metrics as _metrics
from znicz_tpu.observe import tracing as _tracing
from znicz_tpu.resilience import faults as _faults
from znicz_tpu.units import Unit
from znicz_tpu.utils.config import root


class SnapshotCorrupt(RuntimeError):
    """A snapshot failed digest verification (or would not unpickle)
    and no fallback snapshot in its directory loads either."""


def _sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        while True:
            buf = fh.read(chunk)
            if not buf:
                return h.hexdigest()
            h.update(buf)


class Snapshotter(Unit):
    """Writes ``<prefix>_<suffix>.pickle.gz`` on validation improvement.

    Wire with ``snapshotter.link_from(decision)`` and let
    :attr:`gate_skip` follow ``~decision.improved`` (done by
    ``StandardWorkflow.link_snapshotter``).
    """

    def __init__(self, workflow, name: str | None = None,
                 prefix: str = "snapshot",
                 directory: str | None = None,
                 interval: int = 1,
                 keep_last: int = 5,
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.prefix = prefix
        self.directory = directory or str(root.common.dirs.snapshots)
        self.interval = max(1, int(interval))
        #: snapshots retained on disk (0 = unbounded); pruned oldest-
        #: first after each successful write, so the corruption
        #: fallback always has a ladder of recent good files
        self.keep_last = max(0, int(keep_last))
        self.decision = None  # linked by workflow builder
        self.destination: str | None = None  # last written file
        self._fire_count = 0

    def snapshot_suffix(self) -> str:
        d = self.decision
        if d is not None and getattr(d, "min_validation_n_err_pt", None) \
                is not None and getattr(d, "loader", None) is not None:
            return f"{d.min_validation_n_err_pt:.2f}pt"
        if d is not None and getattr(d, "min_validation_mse", None) \
                is not None:
            return f"{d.min_validation_mse:.6f}mse"
        return f"e{self._fire_count}"

    def run(self) -> None:
        self._fire_count += 1
        if self._fire_count % self.interval:
            return
        # all processes execute this unit in lockstep under SPMD, so
        # collective reads of model-sharded state are safe here; the
        # gather must run on EVERY process (it's a collective), but
        # only process 0 writes the file (a shared snapshot directory
        # must not see concurrent writers).  The path is deterministic
        # (lockstep decision state), so every process records the SAME
        # destination — crash auto-resume must load one snapshot on
        # all processes, not master-only.
        # MULTI-HOST REQUIREMENT: the snapshot directory must be a
        # SHARED filesystem (NFS/GCS-fuse/...) — process 0 is the only
        # writer, but every process records `destination` and crash
        # auto-resume loads it on all processes.  On per-host local
        # disks the non-master hosts would resume from a path that
        # does not exist; the barrier+existence check below turns that
        # silent failure into a loud warning at write time.
        import jax
        state = self.workflow.state_dict(allow_collective=True)
        suffix = self.snapshot_suffix()
        path = os.path.join(self.directory,
                            f"{self.prefix}_{suffix}.pickle.gz")
        multi = jax.process_count() > 1
        tolerate = bool(root.common.engine.get(
            "snapshot_tolerate_failures", True))
        write_exc: "Exception | None" = None
        if jax.process_index() == 0:
            try:
                written = self.write(state, self.directory, self.prefix,
                                     suffix)
                assert written == path
                self.info("snapshot → %s", path)
            except Exception as exc:
                if not multi and not tolerate:
                    raise
                # multi: a lone raise here would strand the peers in
                # the barrier below — gather the failure, decide
                # together
                write_exc = exc
        if multi:
            import numpy as np

            from znicz_tpu.parallel.process_shard import allgather_sum
            # doubles as the write barrier for the existence check
            if allgather_sum(
                    np.array([1.0 if write_exc else 0.0]))[0] > 0:
                if not tolerate:
                    raise RuntimeError(
                        "snapshot write failed on process 0; every "
                        "process aborts together") from write_exc
                write_exc = write_exc or RuntimeError(
                    "snapshot write failed on process 0")
            elif jax.process_index() != 0 and not os.path.exists(path):
                self.warning(
                    "snapshot %s is not visible on process %d — the "
                    "snapshot directory is NOT a shared filesystem; "
                    "auto-resume will fail on this host.  Point "
                    "`directory` (or root.common.dirs.snapshots) at "
                    "storage mounted on every host.", path,
                    jax.process_index())
        if write_exc is not None:
            # absorbed write failure: training continues; rollback and
            # auto-resume keep pointing at the last GOOD snapshot
            _metrics.snapshot_failures("write").inc()
            _metrics.recoveries("snapshot_write").inc()
            self.warning(
                "snapshot write failed (%s) — continuing; last good "
                "snapshot remains %s", write_exc, self.destination)
            return
        self.destination = path
        # round 13: feed the znicz_snapshot_age_seconds callback gauge
        # — /readyz turns "no good snapshot lately" into staleness so
        # a supervisor sees a stalled trainer as not-ready
        from znicz_tpu.resilience.publisher import mark_artifact_written
        mark_artifact_written(f"snapshot:{self.prefix}")
        if jax.process_index() == 0 and self.keep_last:
            self.prune(self.directory, self.prefix, self.keep_last,
                       keep=path)

    @staticmethod
    def _fence_on_sidecar(path: str, entry_mtime: float | None,
                          timeout_s: float) -> str:
        """Round-18 multi-process write discipline: non-zero processes
        never write shared artifacts — they FENCE on process 0's
        ``.sha256`` sidecar appearing (the sidecar lands strictly
        after the data replace, so its arrival proves a complete
        file).  ``entry_mtime`` is the sidecar's mtime before the
        fence (None = absent): a pre-existing sidecar only satisfies
        the fence once its mtime moves — or when it is FRESH (written
        within 2 s of fence entry, i.e. process 0 simply finished
        before this process arrived at the lockstep site) — so a
        stale same-name artifact from an earlier run cannot fake
        completion."""
        log = logging.getLogger("Snapshotter")
        sidecar = f"{path}.sha256"
        entry_wall = time.time()
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                mtime = os.path.getmtime(sidecar)
            except OSError:
                mtime = None
            if mtime is not None and (entry_mtime is None
                                      or mtime > entry_mtime
                                      or mtime >= entry_wall - 2.0):
                return path
            time.sleep(0.02)
        if os.path.exists(sidecar) and os.path.exists(path):
            log.warning(
                "sidecar fence on %s timed out after %.0fs but the "
                "artifact exists — accepting the (possibly stale) "
                "file", path, timeout_s)
            return path
        raise OSError(
            f"sidecar fence on {path} timed out after {timeout_s:.0f}s "
            f"— process 0 never completed the write (shared filesystem "
            f"not mounted on every host, or the master write failed)")

    @staticmethod
    def write(state: dict, directory: str, prefix: str,
              suffix: str) -> str:
        """Atomic ``<prefix>_<suffix>.pickle.gz`` state write — the one
        serialization point (the launcher's emergency snapshots, the
        periodic unit and the elastic checkpoint-on-signal all use
        it).  Leaves a ``.sha256`` sidecar whose digest :meth:`load`
        verifies before trusting the file.

        Multi-process discipline (round 18): ONLY process 0 writes —
        a call on any other process fences on the sidecar appearing
        (``engine.snapshot_fence_timeout_s``, default 120 s) and
        returns the same path, so a lockstep gang calling ``write``
        everywhere can never produce a torn or double-written
        snapshot."""
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, f"{prefix}_{suffix}.pickle.gz")
        from znicz_tpu.parallel.process_shard import process_info
        pidx, pcount = process_info()
        if pcount > 1 and pidx != 0:
            sidecar = f"{path}.sha256"
            try:
                entry_mtime = os.path.getmtime(sidecar)
            except OSError:
                entry_mtime = None
            return Snapshotter._fence_on_sidecar(
                path, entry_mtime,
                float(root.common.engine.get(
                    "snapshot_fence_timeout_s", 120.0)))
        # per-process tmp: concurrent writers on a shared fs (defense
        # in depth — run() already single-writes) must not truncate
        # each other's in-progress stream before the atomic replace
        tmp = f"{path}.{os.getpid()}.tmp"
        start = time.perf_counter()
        try:
            with _tracing.TRACER.span("snapshot_save", cat="snapshot"):
                with gzip.open(tmp, "wb") as f:
                    if _faults.fire("snapshot.write_fail") is not None:
                        raise OSError(
                            "injected snapshot write failure")
                    pickle.dump(state, f,
                                protocol=pickle.HIGHEST_PROTOCOL)
                digest = _sha256_file(tmp)
                os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):  # never leave half a stream behind
                os.unlink(tmp)
            raise
        # sidecar AFTER the data replace: a crash between the two
        # leaves a digestless (still loadable) file, never a digest
        # pointing at missing data
        side_tmp = f"{path}.sha256.{os.getpid()}.tmp"
        with open(side_tmp, "w") as f:
            f.write(digest + "\n")
        os.replace(side_tmp, f"{path}.sha256")
        _metrics.snapshot_seconds("save").observe(
            time.perf_counter() - start)
        return path

    @staticmethod
    def _load_verified(path: str) -> dict:
        """One file: digest check (when a sidecar exists) + unpickle;
        any integrity failure raises :class:`SnapshotCorrupt`."""
        sidecar = f"{path}.sha256"
        if os.path.exists(sidecar):
            with open(sidecar) as f:
                want = f.read().strip()
            got = _sha256_file(path)
            if got != want:
                raise SnapshotCorrupt(
                    f"{path}: sha256 {got[:12]}… != sidecar "
                    f"{want[:12]}…")
        try:
            with gzip.open(path, "rb") as f:
                return pickle.load(f)
        except SnapshotCorrupt:
            raise
        except Exception as exc:  # truncated gzip, bad pickle, ...
            raise SnapshotCorrupt(f"{path}: unreadable snapshot "
                                  f"({exc})") from exc

    @staticmethod
    def load(path: str) -> dict:
        """Load a snapshot, verifying its sha256 sidecar.  On
        corruption, fall back to the newest OTHER snapshot in the same
        directory that verifies (counting
        ``znicz_snapshot_failures_total{op=load}`` /
        ``znicz_recoveries_total{kind=snapshot_fallback}``) so the
        resume/rollback path lands on the previous good state instead
        of dying on one bad file.  Raises :class:`SnapshotCorrupt`
        when nothing in the directory loads."""
        log = logging.getLogger("Snapshotter")
        start = time.perf_counter()
        with _tracing.TRACER.span("snapshot_load", cat="snapshot"):
            try:
                state = Snapshotter._load_verified(path)
            except SnapshotCorrupt as exc:
                _metrics.snapshot_failures("load").inc()
                log.warning("%s — trying older snapshots", exc)
                fallbacks = [
                    p for p in glob.glob(os.path.join(
                        os.path.dirname(path) or ".", "*.pickle.gz"))
                    if os.path.abspath(p) != os.path.abspath(path)]
                fallbacks.sort(key=os.path.getmtime, reverse=True)
                for fb in fallbacks:
                    try:
                        state = Snapshotter._load_verified(fb)
                    except SnapshotCorrupt as fb_exc:
                        log.warning("%s", fb_exc)
                        continue
                    log.warning("recovered from older snapshot %s", fb)
                    _metrics.recoveries("snapshot_fallback").inc()
                    break
                else:
                    raise SnapshotCorrupt(
                        f"{path} is corrupt and no fallback snapshot "
                        f"in its directory verifies") from exc
        _metrics.snapshot_seconds("load").observe(
            time.perf_counter() - start)
        return state

    @staticmethod
    def prune(directory: str, prefix: str, keep_last: int,
              keep: str | None = None) -> list[str]:
        """Keep the ``keep_last`` newest GOOD ``<prefix>_*.pickle.gz``
        snapshots (plus ``keep``, the one just written), delete the
        rest with their sidecars; returns the deleted paths.

        Round-13 race fix: keep-last accounting runs over files whose
        sidecar digest VERIFIES (a file with no sidecar — the
        crash-between-replace-and-sidecar window — counts as good,
        matching :meth:`_load_verified`'s acceptance).  A corrupt file
        must neither occupy a retention slot nor survive, because a
        concurrent :meth:`load` falling back from it must always find
        the newest good snapshot still on disk — previously ``keep_last``
        mtime slots could all be consumed by corrupt files, deleting
        the very snapshot a reader was about to fall back to."""
        files = glob.glob(os.path.join(directory,
                                       f"{prefix}_*.pickle.gz"))
        files.sort(key=os.path.getmtime, reverse=True)
        good, bad = [], []
        for path in files:
            sidecar = f"{path}.sha256"
            ok = True
            try:
                if os.path.exists(sidecar):
                    with open(sidecar) as f:
                        ok = _sha256_file(path) == f.read().strip()
            except OSError:  # racing reader/pruner — leave it alone
                continue
            (good if ok else bad).append(path)
        protected = {os.path.abspath(p) for p in good[:keep_last]}
        if keep:
            protected.add(os.path.abspath(keep))
        deleted = []
        for path in bad + good[keep_last:]:
            if os.path.abspath(path) in protected:
                continue
            try:
                os.unlink(path)
                sidecar = f"{path}.sha256"
                if os.path.exists(sidecar):
                    os.unlink(sidecar)
                deleted.append(path)
            except OSError:  # concurrent pruner / already gone
                pass
        return deleted
