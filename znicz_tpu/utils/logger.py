"""Per-object logging mixin (reference: ``veles/logger.py``).

Every framework object derives from :class:`Logger` and gets
``debug``/``info``/``warning``/``error`` methods routed through the
stdlib ``logging`` hierarchy under ``znicz_tpu.<ClassName>``.  The
reference's MongoDB event sink is out of scope; structured metrics go
through :mod:`znicz_tpu.utils.metrics` instead.
"""

from __future__ import annotations

import logging


_CONFIGURED = False


def setup_logging(level: int = logging.INFO) -> None:
    """Idempotent root-logger setup with a compact console format."""
    global _CONFIGURED
    if _CONFIGURED:
        logging.getLogger("znicz_tpu").setLevel(level)
        return
    handler = logging.StreamHandler()
    handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname).1s %(name)s: %(message)s",
                          datefmt="%H:%M:%S"))
    pkg_logger = logging.getLogger("znicz_tpu")
    pkg_logger.addHandler(handler)
    pkg_logger.setLevel(level)
    pkg_logger.propagate = False
    _CONFIGURED = True


class Logger:
    """Mixin: named logger per concrete class, with an instance tag."""

    def __init__(self, **kwargs) -> None:
        super().__init__()
        self._logger_ = logging.getLogger(
            f"znicz_tpu.{type(self).__name__}")

    @property
    def logger(self) -> logging.Logger:
        try:
            return self._logger_
        except AttributeError:  # subclass skipped __init__
            self._logger_ = logging.getLogger(
                f"znicz_tpu.{type(self).__name__}")
            return self._logger_

    def debug(self, msg: str, *args) -> None:
        self.logger.debug(msg, *args)

    def info(self, msg: str, *args) -> None:
        self.logger.info(msg, *args)

    def warning(self, msg: str, *args) -> None:
        self.logger.warning(msg, *args)

    def error(self, msg: str, *args) -> None:
        self.logger.error(msg, *args)
