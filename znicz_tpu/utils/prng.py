"""Seeded deterministic random generators (reference: ``veles/prng/``).

The reference shipped seed files and generated random streams on-device
with custom kernels; bit-exact parity with those streams is impossible
(documented in SURVEY.md §2.3) — the parity target is statistical.

Design: one named registry of :class:`RandomGenerator` objects
(``prng.get()`` returns the default, like the reference's ``rnd``).
Each generator owns

- a host ``numpy.random.Generator`` for control-plane randomness
  (dataset shuffles, weight init done host-side), and
- a jax PRNG key chain for device randomness; ``key()`` splits off a
  fresh subkey statefully for eager use, while jit regions carry key
  state as an explicit leaf (see ``accelerated_units``).
"""

from __future__ import annotations

import numpy as np

import jax

from znicz_tpu.utils.config import root


class RandomGenerator:
    def __init__(self, seed: int | None = None, name: str = "default") -> None:
        self.name = name
        self.seed(seed if seed is not None else int(root.common.seed))

    def seed(self, seed: int) -> None:
        self._seed = int(seed)
        self.numpy = np.random.default_rng(self._seed)
        self._key = jax.random.key(self._seed)

    @property
    def initial_seed(self) -> int:
        return self._seed

    def key(self) -> jax.Array:
        """Split off a fresh jax PRNG subkey (stateful, host-side)."""
        self._key, sub = jax.random.split(self._key)
        return sub

    # --- host-side convenience used for weight fills -------------------
    def fill_uniform(self, shape, vmin: float, vmax: float,
                     dtype=np.float32) -> np.ndarray:
        return self.numpy.uniform(vmin, vmax, size=shape).astype(dtype)

    def fill_normal(self, shape, mean: float = 0.0, stddev: float = 1.0,
                    dtype=np.float32) -> np.ndarray:
        return self.numpy.normal(mean, stddev, size=shape).astype(dtype)

    def shuffle(self, arr: np.ndarray) -> None:
        self.numpy.shuffle(arr)

    def permutation(self, n: int) -> np.ndarray:
        return self.numpy.permutation(n)

    def randint(self, low: int, high: int, size=None):
        return self.numpy.integers(low, high, size=size)

    def get_state(self) -> dict:
        """Serializable state for snapshot/resume trajectory fidelity."""
        return {
            "seed": self._seed,
            "numpy_state": self.numpy.bit_generator.state,
            "jax_key": np.asarray(jax.random.key_data(self._key)),
        }

    def set_state(self, state: dict) -> None:
        self._seed = int(state["seed"])
        self.numpy = np.random.default_rng(self._seed)
        self.numpy.bit_generator.state = state["numpy_state"]
        self._key = jax.random.wrap_key_data(
            np.asarray(state["jax_key"], dtype=np.uint32))


_generators: dict[str, RandomGenerator] = {}


def get(name: str = "default") -> RandomGenerator:
    gen = _generators.get(name)
    if gen is None:
        gen = _generators[name] = RandomGenerator(name=name)
    return gen


def seed_all(seed: int) -> None:
    """Reseed every registered generator (tests / run reproducibility)."""
    root.common.seed = int(seed)
    for gen in _generators.values():
        gen.seed(seed)
    if "default" not in _generators:
        get("default")
