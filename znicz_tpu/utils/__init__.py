"""Services layer: config tree, logging, seeded PRNG, snapshots, timing.

Rebuilds the reference's L6 services (reference: ``veles/config.py``,
``veles/logger.py``, ``veles/prng/``, ``veles/snapshotter.py``).
"""
