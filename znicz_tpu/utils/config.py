"""Global configuration tree.

Rebuilds the reference's attribute-tree config (reference:
``veles/config.py``): a global ``root`` object whose leaves are set by
sample config modules (``root.mnist.learning_rate = 0.03``) and whose
``root.common.*`` subtree holds platform settings.  Intermediate nodes
auto-vivify on attribute access, so config files can write deep paths
without declaring parents.

TPU-first deltas vs the reference:

- ``root.common.engine.backend`` defaults to ``"xla"`` (was
  ``"ocl"``/``"cuda"``);
- ``root.common.precision_type`` admits ``"bfloat16"`` — the native MXU
  input dtype — beside ``"float32"``/``"float64"``;
- ``root.common.precision_level`` keeps the reference's determinism
  knob semantics (0 = fast, 1 = deterministic accumulation, 2 =
  strictest) and maps onto ``jax.lax.Precision`` / f32 accumulation.
"""

from __future__ import annotations

import copy
import os
from typing import Any, Iterator


class Config:
    """A node in the attribute tree.  Leaves are ordinary values."""

    __slots__ = ("__dict__", "_path")

    def __init__(self, path: str = "root", **leaves: Any) -> None:
        object.__setattr__(self, "_path", path)
        for name, value in leaves.items():
            setattr(self, name, value)

    @property
    def path(self) -> str:
        return self._path

    def __getattr__(self, name: str) -> "Config":
        # Only called when normal lookup fails: auto-vivify a child node.
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        child = Config(f"{self._path}.{name}")
        self.__dict__[name] = child
        return child

    def __setattr__(self, name: str, value: Any) -> None:
        if isinstance(value, dict):
            node = Config(f"{self._path}.{name}")
            node.update(value)
            value = node
        self.__dict__[name] = value

    def update(self, tree: dict) -> "Config":
        """Recursively merge a plain-dict tree into this node."""
        for name, value in tree.items():
            if isinstance(value, dict):
                existing = self.__dict__.get(name)
                if isinstance(existing, Config):
                    existing.update(value)
                else:
                    setattr(self, name, value)
            else:
                setattr(self, name, value)
        return self

    def get(self, name: str, default: Any = None) -> Any:
        """Read a leaf without vivifying it."""
        value = self.__dict__.get(name, default)
        return value

    def as_dict(self) -> dict:
        out: dict = {}
        for name, value in self.__dict__.items():
            out[name] = value.as_dict() if isinstance(value, Config) else value
        return out

    def items(self) -> Iterator[tuple[str, Any]]:
        return iter(self.__dict__.items())

    def __contains__(self, name: str) -> bool:
        return name in self.__dict__

    def __repr__(self) -> str:
        return f"Config({self._path}: {sorted(self.__dict__)})"


def _default_root() -> Config:
    r = Config("root")
    r.common.engine.backend = "xla"  # "xla" | "numpy"
    r.common.precision_type = "float32"  # "bfloat16" | "float32" | "float64"
    r.common.precision_level = 0  # 0 fast, 1 deterministic sums, 2 strictest
    r.common.dirs.cache = os.path.expanduser("~/.cache/znicz_tpu")
    r.common.dirs.snapshots = os.path.expanduser("~/.cache/znicz_tpu/snapshots")
    r.common.dirs.datasets = os.path.expanduser("~/.cache/znicz_tpu/datasets")
    r.common.dirs.plots = os.path.expanduser("~/.cache/znicz_tpu/plots")
    r.common.dirs.images = os.path.expanduser("~/.cache/znicz_tpu/images")
    r.common.seed = 1234
    r.common.graphics.render = True       # draw PNGs in the render thread
    r.common.graphics.publish_port = None  # zmq PUB port for live clients
    return r


#: The global configuration tree, mutated by sample ``*_config.py`` files.
root = _default_root()

#: sample-default subtrees re-applied on reset (name → dict)
_registered_defaults: dict[str, dict] = {}


def _merge_defaults(node: Config, defaults: dict) -> None:
    """Fill missing leaves only — explicit config wins over defaults."""
    for key, value in defaults.items():
        if isinstance(value, dict):
            child = node.__dict__.get(key)
            if child is None:
                child = getattr(node, key)  # vivify an empty subtree
            if isinstance(child, Config):
                _merge_defaults(child, value)
            # else: an explicitly-set leaf shadows the default subtree
        elif key not in node.__dict__:
            setattr(node, key, copy.deepcopy(value))


def register_defaults(name: str, defaults: dict) -> None:
    """Register a sample's default config subtree under ``root.<name>``.

    Samples call this at import; the defaults survive :func:`reset_root`
    (tests reset between cases).  Defaults never clobber leaves already
    set (by a config module or CLI ``--root`` override) — import order
    of sample modules vs config application is irrelevant.
    """
    _registered_defaults[name] = copy.deepcopy(defaults)
    _merge_defaults(getattr(root, name), defaults)


def reset_root() -> None:
    """Restore ``root`` to platform + registered sample defaults
    (used by tests)."""
    fresh = _default_root()
    root.__dict__.clear()
    root.__dict__.update(fresh.__dict__)
    for name, defaults in _registered_defaults.items():
        _merge_defaults(getattr(root, name), defaults)
