"""Publishing: post-training report generation.

Rebuilds the reference's ``veles/publishing/`` — after a training run
the Publisher unit renders a report of what ran and how well: model
architecture, config, convergence metrics, timing, artifacts.  The
reference had html/pdf/confluence backends; here the backends are
Markdown and self-contained HTML (no external renderers in this
environment — the HTML backend embeds the plot PNGs base64-inline so
the report is one portable file).
"""

from __future__ import annotations

import base64
import datetime
import glob
import html
import json
import os

import numpy as np

from znicz_tpu.units import Unit
from znicz_tpu.utils.config import root
from znicz_tpu.utils.introspect import slowest_units, validation_metrics


def _layer_rows(workflow) -> list[dict]:
    rows = []
    for unit in getattr(workflow, "forwards", []):
        n_params = 0
        for attr in getattr(unit, "EXPORT_PARAMS", ("weights", "bias")):
            vec = getattr(unit, attr, None)
            if vec:  # shape, not mem: the device copy may be
                n_params += int(np.prod(vec.shape))  # authoritative
        rows.append({
            "name": unit.name,
            "type": type(unit).__name__,
            "output_shape": tuple(unit.output.shape[1:])
            if unit.output else (),
            "parameters": n_params,
        })
    return rows


_METRIC_LABELS = {
    "min_validation_n_err_pt": "best validation error %",
    "min_validation_mse": "best validation MSE",
}


def _metric_rows(workflow) -> dict:
    out: dict = {}
    loader = getattr(workflow, "loader", None)
    if loader is not None:
        out["epochs"] = int(loader.epoch_number)
    for attr, value in validation_metrics(workflow).items():
        out[_METRIC_LABELS.get(attr, attr)] = value
    return out


def gather_report(workflow) -> dict:
    """Everything a report renders, as plain data (also the json
    side-output — scripts consume it)."""
    timing = slowest_units(workflow, n=10)
    # plots: only THIS workflow's plotter outputs (the plots dir is
    # shared across runs and samples), and only after the async render
    # thread has drawn everything submitted — an unfinished flush
    # means a PNG could still be mid-write, so embed nothing then
    from znicz_tpu import graphics
    flushed = graphics.flush_server()
    if not flushed:
        import logging
        logging.getLogger("znicz_tpu.publishing").warning(
            "graphics flush timed out — report omits plots rather "
            "than embed mid-write PNGs")
    plots: list[str] = []
    if flushed:
        plots_dir = str(root.common.dirs.plots)
        unit_names = {u.name for u in workflow.units}
        started = getattr(workflow, "run_started_at", 0.0)
        plots = sorted(
            p for p in glob.glob(os.path.join(plots_dir, "*.png"))
            if os.path.splitext(os.path.basename(p))[0] in unit_names
            and os.path.getmtime(p) >= started - 1.0)
    snap = getattr(workflow, "snapshotter", None)
    return {
        "title": workflow.name,
        "generated": datetime.datetime.now().isoformat(
            sep=" ", timespec="seconds"),
        "metrics": _metric_rows(workflow),
        "layers": _layer_rows(workflow),
        "timing": timing,
        "plots": plots,
        "snapshot": snap.destination if snap is not None else None,
        "config": root.get(workflow.name).as_dict()
        if workflow.name in root else {},
    }


def render_markdown(report: dict) -> str:
    lines = [f"# Training report: {report['title']}",
             "", f"*Generated {report['generated']}*", ""]
    if report["metrics"]:
        lines += ["## Results", ""]
        for key, value in report["metrics"].items():
            lines.append(f"- **{key}**: {value}")
        lines.append("")
    if report["layers"]:
        lines += ["## Model", "",
                  "| layer | type | output shape | parameters |",
                  "|---|---|---|---|"]
        for row in report["layers"]:
            lines.append(
                f"| {row['name']} | {row['type']} | "
                f"{row['output_shape']} | {row['parameters']:,} |")
        total = sum(r["parameters"] for r in report["layers"])
        lines += ["", f"Total parameters: **{total:,}**", ""]
    if report["config"]:
        lines += ["## Configuration", "", "```json",
                  json.dumps(report["config"], indent=2, default=str),
                  "```", ""]
    if report["timing"]:
        lines += ["## Slowest units", "",
                  "| unit | runs | total s |", "|---|---|---|"]
        for row in report["timing"]:
            lines.append(f"| {row['unit']} | {row['runs']} | "
                         f"{row['total_s']} |")
        lines.append("")
    if report["snapshot"]:
        lines += [f"Best snapshot: `{report['snapshot']}`", ""]
    if report["plots"]:
        lines += ["## Plots", ""]
        lines += [f"![{os.path.basename(p)}]({p})" for p in report["plots"]]
        lines.append("")
    return "\n".join(lines)


def render_html(report: dict) -> str:
    """Self-contained HTML: plots embedded base64 inline."""
    md_body = []
    md_body.append(f"<h1>Training report: "
                   f"{html.escape(report['title'])}</h1>")
    md_body.append(f"<p><em>Generated "
                   f"{html.escape(report['generated'])}</em></p>")
    if report["metrics"]:
        md_body.append("<h2>Results</h2><ul>")
        for key, value in report["metrics"].items():
            md_body.append(f"<li><b>{html.escape(str(key))}</b>: "
                           f"{html.escape(str(value))}</li>")
        md_body.append("</ul>")
    if report["layers"]:
        md_body.append("<h2>Model</h2><table border=1 "
                       "cellpadding=4><tr><th>layer</th><th>type</th>"
                       "<th>output shape</th><th>parameters</th></tr>")
        for row in report["layers"]:
            md_body.append(
                f"<tr><td>{html.escape(row['name'])}</td>"
                f"<td>{html.escape(row['type'])}</td>"
                f"<td>{html.escape(str(row['output_shape']))}</td>"
                f"<td>{row['parameters']:,}</td></tr>")
        md_body.append("</table>")
    if report["config"]:
        md_body.append(
            "<h2>Configuration</h2><pre>"
            + html.escape(json.dumps(report["config"], indent=2,
                                     default=str)) + "</pre>")
    if report["timing"]:
        md_body.append("<h2>Slowest units</h2><table border=1 "
                       "cellpadding=4><tr><th>unit</th><th>runs</th>"
                       "<th>total s</th></tr>")
        for row in report["timing"]:
            md_body.append(
                f"<tr><td>{html.escape(row['unit'])}</td>"
                f"<td>{row['runs']}</td><td>{row['total_s']}</td></tr>")
        md_body.append("</table>")
    if report["snapshot"]:
        md_body.append(
            f"<p>Best snapshot: <code>"
            f"{html.escape(str(report['snapshot']))}</code></p>")
    for p in report["plots"]:
        try:
            with open(p, "rb") as f:
                data = base64.b64encode(f.read()).decode()
            md_body.append(
                f"<h3>{html.escape(os.path.basename(p))}</h3>"
                f'<img src="data:image/png;base64,{data}" '
                f'style="max-width:720px">')
        except OSError:
            continue
    body = "\n".join(md_body)
    return (f"<!DOCTYPE html><html><head><meta charset='utf-8'>"
            f"<title>{html.escape(report['title'])}</title></head>"
            f"<body>{body}</body></html>")


class Publisher(Unit):
    """End-of-training report unit (reference: ``Publisher``).

    Wire after the Decision with ``gate_skip = ~decision.complete`` —
    it fires exactly once, when training finishes (done by
    ``StandardWorkflow.link_publisher``)."""

    KNOWN_FORMATS = ("md", "html", "json")

    def __init__(self, workflow, name: str | None = None,
                 out_dir: str | None = None,
                 formats: tuple = ("md", "html", "json"),
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.out_dir = out_dir
        self.formats = tuple(formats)
        # fail at wiring time, not after hours of training
        unknown = [f for f in self.formats if f not in self.KNOWN_FORMATS]
        if unknown:
            raise ValueError(f"unknown report format(s) {unknown} "
                             f"(have {self.KNOWN_FORMATS})")
        self.destinations: list[str] = []

    def run(self) -> None:
        wf = self.workflow
        out_dir = self.out_dir or str(root.common.dirs.cache)
        os.makedirs(out_dir, exist_ok=True)
        report = gather_report(wf)
        base = os.path.join(out_dir, f"{wf.name}_report")
        self.destinations = []
        for fmt in self.formats:
            path = f"{base}.{fmt}"
            if fmt == "md":
                content = render_markdown(report)
            elif fmt == "html":
                content = render_html(report)
            else:  # "json" — formats validated in __init__
                content = json.dumps(report, indent=2, default=str)
            with open(path, "w") as f:
                f.write(content)
            self.destinations.append(path)
        self.info("report → %s", ", ".join(self.destinations))
