"""MnistRBM: RBM pretraining sample (reference:
``znicz/samples/MnistRBM/`` — north-star config #4).

No MNIST download in this environment (zero egress); the dataset is a
synthetic stand-in: noisy binary prototype patterns per class with the
same value range ([0,1] probabilities) and minibatch geometry.

Workflow topology (custom, like the reference's — RBMs have no
backward chain, so StandardWorkflow does not apply):

.. code-block:: text

    repeater → loader → encoder(All2AllSigmoid) → binarization
             → gradient_rbm(CD-1, shares encoder weights/bias)
             → evaluator_rbm(reconstruction MSE) → decision → loop

On the XLA backend the loader-gather → encoder → sampling → CD update
→ evaluation chain compiles into ONE jit region per forward_mode.
"""

from __future__ import annotations

import numpy as np

from znicz_tpu.accelerated_units import AcceleratedWorkflow, RegionUnit
from znicz_tpu.backends import Device, NumpyDevice
from znicz_tpu.loader.fullbatch import ArrayLoader
from znicz_tpu.ops.all2all import All2AllSigmoid
from znicz_tpu.ops.decision import DecisionMSE
from znicz_tpu.ops.rbm_units import Binarization, EvaluatorRBM, GradientRBM
from znicz_tpu.units import Repeater
from znicz_tpu.utils.config import register_defaults, root

register_defaults("mnist_rbm", {
    "minibatch_size": 32,
    "n_hidden": 48,
    "learning_rate": 0.08,
    "max_epochs": 25,
})


def make_data(seed: int = 23, n_per_class: int = 64, n_classes: int = 6,
              side: int = 8):
    """Noisy binary prototype images in [0,1]."""
    rng = np.random.default_rng(seed)
    protos = (rng.uniform(size=(n_classes, side * side)) < 0.35)
    data = np.concatenate([
        np.clip(p.astype(np.float32)
                + 0.15 * rng.normal(size=(n_per_class, side * side)),
                0.0, 1.0)
        for p in protos]).astype(np.float32)
    order = rng.permutation(len(data))
    return data[order]


class RBMWorkflow(AcceleratedWorkflow):
    """CD-1 RBM training workflow."""

    def __init__(self, workflow=None, name: str | None = None,
                 loader_factory=None, n_hidden: int = 48,
                 learning_rate: float = 0.08,
                 max_epochs: int | None = 25, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.repeater = Repeater(self, name="repeater")
        self.loader = loader_factory(self)
        self.encoder = All2AllSigmoid(
            self, output_sample_shape=n_hidden, name="encoder")
        self.encoder.link_attrs(self.loader,
                                ("input", "minibatch_data"))
        self.binarization = Binarization(self, name="binarization")
        self.binarization.link_attrs(self.encoder, ("input", "output"))
        self.grbm = GradientRBM(self, name="gradient_rbm",
                                learning_rate=learning_rate)
        self.grbm.link_attrs(self.loader, ("input", "minibatch_data"))
        self.grbm.link_attrs(self.loader, "forward_mode", two_way=False)
        self.grbm.link_attrs(self.encoder, ("hidden", "output"),
                             "weights", ("hbias", "bias"))
        self.grbm.link_attrs(self.binarization,
                             ("hidden_sample", "output"))
        self.evaluator = EvaluatorRBM(self, name="evaluator")
        self.evaluator.link_attrs(self.grbm,
                                  ("output", "reconstruction"))
        self.evaluator.link_attrs(self.loader,
                                  ("target", "minibatch_data"),
                                  "minibatch_valid", "minibatch_class")
        self.decision = DecisionMSE(self, name="decision",
                                    max_epochs=max_epochs)
        self.decision.loader = self.loader
        self.decision.evaluator = self.evaluator
        # control flow
        self.repeater.link_from(self.start_point)
        self.loader.link_from(self.repeater)
        self.encoder.link_from(self.loader)
        self.binarization.link_from(self.encoder)
        self.grbm.link_from(self.binarization)
        self.evaluator.link_from(self.grbm)
        self.decision.link_from(self.evaluator)
        self.repeater.link_from(self.decision)
        self.repeater.gate_block = self.decision.complete
        self.end_point.link_from(self.decision)
        self.end_point.gate_block = ~self.decision.complete
        self._region_unit: RegionUnit | None = None

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if not isinstance(self.device, NumpyDevice) \
                and self._region_unit is None:
            members = [self.loader, self.encoder, self.binarization,
                       self.grbm, self.evaluator]
            region = RegionUnit(self, members, name="rbm_region")
            region.initialize(device=self.device)
            region._initialized = True
            self.encoder.unlink_from(self.loader)
            self.decision.unlink_from(self.evaluator)
            region.link_from(self.loader)
            self.decision.link_from(region)
            self._region_unit = region


def build(**overrides) -> RBMWorkflow:
    cfg = dict(root.mnist_rbm.as_dict())
    cfg.update(overrides)
    data = make_data()
    n_train = int(0.8 * len(data))
    wf = RBMWorkflow(
        name="mnist_rbm",
        loader_factory=lambda w: ArrayLoader(
            w, train_data=data[:n_train], valid_data=data[n_train:],
            minibatch_size=cfg["minibatch_size"]),
        n_hidden=cfg["n_hidden"],
        learning_rate=cfg["learning_rate"],
        max_epochs=cfg["max_epochs"])
    wf._max_fires = 10_000_000
    return wf


def run(load, main):
    """Reference sample entry protocol (``veles <sample> <config>``):
    the launcher passes ``load`` (construct/resume) and ``main``
    (initialize + train)."""
    load(build)
    main()
