"""ImageNet AlexNet — the flagship / benchmark workload
(reference: ``znicz/samples/imagenet/`` AlexNet ``StandardWorkflow``
layers config; BASELINE.json north star: ≥8k images/sec on v4-32).

Canonical one-tower AlexNet geometry (227×227×3 input):

.. code-block:: text

    conv 96 11×11 /4  + ReLU → LRN → maxpool 3×3 /2        (55→27)
    conv 256 5×5 p2   + ReLU → LRN → maxpool 3×3 /2        (27→13)
    conv 384 3×3 p1   + ReLU
    conv 384 3×3 p1   + ReLU
    conv 256 3×3 p1   + ReLU → maxpool 3×3 /2              (13→6)
    fc 4096 + ReLU → dropout 0.5
    fc 4096 + ReLU → dropout 0.5
    softmax 1000

ImageNet itself is not downloadable here; the loader feeds uint8
synthetic frames of the exact geometry (throughput is
content-independent).  With a real ImageNet pipeline on disk, swap the
``loader_factory``.
"""

from __future__ import annotations

from znicz_tpu import datasets
from znicz_tpu.loader.fullbatch import ArrayLoader
from znicz_tpu.models.standard_workflow import StandardWorkflow
from znicz_tpu.utils.config import register_defaults, root

register_defaults("alexnet", {
    "minibatch_size": 128,
    "learning_rate": 0.01,
    "gradient_moment": 0.9,
    "weights_decay": 0.0005,
    "dropout": 0.5,
    "n_classes": 1000,
    "max_epochs": 90,
    "image_size": 227,
    "n_train_samples": 1024,   # synthetic-mode dataset size
    "n_valid_samples": 128,
})


def layers(cfg) -> list[dict]:
    gd_cfg = {"learning_rate": cfg["learning_rate"],
              "gradient_moment": cfg["gradient_moment"],
              "weights_decay": cfg["weights_decay"]}
    lrn = {"n": 5, "alpha": 1e-4, "beta": 0.75, "k": 2.0}
    pool = {"kx": 3, "ky": 3, "sliding": (2, 2)}
    return [
        {"type": "conv_str",
         "->": {"n_kernels": 96, "kx": 11, "ky": 11, "sliding": (4, 4),
                "weights_stddev": 0.01}, "<-": gd_cfg},
        {"type": "norm", "->": dict(lrn)},
        {"type": "max_pooling", "->": dict(pool)},
        {"type": "conv_str",
         "->": {"n_kernels": 256, "kx": 5, "ky": 5, "padding": 2,
                "weights_stddev": 0.01}, "<-": gd_cfg},
        {"type": "norm", "->": dict(lrn)},
        {"type": "max_pooling", "->": dict(pool)},
        {"type": "conv_str",
         "->": {"n_kernels": 384, "kx": 3, "ky": 3, "padding": 1,
                "weights_stddev": 0.01}, "<-": gd_cfg},
        {"type": "conv_str",
         "->": {"n_kernels": 384, "kx": 3, "ky": 3, "padding": 1,
                "weights_stddev": 0.01}, "<-": gd_cfg},
        {"type": "conv_str",
         "->": {"n_kernels": 256, "kx": 3, "ky": 3, "padding": 1,
                "weights_stddev": 0.01}, "<-": gd_cfg},
        {"type": "max_pooling", "->": dict(pool)},
        {"type": "all2all_str",
         "->": {"output_sample_shape": 4096, "weights_stddev": 0.005},
         "<-": gd_cfg},
        {"type": "dropout", "->": {"dropout_ratio": cfg["dropout"]}},
        {"type": "all2all_str",
         "->": {"output_sample_shape": 4096, "weights_stddev": 0.005},
         "<-": gd_cfg},
        {"type": "dropout", "->": {"dropout_ratio": cfg["dropout"]}},
        {"type": "softmax",
         "->": {"output_sample_shape": cfg["n_classes"],
                "weights_stddev": 0.01}, "<-": gd_cfg},
    ]


def build(streaming_dir: str | None = None, **overrides) -> StandardWorkflow:
    """``streaming_dir``: train from a class-per-subdir JPEG tree via
    the streaming ``FileImageLoader`` (native C++ decode pool, double
    -buffered) instead of the device-resident synthetic store — the
    real-ImageNet consumption mode (reference:
    ``znicz/samples/imagenet/`` fed from the file system too)."""
    cfg = dict(root.alexnet.as_dict())
    cfg.update(overrides)
    size = cfg["image_size"]
    if streaming_dir is not None:
        from znicz_tpu.loader.image import FileImageLoader

        n_total = cfg["n_train_samples"] + cfg["n_valid_samples"]

        def loader_factory(w):
            return FileImageLoader(
                w, train_dir=streaming_dir,
                validation_fraction=(
                    cfg["n_valid_samples"] / max(1, n_total)),
                out_hw=(size, size), resize_hw=(256, 256),
                minibatch_size=cfg["minibatch_size"])
    else:
        n_train, n_valid = cfg["n_train_samples"], cfg["n_valid_samples"]
        x, y = datasets.synthetic_imagenet(
            n_train + n_valid, size=size, n_classes=cfg["n_classes"])

        def loader_factory(w):
            return ArrayLoader(
                w,
                train_data=x[:n_train], train_labels=y[:n_train],
                valid_data=x[n_train:], valid_labels=y[n_train:],
                minibatch_size=cfg["minibatch_size"],
                normalization_scale=2.0 / 255.0, normalization_bias=-1.0)
    wf = StandardWorkflow(
        name="alexnet",
        loader_factory=loader_factory,
        layers=layers(cfg),
        decision_config={"max_epochs": cfg["max_epochs"]})
    wf._max_fires = 10 ** 9
    return wf


def run(load, main):
    """Reference sample entry protocol (``veles <sample> <config>``):
    the launcher passes ``load`` (construct/resume) and ``main``
    (initialize + train)."""
    load(build)
    main()
