"""Sample/model zoo (reference: ``znicz/samples/`` — each sample is a
workflow builder plus a config; SURVEY.md §2.4).  Each module exposes
``build(**overrides) -> StandardWorkflow`` and ``run()``."""
