"""Mnist784: the 784→N→784 fully-connected autoencoder (reference:
``znicz/samples/Mnist784/`` — MSE reconstruction of the input image
through a tanh bottleneck; north-star config #4 family)."""

from __future__ import annotations

from znicz_tpu import datasets
from znicz_tpu.loader.fullbatch import ArrayLoader
from znicz_tpu.models.standard_workflow import StandardWorkflow
from znicz_tpu.utils.config import register_defaults, root

register_defaults("mnist784", {
    "minibatch_size": 100,
    "learning_rate": 0.003,
    "gradient_moment": 0.9,
    "bottleneck": 64,
    "max_epochs": 20,
    "validation_fraction": 0.1,
})


def build(**overrides) -> StandardWorkflow:
    cfg = dict(root.mnist784.as_dict())
    cfg.update(overrides)
    wf_kwargs = {k: cfg.pop(k) for k in ("snapshotter_config",
                                         "lr_adjuster_config",
                                         "evaluator_config")
                 if k in cfg}
    train_x, _, test_x, _ = datasets.load_mnist()
    limit = cfg.get("n_train_samples")  # tests/CI: cap the dataset
    if limit:
        train_x, test_x = train_x[:int(limit)], test_x[:max(
            1, int(limit) // 6)]
    n_valid = int(len(train_x) * cfg["validation_fraction"])
    gd_cfg = {"learning_rate": cfg["learning_rate"],
              "gradient_moment": cfg["gradient_moment"]}
    wf = StandardWorkflow(
        name="mnist784",
        loader_factory=lambda w: ArrayLoader(
            w,
            train_data=train_x[n_valid:].reshape(-1, 784),
            valid_data=train_x[:n_valid].reshape(-1, 784),
            test_data=test_x.reshape(-1, 784),
            minibatch_size=cfg["minibatch_size"],
            normalization_scale=1.0 / 255.0),
        layers=[
            {"type": "all2all_tanh",
             "->": {"output_sample_shape": cfg["bottleneck"]},
             "<-": gd_cfg},
            # linear output layer: MSE against the normalized input
            {"type": "all2all", "->": {"output_sample_shape": 784},
             "<-": gd_cfg},
        ],
        loss="mse",
        decision_config={"max_epochs": cfg["max_epochs"]},
        **wf_kwargs)
    wf._max_fires = 100_000_000
    return wf


def run(load, main):
    """Reference sample entry protocol (``veles <sample> <config>``)."""
    load(build)
    main()
