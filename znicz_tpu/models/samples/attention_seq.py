"""Sequence-classification sample for the attention op family
(beyond the 2015 reference, which predates attention — SURVEY.md §5.7
marks the family as this framework's long-context extension).

Task: each sample is a (T, D) sequence of noise with a marker token
injected somewhere; the class is which third of the sequence holds the
marker.  Solving it requires cross-position mixing — exactly what a
position-agnostic per-token model cannot do — so a falling validation
error certifies the attention unit end to end.

Run: ``python -m znicz_tpu attention_seq``
(``--root attention_seq.seq_parallel=True`` rides the ring over a
mesh's model axis when one is present).
"""

from __future__ import annotations

import numpy as np

from znicz_tpu.loader.fullbatch import ArrayLoader
from znicz_tpu.models.standard_workflow import StandardWorkflow
from znicz_tpu.utils.config import register_defaults, root

register_defaults("attention_seq", {
    "minibatch_size": 32,
    "learning_rate": 0.05,
    "gradient_moment": 0.9,
    "n_heads": 4,
    "seq_len": 12,
    "features": 16,
    "n_classes": 3,
    "n_train": 384,
    "n_valid": 96,
    "max_epochs": 30,
    "seq_parallel": False,
    "seed": 9,
})


def make_data(cfg):
    rng = np.random.default_rng(cfg["seed"])
    n = cfg["n_train"] + cfg["n_valid"]
    t, d, n_classes = cfg["seq_len"], cfg["features"], cfg["n_classes"]
    span = t // n_classes
    x = rng.normal(0, 0.3, size=(n, t, d)).astype(np.float32)
    y = rng.integers(0, n_classes, size=n).astype(np.int32)
    for i in range(n):
        pos = y[i] * span + rng.integers(0, span)
        x[i, pos] += 2.0
    return x, y


def build(**overrides) -> StandardWorkflow:
    cfg = dict(root.attention_seq.as_dict())
    cfg.update(overrides)
    x, y = make_data(cfg)
    n_train = cfg["n_train"]
    gd_cfg = {"learning_rate": cfg["learning_rate"],
              "gradient_moment": cfg["gradient_moment"]}
    wf = StandardWorkflow(
        name="attention_seq",
        loader_factory=lambda w: ArrayLoader(
            w, train_data=x[:n_train], train_labels=y[:n_train],
            valid_data=x[n_train:], valid_labels=y[n_train:],
            minibatch_size=cfg["minibatch_size"]),
        layers=[
            {"type": "attention",
             "->": {"n_heads": cfg["n_heads"],
                    "seq_parallel": cfg["seq_parallel"]},
             "<-": gd_cfg},
            {"type": "softmax",
             "->": {"output_sample_shape": cfg["n_classes"]},
             "<-": gd_cfg},
        ],
        decision_config={"max_epochs": cfg["max_epochs"]})
    wf._max_fires = 10 ** 9
    return wf


def run(load, main):
    load(build)
    main()
