"""CIFAR-10 conv workflow — north-star config #2
(reference: ``znicz/samples/CIFAR10/cifar.py`` + ``cifar_config.py`` —
Conv + Pooling + LRN + All2All).

Real CIFAR-10 binary batches are used when present; otherwise
synthetic 32×32×3 class-prototype images.
"""

from __future__ import annotations

from znicz_tpu import datasets
from znicz_tpu.loader.fullbatch import ArrayLoader
from znicz_tpu.models.standard_workflow import StandardWorkflow
from znicz_tpu.utils.config import register_defaults, root

register_defaults("cifar", {
    "minibatch_size": 100,
    "learning_rate": 0.02,
    "gradient_moment": 0.9,
    "weights_decay": 0.0005,
    "max_epochs": 30,
    "validation_fraction": 0.1,
})


def layers(cfg) -> list[dict]:
    gd_cfg = {"learning_rate": cfg["learning_rate"],
              "gradient_moment": cfg["gradient_moment"],
              "weights_decay": cfg["weights_decay"]}
    return [
        {"type": "conv_str",
         "->": {"n_kernels": 32, "kx": 5, "ky": 5, "padding": 2},
         "<-": gd_cfg},
        {"type": "maxabs_pooling", "->": {"kx": 3, "ky": 3,
                                          "sliding": (2, 2)}},
        {"type": "norm", "->": {"n": 5, "alpha": 5e-5, "beta": 0.75}},
        {"type": "conv_str",
         "->": {"n_kernels": 32, "kx": 5, "ky": 5, "padding": 2},
         "<-": gd_cfg},
        {"type": "avg_pooling", "->": {"kx": 3, "ky": 3,
                                       "sliding": (2, 2)}},
        {"type": "norm", "->": {"n": 5, "alpha": 5e-5, "beta": 0.75}},
        {"type": "conv_str",
         "->": {"n_kernels": 64, "kx": 5, "ky": 5, "padding": 2},
         "<-": gd_cfg},
        {"type": "avg_pooling", "->": {"kx": 3, "ky": 3,
                                       "sliding": (2, 2)}},
        {"type": "softmax", "->": {"output_sample_shape": 10},
         "<-": gd_cfg},
    ]


def build(**overrides) -> StandardWorkflow:
    cfg = dict(root.cifar.as_dict())
    cfg.update(overrides)
    train_x, train_y, test_x, test_y = datasets.load_cifar10()
    n_valid = int(len(train_x) * cfg["validation_fraction"])
    wf = StandardWorkflow(
        name="cifar",
        loader_factory=lambda w: ArrayLoader(
            w,
            train_data=train_x[n_valid:], train_labels=train_y[n_valid:],
            valid_data=train_x[:n_valid], valid_labels=train_y[:n_valid],
            test_data=test_x, test_labels=test_y,
            minibatch_size=cfg["minibatch_size"],
            normalization_scale=2.0 / 255.0, normalization_bias=-1.0),
        layers=layers(cfg),
        decision_config={"max_epochs": cfg["max_epochs"]})
    wf._max_fires = 100_000_000
    return wf


def run(load, main):
    """Reference sample entry protocol (``veles <sample> <config>``):
    the launcher passes ``load`` (construct/resume) and ``main``
    (initialize + train)."""
    load(build)
    main()
