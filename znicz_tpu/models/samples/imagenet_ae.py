"""ImagenetAE: convolutional autoencoder pretraining at ImageNet
geometry (reference: ``znicz/samples/ImagenetAE/`` — the conv-AE
pretraining workflow for the AlexNet family).

ImageNet itself is not downloadable here; synthetic frames of the
exact geometry stand in (reconstruction loss is content-agnostic for
the pipeline's correctness; swap the loader factory for
``FileImageLoader`` over a real tree — see :mod:`.imagenet`)."""

from __future__ import annotations

from znicz_tpu import datasets
from znicz_tpu.loader.fullbatch import ArrayLoader
from znicz_tpu.models.standard_workflow import StandardWorkflow
from znicz_tpu.utils.config import register_defaults, root

register_defaults("imagenet_ae", {
    "minibatch_size": 64,
    "learning_rate": 0.005,
    "gradient_moment": 0.9,
    "image_size": 216,         # divisible through conv 8/4 + pool 2
    "n_kernels": 16,
    "kx": 8,
    "ky": 8,
    "sliding": (4, 4),
    "max_epochs": 10,
    "n_train_samples": 512,
    "n_valid_samples": 64,
})


def build(**overrides) -> StandardWorkflow:
    cfg = dict(root.imagenet_ae.as_dict())
    cfg.update(overrides)
    wf_kwargs = {k: cfg.pop(k) for k in ("snapshotter_config",
                                         "lr_adjuster_config",
                                         "evaluator_config")
                 if k in cfg}
    size = cfg["image_size"]
    n_train, n_valid = cfg["n_train_samples"], cfg["n_valid_samples"]
    x, _ = datasets.synthetic_imagenet(n_train + n_valid, size=size,
                                       n_classes=2)
    gd_cfg = {"learning_rate": cfg["learning_rate"],
              "gradient_moment": cfg["gradient_moment"]}
    conv_cfg = {"n_kernels": cfg["n_kernels"], "kx": cfg["kx"],
                "ky": cfg["ky"], "sliding": tuple(cfg["sliding"])}
    wf = StandardWorkflow(
        name="imagenet_ae",
        loader_factory=lambda w: ArrayLoader(
            w, train_data=x[:n_train], valid_data=x[n_train:],
            minibatch_size=cfg["minibatch_size"],
            normalization_scale=2.0 / 255.0, normalization_bias=-1.0),
        layers=[
            {"type": "conv_tanh", "->": conv_cfg, "<-": gd_cfg},   # 0
            {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},     # 1
            {"type": "depooling", "tied_to": 1},                   # 2
            {"type": "deconv_tanh", "tied_to": 0, "<-": gd_cfg},   # 3
        ],
        loss="mse",
        decision_config={"max_epochs": cfg["max_epochs"]},
        **wf_kwargs)
    wf._max_fires = 10 ** 9
    return wf


def run(load, main):
    """Reference sample entry protocol (``veles <sample> <config>``)."""
    load(build)
    main()
