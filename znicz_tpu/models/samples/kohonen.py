"""Kohonen SOM sample (reference: ``znicz/samples/Kohonen/`` /
``DemoKohonen`` — unsupervised 2-D map of a point cloud).

Topology:

.. code-block:: text

    repeater → loader → kohonen_forward(winners) → kohonen_trainer
             → decision(epochs) → loop

Quality metric: mean quantization error (squared distance to the
winner), accumulated on device per epoch like the evaluators do.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from znicz_tpu.accelerated_units import AcceleratedWorkflow, RegionUnit
from znicz_tpu.backends import Device, NumpyDevice
from znicz_tpu.loader.fullbatch import ArrayLoader
from znicz_tpu.memory import Vector
from znicz_tpu.ops.decision import DecisionBase
from znicz_tpu.ops.kohonen import KohonenForward, KohonenTrainer
from znicz_tpu.units import Repeater
from znicz_tpu.utils.config import register_defaults, root

register_defaults("kohonen", {
    "minibatch_size": 40,
    "shape": (8, 8),
    "learning_rate": 0.5,
    "max_epochs": 12,
})


def make_data(seed: int = 31, n: int = 800):
    """Ring + two blobs in 2-D — classic SOM demo distribution."""
    rng = np.random.default_rng(seed)
    theta = rng.uniform(0, 2 * np.pi, n // 2)
    ring = np.stack([np.cos(theta), np.sin(theta)], 1)
    ring += 0.05 * rng.normal(size=ring.shape)
    blobs = np.concatenate([
        [2.0, 0.5] + 0.15 * rng.normal(size=(n // 4, 2)),
        [-1.5, -1.5] + 0.15 * rng.normal(size=(n // 4, 2))])
    data = np.concatenate([ring, blobs]).astype(np.float32)
    return data[rng.permutation(len(data))]


class DecisionSOM(DecisionBase):
    """Epoch bookkeeping on the accumulated quantization error."""

    SNAPSHOT_ATTRS = ("epoch_qe", "best_qe", "_epochs_without_improvement")

    def __init__(self, workflow, name=None, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.forward = None   # KohonenForward (hits + epoch_qe live there)
        self.epoch_qe = np.inf
        self.best_qe = None

    def accumulate_minibatch(self) -> None:
        pass  # accumulated on device (forward.epoch_qe)

    def on_epoch_ended(self) -> None:
        acc: Vector = self.forward.epoch_qe
        acc.map_read()
        n = max(self.loader.total_samples, 1)
        self.epoch_qe = float(acc.mem) / n
        acc.map_invalidate()
        acc.mem[...] = 0
        hits = self.forward.hits
        hits.map_read()
        used = int((hits.mem > 0).sum())
        hits.map_invalidate()
        hits.mem[...] = 0
        if self.best_qe is None or self.epoch_qe < self.best_qe:
            self.best_qe = self.epoch_qe
            self.improved.value = True
        self.info("epoch %d: quantization err %.5f, neurons used %d/%d",
                  self.loader.epoch_number, self.epoch_qe, used,
                  self.forward.n_neurons)


class KohonenQE(KohonenForward):
    """KohonenForward + on-device epoch accumulator of the
    quantization error (one host sync per epoch, as the evaluators
    do)."""

    def __init__(self, workflow, shape, name=None, **kwargs) -> None:
        super().__init__(workflow, shape, name=name, **kwargs)
        self.epoch_qe = Vector(name=f"{self.name}.epoch_qe")

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if not self.epoch_qe:
            self.epoch_qe.reset(np.zeros((), dtype=np.float32))
        self.init_vectors(self.epoch_qe)

    def numpy_run(self) -> None:
        super().numpy_run()
        self.epoch_qe.map_write()
        self.epoch_qe.mem[...] += self.output.mem.sum()

    def xla_run(self) -> None:
        super().xla_run()
        self.epoch_qe.devmem = (self.epoch_qe.devmem
                                + jnp.sum(self.output.devmem))


class KohonenWorkflow(AcceleratedWorkflow):
    def __init__(self, workflow=None, name=None, loader_factory=None,
                 shape=(8, 8), learning_rate: float = 0.5,
                 max_epochs: int = 12, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.repeater = Repeater(self, name="repeater")
        self.loader = loader_factory(self)
        self.forward = KohonenQE(self, shape, name="kohonen")
        self.forward.link_attrs(self.loader, ("input", "minibatch_data"))
        self.trainer = KohonenTrainer(self, name="trainer",
                                      learning_rate=learning_rate)
        self.trainer.link_attrs(self.loader, ("input", "minibatch_data"))
        self.trainer.link_attrs(self.loader, "forward_mode",
                                two_way=False)
        self.trainer.link_attrs(self.forward, "weights", "winners")
        self.trainer.shape_grid = shape
        self.decision = DecisionSOM(self, name="decision",
                                    max_epochs=max_epochs)
        self.decision.loader = self.loader
        self.decision.forward = self.forward
        self.repeater.link_from(self.start_point)
        self.loader.link_from(self.repeater)
        self.forward.link_from(self.loader)
        self.trainer.link_from(self.forward)
        self.decision.link_from(self.trainer)
        self.repeater.link_from(self.decision)
        self.repeater.gate_block = self.decision.complete
        self.end_point.link_from(self.decision)
        self.end_point.gate_block = ~self.decision.complete
        self._region_unit: RegionUnit | None = None

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if not isinstance(self.device, NumpyDevice) \
                and self._region_unit is None:
            members = [self.loader, self.forward, self.trainer]
            region = RegionUnit(self, members, name="som_region")
            region.initialize(device=self.device)
            region._initialized = True
            self.forward.unlink_from(self.loader)
            self.decision.unlink_from(self.trainer)
            region.link_from(self.loader)
            self.decision.link_from(region)
            self._region_unit = region


def build(**overrides) -> KohonenWorkflow:
    cfg = dict(root.kohonen.as_dict())
    cfg.update(overrides)
    data = make_data()
    n_train = int(0.9 * len(data))
    wf = KohonenWorkflow(
        name="kohonen",
        loader_factory=lambda w: ArrayLoader(
            w, train_data=data[:n_train], valid_data=data[n_train:],
            minibatch_size=cfg["minibatch_size"]),
        shape=tuple(cfg["shape"]),
        learning_rate=cfg["learning_rate"],
        max_epochs=cfg["max_epochs"])
    wf._max_fires = 10_000_000
    return wf


def run(load, main):
    """Reference sample entry protocol (``veles <sample> <config>``):
    the launcher passes ``load`` (construct/resume) and ``main``
    (initialize + train)."""
    load(build)
    main()
