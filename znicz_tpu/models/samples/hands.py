"""Hands: open/closed hand-posture binary classifier (reference:
``znicz/samples/Hands/`` — small grayscale images, two classes,
fully-connected net).

Real data: ``root.common.dirs.datasets/hands`` with one subdirectory
per posture class; otherwise synthetic two-class grayscale images.
"""

from __future__ import annotations

import os

from znicz_tpu import datasets
from znicz_tpu.loader.fullbatch import ArrayLoader
from znicz_tpu.models.standard_workflow import StandardWorkflow
from znicz_tpu.utils.config import register_defaults, root

register_defaults("hands", {
    "minibatch_size": 40,
    "learning_rate": 0.05,
    "gradient_moment": 0.9,
    "hidden": 30,
    "image_size": 24,
    "max_epochs": 30,
    "validation_fraction": 0.15,
})


def _data_dir() -> str:
    return os.path.join(str(root.common.dirs.datasets), "hands")


def build(**overrides) -> StandardWorkflow:
    cfg = dict(root.hands.as_dict())
    cfg.update(overrides)
    size = cfg["image_size"]
    gd_cfg = {"learning_rate": cfg["learning_rate"],
              "gradient_moment": cfg["gradient_moment"]}
    layers = [
        {"type": "all2all_tanh",
         "->": {"output_sample_shape": cfg["hidden"]}, "<-": gd_cfg},
        {"type": "softmax", "->": {"output_sample_shape": 2},
         "<-": gd_cfg},
    ]
    if os.path.isdir(_data_dir()):
        from znicz_tpu.loader.image import FullBatchImageLoader

        def loader_factory(w):
            return FullBatchImageLoader(
                w, train_dir=_data_dir(),
                validation_fraction=cfg["validation_fraction"],
                out_hw=(size, size), resize_hw=None, grayscale=True,
                normalization_scale=2.0 / 255.0,
                normalization_bias=-1.0,
                minibatch_size=cfg["minibatch_size"])
    else:
        x, y, _, _ = datasets.synthetic_images(
            n_train=400, n_test=0, size=size, channels=0,
            n_classes=2, seed=47)
        n_valid = int(len(x) * cfg["validation_fraction"])
        flat = (x.reshape(len(x), -1).astype("float32") / 127.5) - 1.0

        def loader_factory(w):
            return ArrayLoader(
                w, train_data=flat[n_valid:], train_labels=y[n_valid:],
                valid_data=flat[:n_valid], valid_labels=y[:n_valid],
                minibatch_size=cfg["minibatch_size"])
    wf = StandardWorkflow(
        name="hands",
        loader_factory=loader_factory,
        layers=layers,
        decision_config={"max_epochs": cfg["max_epochs"]})
    wf._max_fires = 10_000_000
    return wf


def run(load, main):
    """Reference sample entry protocol (``veles <sample> <config>``)."""
    load(build)
    main()
