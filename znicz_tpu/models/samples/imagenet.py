"""ImageNet from disk: AlexNet over the streaming image pipeline
(reference: ``znicz/samples/imagenet/`` — the dataset-preparation +
training workflow pair; here preparation collapses into the
native-decode streaming loader).

Point ``root.imagenet.train_dir`` (class-per-subdirectory JPEG tree,
standard ImageNet layout) at the dataset; ``valid_dir`` optional
(else ``validation_fraction`` carves one out).  The decode/augment
path is the C++ worker pool (:mod:`znicz_tpu.native`): resize-256 →
random-crop-227 + horizontal flip on train, center crop on eval,
double-buffered so decode of batch N+1 overlaps device compute of
batch N — the SURVEY.md §7 "input pipeline at 8k img/s" design.

The AlexNet layer stack is shared with :mod:`.alexnet` (the
synthetic-data benchmark variant).
"""

from __future__ import annotations

from znicz_tpu.loader.image import FileImageLoader
from znicz_tpu.models.samples.alexnet import layers
from znicz_tpu.models.standard_workflow import StandardWorkflow
from znicz_tpu.utils.config import register_defaults, root

register_defaults("imagenet", {
    "train_dir": None,           # REQUIRED: ImageNet train tree
    "valid_dir": None,
    "validation_fraction": 0.04,
    "minibatch_size": 128,
    "learning_rate": 0.01,
    "gradient_moment": 0.9,
    "weights_decay": 0.0005,
    "dropout": 0.5,
    "n_classes": 1000,
    "max_epochs": 90,
    "image_size": 227,
    "resize_size": 256,
    "decode_threads": 0,         # 0 → hardware concurrency
})


def build(**overrides) -> StandardWorkflow:
    cfg = dict(root.imagenet.as_dict())
    cfg.update(overrides)
    if not cfg["train_dir"]:
        raise ValueError(
            "root.imagenet.train_dir must point at an ImageNet-layout "
            "image tree (class-per-subdirectory)")
    size = int(cfg["image_size"])
    resize = int(cfg["resize_size"])
    wf_kwargs = {k: cfg.pop(k) for k in ("snapshotter_config",
                                         "lr_adjuster_config",
                                         "evaluator_config")
                 if k in cfg}
    wf = StandardWorkflow(
        name="imagenet",
        loader_factory=lambda w: FileImageLoader(
            w, train_dir=cfg["train_dir"], valid_dir=cfg["valid_dir"],
            validation_fraction=cfg["validation_fraction"],
            out_hw=(size, size), resize_hw=(resize, resize),
            random_crop=True, random_flip=True,
            normalization_scale=2.0 / 255.0, normalization_bias=-1.0,
            minibatch_size=cfg["minibatch_size"],
            n_threads=cfg["decode_threads"]),
        layers=layers(cfg),
        decision_config={"max_epochs": cfg["max_epochs"]},
        **wf_kwargs)
    wf._max_fires = 10 ** 9
    return wf


def run(load, main):
    """Reference sample entry protocol (``veles <sample> <config>``)."""
    load(build)
    main()
