"""MnistAE: the convolutional autoencoder (reference:
``znicz/samples/MnistAE/`` — conv → maxpool encoder, depooling →
deconv decoder, MSE reconstruction; the sample that exercises
Deconv/GDDeconv/Depooling; north-star config #4)."""

from __future__ import annotations

from znicz_tpu import datasets
from znicz_tpu.loader.fullbatch import ArrayLoader
from znicz_tpu.models.standard_workflow import StandardWorkflow
from znicz_tpu.utils.config import register_defaults, root

register_defaults("mnist_ae", {
    "minibatch_size": 100,
    "learning_rate": 0.0005,
    "gradient_moment": 0.9,
    "n_kernels": 9,
    "kx": 5,
    "ky": 5,
    "sliding": (2, 2),
    "max_epochs": 15,
    "validation_fraction": 0.1,
})


def build(**overrides) -> StandardWorkflow:
    cfg = dict(root.mnist_ae.as_dict())
    cfg.update(overrides)
    wf_kwargs = {k: cfg.pop(k) for k in ("snapshotter_config",
                                         "lr_adjuster_config",
                                         "evaluator_config")
                 if k in cfg}
    train_x, _, test_x, _ = datasets.load_mnist()
    limit = cfg.get("n_train_samples")  # tests/CI: cap the dataset
    if limit:
        train_x, test_x = train_x[:int(limit)], test_x[:max(
            1, int(limit) // 6)]
    n_valid = int(len(train_x) * cfg["validation_fraction"])
    gd_cfg = {"learning_rate": cfg["learning_rate"],
              "gradient_moment": cfg["gradient_moment"]}
    conv_cfg = {"n_kernels": cfg["n_kernels"], "kx": cfg["kx"],
                "ky": cfg["ky"], "sliding": tuple(cfg["sliding"])}
    wf = StandardWorkflow(
        name="mnist_ae",
        loader_factory=lambda w: ArrayLoader(
            w,
            train_data=train_x[n_valid:, :, :, None],
            valid_data=train_x[:n_valid, :, :, None],
            test_data=test_x[:, :, :, None],
            minibatch_size=cfg["minibatch_size"],
            normalization_scale=1.0 / 255.0),
        layers=[
            {"type": "conv_tanh", "->": conv_cfg, "<-": gd_cfg},   # 0
            {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},     # 1
            {"type": "depooling", "tied_to": 1},                   # 2
            {"type": "deconv_tanh", "tied_to": 0, "<-": gd_cfg},   # 3
        ],
        loss="mse",
        decision_config={"max_epochs": cfg["max_epochs"]},
        **wf_kwargs)
    wf._max_fires = 100_000_000
    return wf


def run(load, main):
    """Reference sample entry protocol (``veles <sample> <config>``)."""
    load(build)
    main()
