"""Wine sample config module (reference convention: a ``*_config.py``
beside each sample mutates the global ``root`` tree before the
workflow module builds — ``veles wine.py wine_config.py``)."""

from znicz_tpu.utils.config import root

root.wine.max_epochs = 12
root.wine.learning_rate = 0.5
root.wine.minibatch_size = 10
