"""Channels: TV-channel logo classifier (reference:
``znicz/samples/Channels/`` — color logo crops through a conv net;
the historical production demo of the reference stack).

Real data: ``root.common.dirs.datasets/channels`` with one
subdirectory per channel; otherwise synthetic logo-like color images.
"""

from __future__ import annotations

import os

from znicz_tpu import datasets
from znicz_tpu.loader.fullbatch import ArrayLoader
from znicz_tpu.models.standard_workflow import StandardWorkflow
from znicz_tpu.utils.config import register_defaults, root

register_defaults("channels", {
    "minibatch_size": 50,
    "learning_rate": 0.02,
    "gradient_moment": 0.9,
    "weights_decay": 0.0005,
    "n_channels": 8,
    "image_size": 32,
    "max_epochs": 30,
    "validation_fraction": 0.15,
})


def _data_dir() -> str:
    return os.path.join(str(root.common.dirs.datasets), "channels")


def layers(cfg) -> list[dict]:
    gd_cfg = {"learning_rate": cfg["learning_rate"],
              "gradient_moment": cfg["gradient_moment"],
              "weights_decay": cfg["weights_decay"]}
    return [
        {"type": "conv_str",
         "->": {"n_kernels": 16, "kx": 5, "ky": 5, "padding": 2},
         "<-": gd_cfg},
        {"type": "max_pooling", "->": {"kx": 2, "ky": 2,
                                       "sliding": (2, 2)}},
        {"type": "conv_str",
         "->": {"n_kernels": 32, "kx": 5, "ky": 5, "padding": 2},
         "<-": gd_cfg},
        {"type": "max_pooling", "->": {"kx": 2, "ky": 2,
                                       "sliding": (2, 2)}},
        {"type": "all2all_tanh", "->": {"output_sample_shape": 64},
         "<-": gd_cfg},
        {"type": "softmax",
         "->": {"output_sample_shape": cfg["n_channels"]},
         "<-": gd_cfg},
    ]


def build(**overrides) -> StandardWorkflow:
    cfg = dict(root.channels.as_dict())
    cfg.update(overrides)
    size = cfg["image_size"]
    if os.path.isdir(_data_dir()):
        from znicz_tpu.loader.image import FullBatchImageLoader

        def loader_factory(w):
            return FullBatchImageLoader(
                w, train_dir=_data_dir(),
                validation_fraction=cfg["validation_fraction"],
                out_hw=(size, size), resize_hw=None,
                normalization_scale=2.0 / 255.0,
                normalization_bias=-1.0,
                minibatch_size=cfg["minibatch_size"])
    else:
        x, y, _, _ = datasets.synthetic_images(
            n_train=cfg["n_channels"] * 60, n_test=0, size=size,
            channels=3, n_classes=cfg["n_channels"], seed=48)
        n_valid = int(len(x) * cfg["validation_fraction"])

        def loader_factory(w):
            return ArrayLoader(
                w, train_data=x[n_valid:], train_labels=y[n_valid:],
                valid_data=x[:n_valid], valid_labels=y[:n_valid],
                minibatch_size=cfg["minibatch_size"],
                normalization_scale=2.0 / 255.0,
                normalization_bias=-1.0)
    wf = StandardWorkflow(
        name="channels",
        loader_factory=loader_factory,
        layers=layers(cfg),
        decision_config={"max_epochs": cfg["max_epochs"]})
    wf._max_fires = 100_000_000
    return wf


def run(load, main):
    """Reference sample entry protocol (``veles <sample> <config>``)."""
    load(build)
    main()
