"""MnistSimple: the 784–100–10 MLP — north-star config #1
(reference: ``znicz/samples/MnistSimple/`` — ``All2AllTanh(100)`` +
``All2AllSoftmax(10)``; BASELINE.json config "MNIST 784-100-10 MLP").

Real MNIST idx files are used when present under
``root.common.dirs.datasets/mnist``; otherwise a synthetic
MNIST-shaped dataset (see :mod:`znicz_tpu.datasets`).
"""

from __future__ import annotations

from znicz_tpu import datasets
from znicz_tpu.loader.fullbatch import ArrayLoader
from znicz_tpu.models.standard_workflow import StandardWorkflow
from znicz_tpu.utils.config import register_defaults, root

register_defaults("mnist", {
    "minibatch_size": 100,
    "learning_rate": 0.03,
    "gradient_moment": 0.9,
    "weights_decay": 0.0005,
    "hidden": 100,
    "max_epochs": 30,
    "validation_fraction": 0.1,
})


def build(**overrides) -> StandardWorkflow:
    cfg = dict(root.mnist.as_dict())
    cfg.update(overrides)
    train_x, train_y, test_x, test_y = datasets.load_mnist()
    # normalize to [-1, 1] and flatten to 784 like the reference loader
    n_valid = int(len(train_x) * cfg["validation_fraction"])
    gd_cfg = {"learning_rate": cfg["learning_rate"],
              "gradient_moment": cfg["gradient_moment"],
              "weights_decay": cfg["weights_decay"]}
    wf = StandardWorkflow(
        name="mnist",
        loader_factory=lambda w: ArrayLoader(
            w,
            train_data=train_x[n_valid:].reshape(-1, 784),
            train_labels=train_y[n_valid:],
            valid_data=train_x[:n_valid].reshape(-1, 784),
            valid_labels=train_y[:n_valid],
            test_data=test_x.reshape(-1, 784), test_labels=test_y,
            minibatch_size=cfg["minibatch_size"],
            normalization_scale=2.0 / 255.0, normalization_bias=-1.0),
        layers=[
            {"type": "all2all_tanh",
             "->": {"output_sample_shape": cfg["hidden"]},
             "<-": gd_cfg},
            {"type": "softmax", "->": {"output_sample_shape": 10},
             "<-": gd_cfg},
        ],
        decision_config={"max_epochs": cfg["max_epochs"]})
    wf._max_fires = 100_000_000
    return wf


def run(load, main):
    """Reference sample entry protocol (``veles <sample> <config>``):
    the launcher passes ``load`` (construct/resume) and ``main``
    (initialize + train)."""
    load(build)
    main()
