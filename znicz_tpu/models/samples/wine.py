"""Wine: the "hello world" MLP — fastest functional smoke
(reference: ``znicz/samples/Wine/`` — a tiny UCI-wine MLP).

Trains on the REAL UCI Wine dataset (scikit-learn bundles it, so no
egress is needed; see ``datasets.load_wine``), matching the data the
reference's functional test asserted golden error counts on.  Config
leaves mirror the reference's ``root.wine.*``.
"""

from __future__ import annotations

from znicz_tpu import datasets
from znicz_tpu.loader.fullbatch import ArrayLoader
from znicz_tpu.models.standard_workflow import StandardWorkflow
from znicz_tpu.utils.config import register_defaults, root

register_defaults("wine", {
    "minibatch_size": 10,
    "learning_rate": 0.3,
    "layers": [8],
    "max_epochs": 50,
})


def make_data():
    return datasets.load_wine()


def build(**overrides) -> StandardWorkflow:
    cfg = dict(root.wine.as_dict())
    cfg.update(overrides)
    wf_kwargs = {k: cfg.pop(k) for k in ("snapshotter_config",
                                         "lr_adjuster_config",
                                         "evaluator_config")
                 if k in cfg}
    data, labels = make_data()
    n_train = 150
    layers = [
        {"type": "all2all_tanh",
         "->": {"output_sample_shape": n},
         "<-": {"learning_rate": cfg["learning_rate"]}}
        for n in cfg["layers"]
    ] + [{"type": "softmax", "->": {"output_sample_shape": 3},
          "<-": {"learning_rate": cfg["learning_rate"]}}]
    wf = StandardWorkflow(
        name="wine",
        loader_factory=lambda w: ArrayLoader(
            w, train_data=data[:n_train], train_labels=labels[:n_train],
            valid_data=data[n_train:], valid_labels=labels[n_train:],
            minibatch_size=cfg["minibatch_size"]),
        layers=layers,
        decision_config={"max_epochs": cfg["max_epochs"]},
        **wf_kwargs)
    wf._max_fires = 10_000_000
    return wf


def run(load, main):
    """Reference sample entry protocol (``veles <sample> <config>``):
    the launcher passes ``load`` (construct/resume) and ``main``
    (initialize + train)."""
    load(build)
    main()
