"""StandardWorkflow: declarative model assembly + training-loop wiring.

Rebuilds the reference's ``znicz/standard_workflow.py``: a complete
training loop from a declarative ``layers`` list.  Layer dicts use the
reference's convention — ``{"type": <name>, "->": {forward kwargs},
"<-": {gradient kwargs}}``.

Topology (both backends):

.. code-block:: text

    start → repeater → loader(host pick) → [hot chain] → decision ─→ repeater
                                                            └─(complete)→ end
    side chain on decision.improved: snapshotter

The hot chain is backend-dependent — the TPU-first core of the design:

- ``xla``: ONE :class:`~znicz_tpu.accelerated_units.RegionUnit`
  compiling loader-gather → forwards → evaluator → backwards into a
  single donated-buffer XLA program (two variants: train minibatches
  run the backward units, validation/test minibatches skip them via
  the region's static key);
- ``numpy``: the oracle path — each unit fires eagerly through the
  scheduler exactly like the reference's NumPy backend.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import numpy as np

from znicz_tpu.accelerated_units import AcceleratedWorkflow, RegionUnit
from znicz_tpu.backends import NumpyDevice
from znicz_tpu.loader.base import TRAIN, Loader
from znicz_tpu.mutable import Bool
from znicz_tpu.ops import activation, all2all, conv, cutter, dropout, pooling
from znicz_tpu.ops import attention, deconv, depooling, lstm, normalization
from znicz_tpu.ops import embedding, layer_norm, pos_encoding
from znicz_tpu.ops import seq_reshape
from znicz_tpu.ops import gd, gd_conv, gd_pooling  # noqa: F401 (pairs)
from znicz_tpu.ops.decision import DecisionGD, DecisionMSE
from znicz_tpu.ops.lr_adjust import LearningRateAdjust
from znicz_tpu.ops.evaluator import EvaluatorMSE, EvaluatorSoftmax
from znicz_tpu.ops.nn_units import Forward, gd_for
from znicz_tpu.units import Repeater
from znicz_tpu.utils.snapshotter import Snapshotter


#: layer-type registry: name → forward class (backward via gd_for)
_LAYER_TYPES: dict[str, type] = {}


def register_layer_type(name: str, forward_cls: type) -> None:
    _LAYER_TYPES[name] = forward_cls


def layer_type(name: str) -> type:
    try:
        return _LAYER_TYPES[name]
    except KeyError:
        raise ValueError(f"unknown layer type '{name}' "
                         f"(have {sorted(_LAYER_TYPES)})") from None


for _name, _cls in {
    "all2all": all2all.All2All,
    "all2all_tanh": all2all.All2AllTanh,
    "all2all_relu": all2all.All2AllRELU,
    "all2all_str": all2all.All2AllStrictRELU,
    "all2all_sigmoid": all2all.All2AllSigmoid,
    "softmax": all2all.All2AllSoftmax,
    "conv": conv.Conv,
    "conv_tanh": conv.ConvTanh,
    "conv_relu": conv.ConvRELU,
    "conv_str": conv.ConvStrictRELU,
    "conv_sigmoid": conv.ConvSigmoid,
    "max_pooling": pooling.MaxPooling,
    "maxabs_pooling": pooling.MaxAbsPooling,
    "avg_pooling": pooling.AvgPooling,
    "stochastic_pooling": pooling.StochasticPooling,
    "norm": normalization.LRNormalizerForward,
    "cutter": cutter.Cutter,
    "dropout": dropout.DropoutForward,
    "activation_tanh": activation.ForwardTanh,
    "activation_relu": activation.ForwardRELU,
    "activation_str": activation.ForwardStrictRELU,
    "activation_sigmoid": activation.ForwardSigmoid,
    "activation_log": activation.ForwardLog,
    "activation_mul": activation.ForwardMul,
    "deconv": deconv.Deconv,
    "deconv_tanh": deconv.DeconvTanh,
    "deconv_relu": deconv.DeconvRELU,
    "deconv_sigmoid": deconv.DeconvSigmoid,
    "depooling": depooling.Depooling,
    "lstm": lstm.LSTM,
    "attention": attention.MultiHeadAttention,
    "to_sequence": seq_reshape.ToSequence,
    "last_token": seq_reshape.LastToken,
    "pos_encoding": pos_encoding.PositionalEncoding,
    "layer_norm": layer_norm.LayerNorm,
    "embedding": embedding.Embedding,
}.items():
    register_layer_type(_name, _cls)


class StandardWorkflow(AcceleratedWorkflow):
    """Declarative training workflow.

    Parameters
    ----------
    loader_factory:
        ``callable(workflow) -> Loader`` building the dataset unit.
    layers:
        list of layer dicts (``{"type", "->", "<-"}``).
    loss:
        ``"softmax"`` (classification) or ``"mse"``.
    decision_config / snapshotter_config:
        kwargs for the Decision / Snapshotter units
        (``snapshotter_config=None`` disables snapshots).
    """

    def __init__(self, workflow=None, name: str | None = None,
                 loader_factory: Callable[["StandardWorkflow"], Loader]
                 | None = None,
                 layers: Sequence[dict] = (),
                 loss: str = "softmax",
                 evaluator_config: dict[str, Any] | None = None,
                 decision_config: dict[str, Any] | None = None,
                 snapshotter_config: dict[str, Any] | None = None,
                 lr_adjuster_config: dict[str, Any] | None = None,
                 anomaly_guard: bool | None = None,
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        if loader_factory is None:
            raise ValueError("loader_factory is required")
        self.layers_config = list(layers)
        self.loss = loss
        # kept for the SDC sentinel's shadow-oracle clone (round 19)
        self._loader_factory = loader_factory
        self._evaluator_config = dict(evaluator_config or {})
        self._decision_config = dict(decision_config or {})

        self.repeater = Repeater(self, name="repeater")
        self.loader = loader_factory(self)
        assert isinstance(self.loader, Loader)
        self.forwards: list[Forward] = []
        self.gds: list = []
        self.anomaly_guard = None
        self.integrity = None  # the round-19 SDC sentinel
        self._pipeline = None  # round-20 pipeline executor (lazy)
        self.link_forwards()
        self.link_evaluator(**(evaluator_config or {}))
        self.link_decision(**(decision_config or {}))
        self.link_gds()
        from znicz_tpu.utils.config import root as _root
        guard_on = (anomaly_guard if anomaly_guard is not None
                    else bool(_root.common.engine.get("anomaly_guard",
                                                      True)))
        if guard_on:
            self.link_anomaly_guard()
        self.link_loop()
        self.snapshotter = None
        self.image_saver = None
        if snapshotter_config is not None:
            self.link_snapshotter(**snapshotter_config)
        self.lr_adjuster = None
        if lr_adjuster_config is None and any(
                "lr_policy" in spec.get("<-", {})
                or "bias_lr_policy" in spec.get("<-", {})
                for spec in self.layers_config):
            lr_adjuster_config = {}  # per-layer policies imply an adjuster
        if lr_adjuster_config is not None:
            self.link_lr_adjuster(**lr_adjuster_config)
        self._region_unit: RegionUnit | None = None

    # ------------------------------------------------------------------
    # builders (reference API surface: link_forwards / link_gds / ...)
    # ------------------------------------------------------------------
    def link_forwards(self) -> None:
        prev = None
        for spec in self.layers_config:
            cls = layer_type(spec["type"])
            cfg = dict(spec.get("->", {}))
            tied = spec.get("tied_to")  # autoencoder decoder layers
            #                             reference the encoder layer
            #                             they invert (MnistAE/
            #                             ImagenetAE topology)
            tied_unit = None
            if tied is not None:
                tied_unit = self.forwards[tied]
                if issubclass(cls, deconv.Deconv):
                    # geometry mirrors the tied conv layer
                    tied_cfg = self.layers_config[tied].get("->", {})
                    for key in ("n_kernels", "kx", "ky", "sliding",
                                "padding"):
                        if key in tied_cfg:
                            cfg.setdefault(key, tied_cfg[key])
            unit = cls(self, **cfg)
            if tied_unit is not None:
                if issubclass(cls, deconv.Deconv):
                    unit.output_shape_source = tied_unit.input
                    if spec.get("tied_weights"):
                        unit.link_attrs(tied_unit, "weights")
                elif issubclass(cls, depooling.Depooling):
                    unit.pooling_unit = tied_unit
                else:
                    raise ValueError(
                        f"layer type '{spec['type']}' does not "
                        f"support tied_to")
            if prev is None:
                unit.link_attrs(self.loader, ("input", "minibatch_data"))
            else:
                unit.link_attrs(prev, ("input", "output"))
            if "forward_mode" in unit.__dict__:  # stochastic units track
                unit.link_attrs(self.loader, "forward_mode",
                                two_way=False)  # the minibatch class
            self.forwards.append(unit)
            prev = unit

    def link_evaluator(self, **config) -> None:
        last = self.forwards[-1]
        if self.loss == "softmax":
            ev = EvaluatorSoftmax(self, name="evaluator", **config)
            ev.link_attrs(last, "output", "max_idx")
            ev.link_attrs(self.loader, ("labels", "minibatch_labels"),
                          "minibatch_valid", "minibatch_class")
        elif self.loss == "mse":
            ev = EvaluatorMSE(self, name="evaluator", **config)
            ev.link_attrs(last, "output")
            ev.link_attrs(self.loader, ("target", "minibatch_data"),
                          "minibatch_valid", "minibatch_class")
        else:
            raise ValueError(f"unknown loss '{self.loss}'")
        self.evaluator = ev

    def link_decision(self, **config) -> None:
        cls = DecisionGD if self.loss == "softmax" else DecisionMSE
        self.decision = cls(self, name="decision", **config)
        self.decision.loader = self.loader
        self.decision.evaluator = self.evaluator

    def link_gds(self) -> None:
        """Build the backward chain via the fwd↔bwd pairing registry
        (reference: MatchingObject-driven ``link_gds``)."""
        self.gds = []
        next_gd = None
        for i, fwd in enumerate(reversed(self.forwards)):
            spec = self.layers_config[len(self.forwards) - 1 - i]
            cls = gd_for(type(fwd))
            gd_kwargs = {k: v for k, v in spec.get("<-", {}).items()
                         if k not in ("lr_policy", "bias_lr_policy")}
            unit = cls(self, need_err_input=(i != len(self.forwards) - 1),
                       **gd_kwargs)
            unit.forward_unit = fwd  # geometry/mask/activation source
            unit.link_attrs(fwd, "input", "output", "weights", "bias")
            if next_gd is None:
                unit.link_attrs(self.evaluator, "err_output")
            else:
                unit.link_attrs(next_gd, ("err_output", "err_input"))
            # train minibatches only (reference: decision.gd_skip)
            unit.gate_skip = Bool._derived(
                lambda: self.loader.minibatch_class != TRAIN)
            self.gds.append(unit)
            next_gd = unit
        self.gds.reverse()

    def link_anomaly_guard(self) -> None:
        """Attach the resilience anomaly guard (round 11): the
        evaluator seeds per-step finite flags, every weighted GD folds
        its gradient check in and gates its update, and the guard unit
        commits the streak/totals state the Decision unit reads (see
        :mod:`znicz_tpu.resilience.guard`).  Gate:
        ``root.common.engine.anomaly_guard`` (default on) or the
        ``anomaly_guard`` constructor argument."""
        from znicz_tpu.resilience.guard import AnomalyGuard
        guard = AnomalyGuard(self, name="anomaly_guard")
        self.anomaly_guard = guard
        self.evaluator.link_attrs(guard, "step_flags", "fault_inject",
                                  two_way=False)
        for gd_unit in self.gds:
            gd_unit.link_attrs(guard, ("anomaly_flag", "step_flags"),
                               two_way=False)
        if guard.sdc_fingerprint is not None:
            # round 19: the SDC fingerprint rides the same region —
            # evaluator zero-seeds it per train step, every weighted
            # GD folds its checksums in, the sentinel reads it at
            # vote/audit cadence (resilience.integrity)
            from znicz_tpu.resilience.integrity import IntegritySentinel
            self.evaluator.link_attrs(guard, "sdc_fingerprint",
                                      two_way=False)
            for gd_unit in self.gds:
                gd_unit.link_attrs(guard, "sdc_fingerprint",
                                   "sdc_inject", two_way=False)
            self.integrity = IntegritySentinel(self)

    def rollback_to_snapshot(self, streak: int) -> bool:
        """Anomaly-streak recovery (called by the Decision unit after
        K consecutive non-finite steps): reload the Snapshotter's last
        good checkpoint through the digest-verified load path and
        resume mid-epoch (the round-10 resume machinery restores the
        loader cursor, PRNG streams and optimizer state).  Returns
        True when a rollback happened.  Without a snapshot the guard
        has still prevented weight poisoning (anomalous updates were
        skipped), so the run continues with a warning."""
        import os as _os

        from znicz_tpu.observe import metrics as _metrics
        from znicz_tpu.utils.snapshotter import Snapshotter
        snap = self.snapshotter
        path = snap.destination if snap is not None else None
        if self.anomaly_guard is not None:
            self.anomaly_guard.reset_streak()
            self.anomaly_guard.reset_sdc_fingerprint()
        if not path or not _os.path.exists(path):
            self.warning(
                "anomaly streak %d with no snapshot to roll back to — "
                "anomalous updates were skipped, continuing as-is",
                streak)
            return False
        state = Snapshotter.load(path)
        self.load_state(state)
        _metrics.anomaly_rollbacks(self.name).inc()
        _metrics.recoveries("rollback").inc()
        self.warning("anomaly streak %d: rolled back to %s and "
                     "resumed", streak, path)
        return True

    def link_loop(self) -> None:
        """Wire the training loop's control flow."""
        self.repeater.link_from(self.start_point)
        self.loader.link_from(self.repeater)
        self.decision.link_from(self._link_hot_chain(self.loader))
        self.repeater.link_from(self.decision)
        self.repeater.gate_block = self.decision.complete
        self.end_point.link_from(self.decision)
        self.end_point.gate_block = ~self.decision.complete

    def _link_hot_chain(self, after):
        """Backend-independent wiring is impossible to decide before
        ``initialize`` (device unknown), so both paths are wired and
        gated: the RegionUnit disables itself on the numpy backend and
        the eager chain is skipped on the XLA backend."""
        # eager oracle chain
        prev = after
        for fwd in self.forwards:
            fwd.link_from(prev)
            prev = fwd
        self.evaluator.link_from(prev)
        prev = self.evaluator
        for gd_unit in reversed(self.gds):
            gd_unit.link_from(prev)
            prev = gd_unit
        if self.anomaly_guard is not None:
            # the guard commits the step's anomaly verdict AFTER the
            # last backward unit (same position it holds in the
            # region's trace order)
            self.anomaly_guard.link_from(prev)
            prev = self.anomaly_guard
        return prev

    def _relink_end_point_last(self) -> None:
        """Keep ``end_point`` the LAST successor of the decision so
        epoch side-chain units (snapshotter, plotters, image saver,
        lr adjuster) still fire on the final epoch before the workflow
        stops (the scheduler drains successors in link order)."""
        if self.decision in self.end_point.links_from:
            self.end_point.unlink_from(self.decision)
            self.end_point.link_from(self.decision)

    def link_lr_adjuster(self, lr_policy=None, bias_lr_policy=None) -> None:
        """Attach a :class:`LearningRateAdjust` over the weighted GD
        units (reference: ``link_lr_adjuster``).  Per-layer overrides
        ride in the layer spec's ``"<-"`` dict as ``lr_policy`` /
        ``bias_lr_policy``; the arguments here are the defaults."""
        from znicz_tpu.ops.nn_units import WeightlessGradientUnit
        adj = LearningRateAdjust(self, name="lr_adjuster")
        adj.loader = self.loader
        for i, gd_unit in enumerate(self.gds):
            if isinstance(gd_unit, WeightlessGradientUnit):
                continue  # no learning-rate state to schedule
            spec = self.layers_config[i].get("<-", {})
            adj.add_gd_unit(
                gd_unit,
                lr_policy=spec.get("lr_policy", lr_policy),
                bias_lr_policy=spec.get("bias_lr_policy", bias_lr_policy))
        adj.link_from(self.decision)
        self._relink_end_point_last()
        self.lr_adjuster = adj

    def link_snapshotter(self, **config) -> None:
        self.snapshotter = Snapshotter(self, name="snapshotter", **config)
        self.snapshotter.decision = self.decision
        self.snapshotter.link_from(self.decision)
        self._relink_end_point_last()
        self.snapshotter.gate_skip = ~self.decision.improved
        # snapshotter rides the loop edge; repeater waits for no one
        # extra (Repeater = any-gate), so no deadlock.

    # -- observability side chains (reference: link_image_saver and the
    # samples' plotter wiring) -----------------------------------------
    def _epoch_side_unit(self, unit) -> None:
        unit.link_from(self.decision)
        self._relink_end_point_last()
        unit.gate_skip = ~self.decision.epoch_ended

    def link_error_plotter(self, server=None):
        """Error-percentage curves per sample class, one point per
        epoch (reference: the AccumulatingPlotter triple every sample
        wired)."""
        from znicz_tpu.loader.base import CLASS_NAME
        from znicz_tpu.plotting_units import AccumulatingPlotter
        p = AccumulatingPlotter(self, name="error_plotter",
                                server=server, ylabel="error %")
        metric = ("epoch_n_err_pt" if self.loss == "softmax"
                  else "epoch_mse")
        for cls in range(3):
            p.add_series(
                CLASS_NAME[cls],
                lambda cls=cls: (getattr(self.decision, metric)[cls]
                                 if self.loader.class_lengths[cls] else None))
        self._epoch_side_unit(p)
        self.error_plotter = p
        return p

    def link_confusion_plotter(self, klass: int = 1, server=None):
        """Validation (or given class) confusion-matrix heatmap; turns
        on the evaluator's device-side confusion accumulation."""
        from znicz_tpu.plotting_units import MatrixPlotter
        if not getattr(self.evaluator, "compute_confusion", False):
            raise ValueError(
                "confusion plotter needs the evaluator built with "
                "compute_confusion=True (pass evaluator_config)")
        p = MatrixPlotter(
            self, name="confusion_matrix", server=server,
            fetch=lambda: self.decision.confusion_matrixes[klass])
        self._epoch_side_unit(p)
        self.confusion_plotter = p
        return p

    def link_weights_plotter(self, layer: int = 0, sample_shape=None,
                             server=None):
        """First-layer filters as a tiled image (reference:
        ``Weights2D``)."""
        from znicz_tpu.ops.nn_plotting_units import Weights2D
        p = Weights2D(self, name=f"weights2d_l{layer}", server=server,
                      sample_shape=sample_shape)
        p.link_attrs(self.forwards[layer], ("input", "weights"),
                     two_way=False)
        self._epoch_side_unit(p)
        self.weights_plotter = p
        return p

    def link_image_saver(self, **config):
        """Dump misclassified samples per epoch (reference:
        ``link_image_saver``); classification workflows only."""
        from znicz_tpu.ops.image_saver import ImageSaver
        if self.loss != "softmax":
            raise ValueError("image saver needs a classification loss")
        s = ImageSaver(self, name="image_saver", **config)
        s.link_attrs(self.loader, ("input", "minibatch_data"),
                     ("labels", "minibatch_labels"),
                     ("indices", "minibatch_indices"),
                     "minibatch_valid", "minibatch_class", "epoch_number",
                     two_way=False)
        s.link_attrs(self.forwards[-1], "max_idx", two_way=False)
        s.link_from(self.decision)  # after the step's compute
        self._relink_end_point_last()
        self.image_saver = s
        return s

    def link_shell(self, **config):
        """Interactive console once per epoch (reference: ``Shell``
        from ``veles/interaction.py``)."""
        from znicz_tpu.interaction import Shell
        s = Shell(self, name="shell", **config)
        self._epoch_side_unit(s)
        self.shell = s
        return s

    def link_weight_publisher(self, **config):
        """Publish the trained forward chain every N epochs into a
        serving handoff directory (round 13 — the training half of the
        continuous train-to-serve loop; a serving process's
        :class:`~znicz_tpu.resilience.publisher.PublicationWatcher`
        picks the bundles up for canary-gated hot swaps).  Config:
        ``directory``, ``prefix``, ``every_n_epochs``."""
        from znicz_tpu.resilience.publisher import WeightPublisher
        p = WeightPublisher(self, name="weight_publisher", **config)
        self._epoch_side_unit(p)
        self.weight_publisher = p
        return p

    def link_publisher(self, **config):
        """Post-training report generation (reference: ``Publisher``
        from ``veles/publishing/``): fires once, when the decision
        raises ``complete``."""
        from znicz_tpu.publishing import Publisher
        p = Publisher(self, name="publisher", **config)
        p.link_from(self.decision)
        self._relink_end_point_last()
        p.gate_skip = ~self.decision.complete
        self.publisher = p
        return p

    def run_chunked(self, steps_per_dispatch: int = 32) -> None:
        """Fast-path training driver: amortize dispatch cost by running
        up to ``steps_per_dispatch`` minibatch steps per device call
        (``JitRegion.run_chunk`` — one ``lax.scan`` program), the
        idiomatic JAX training loop.

        Semantics vs :meth:`run`: identical trajectory — the loader's
        device-resident schedule reproduces the per-step index stream
        bitwise, stochastic units advance their device PRNG chains per
        scanned step, and the evaluator's error counters accumulate on
        device exactly as in per-step mode.  Chunks never cross a
        class-segment or epoch boundary, so decision bookkeeping and
        the epoch side chain (snapshotter, plotters, LR adjuster) fire
        at the same points; an active LR-adjust policy is applied at
        chunk granularity (piecewise-constant within a chunk) rather
        than per step.  Requires the XLA backend + a device-schedule
        loader; falls back to :meth:`run` otherwise.
        """
        region_unit = self._region_unit
        loader = self.loader
        if (region_unit is None or steps_per_dispatch <= 1
                or not loader._on_device_schedule()):
            return self.run()
        per_step = [u for u in self.units
                    if getattr(u, "NEEDS_PER_STEP_MINIBATCHES", False)]
        if per_step:
            # such units consume EVERY minibatch (e.g. ImageSaver's
            # worst-sample dumps); inside a scanned chunk only the
            # last step's data survives
            self.warning("run_chunked: %s need per-step minibatches — "
                         "falling back to per-step run()",
                         [u.name for u in per_step])
            return self.run()
        region = region_unit.region
        assert region is not None
        decision = self.decision
        side_units = [u for u in decision.links_to
                      if u is not self.repeater and u is not self.end_point]
        import time as _time
        self.run_started_at = _time.time()
        self.stopped.value = False
        chunks = 0
        while not decision.complete and not self.stopped:
            loader.run()  # host bookkeeping (+ schedule upload if stale)
            cls = loader.minibatch_class
            k = 1
            while (k < steps_per_dispatch and not loader.epoch_ended
                   and loader._cursor < len(loader._schedule)
                   and loader._schedule[loader._cursor][0] == cls):
                loader.run()
                k += 1
            region.run_chunk(k)
            if self.lr_adjuster is not None and cls == TRAIN:
                # chunk-granular application of the per-step policy
                self.lr_adjuster._n_iterations += k - 1
                self.lr_adjuster.run()
            decision.run()
            if decision.epoch_ended or decision.complete:
                for unit in side_units:
                    if unit is self.lr_adjuster:
                        continue  # handled above
                    if not unit.gate_block and not unit.gate_skip:
                        unit._fire()
            chunks += 1
            if self._max_fires is not None and chunks > self._max_fires:
                raise RuntimeError(
                    f"workflow '{self.name}' exceeded max_fires="
                    f"{self._max_fires} chunks (runaway loop?)")

    def run_accumulated(self, microbatches: int | None = None) -> None:
        """Gradient-accumulation training driver (round 20): every
        optimizer step consumes ``M = engine.grad_accum`` consecutive
        TRAIN minibatches through ONE device program
        (:meth:`JitRegion.run_accum` — a ``lax.scan`` of M−1
        accumulate-only bodies feeding one apply body), so the global
        batch is ``M × minibatch_size`` while per-step activation
        memory stays at one microbatch.

        Semantics: the applied update is bitwise-equal to a fused
        batch of ``M × minibatch_size`` whenever the arithmetic is
        exact (each microbatch gradient is normalized by its own
        minibatch size; the apply body divides the accumulated sum by
        M).  Anomaly verdicts AND across the M microbatches — one NaN
        anywhere skips the whole accumulated step — and the SDC
        fingerprints fold once, at apply.  Eval/validation minibatches
        run unaccumulated through the regular region program.
        """
        region_unit = self._region_unit
        loader = self.loader
        if microbatches is None:
            from znicz_tpu.utils.config import root
            microbatches = int(root.common.engine.get("grad_accum", 1) or 1)
        n_micro = int(microbatches)
        if n_micro <= 1:
            return self.run()
        if region_unit is None or not loader._on_device_schedule():
            raise RuntimeError(
                f"workflow '{self.name}': run_accumulated requires the "
                f"XLA region + a device-schedule loader (accumulation "
                f"is an on-device scan; there is no meaningful host "
                f"fallback)")
        span = loader.max_minibatch_size * n_micro
        n_train = int(loader.class_lengths[TRAIN])
        if n_train % span != 0:
            raise RuntimeError(
                f"workflow '{self.name}': TRAIN set of {n_train} does "
                f"not divide into accumulated steps of "
                f"{loader.max_minibatch_size} × {n_micro} microbatches — "
                f"a ragged tail microbatch would break the fixed "
                f"accumulation program")
        region = region_unit.region
        assert region is not None
        decision = self.decision
        side_units = [u for u in decision.links_to
                      if u is not self.repeater and u is not self.end_point]
        guard = getattr(self, "anomaly_guard", None)
        from znicz_tpu.observe import metrics as _metrics
        _metrics.grad_accum_microbatches(self.name).set(n_micro)
        import time as _time
        self.run_started_at = _time.time()
        self.stopped.value = False
        steps = 0
        while not decision.complete and not self.stopped:
            loader.run()  # host bookkeeping (+ schedule upload if stale)
            cls = loader.minibatch_class
            if cls == TRAIN:
                for _ in range(n_micro - 1):
                    loader.run()  # advance the index stream M−1 more
                if guard is not None:
                    guard.host_run()  # arm fault/SDC injections
                region.run_accum(n_micro)
                if self.lr_adjuster is not None:
                    # ONE optimizer step happened, whatever M is
                    self.lr_adjuster.run()
            else:
                region.run()
            decision.run()
            if decision.epoch_ended or decision.complete:
                for unit in side_units:
                    if unit is self.lr_adjuster:
                        continue  # handled above
                    if not unit.gate_block and not unit.gate_skip:
                        unit._fire()
            steps += 1
            if self._max_fires is not None and steps > self._max_fires:
                raise RuntimeError(
                    f"workflow '{self.name}' exceeded max_fires="
                    f"{self._max_fires} accumulated steps "
                    f"(runaway loop?)")

    def run_pipelined(self, n_stages: int,
                      microbatches: int | None = None,
                      schedule: str = "1f1b") -> None:
        """Pipeline-parallel training driver (round 20): split the
        forward/backward chain into ``n_stages`` contiguous stages and
        drive each TRAIN optimizer step through the
        :class:`~znicz_tpu.parallel.pipeline.PipelineExecutor`'s
        merged 1F1B (or GPipe) schedule over ``M = engine.grad_accum``
        microbatches.  Riding the accumulation phases keeps the
        trained trajectory identical to :meth:`run_accumulated` —
        each stage buffers M−1 microbatch gradients and applies once —
        while per-stage live activations stay at ONE microbatch.
        Eval/validation minibatches run through the unstaged region
        program unchanged.
        """
        from znicz_tpu.parallel.pipeline import PipelineExecutor
        region_unit = self._region_unit
        loader = self.loader
        if microbatches is None:
            from znicz_tpu.utils.config import root
            microbatches = int(root.common.engine.get("grad_accum", 1) or 1)
        n_micro = int(microbatches)
        if region_unit is None or not loader._on_device_schedule():
            raise RuntimeError(
                f"workflow '{self.name}': run_pipelined requires the "
                f"XLA region + a device-schedule loader")
        span = loader.max_minibatch_size * n_micro
        n_train = int(loader.class_lengths[TRAIN])
        if n_train % span != 0:
            raise RuntimeError(
                f"workflow '{self.name}': TRAIN set of {n_train} does "
                f"not divide into pipelined steps of "
                f"{loader.max_minibatch_size} × {n_micro} microbatches")
        executor = self._pipeline
        if (executor is None or executor.n_stages != int(n_stages)
                or executor.n_micro != n_micro
                or executor.schedule_kind != schedule):
            executor = self._pipeline = PipelineExecutor(
                self, n_stages, n_micro, schedule=schedule)
        region = region_unit.region
        assert region is not None
        decision = self.decision
        side_units = [u for u in decision.links_to
                      if u is not self.repeater and u is not self.end_point]
        guard = getattr(self, "anomaly_guard", None)
        import time as _time
        self.run_started_at = _time.time()
        self.stopped.value = False
        steps = 0
        while not decision.complete and not self.stopped:
            loader.run()
            cls = loader.minibatch_class
            if cls == TRAIN:
                for _ in range(n_micro - 1):
                    loader.run()
                if guard is not None:
                    guard.host_run()
                executor.run_step()
                if self.lr_adjuster is not None:
                    self.lr_adjuster.run()
            else:
                region.run()
            decision.run()
            if decision.epoch_ended or decision.complete:
                for unit in side_units:
                    if unit is self.lr_adjuster:
                        continue
                    if not unit.gate_block and not unit.gate_skip:
                        unit._fire()
            steps += 1
            if self._max_fires is not None and steps > self._max_fires:
                raise RuntimeError(
                    f"workflow '{self.name}' exceeded max_fires="
                    f"{self._max_fires} pipelined steps (runaway loop?)")

    def build_shadow(self) -> "StandardWorkflow":
        """A numpy-backend clone for the SDC sentinel's
        redundant-compute audit: same declarative config (identical
        construction order ⇒ identical unit/vector names, so
        ``load_state`` restores the clone leaf-for-leaf), no guard
        (the shadow IS the trusted oracle), no snapshots/side-chains.
        The audit drives it one minibatch at a time after a
        ``load_state`` of the live workflow's pre-step state."""
        from znicz_tpu.backends import NumpyDevice
        shadow = StandardWorkflow(
            name=f"{self.name}_shadow",
            loader_factory=self._loader_factory,
            layers=self.layers_config,
            loss=self.loss,
            evaluator_config=self._evaluator_config,
            decision_config={**self._decision_config,
                             "max_epochs": None,
                             "fail_iterations": 10 ** 9},
            snapshotter_config=None,
            anomaly_guard=False)
        shadow._max_fires = 10 ** 9
        shadow.initialize(device=NumpyDevice())
        return shadow

    def export_forward(self, path: str) -> str:
        """Serialize the trained forward chain for serving
        (reference: ``ForwardExporter``; see
        :mod:`znicz_tpu.export`)."""
        from znicz_tpu.export import export_forward
        return export_forward(self, path)

    def hot_chain_units(self) -> list:
        """The per-minibatch hot chain in trace order — the unit list
        a :class:`~znicz_tpu.accelerated_units.JitRegion` compiles and
        the population engine vmaps (loader gather → forwards →
        evaluator → backwards, anomaly guard last)."""
        members = [self.loader, *self.forwards, self.evaluator,
                   *reversed(self.gds)]
        if self.anomaly_guard is not None:
            members.append(self.anomaly_guard)
        return members

    def promote_lr_leaves(self) -> None:
        """Turn every weighted GD unit's learning rate into a device
        leaf (its ``lr_state`` Vector, the same slot a
        :class:`LearningRateAdjust` schedule uses) holding the
        configured ``[lr, lr_bias]``.  The population engine calls
        this so learning rates become *member-stacked* state — each of
        the K replicas trains (and mutates) its own rate without a
        recompile.  Idempotent; call after ``initialize``.  Finite
        steps are bitwise identical to the baked-constant path (same
        f32 value, same multiply)."""
        for gd_unit in self.gds:
            if gd_unit.weights is None or not gd_unit.weights:
                continue
            if gd_unit.lr_state:
                continue  # already scheduled / promoted
            gd_unit.lr_state.reset(np.asarray(
                [gd_unit.learning_rate, gd_unit.learning_rate_bias],
                dtype=np.float32))
            gd_unit.init_vectors(gd_unit.lr_state)

    # ------------------------------------------------------------------
    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if not isinstance(self.device, NumpyDevice) \
                and self._region_unit is None:
            self._compile_region()

    def _compile_region(self) -> None:
        """Swap the eager hot chain for one jit region (xla backend)."""
        members = self.hot_chain_units()
        guard = self.anomaly_guard
        region = RegionUnit(self, members, name="train_region")
        region.initialize(device=self.device)
        region._initialized = True
        # rewire: loader → [guard host hook] → region → decision (drop
        # the eager chain).  Like the loader, the guard stays in the
        # control graph for its per-step host_run (the fault-inject
        # leaf) while its device compute runs inside the region.
        tail = guard if guard is not None \
            else (self.gds[0] if self.gds else self.evaluator)
        self.decision.unlink_from(tail)
        first_fwd = self.forwards[0]
        first_fwd.unlink_from(self.loader)
        if guard is not None:
            guard.unlink_from(self.gds[0] if self.gds
                              else self.evaluator)
            guard.link_from(self.loader)
            region.link_from(guard)
        else:
            region.link_from(self.loader)
        self.decision.link_from(region)
        self._region_unit = region
