"""Model assembly and the sample zoo (reference:
``znicz/standard_workflow.py`` + ``znicz/samples/``)."""

from znicz_tpu.models.standard_workflow import (  # noqa: F401
    StandardWorkflow,
    register_layer_type,
)
