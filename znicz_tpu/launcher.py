"""Launcher: workflow lifecycle owner + execution-mode select.

Rebuilds the reference's ``veles/launcher.py``.  The reference Launcher
picked standalone / ``--master`` / ``--slave`` mode, owned the Twisted
reactor, spawned the graphics server and drove ``workflow.run``.

TPU-first deltas (SURVEY.md §2.5, §5.8): the master–slave cluster
(Twisted TCP control + ZeroMQ data plane, ``veles/server.py`` /
``veles/client.py``) is replaced by **synchronous SPMD** — every host
runs the same program over a global device mesh and XLA inserts the
gradient all-reduce over ICI/DCN.  So "mode" here means:

- *standalone*: single process, all locally visible devices;
- *distributed*: ``jax.distributed.initialize`` (PJRT multi-host
  bootstrap over DCN) — the ``--listen`` host is process 0
  ("master" in reference terms: it owns snapshots and logging), every
  other host joins with ``--master host:port`` exactly like reference
  slaves did.  There is no job queue: the loader shards minibatches
  over the mesh's ``data`` axis instead
  (``generate_data_for_slave`` → sharding spec).

Failure handling parity (SURVEY.md §5.3): SPMD is gang-scheduled, so
the reference's elastic drop-slave/requeue becomes **checkpoint +
auto-resume**: SIGINT/SIGTERM write an emergency snapshot, and
``retries > 0`` re-enters the run loop resuming from the newest
snapshot.
"""

from __future__ import annotations

import glob
import os
import signal
import traceback
from typing import Any, Callable

from znicz_tpu.backends import Device
from znicz_tpu.utils.config import root
from znicz_tpu.utils.logger import Logger
from znicz_tpu.utils.snapshotter import Snapshotter
from znicz_tpu.workflow import Workflow


class Launcher(Logger):
    """Owns device selection, distributed bootstrap and the run loop.

    The reference sample protocol is preserved: every sample module
    exposes ``run(load, main)``; :meth:`boot` calls it with closures
    bound to this launcher — ``load(factory, **kwargs)`` constructs
    (or resumes) the workflow, ``main(**kwargs)`` initializes and runs
    it.
    """

    def __init__(self, backend: str | None = None,
                 snapshot: str | None = None,
                 listen: str | None = None,
                 master: str | None = None,
                 n_processes: int | None = None,
                 process_id: int | None = None,
                 retries: int = 0,
                 graphics: bool | None = None,
                 web_status: int | None = None,
                 web_status_host: str = "127.0.0.1",
                 load_kwargs: dict | None = None,
                 chunk: int = 1,
                 n_model: int = 1,
                 n_seq: int = 1,
                 **kwargs) -> None:
        super().__init__(**kwargs)
        if snapshot is None:
            # elastic restart contract (round 18): the gang supervisor
            # hands the relaunched workers the newest digest-verified
            # snapshot through the env so the SAME command line resumes
            snapshot = os.environ.get("ZNICZ_RESUME_SNAPSHOT") or None
        #: model-axis size for the global mesh (tensor parallelism over
        #: the distributed device grid; 1 = pure DP)
        self.n_model = int(n_model)
        #: seq-axis size for the global mesh (sequence parallelism —
        #: the ring rides its own third axis on a data×model×seq grid;
        #: 1 = historical 2-D mesh)
        self.n_seq = int(n_seq)
        #: steps per device dispatch (>1 → StandardWorkflow.run_chunked)
        self.chunk = int(chunk)
        self.backend = backend
        self.snapshot = snapshot
        self.retries = int(retries)
        #: extra kwargs merged into every _load(factory, ...) call —
        #: the channel by which embedding drivers (e.g. --optimize
        #: trials) parameterize a sample's build without editing it
        self.load_kwargs = dict(load_kwargs or {})
        self.web_status = web_status  # port (0 = auto) or None = off
        self.web_status_host = web_status_host  # "0.0.0.0" for remote
        self.web_server = None
        self.workflow: Workflow | None = None
        self.device: Device | None = None
        self._snapshot_state: dict | None = None
        self._graphics = graphics
        self._interrupted = False
        self._old_handlers: dict[int, Any] = {}
        #: round 18: in-process elastic supervision (attached by
        #: run_workflow when the heartbeat channel is configured)
        self._worker_supervisor = None
        # distributed mode ------------------------------------------------
        if listen and master:
            raise ValueError("--listen and --master are exclusive")
        self.coordinator = listen or master
        self.process_id = process_id
        self.n_processes = n_processes
        self.is_master = master is None  # standalone or the --listen host
        if not self.coordinator:
            # env bring-up (parallel.distributed contract): export
            # ZNICZ_COORDINATOR / ZNICZ_NUM_PROCESSES /
            # ZNICZ_PROCESS_ID and run the SAME command on every host
            # — the pod-scale path where flags never differ per host
            from znicz_tpu.parallel import distributed
            spec = distributed.env_spec()
            if spec is not None:
                self.coordinator = spec["coordinator_address"]
                self.n_processes = spec.get("num_processes",
                                            self.n_processes)
                if self.process_id is None:
                    self.process_id = spec.get("process_id")
                self.is_master = (self.process_id or 0) == 0
                self._init_distributed(self.is_master)
                return
        if self.coordinator:
            self._init_distributed(listen is not None)

    # ------------------------------------------------------------------
    # modes
    # ------------------------------------------------------------------
    @property
    def mode(self) -> str:
        if not self.coordinator:
            return "standalone"
        return "master" if self.is_master else "slave"

    def _init_distributed(self, is_coordinator: bool) -> None:
        """PJRT multi-host bootstrap (replaces the reference's
        Server/Client handshake; reference: ``veles/server.py``) —
        idempotent, shared with bench.py via
        ``parallel.distributed.ensure_initialized``."""
        import jax

        from znicz_tpu.parallel import distributed
        process_id = self.process_id
        if process_id is None and is_coordinator:
            process_id = 0
        self.info("distributed init (%s) @ %s",
                  "coordinator" if is_coordinator else "worker",
                  self.coordinator)
        distributed.ensure_initialized(
            coordinator=self.coordinator,
            num_processes=self.n_processes,
            process_id=process_id)
        self.is_master = jax.process_index() == 0

    # ------------------------------------------------------------------
    # device
    # ------------------------------------------------------------------
    def make_device(self) -> Device:
        if self.device is None:
            if self.coordinator and self.backend == "numpy":
                raise ValueError(
                    "distributed mode requires an XLA backend — the "
                    "host-only numpy oracle cannot join a device mesh "
                    "(each process would silently train an independent "
                    "replica)")
            if not self.coordinator and (self.n_model > 1
                                         or self.n_seq > 1):
                raise ValueError(
                    f"n_model={self.n_model}/n_seq={self.n_seq} "
                    f"requires distributed mode (--listen/--master or "
                    f"the ZNICZ_* env builds the global mesh); a "
                    f"standalone run would silently ignore it")
            if self.coordinator:
                # Distributed mode: SPMD over the GLOBAL mesh (all
                # hosts' devices, data × model[, seq]); XLA lays the
                # gradient all-reduce over ICI/DCN.  This is the whole
                # point of the bootstrap — a local-only device would
                # silently train per-host replicas.
                from znicz_tpu.backends import XLADevice
                from znicz_tpu.parallel import make_mesh
                self.device = XLADevice(
                    mesh=make_mesh(n_model=self.n_model,
                                   n_seq=self.n_seq))
            else:
                self.device = Device.create(self.backend)
        return self.device

    # ------------------------------------------------------------------
    # reference sample protocol: run(load, main)
    # ------------------------------------------------------------------
    def boot(self, run_fn: Callable) -> Workflow:
        """Drive a sample module's ``run(load, main)``."""
        run_fn(self._load, self._main)
        if self.workflow is None:
            raise RuntimeError(
                "run(load, main) never called load(factory, ...)")
        return self.workflow

    def _load(self, factory: Callable[..., Workflow], **kwargs):
        """Construct the workflow; stage snapshot state when resuming.

        Returns ``(workflow, snapshot_was_loaded)`` like the reference
        ``Main._load``.
        """
        merged = dict(self.load_kwargs)
        merged.update(kwargs)
        self.workflow = factory(**merged)
        loaded = False
        if self.snapshot:
            self._snapshot_state = Snapshotter.load(self.snapshot)
            loaded = True
            self.info("staged snapshot %s", self.snapshot)
        return self.workflow, loaded

    def _main(self, **kwargs) -> None:
        wf = self.workflow
        if wf is None:
            raise RuntimeError("main() called before load()")
        attempt = 0
        while True:
            try:
                self.run_workflow(wf, **kwargs)
                return
            except KeyboardInterrupt:
                raise
            except Exception:
                attempt += 1
                if attempt > self.retries:
                    raise
                latest = self.latest_snapshot(wf)
                self.warning("workflow crashed (attempt %d/%d):\n%s",
                             attempt, self.retries,
                             traceback.format_exc())
                if latest:
                    self.info("auto-resume from %s", latest)
                    self._snapshot_state = Snapshotter.load(latest)

    # ------------------------------------------------------------------
    def run_workflow(self, workflow: Workflow, **kwargs) -> Workflow:
        """initialize → (resume state) → run, with signal-safe
        emergency snapshots."""
        if self._graphics is not None:
            # reference Launcher owned the graphics server spawn; here
            # the render thread starts lazily on first plotter use —
            # the flag force-disables (or pre-warms) it
            root.common.graphics.render = bool(self._graphics)
            if self._graphics:
                from znicz_tpu import graphics
                graphics.get_server()
        if self.web_status is not None and self.web_server is None \
                and self.is_master:
            from znicz_tpu.web_status import WebStatusServer
            self.web_server = WebStatusServer(
                port=self.web_status, host=self.web_status_host)
        if self.web_server is not None:
            self.web_server.register(workflow)
        device = self.make_device()
        if not workflow.is_initialized:
            workflow.initialize(device=device, **kwargs)
        if self._snapshot_state is not None:
            workflow.load_state(self._snapshot_state)
            self._snapshot_state = None
        # round 18: elastic supervision — ZNICZ_HEARTBEAT_DIR (or
        # engine.heartbeat_dir) attaches the per-process heartbeat
        # writer, the preemption handler (SIGTERM → barriered
        # checkpoint-on-signal at the next step boundary) and the
        # collective-hang self-watchdog; process 0 additionally feeds
        # the peer-age gauges /metrics + /readyz expose
        from znicz_tpu.resilience import supervisor as _supervisor
        sup_cfg = _supervisor.worker_config()
        if sup_cfg is not None and self._worker_supervisor is None:
            self._worker_supervisor = _supervisor.WorkerSupervisor(
                workflow, is_master=self.is_master, **sup_cfg)
            self._worker_supervisor.attach()
        self._install_signal_handlers(workflow)
        try:
            if self.chunk > 1 and hasattr(workflow, "run_chunked"):
                workflow.run_chunked(self.chunk)
            else:
                workflow.run()
        except KeyboardInterrupt:
            self._emergency_snapshot(workflow)
            raise
        finally:
            self._restore_signal_handlers()
            if self._worker_supervisor is not None:
                self._worker_supervisor.detach()
                self._worker_supervisor = None
        return workflow

    # ------------------------------------------------------------------
    # failure handling (SURVEY.md §5.3 parity)
    # ------------------------------------------------------------------
    def _install_signal_handlers(self, workflow: Workflow) -> None:
        def handler(signum, frame):
            if self._interrupted:  # second signal: hard exit
                raise KeyboardInterrupt
            supervisor = self._worker_supervisor
            if supervisor is not None and signum == signal.SIGTERM:
                # round 18 preemption path: defer to the NEXT step
                # boundary — the whole gang checkpoints at the same
                # barrier step (master writes, others fence on the
                # sidecar) and exits EXIT_PREEMPTED, losing at most
                # the one in-flight step.  Signal-safe: one flag file.
                self._interrupted = True
                supervisor.request_preempt(f"signal {signum}")
                return
            self._interrupted = True
            self.warning("signal %d: emergency snapshot + stop", signum)
            self._emergency_snapshot(workflow)
            workflow.stop()

        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                self._old_handlers[sig] = signal.signal(sig, handler)
            except ValueError:  # not the main thread (tests)
                pass

    def _restore_signal_handlers(self) -> None:
        for sig, old in self._old_handlers.items():
            try:
                signal.signal(sig, old)
            except ValueError:
                pass
        self._old_handlers.clear()
        self._interrupted = False

    def _emergency_snapshot(self, workflow: Workflow) -> str | None:
        if not self.is_master:  # reference: master owns snapshots
            return None
        try:
            path = Snapshotter.write(
                workflow.state_dict(), str(root.common.dirs.snapshots),
                workflow.name, "interrupted")
            self.info("emergency snapshot → %s", path)
            return path
        except Exception:  # pragma: no cover - best effort on the way out
            self.exception("emergency snapshot failed")
            return None

    def latest_snapshot(self, workflow: Workflow) -> str | None:
        """Newest snapshot belonging to THIS workflow (for auto-resume).

        The snapshots directory is shared between samples, so only
        files matching the workflow's snapshotter prefix (or the
        workflow name for emergency snapshots) are candidates."""
        snap = getattr(workflow, "snapshotter", None)
        if snap is not None and snap.destination:
            return snap.destination
        # glob fallback: search the workflow's own snapshot directory
        # (it may differ from the global default) plus the default
        directory = str(root.common.dirs.snapshots)
        directories = {directory}
        prefixes = {workflow.name}
        if snap is not None:
            prefixes.add(snap.prefix)
            directories.add(snap.directory)
        files: list[str] = []
        for d in directories:
            for prefix in prefixes:
                files += glob.glob(
                    os.path.join(d, f"{prefix}_*.pickle.gz"))
        files.sort(key=os.path.getmtime)
        return files[-1] if files else None

    # ------------------------------------------------------------------
    def stop(self) -> None:
        if self.workflow is not None:
            self.workflow.stop()
