"""Image loaders: directory datasets, decode + augment, streaming or
full-batch.

Rebuilds the reference's image-loading stack (reference:
``veles/loader/image.py``, ``file_image.py``, ``fullbatch_image.py`` —
``ImageLoader``/``FileImageLoader``/``FullBatchImageLoader`` with
decode+resize+crop, scale and color options, mean subtraction).

TPU-first design: the decode/augment path is the **native C++ worker
pool** (:mod:`znicz_tpu.native` — libjpeg/libpng + bilinear resize +
crop/flip + affine normalize, SURVEY.md §7 "input pipeline at 8k
img/s"), double-buffered so batch N+1 decodes on host CPU while the
TPU computes batch N.  PIL is the fallback when the toolchain is
unavailable.  Two consumption modes:

- :class:`ImageLoader` / :class:`FileImageLoader` — *streaming*: files
  decode per minibatch straight into the loader's pinned host buffer;
  the jit region uploads it with the step.  Scales to datasets that
  don't fit in HBM (ImageNet).
- :class:`FullBatchImageLoader` — decode everything once into the
  device-resident full-batch store; minibatch assembly stays an
  on-device gather (small datasets: MNIST-scale).
"""

from __future__ import annotations

import os
import time

import numpy as np

from znicz_tpu.loader.base import Loader, TEST, TRAIN, VALID
from znicz_tpu.loader.fullbatch import FullBatchLoader
from znicz_tpu.memory import Vector
from znicz_tpu.observe import metrics as _metrics
from znicz_tpu.observe import tracing as _tracing

IMAGE_EXTENSIONS = (".jpg", ".jpeg", ".png")


def scan_directory(directory: str,
                   label_map: dict[str, int] | None = None
                   ) -> tuple[list[str], list[int], dict[str, int]]:
    """Class-per-subdirectory scan (reference: FileImageLoader's
    directory walk).  Returns (paths, labels, label_map); flat
    directories (no subdirs) get label 0 and do NOT claim label-map
    authority — a later split with class subdirs may still build the
    map."""
    subdirs = sorted(
        d for d in os.listdir(directory)
        if os.path.isdir(os.path.join(directory, d)))
    paths: list[str] = []
    labels: list[int] = []
    if not subdirs:
        files = sorted(
            f for f in os.listdir(directory)
            if f.lower().endswith(IMAGE_EXTENSIONS))
        for f in files:
            paths.append(os.path.join(directory, f))
            labels.append(0)
        return paths, labels, label_map
    if label_map is None:
        label_map = {d: i for i, d in enumerate(subdirs)}
    for d in subdirs:
        if d not in label_map:
            raise ValueError(f"class dir '{d}' missing from label map")
        full = os.path.join(directory, d)
        for f in sorted(os.listdir(full)):
            if f.lower().endswith(IMAGE_EXTENSIONS):
                paths.append(os.path.join(full, f))
                labels.append(label_map[d])
    return paths, labels, label_map


def carve_validation(paths: list[str], labels: list[int],
                     fraction: float, rnd
                     ) -> tuple[tuple[list[str], list[int]],
                                tuple[list[str], list[int]]]:
    """Split a train file list into (valid, train) via a seeded
    permutation — the shared carve policy of both image loaders."""
    n_valid = int(len(paths) * fraction)
    perm = rnd.permutation(len(paths))
    v_idx, t_idx = perm[:n_valid], perm[n_valid:]
    return (([paths[i] for i in v_idx], [labels[i] for i in v_idx]),
            ([paths[i] for i in t_idx], [labels[i] for i in t_idx]))


def _decode_pil(path: str, out_hw: tuple[int, int],
                resize_hw: tuple[int, int] | None, channels: int,
                random_crop: bool, random_flip: bool,
                scale: float, bias: float,
                rng: np.random.Generator) -> np.ndarray:
    """Python fallback matching the native pipeline's semantics
    (bilinear resize → crop → optional flip → affine)."""
    from PIL import Image

    out_h, out_w = out_hw
    blank_shape = (out_h, out_w) if channels == 1 else (out_h, out_w, 3)
    try:
        img = Image.open(path).convert("RGB")
    except Exception:
        # corrupt/unreadable file: zero-fill, matching the native
        # path's failed-decode semantics
        return np.zeros(blank_shape, dtype=np.float32)
    if resize_hw is not None:
        rh, rw = resize_hw
        img = img.resize((rw, rh), Image.BILINEAR)
    arr = np.asarray(img, dtype=np.float32)
    max_dy = arr.shape[0] - out_h
    max_dx = arr.shape[1] - out_w
    if max_dy < 0 or max_dx < 0:
        return np.zeros(blank_shape, dtype=np.float32)
    if random_crop:
        dy = int(rng.integers(0, max_dy + 1))
        dx = int(rng.integers(0, max_dx + 1))
    else:
        dy, dx = max_dy // 2, max_dx // 2
    arr = arr[dy:dy + out_h, dx:dx + out_w]
    if random_flip and rng.integers(0, 2):
        arr = arr[:, ::-1]
    if channels == 1:
        arr = (0.299 * arr[..., 0] + 0.587 * arr[..., 1]
               + 0.114 * arr[..., 2])
    return arr * scale + bias


class ImageLoader(Loader):
    """Streaming minibatch image loader.

    Subclasses (or callers of :class:`FileImageLoader`) provide
    ``file_paths`` (global-index-aligned: test, validation, train) and
    ``file_labels``.  Each step decodes the scheduled files into
    ``minibatch_data`` (NHWC, or NHW when ``grayscale``; raw uint8
    pixels upload and the affine normalize runs on-device into the
    activation-storage dtype);
    train minibatches optionally get random-crop/flip augmentation
    (reference's scale/crop options) while eval gets center crops.

    ``prefetch=True`` double-buffers: while the device chews step N,
    the native pool decodes step N+1 into the OTHER staging buffer and
    the Vector rebinds to whichever buffer holds the current batch —
    a zero-copy handoff (the old design memcpy'd the spare buffer into
    the Vector each step: ~10 ms/step of pure host overhead at
    ImageNet batch 256 on one core, measured in
    ``benchmarks/stream_probe.py``).
    """

    def __init__(self, workflow, name: str | None = None,
                 out_hw: tuple[int, int] = (227, 227),
                 resize_hw: tuple[int, int] | None = (256, 256),
                 grayscale: bool = False,
                 random_crop: bool = True,
                 random_flip: bool = True,
                 normalization_scale: float = 1.0 / 127.5,
                 normalization_bias: float = -1.0,
                 n_threads: int = 0,
                 prefetch: bool = True,
                 use_native: bool | None = None,
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.out_hw = tuple(out_hw)
        self.resize_hw = None if resize_hw is None else tuple(resize_hw)
        self.grayscale = bool(grayscale)
        self.random_crop = bool(random_crop)
        self.random_flip = bool(random_flip)
        self.normalization_scale = float(normalization_scale)
        self.normalization_bias = float(normalization_bias)
        self.n_threads = n_threads
        self.prefetch = bool(prefetch)
        self.use_native = use_native
        self.file_paths: list[str] = []
        self.file_labels: list[int] = []
        #: raw uint8 host staging buffer — decoded pixels upload
        #: un-normalized (4× smaller host→device transfer); the affine
        #: normalize runs on-device in xla_run
        self.minibatch_raw = Vector(name=f"{self.name}.minibatch_raw",
                                    batch_major=True)
        self._pipe = None
        #: two staging buffers: the decode pool fills one while the
        #: device consumes the other; the Vector rebinds per step
        self._buffers: list[np.ndarray] | None = None
        self._decode_buf = 0                    # buffer being decoded
        self._pending: tuple[int, int] | None = None  # (epoch, cursor)
        self._pil_rng = np.random.default_rng(1)
        #: overlap telemetry: hits = steps served by a prefetched
        #: decode, misses = synchronous decodes (now only the first
        #: step and schedule jumps — the counter-based shuffle lets
        #: the decode pool run ahead across epoch boundaries too),
        #: wait_s = total time blocked on in-flight decodes.  wait_s
        #: ≈ 0 with hits > 0 means the decode fully overlapped the
        #: consumer's compute window.  Mirrored into the round-9
        #: metrics registry (``znicz_loader_prefetch_total``,
        #: ``znicz_input_wait_seconds``) so loader overlap shows on
        #: ``/metrics`` and in ``trace_top.py --spans`` beside
        #: everything else.
        self.prefetch_hits = 0
        self.prefetch_misses = 0
        self.prefetch_wait_s = 0.0
        self.epoch_cross_prefetches = 0

    # subclasses fill file_paths/file_labels/class_lengths here
    def load_data(self) -> None:
        if not self.file_paths:
            raise ValueError(f"{self}: no file paths provided")
        if len(self.file_paths) != len(self.file_labels):
            raise ValueError(f"{self}: paths/labels length mismatch")

    @property
    def channels(self) -> int:
        return 1 if self.grayscale else 3

    @property
    def sample_shape(self) -> tuple[int, ...]:
        h, w = self.out_hw
        return (h, w) if self.grayscale else (h, w, 3)

    # minibatch_raw is a transient staging buffer like the rest
    SNAPSHOT_EXCLUDE = Loader.SNAPSHOT_EXCLUDE + ("minibatch_raw",)

    def create_minibatch_data(self) -> None:
        shape = (self.max_minibatch_size,) + self.sample_shape
        self.minibatch_raw.reset(np.zeros(shape, dtype=np.uint8))
        self.minibatch_data.reset(np.zeros(shape,
                                           dtype=self.act_store_dtype))
        self.minibatch_labels.reset(
            np.zeros(self.max_minibatch_size, dtype=np.int32))

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        self.init_vectors(self.minibatch_raw)
        use_native = self.use_native
        if use_native is None:
            from znicz_tpu.native import ImagePipeline
            use_native = ImagePipeline.available()
        if use_native:
            # with use_native=True and no toolchain, this constructor
            # raises carrying the build error
            from znicz_tpu.native import ImagePipeline
            self._pipe = ImagePipeline(self.n_threads)
            # buffer 0 reuses minibatch_raw's own allocation (a third
            # full-size array would be waste); prefetch adds buffer 1
            self._buffers = [self.minibatch_raw.mem]
            if self.prefetch:
                self._buffers.append(
                    np.zeros_like(self.minibatch_raw.mem))
            self._decode_buf = 0
            self._pending = None
        else:
            self._pipe = None
        self.prefetch_hits = 0
        self.prefetch_misses = 0
        self.prefetch_wait_s = 0.0
        self.epoch_cross_prefetches = 0
        if _metrics.enabled():
            # decode double-buffering = one batch in flight
            _metrics.prefetch_depth(self.name).set(
                1 if (self.prefetch and self._pipe is not None) else 0)
        self._pil_rng = np.random.default_rng(
            self.rnd.randint(0, 2 ** 31))

    def stop(self) -> None:
        if self._pipe is not None:
            self._pipe.close()
            self._pipe = None
        super().stop()

    # -- decode machinery ----------------------------------------------
    def _augment_flags(self, minibatch_class: int) -> tuple[bool, bool]:
        train = minibatch_class == TRAIN
        return (self.random_crop and train, self.random_flip and train)

    def _submit(self, idx: np.ndarray, minibatch_class: int,
                out: np.ndarray, seed: int) -> None:
        crop, flip = self._augment_flags(minibatch_class)
        paths = [self.file_paths[i] for i in idx]
        self._pipe.submit(
            paths, out, out_hw=self.out_hw, resize_hw=self.resize_hw,
            channels=self.channels, random_crop=crop, random_flip=flip,
            seed=seed)  # raw uint8 out; normalize runs on-device

    def _decode_sync(self, idx: np.ndarray, minibatch_class: int,
                     out: np.ndarray, seed: int) -> None:
        if self._pipe is not None:
            self._submit(idx, minibatch_class, out, seed)
            n_failed = self._pipe.wait()
            if n_failed:
                self.warning("%d failed decodes (zero-filled)", n_failed)
            return
        crop, flip = self._augment_flags(minibatch_class)
        for row, i in enumerate(idx):
            out[row] = np.rint(_decode_pil(
                self.file_paths[i], self.out_hw, self.resize_hw,
                self.channels, crop, flip, 1.0, 0.0,
                self._pil_rng)).astype(np.uint8)

    def _peek_next(self) -> tuple[np.ndarray, int, tuple[int, int]]:
        """Indices, class and ``(epoch, cursor)`` of the NEXT schedule
        entry — including across the epoch boundary: the counter-based
        shuffle (``Loader.schedule_entry``) fixes the next epoch's
        order before it starts, so the old stale-order bail-out (the
        one guaranteed decode stall per epoch) is gone; each crossing
        the prefetch serves is a recovered stall, counted in
        ``epoch_cross_prefetches``."""
        if self._cursor < len(self._schedule):
            pos = (self.epoch_number, self._cursor)
        else:
            pos = (self.epoch_number + 1, 0)
        idx, cls, _count = self.schedule_entry(*pos)
        return idx, cls, pos

    def _decode_seed(self, epoch: int, cursor: int) -> int:
        return (int(self._seed_base) + epoch * 1_000_003 + cursor) \
            & (2 ** 63 - 1)

    def host_run(self) -> None:
        if not hasattr(self, "_seed_base"):
            self._seed_base = self.rnd.randint(0, 2 ** 31)
        super().host_run()  # picks indices, epoch bookkeeping
        idx = self._host_indices
        cur = (self.epoch_number, self._cursor - 1)
        if self._pipe is not None:
            if self.prefetch and self._pending == cur:
                with _tracing.TRACER.span(f"input_wait:{self.name}",
                                          cat="loader"):
                    t0 = time.perf_counter()
                    n_failed = self._pipe.wait()
                    waited = time.perf_counter() - t0
                self.prefetch_wait_s += waited
                self.prefetch_hits += 1
                crossed = cur[1] == 0 and cur[0] > 0
                if crossed:
                    self.epoch_cross_prefetches += 1
                if _metrics.enabled():
                    _metrics.input_wait_seconds(self.name).observe(
                        waited)
                    _metrics.loader_prefetch(self.name, "hit").inc()
                    if crossed:
                        _metrics.loader_prefetch(
                            self.name, "epoch_cross").inc()
                if n_failed:
                    self.warning("%d failed decodes (zero-filled)",
                                 n_failed)
            else:
                self.prefetch_misses += 1
                if self._pending is not None:
                    # a stale prefetch is in flight (schedule jumped:
                    # resume/reshuffle) — drain it before resubmitting
                    self._pipe.wait()
                t0 = time.perf_counter()
                self._decode_sync(idx, self.minibatch_class,
                                  self._buffers[self._decode_buf],
                                  self._decode_seed(*cur))
                if _metrics.enabled():
                    # a synchronous decode is 100% un-hidden input time
                    _metrics.input_wait_seconds(self.name).observe(
                        time.perf_counter() - t0)
                    _metrics.loader_prefetch(self.name, "miss").inc()
            # zero-copy handoff: rebind the Vector to the filled
            # buffer; the pool decodes the NEXT batch into the other
            filled = self._decode_buf
            self.minibatch_raw.mem = self._buffers[filled]
            self._pending = None
            # queue next step's decode BEFORE the upload below: the
            # C++ workers chew N+1 while device_put streams batch N
            # and the device computes it
            if self.prefetch:
                nidx, ncls, pos = self._peek_next()
                self._decode_buf = 1 - filled
                self._submit(nidx, ncls,
                             self._buffers[self._decode_buf],
                             self._decode_seed(*pos))
                self._pending = pos
        else:
            self.minibatch_raw.map_invalidate()
            self._decode_sync(idx, self.minibatch_class,
                              self.minibatch_raw.mem,
                              self._decode_seed(*cur))
        # labels ride host-side (global label table lookup)
        self.minibatch_labels.map_invalidate()
        self.minibatch_labels.mem[...] = np.asarray(
            [self.file_labels[i] for i in idx], dtype=np.int32)
        if self.device is not None and not self.device.is_host_only:
            self.minibatch_raw.unmap()
            self.minibatch_labels.unmap()

    # raw uint8 pixels are staged host-side and uploaded by host_run's
    # unmap; the device path applies the affine normalize (fused into
    # the jit region, writing the activation-storage dtype)
    def numpy_run(self) -> None:
        self.minibatch_raw.map_read()
        self.minibatch_data.map_invalidate()
        self.minibatch_data.mem[...] = (
            self.minibatch_raw.mem.astype(np.float32)
            * np.float32(self.normalization_scale)
            + np.float32(self.normalization_bias))

    def xla_run(self) -> None:
        import jax.numpy as jnp
        self.minibatch_data.devmem = (
            self.minibatch_raw.devmem.astype(jnp.float32)
            * self.normalization_scale + self.normalization_bias)


class FileImageLoader(ImageLoader):
    """Directory-tree image dataset: one directory per split, one
    subdirectory per class (reference: ``FileImageLoader``).

    ``validation_fraction`` carves a validation split off the train
    directory when no explicit validation directory exists."""

    def __init__(self, workflow,
                 train_dir: str,
                 valid_dir: str | None = None,
                 test_dir: str | None = None,
                 validation_fraction: float = 0.0,
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.train_dir = train_dir
        self.valid_dir = valid_dir
        self.test_dir = test_dir
        self.validation_fraction = float(validation_fraction)

    def load_data(self) -> None:
        train_paths, train_labels, label_map = \
            scan_directory(self.train_dir)
        splits: dict[int, tuple[list[str], list[int]]] = {
            TRAIN: (train_paths, train_labels), VALID: ([], []),
            TEST: ([], [])}
        if self.valid_dir is not None:
            # thread the map through: a flat train dir leaves it None
            # and the first classed split builds the authority
            vp, vl, label_map = scan_directory(self.valid_dir, label_map)
            splits[VALID] = (vp, vl)
        elif self.validation_fraction > 0:
            splits[VALID], splits[TRAIN] = carve_validation(
                train_paths, train_labels, self.validation_fraction,
                self.rnd)
        if self.test_dir is not None:
            tp, tl, label_map = scan_directory(self.test_dir, label_map)
            splits[TEST] = (tp, tl)
        self.label_map = label_map or {}
        self.file_paths = []
        self.file_labels = []
        for cls in (TEST, VALID, TRAIN):  # global index order
            p, l = splits[cls]
            self.class_lengths[cls] = len(p)
            self.file_paths += p
            self.file_labels += l
        super().load_data()


class FullBatchImageLoader(FullBatchLoader):
    """Decode the whole image dataset once (native pool, center crops,
    no augmentation) into the device-resident full-batch store
    (reference: ``FullBatchImageLoader`` — dataset as one ``Vector``,
    minibatch = on-device gather)."""

    def __init__(self, workflow,
                 train_dir: str,
                 valid_dir: str | None = None,
                 test_dir: str | None = None,
                 validation_fraction: float = 0.0,
                 out_hw: tuple[int, int] = (32, 32),
                 resize_hw: tuple[int, int] | None = None,
                 grayscale: bool = False,
                 n_threads: int = 0,
                 use_native: bool | None = None,
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.train_dir = train_dir
        self.valid_dir = valid_dir
        self.test_dir = test_dir
        self.validation_fraction = float(validation_fraction)
        self.out_hw = tuple(out_hw)
        self.resize_hw = None if resize_hw is None else tuple(resize_hw)
        self.grayscale = bool(grayscale)
        self.n_threads = n_threads
        self.use_native = use_native

    def load_data(self) -> None:
        label_map: dict[str, int] | None = None
        dirs = {TEST: self.test_dir, VALID: self.valid_dir,
                TRAIN: self.train_dir}
        splits: dict[int, tuple[list[str], list[int]]] = {}
        # train dir owns label-map authority (same rule as
        # FileImageLoader); eval dirs must conform to it
        for cls in (TRAIN, VALID, TEST):
            d = dirs[cls]
            if d is None:
                splits[cls] = ([], [])
                continue
            p, l, label_map = scan_directory(d, label_map)
            splits[cls] = (p, l)
        if self.valid_dir is None and self.validation_fraction > 0:
            tp, tl = splits[TRAIN]
            splits[VALID], splits[TRAIN] = carve_validation(
                tp, tl, self.validation_fraction, self.rnd)
        paths: list[str] = []
        labels: list[int] = []
        for cls in (TEST, VALID, TRAIN):  # global index order
            p, l = splits[cls]
            self.class_lengths[cls] = len(p)
            paths += p
            labels += l
        if not paths:
            raise ValueError(f"{self}: no images found")
        h, w = self.out_hw
        channels = 1 if self.grayscale else 3
        shape = (len(paths), h, w) if self.grayscale \
            else (len(paths), h, w, 3)
        data = np.zeros(shape, dtype=np.float32)
        use_native = self.use_native
        if use_native is None:
            from znicz_tpu.native import ImagePipeline
            use_native = ImagePipeline.available()
        if use_native:
            from znicz_tpu.native import ImagePipeline
            pipe = ImagePipeline(self.n_threads)
            pipe.submit(paths, data, out_hw=self.out_hw,
                        resize_hw=self.resize_hw, channels=channels)
            n_failed = pipe.wait()
            if n_failed:
                self.warning("%d failed decodes (zero-filled)",
                             n_failed)
            pipe.close()
        else:
            rng = np.random.default_rng(0)
            for i, p in enumerate(paths):
                data[i] = _decode_pil(
                    p, self.out_hw, self.resize_hw, channels,
                    False, False, 1.0, 0.0, rng)
        self.original_data.reset(data)
        self.original_labels.reset(np.asarray(labels, dtype=np.int32))


#: re-exported symbol parity with the reference's loader modules
__all__ = ["ImageLoader", "FileImageLoader", "FullBatchImageLoader",
           "scan_directory", "IMAGE_EXTENSIONS"]
