"""Streaming data plane: sharded async prefetch that hides the input
pipeline under the step.

The third way between the reference's synchronous per-minibatch reads
and this repo's device-resident :class:`FullBatchLoader` (which caps
every workload at device memory — BENCH r03's 13.4k img/s/chip came
precisely from making inputs resident):
:class:`StreamingLoader` reads per-host file shards through a
background pipeline into a bounded ring of host staging buffers
(:class:`znicz_tpu.memory.StagingRing`), uploads them ahead of the
consumer with ``device_put`` prefetch (``prefetch_depth`` batches in
flight), and delivers each step's batch as a pointer swap
(:meth:`Vector.accept_device`) — so a training step's input cost is
the *wait* for an already-issued transfer, ≈ 0 when the pipeline keeps
up.  Host memory is pinned at ``ring_slots × batch_bytes`` no matter
how large the dataset is.

Three design decisions carry the whole plane:

1. **Counter-based shuffling** (:func:`znicz_tpu.loader.base.
   epoch_permutation`): epoch *e*'s order is a pure function of
   ``(shuffle_seed, e)``, so (a) the producer legally prefetches
   ACROSS epoch boundaries (no stale-order hazard — the order of an
   epoch that has not started yet is already decided), (b) every
   process of a multi-host run derives the same global order from the
   shared seed and reads only its ``1/N`` row slice of every
   minibatch (:meth:`StreamingLoader.local_indices` — together the
   slices partition the epoch exactly), and (c) a streamed epoch
   reproduces the :class:`FullBatchLoader` shuffled order
   **bit-for-bit** for the same seed (both derive from the same
   function; ``tests/test_streaming_loader.py`` pins it).

2. **Pipelined, not batched**: reader pool (shard gather into a ring
   slot) → uploader thread (``device_put`` + release the slot) →
   bounded device queue (depth = ``prefetch_depth``) → consumer.  Each
   stage overlaps the others and the device step; the bounded queues
   are the backpressure.

3. **Static signatures**: the staged batch rides in the dataset's raw
   dtype (uint8 images upload 4× smaller) and the affine normalize
   runs on-device inside the jit region (:meth:`xla_run`); shapes,
   dtypes and shardings are identical every step, so a warmed train
   loop adds ZERO XLA compiles (``tests/test_retrace_guard.py``).

Telemetry (round-9 registry): ``znicz_input_wait_seconds`` (consumer
block — ≈ 0 when hidden), ``znicz_input_stage_seconds`` (producer
cost — the work being hidden), ``znicz_prefetch_depth``,
``znicz_loader_prefetch_total{event=hit|miss|epoch_cross}``, and
uploads count into ``znicz_device_transfer_bytes_total{h2d}`` like
every other transfer.  ``input_hidden = 1 − wait_sum/stage_sum`` is
the overlap attestation ``stream_bench`` and the multichip dryrun
report.

On-disk format (:func:`write_shards`): a directory of ``.npy`` shard
files plus ``manifest.json`` (class lengths, sample shape, dtype).
Samples are stored in global-index order (test, validation, train) —
the same convention as the full-batch loaders — and read back through
``numpy`` memory maps, so a "read" is page-cache traffic in a reader
thread, never a resident copy.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
import zlib
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

import numpy as np

from znicz_tpu.loader.base import Loader
from znicz_tpu.memory import StagingRing, Vector
from znicz_tpu.observe import metrics as _metrics
from znicz_tpu.observe import tracing as _tracing
from znicz_tpu.resilience import faults as _faults
from znicz_tpu.utils.config import root

MANIFEST_NAME = "manifest.json"


class ShardReadError(RuntimeError):
    """A shard read failed (CRC mismatch, IO error, injected fault).
    Carries the shard index so the retry path can quarantine a
    persistently bad shard and continue the epoch."""

    def __init__(self, shard: int | None, msg: str) -> None:
        super().__init__(msg)
        self.shard = shard


class PipelineDead(RuntimeError):
    """The streaming pipeline's producer or uploader thread died.
    Raised in the CONSUMER (propagated through the bounded device
    queue by a poison-pill sentinel — the consumer never hangs on a
    dead producer); the loader absorbs a bounded number of these by
    rebuilding the pipeline (``engine.reader_restarts``, default 2)."""


def _file_crc32(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as fh:
        while True:
            buf = fh.read(chunk)
            if not buf:
                return crc
            crc = zlib.crc32(buf, crc)


# ----------------------------------------------------------------------
# on-disk shard format
# ----------------------------------------------------------------------
def write_shards(out_dir: str,
                 train_data: np.ndarray,
                 train_labels: np.ndarray | None = None,
                 valid_data: np.ndarray | None = None,
                 valid_labels: np.ndarray | None = None,
                 test_data: np.ndarray | None = None,
                 test_labels: np.ndarray | None = None,
                 rows_per_shard: int = 4096) -> str:
    """Write arrays as a sharded streaming dataset (the inverse of
    :class:`ShardReader`).  Rows land in global-index order — test,
    validation, train — matching the full-batch loader convention, so
    index *i* means the same sample to every loader family."""
    os.makedirs(out_dir, exist_ok=True)
    datas: list[np.ndarray] = []
    labels: list[np.ndarray | None] = []
    lengths = [0, 0, 0]
    for cls, (d, lab) in enumerate(((test_data, test_labels),
                                    (valid_data, valid_labels),
                                    (train_data, train_labels))):
        if d is None:
            if lab is not None:
                raise ValueError(f"labels without data for class {cls}")
            continue
        lengths[cls] = len(d)
        datas.append(np.asarray(d))
        labels.append(None if lab is None
                      else np.asarray(lab, dtype=np.int32))
    if not datas:
        raise ValueError("write_shards: no data given")
    if any(lab is not None for lab in labels) \
            and any(lab is None for lab in labels):
        raise ValueError("labels given for some classes but not others")
    data = np.concatenate(datas, axis=0)
    labs = (np.concatenate([lab for lab in labels if lab is not None])
            if labels[0] is not None else None)
    shards = []
    for i, lo in enumerate(range(0, len(data), int(rows_per_shard))):
        chunk = np.ascontiguousarray(data[lo:lo + rows_per_shard])
        fn = f"data-{i:05d}.npy"
        np.save(os.path.join(out_dir, fn), chunk)
        # per-shard integrity digest (round 11): readers verify on
        # first open and quarantine-and-continue on mismatch
        entry: dict = {"data": fn, "rows": int(len(chunk)),
                       "crc32": _file_crc32(os.path.join(out_dir, fn))}
        if labs is not None:
            lfn = f"labels-{i:05d}.npy"
            np.save(os.path.join(out_dir, lfn),
                    labs[lo:lo + rows_per_shard])
            entry["labels"] = lfn
            entry["labels_crc32"] = _file_crc32(
                os.path.join(out_dir, lfn))
        shards.append(entry)
    manifest = {"version": 1,
                "class_lengths": [int(n) for n in lengths],
                "sample_shape": [int(s) for s in data.shape[1:]],
                "dtype": str(data.dtype),
                "shards": shards}
    with open(os.path.join(out_dir, MANIFEST_NAME), "w") as fh:
        json.dump(manifest, fh, indent=1)
    return out_dir


class ShardReader:
    """Memory-mapped random-access view over a shard directory.

    Shard files open as read-only ``numpy`` memory maps on first
    touch; :meth:`gather` fancy-indexes them into a caller buffer, so
    the actual disk IO happens as page faults inside whatever reader
    thread called — the streaming loader's pool parallelism.  Labels
    (tiny) load eagerly."""

    def __init__(self, directory: str) -> None:
        self.directory = directory
        #: observability label for the rows-quarantined counter; the
        #: owning StreamingLoader stamps its unit name here (fallback:
        #: the shard directory's basename)
        self.obs_label = os.path.basename(
            os.path.normpath(directory)) or directory
        path = os.path.join(directory, MANIFEST_NAME)
        with open(path) as fh:
            self.manifest = json.load(fh)
        self.class_lengths = [int(n)
                              for n in self.manifest["class_lengths"]]
        self.sample_shape = tuple(self.manifest["sample_shape"])
        self.dtype = np.dtype(self.manifest["dtype"])
        self._shards = self.manifest["shards"]
        rows = np.asarray([s["rows"] for s in self._shards],
                          dtype=np.int64)
        self._offsets = np.concatenate(([0], np.cumsum(rows)))
        self.n_samples = int(self._offsets[-1])
        if self.n_samples != sum(self.class_lengths):
            raise ValueError(
                f"{path}: shard rows {self.n_samples} != "
                f"class_lengths sum {sum(self.class_lengths)}")
        self._maps: list[np.ndarray | None] = [None] * len(self._shards)
        self._lock = threading.Lock()
        #: shards that exhausted their read retries: their rows
        #: deliver zeros for the rest of the run (quarantine-and-
        #: continue beats crashing the epoch on one bad file)
        self._quarantined: set[int] = set()
        self.has_labels = all("labels" in s for s in self._shards)
        self._labels: np.ndarray | None = None
        if self.has_labels:
            parts = []
            for i, s in enumerate(self._shards):
                lpath = os.path.join(directory, s["labels"])
                want = s.get("labels_crc32")
                if want is not None and self.verify_crc \
                        and _file_crc32(lpath) != int(want):
                    raise ShardReadError(
                        i, f"{lpath}: labels CRC mismatch — dataset "
                           f"corrupt on disk")
                parts.append(np.load(lpath))
            self._labels = np.concatenate(parts).astype(np.int32)

    @property
    def verify_crc(self) -> bool:
        """``root.common.engine.shard_crc`` (default on): verify each
        shard file's manifest digest on first open.  One sequential
        read per shard per process — page-cache warming the mmap would
        do anyway."""
        return bool(root.common.engine.get("shard_crc", True))

    @property
    def quarantined(self) -> frozenset:
        return frozenset(self._quarantined)

    def quarantine(self, shard: int) -> None:
        """Mark a shard permanently bad: drop its mmap, serve zeros."""
        with self._lock:
            self._quarantined.add(int(shard))
            self._maps[int(shard)] = None

    @property
    def nbytes(self) -> int:
        """Logical dataset size (what a resident loader would hold)."""
        return self.n_samples * self.dtype.itemsize \
            * int(np.prod(self.sample_shape, dtype=np.int64))

    def _mmap(self, shard: int) -> np.ndarray:
        arr = self._maps[shard]
        if arr is None:
            with self._lock:
                arr = self._maps[shard]
                if arr is None:
                    path = os.path.join(
                        self.directory, self._shards[shard]["data"])
                    want = self._shards[shard].get("crc32")
                    if want is not None and self.verify_crc \
                            and _file_crc32(path) != int(want):
                        raise ShardReadError(
                            shard, f"{path}: CRC mismatch (manifest "
                                   f"{int(want)}) — shard corrupt on "
                                   f"disk")
                    arr = np.load(path, mmap_mode="r")
                    self._maps[shard] = arr
        return arr

    def gather(self, idx: np.ndarray, out: np.ndarray) -> None:
        """``out[k] = dataset[idx[k]]`` across shard boundaries.
        Quarantined shards contribute zero rows; fault sites
        ``loader.corrupt_shard`` / ``loader.short_read`` raise here
        exactly like a real CRC/IO failure would."""
        idx = np.asarray(idx, dtype=np.int64)
        shard_of = np.searchsorted(self._offsets, idx, side="right") - 1
        for s in np.unique(shard_of):
            s = int(s)
            mask = shard_of == s
            if s in self._quarantined:
                # round-19 satellite: zero-filled rows are silent data
                # loss — count every one so /metrics (and /readyz,
                # report-only) make the loss loud
                out[mask] = 0
                _metrics.loader_rows_quarantined(self.obs_label).inc(
                    int(mask.sum()))
                continue
            if _faults.fire("loader.corrupt_shard", shard=s) is not None:
                raise ShardReadError(s, f"injected corrupt shard {s}")
            if _faults.fire("loader.short_read", shard=s) is not None:
                raise ShardReadError(s, f"injected short read on "
                                        f"shard {s}")
            rows = idx[mask] - self._offsets[s]
            out[mask] = self._mmap(s)[rows]

    def labels(self, idx: np.ndarray) -> np.ndarray:
        assert self._labels is not None
        return self._labels[np.asarray(idx, dtype=np.int64)]


# ----------------------------------------------------------------------
# the pipeline
# ----------------------------------------------------------------------
@dataclass
class _Item:
    """One staged minibatch travelling read → upload → consume."""
    key: tuple[int, int]                 # (epoch, cursor) it belongs to
    labels: np.ndarray | None
    slot: int | None = None              # ring slot (host-only delivery)
    devarr: object = None                # uploaded device array
    crossed_epoch: bool = field(default=False)
    #: poison pill: a producer/uploader thread died — wakes the
    #: consumer IMMEDIATELY instead of leaving it blocked on the
    #: bounded queue (round-11 satellite: the dead-reader hang fix)
    pill: bool = field(default=False)


class _StreamPipeline:
    """Producer (reader pool → ring slot) + uploader (``device_put`` →
    bounded device queue) threads for one contiguous run of schedule
    positions.  Restarts (snapshot resume, schedule jumps) tear the
    pipeline down and build a fresh one at the new position — rare by
    construction, so simplicity wins over reuse."""

    def __init__(self, loader: "StreamingLoader",
                 epoch: int, cursor: int) -> None:
        self.loader = loader
        self.start_key = (epoch, cursor)
        self.stop_flag = threading.Event()
        self.error: BaseException | None = None
        self.ring = StagingRing(
            loader.ring_slots,
            (loader.local_batch,) + loader.sample_shape,
            loader.dataset_dtype)
        self.read_q: "queue.Queue[_Item]" = queue.Queue(
            maxsize=loader.ring_slots)
        self.dev_q: "queue.Queue[_Item]" = queue.Queue(
            maxsize=loader.prefetch_depth)
        self._pool = (ThreadPoolExecutor(
            loader.n_reader_threads,
            thread_name_prefix=f"{loader.name}.reader")
            if loader.n_reader_threads > 1 else None)
        self._producer = threading.Thread(
            target=self._thread_body, args=(self._produce, epoch, cursor),
            name=f"{loader.name}.producer", daemon=True)
        self._uploader = threading.Thread(
            target=self._thread_body, args=(self._upload,),
            name=f"{loader.name}.uploader", daemon=True)
        self._producer.start()
        self._uploader.start()

    # -- death propagation ---------------------------------------------
    def _thread_body(self, fn, *args) -> None:
        """Run a pipeline stage; on ANY death record the cause and
        push a poison pill through the device queue so the consumer
        raises :class:`PipelineDead` immediately instead of hanging on
        (or slow-polling) the bounded queue."""
        try:
            fn(*args)
        except BaseException as exc:  # noqa: BLE001 — must not die silent
            if not self.stop_flag.is_set():
                if self.error is None:
                    self.error = exc
                try:
                    self.dev_q.put_nowait(
                        _Item((-1, -1), None, pill=True))
                except queue.Full:
                    pass  # consumer has items to drain; the error
                    #       check in take()'s poll loop catches it

    # -- stage 1: shard gather into a ring slot ------------------------
    def _produce(self, epoch: int, cursor: int) -> None:
        loader = self.loader
        n_sched = len(loader._schedule)
        start_epoch = epoch
        while not self.stop_flag.is_set():
            slot = self.ring.acquire(timeout=0.1)
            if slot is None:
                continue
            try:
                if _faults.fire("loader.reader_death") is not None:
                    raise _faults.FaultInjected(
                        f"{loader.name}: injected reader-thread death")
                t0 = time.perf_counter()
                idx, _cls, _count = loader.schedule_entry(epoch, cursor)
                local = loader._local_slice(idx)
                self._gather_retry(local, self.ring.buffer(slot))
                labels = (loader._reader.labels(local)
                          if loader.has_labels else None)
                if _metrics.enabled():
                    _metrics.input_stage_seconds(loader.name).observe(
                        time.perf_counter() - t0)
                item = _Item((epoch, cursor), labels, slot=slot,
                             crossed_epoch=epoch > start_epoch
                             and cursor == 0)
            except BaseException as exc:
                self.ring.release(slot)
                if self.stop_flag.is_set():
                    return
                self.error = exc  # surfaced by the consumer's take()
                raise
            if not self._put(self.read_q, item):
                self.ring.release(slot)
                return
            cursor += 1
            if cursor >= n_sched:
                cursor, epoch = 0, epoch + 1

    def _gather_retry(self, local_idx: np.ndarray,
                      buf: np.ndarray) -> None:
        """Shard gather with exponential-backoff retry and quarantine:
        a transient failure (IO hiccup, injected short read) retries
        up to ``engine.read_retries`` times; a shard still failing
        after that is quarantined (its rows deliver zeros) and the
        gather proceeds — a persistently corrupt shard costs data, not
        the run."""
        loader = self.loader
        reader = loader._reader
        retries = int(root.common.engine.get("read_retries", 2))
        backoff = float(root.common.engine.get("read_backoff_s", 0.05))
        attempts = 0
        while True:
            try:
                self._gather(local_idx, buf)
                if attempts:
                    _metrics.recoveries("shard_retry").inc()
                return
            except ShardReadError as exc:
                if self.stop_flag.is_set():
                    raise
                attempts += 1
                _metrics.loader_read_retries(loader.name).inc()
                if attempts <= retries:
                    loader.warning(
                        "shard read failed (%s) — retry %d/%d",
                        exc, attempts, retries)
                    time.sleep(backoff * (2 ** (attempts - 1)))
                    continue
                shard = exc.shard
                if shard is None or shard in reader.quarantined:
                    raise  # not shard-attributable: real death
                reader.quarantine(shard)
                _metrics.loader_shards_quarantined(loader.name).inc()
                _metrics.recoveries("shard_quarantine").inc()
                loader.warning(
                    "shard %d quarantined after %d failed reads (%s) "
                    "— its rows deliver zeros for the rest of the run",
                    shard, attempts, exc)
                attempts = 0  # fresh budget for the remaining shards

    def _gather(self, local_idx: np.ndarray, buf: np.ndarray) -> None:
        reader = self.loader._reader
        n = len(local_idx)
        pool = self._pool
        t = self.loader.n_reader_threads
        if pool is None or n < 2 * t:
            reader.gather(local_idx, buf)
            return
        step = -(-n // t)  # ceil: t contiguous row ranges
        futs = [pool.submit(reader.gather, local_idx[lo:lo + step],
                            buf[lo:lo + step])
                for lo in range(0, n, step)]
        for f in futs:
            f.result()

    # -- stage 2: device_put ahead of the consumer ---------------------
    def _upload(self) -> None:
        loader = self.loader
        device = loader.device
        on_device = device is not None and not device.is_host_only
        while not self.stop_flag.is_set():
            try:
                item = self.read_q.get(timeout=0.1)
            except queue.Empty:
                continue
            if on_device:
                try:
                    buf = self.ring.buffer(item.slot)
                    devarr = device.put_local_batch(
                        buf, vector=loader.minibatch_raw)
                    if hasattr(devarr, "block_until_ready"):
                        # fence BEFORE releasing the slot: the transfer
                        # may read the host buffer asynchronously, and
                        # the ring hands this slot back for reuse
                        devarr.block_until_ready()
                except BaseException as exc:
                    self.ring.release(item.slot)
                    if self.stop_flag.is_set():
                        return
                    self.error = exc
                    raise
                if _metrics.enabled():
                    _metrics.transfer_bytes("h2d").inc(buf.nbytes)
                self.ring.release(item.slot)
                item.slot = None
                item.devarr = devarr
            if not self._put(self.dev_q, item):
                if item.slot is not None:
                    self.ring.release(item.slot)
                return

    def _put(self, q: "queue.Queue[_Item]", item: _Item) -> bool:
        while not self.stop_flag.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    # -- consumer side -------------------------------------------------
    def take(self, timeout: float = 300.0) -> _Item:
        deadline = time.monotonic() + timeout
        while True:
            try:
                item = self.dev_q.get(timeout=0.1)
            except queue.Empty:
                if self.error is not None:
                    raise PipelineDead(
                        f"{self.loader}: streaming producer died"
                    ) from self.error
                if time.monotonic() > deadline:
                    raise PipelineDead(
                        f"{self.loader}: streaming pipeline produced "
                        f"nothing for {timeout:.0f}s — reader thread "
                        f"dead?") from None
                continue
            if item.pill:
                raise PipelineDead(
                    f"{self.loader}: streaming pipeline thread died"
                ) from self.error
            return item

    def take_nowait(self) -> _Item | None:
        try:
            item = self.dev_q.get_nowait()
        except queue.Empty:
            return None
        if item.pill:
            raise PipelineDead(
                f"{self.loader}: streaming pipeline thread died"
            ) from self.error
        return item

    @property
    def ready(self) -> int:
        """Uploaded batches waiting for the consumer (live gauge)."""
        return self.dev_q.qsize()

    def stop(self) -> None:
        self.stop_flag.set()
        self._producer.join(timeout=5.0)
        self._uploader.join(timeout=5.0)
        if self._pool is not None:
            self._pool.shutdown(wait=False)


# ----------------------------------------------------------------------
# the loader
# ----------------------------------------------------------------------
class StreamingLoader(Loader):
    """Minibatch loader over a sharded on-disk dataset with async
    prefetch (module docstring has the design).

    Parameters
    ----------
    shard_dir:
        directory written by :func:`write_shards` (``manifest.json``
        + ``.npy`` shards).
    prefetch_depth:
        device batches uploaded ahead of the consumer (≥ 1; 2 =
        double-buffered h2d, 3 = triple).  Raise it when the transfer
        is long-latency (tunneled TPU); host footprint grows by one
        staged batch per unit.
    ring_slots:
        host staging buffers feeding the uploader (default
        ``prefetch_depth + 2``: one being read, one being uploaded,
        plus slack).
    n_reader_threads:
        shard-gather parallelism within one minibatch.
    process_index / process_count:
        this host's slice of the data axis (defaults to the jax
        process topology).  Each process stages only rows
        ``[p·B/P, (p+1)·B/P)`` of every global minibatch — per-host
        1/N reads whose union partitions the epoch exactly.
    normalization_scale / normalization_bias:
        optional affine ``x·scale + bias`` fused on-device into the
        jit region (the dataset stays in its raw dtype on the wire).
    """

    SNAPSHOT_EXCLUDE = Loader.SNAPSHOT_EXCLUDE + ("minibatch_raw",)

    def __init__(self, workflow, shard_dir: str,
                 name: str | None = None,
                 normalization_scale: float | None = None,
                 normalization_bias: float = 0.0,
                 prefetch_depth: int = 2,
                 ring_slots: int | None = None,
                 n_reader_threads: int = 2,
                 process_index: int | None = None,
                 process_count: int | None = None,
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.shard_dir = shard_dir
        self.normalization_scale = normalization_scale
        self.normalization_bias = normalization_bias
        self.prefetch_depth = max(1, int(prefetch_depth))
        self.ring_slots = int(ring_slots) if ring_slots \
            else self.prefetch_depth + 2
        self.n_reader_threads = max(1, int(n_reader_threads))
        if (process_index is None) != (process_count is None):
            raise ValueError(f"{self}: give both process_index and "
                             f"process_count or neither")
        self._pidx_arg = process_index
        self._pcount_arg = process_count
        self._pidx, self._pcount = 0, 1
        #: raw staging Vector: the dataset dtype rides the wire, the
        #: affine normalize runs on-device (same policy as ImageLoader)
        self.minibatch_raw = Vector(name=f"{self.name}.minibatch_raw",
                                    batch_major=True)
        self._reader: ShardReader | None = None
        self._pipe: _StreamPipeline | None = None
        self._held: tuple[_StreamPipeline, int] | None = None
        # overlap telemetry mirrors (canonical series hold the truth;
        # these stay readable without the registry, bench-style)
        self.prefetch_hits = 0
        self.prefetch_misses = 0
        self.input_wait_s = 0.0
        self.epoch_cross_prefetches = 0
        #: pipeline rebuilds after a producer/uploader death this run
        self.pipeline_restarts = 0

    # -- dataset ---------------------------------------------------------
    def load_data(self) -> None:
        self._reader = ShardReader(self.shard_dir)
        # rows-quarantined attribution under THIS loader's name (the
        # canonical per-loader label every other loader series uses)
        self._reader.obs_label = self.name
        self.class_lengths = list(self._reader.class_lengths)

    @property
    def has_labels(self) -> bool:
        assert self._reader is not None
        return self._reader.has_labels

    @property
    def sample_shape(self) -> tuple[int, ...]:
        assert self._reader is not None
        return self._reader.sample_shape

    @property
    def dataset_dtype(self) -> np.dtype:
        assert self._reader is not None
        return self._reader.dtype

    @property
    def dataset_nbytes(self) -> int:
        assert self._reader is not None
        return self._reader.nbytes

    @property
    def local_batch(self) -> int:
        """Rows of each global minibatch THIS process stages."""
        return self.max_minibatch_size // self._pcount

    def _local_slice(self, idx: np.ndarray) -> np.ndarray:
        lb = self.local_batch
        return idx[self._pidx * lb:(self._pidx + 1) * lb]

    def local_indices(self, epoch: int, cursor: int) -> np.ndarray:
        """Global sample indices this process reads for schedule
        position ``(epoch, cursor)`` — the per-host 1/N contract the
        2-process-split test pins (union = partition, no dup/drop)."""
        idx, _cls, _count = self.schedule_entry(epoch, cursor)
        return self._local_slice(idx)

    def create_minibatch_data(self) -> None:
        self.minibatch_raw.reset(np.zeros(
            (self.local_batch,) + self.sample_shape,
            dtype=self.dataset_dtype))
        self.minibatch_data.reset(np.zeros(
            (self.max_minibatch_size,) + self.sample_shape,
            dtype=self.act_store_dtype))
        if self.has_labels:
            self.minibatch_labels.reset(np.zeros(
                self.max_minibatch_size, dtype=np.int32))

    def initialize(self, device=None, **kwargs) -> None:
        if self._pidx_arg is not None:
            self._pidx = int(self._pidx_arg)
            self._pcount = int(self._pcount_arg)
        else:
            from znicz_tpu.parallel.process_shard import process_info
            self._pidx, self._pcount = process_info()
        super().initialize(device=device, **kwargs)
        if self.max_minibatch_size % self._pcount:
            raise ValueError(
                f"{self}: minibatch_size {self.max_minibatch_size} not "
                f"divisible by {self._pcount} processes")
        self.init_vectors(self.minibatch_raw)
        self._stop_pipeline()
        self.prefetch_hits = 0
        self.prefetch_misses = 0
        self.input_wait_s = 0.0
        self.epoch_cross_prefetches = 0
        self.pipeline_restarts = 0
        if _metrics.enabled():
            _metrics.prefetch_depth(self.name).set(self.prefetch_depth)

    def stop(self) -> None:
        self._stop_pipeline()
        super().stop()

    def load_state(self, state: dict) -> None:
        super().load_state(state)
        # the in-flight prefetch belongs to the pre-restore trajectory;
        # the first post-resume take() restarts at the restored cursor
        self._stop_pipeline()
        # round 18 (elastic restart): the global schedule is counter-
        # based, so a snapshot written by an N-process gang restores
        # onto ANY surviving process count — the 1/N slice is re-derived
        # here from the LIVE topology, never from the snapshot.  The
        # operator-facing log is what a restart drill greps for.
        if self._pcount > 1 or os.environ.get("ZNICZ_HEARTBEAT_DIR"):
            lb = self.local_batch
            self.info(
                "resumed at epoch %d cursor %d — re-sliced to rows "
                "[%d, %d) of every %d-row global minibatch "
                "(process %d/%d)", int(self.epoch_number),
                int(self._cursor), self._pidx * lb,
                (self._pidx + 1) * lb, self.max_minibatch_size,
                self._pidx, self._pcount)

    def _stop_pipeline(self) -> None:
        self._held = None
        if self._pipe is not None:
            self._pipe.stop()
            self._pipe = None

    def warmup(self) -> None:
        """Start the background pipeline at the current schedule
        position BEFORE the first step, so even step 1 is served from
        an in-flight prefetch (otherwise the first take is the one
        unavoidable synchronous read).  Optional — the pipeline
        self-starts on the first ``host_run`` either way."""
        if self._pipe is not None:
            return
        if self._cursor >= len(self._schedule):
            key = (self.epoch_number + 1, 0)   # next host_run wraps
        else:
            key = (self.epoch_number, self._cursor)
        self._pipe = _StreamPipeline(self, *key)

    # -- the per-step handoff -------------------------------------------
    def _take(self, expected: tuple[int, int]) -> _Item:
        """The staged batch for ``expected``, absorbing a bounded
        number of pipeline deaths: a dead producer/uploader thread
        raises :class:`PipelineDead` in the consumer (poison pill —
        never a hang on the bounded queue), and the loader rebuilds
        the pipeline at the expected position up to
        ``engine.reader_restarts`` (default 2) times per run before
        letting the error propagate.  A restart re-reads the same
        deterministic indices, so a recovered run is bit-identical to
        an undisturbed one."""
        while True:
            try:
                return self._take_inner(expected)
            except PipelineDead as exc:
                self.pipeline_restarts += 1
                limit = int(root.common.engine.get("reader_restarts", 2))
                self._stop_pipeline()
                if self.pipeline_restarts > limit:
                    raise
                self.warning(
                    "streaming pipeline died (%s) — restart %d/%d at "
                    "epoch %d cursor %d", exc, self.pipeline_restarts,
                    limit, *expected)
                _metrics.loader_pipeline_restarts(self.name).inc()
                _metrics.recoveries("reader_restart").inc()

    def _take_inner(self, expected: tuple[int, int]) -> _Item:
        """The staged batch for schedule position ``expected`` —
        served from the prefetch queue (hit) or after a pipeline
        (re)start at that position (miss)."""
        restarted = False
        if self._pipe is None:
            self._pipe = _StreamPipeline(self, *expected)
            restarted = True
        item = self._pipe.take_nowait()
        if item is not None and item.key != expected:
            # resume / schedule jump: the stream in flight is for the
            # wrong trajectory — rebuild at the expected position
            self._release_item(item)
            self._stop_pipeline()
            self._pipe = _StreamPipeline(self, *expected)
            restarted = True
            item = None
        hit = item is not None
        if item is None:
            with _tracing.TRACER.span(f"input_wait:{self.name}",
                                      cat="loader"):
                t0 = time.perf_counter()
                item = self._pipe.take()
                waited = time.perf_counter() - t0
            if item.key != expected:  # only possible pre-restart
                assert not restarted, (item.key, expected)
                self._release_item(item)
                self._stop_pipeline()
                return self._take(expected)
        else:
            waited = 0.0
        self.input_wait_s += waited
        # a boundary entry only counts as a RECOVERED stall when the
        # pipeline actually got ahead across the epoch (a hit); a miss
        # there is just the ordinary stall being repaid
        crossed = item.crossed_epoch and hit
        if _metrics.enabled():
            _metrics.input_wait_seconds(self.name).observe(waited)
            _metrics.loader_prefetch(
                self.name, "hit" if hit else "miss").inc()
            if crossed:
                _metrics.loader_prefetch(self.name, "epoch_cross").inc()
        if hit:
            self.prefetch_hits += 1
        else:
            self.prefetch_misses += 1
        if crossed:
            self.epoch_cross_prefetches += 1
        return item

    def _release_item(self, item: _Item) -> None:
        if item.slot is not None and self._pipe is not None:
            self._pipe.ring.release(item.slot)

    def host_run(self) -> None:
        super().host_run()  # schedule bookkeeping + indices/valid
        expected = (self.epoch_number, self._cursor - 1)
        item = self._take(expected)
        on_device = self.device is not None \
            and not self.device.is_host_only
        # host-only delivery holds the ring slot until the NEXT step
        # (the consumer reads minibatch_raw.mem in numpy_run); device
        # delivery released it at upload time
        if self._held is not None:
            pipe, slot = self._held
            if pipe is self._pipe:
                pipe.ring.release(slot)
            self._held = None
        if on_device:
            self.minibatch_raw.accept_device(item.devarr)
        else:
            self.minibatch_raw.map_invalidate()
            self.minibatch_raw.mem[...] = \
                self._pipe.ring.buffer(item.slot)
            self._held = (self._pipe, item.slot)
        if self.has_labels:
            assert item.labels is not None
            if self._pcount > 1 and on_device:
                # multi-process: this host stages only its label rows;
                # assemble the global batch like the data upload
                self.minibatch_labels.accept_device(
                    self.device.put_local_batch(
                        np.ascontiguousarray(item.labels),
                        vector=self.minibatch_labels))
            else:
                self.minibatch_labels.map_invalidate()
                self.minibatch_labels.mem[...] = item.labels
                if on_device:
                    self.minibatch_labels.unmap()
        if _metrics.enabled() and self._pipe is not None:
            pipe = self._pipe
            _metrics.REGISTRY.gauge(
                "znicz_prefetch_ready_batches",
                "Uploaded batches waiting for the consumer",
                labels=("loader",)).labels(
                    loader=self.name).set(pipe.ready)

    # -- the on-device normalize (fused into the jit region) ------------
    def numpy_run(self) -> None:
        self.minibatch_raw.map_read()
        self.minibatch_data.map_invalidate()
        batch = self.minibatch_raw.mem.astype(np.float32)
        if self.normalization_scale is not None:
            batch = batch * np.float32(self.normalization_scale) \
                + np.float32(self.normalization_bias)
        self.minibatch_data.mem[...] = batch

    def xla_run(self) -> None:
        import jax.numpy as jnp
        batch = self.minibatch_raw.devmem.astype(jnp.float32)
        if self.normalization_scale is not None:
            batch = batch * jnp.float32(self.normalization_scale) \
                + jnp.float32(self.normalization_bias)
        self.minibatch_data.devmem = batch


__all__ = ["StreamingLoader", "ShardReader", "write_shards",
           "MANIFEST_NAME", "ShardReadError", "PipelineDead"]
