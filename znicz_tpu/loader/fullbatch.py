"""Full-batch loaders: whole dataset resident in HBM, minibatch
assembly is an on-device gather (reference:
``veles/loader/fullbatch.py`` — ``FullBatchLoader`` with its
gather-by-index kernel; here the kernel is ``jnp.take`` fused into the
jit region so minibatch assembly costs no host↔device traffic).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from znicz_tpu.loader.base import Loader, TEST, TRAIN, VALID
from znicz_tpu.memory import Vector


class FullBatchLoader(Loader):
    """Loader whose subclass provides the entire dataset as arrays.

    Subclasses implement :meth:`load_data` and fill
    ``original_data`` / ``original_labels`` plus ``class_lengths``.
    Samples must be ordered test, validation, train along axis 0.

    Two TPU-first bandwidth choices:

    - the dataset stays in its ORIGINAL dtype in HBM (uint8 images are
      4× smaller than f32) and normalization is fused into the
      per-step gather inside the jit region;
    - with ``device_schedule`` (default, jit-region path) the shuffled
      permutation and the minibatch schedule live ON DEVICE: per-step
      indices come from a device-resident cursor, so a training step
      issues NO host→device transfers (a permutation upload per epoch
      replaces two uploads per step — decisive on tunneled/remote TPU
      where every transfer is an RPC).
    """

    # the dataset itself: large, immutable, rebuilt by load_data on
    # resume — never serialized into snapshots; sched_* are derived
    # from _shuffled/_schedule (snapshotted) and re-uploaded on resume
    SNAPSHOT_EXCLUDE = Loader.SNAPSHOT_EXCLUDE + (
        "original_data", "original_labels", "sched_perm",
        "sched_starts", "sched_counts", "sched_cursor")

    def __init__(self, workflow, name: str | None = None,
                 normalization_scale: float | None = None,
                 normalization_bias: float = 0.0,
                 device_schedule: bool = True,
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.original_data = Vector(name=f"{self.name}.original_data")
        self.original_labels = Vector(name=f"{self.name}.original_labels")
        #: optional affine normalization x*scale + bias, fused into the
        #: gather (device path) / applied per-minibatch (oracle path)
        self.normalization_scale = normalization_scale
        self.normalization_bias = normalization_bias
        self.device_schedule = bool(device_schedule)
        self.sched_perm = Vector(name=f"{self.name}.sched_perm")
        self.sched_starts = Vector(name=f"{self.name}.sched_starts")
        self.sched_counts = Vector(name=f"{self.name}.sched_counts")
        self.sched_cursor = Vector(name=f"{self.name}.sched_cursor")

    @property
    def has_labels(self) -> bool:
        return bool(self.original_labels)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        self.init_vectors(self.original_data, self.original_labels)
        if self.device is not None and not self.device.is_host_only:
            assert self._shuffled is not None
            self.sched_perm.reset(self._shuffled.copy())
            self.sched_starts.reset(np.asarray(
                [lo for _, lo, _ in self._schedule], dtype=np.int32))
            self.sched_counts.reset(np.asarray(
                [hi - lo for _, lo, hi in self._schedule],
                dtype=np.int32))
            self.sched_cursor.reset(np.zeros((), dtype=np.int32))
            self.init_vectors(self.sched_perm, self.sched_starts,
                              self.sched_counts, self.sched_cursor)
            self._sched_dirty = False  # just uploaded fresh

    # -- device-resident schedule (see class docstring) -----------------
    def _on_device_schedule(self) -> bool:
        return (self.device_schedule and self._in_region
                and self.device is not None
                and not self.device.is_host_only)

    def _sync_device_schedule(self) -> None:
        if not self._sched_dirty:
            return
        # dirty the HOST copies; the region's unmap sweep uploads them
        # (once per epoch shuffle / snapshot resume, not per step)
        self.sched_perm.map_invalidate()
        self.sched_perm.mem[...] = self._shuffled
        self.sched_cursor.map_invalidate()
        self.sched_cursor.mem[...] = self._cursor - 1  # entry just picked
        self._sched_dirty = False

    def load_state(self, state: dict) -> None:
        super().load_state(state)
        self._sched_dirty = True  # device copies are stale after resume

    def create_minibatch_data(self) -> None:
        sample_shape = self.original_data.shape[1:]
        self.minibatch_data.reset(np.zeros(
            (self.max_minibatch_size,) + tuple(sample_shape),
            dtype=self.act_store_dtype))
        if self.has_labels:
            self.minibatch_labels.reset(np.zeros(
                self.max_minibatch_size, dtype=np.int32))

    # -- the gather -----------------------------------------------------
    def _normalize_np(self, batch: np.ndarray) -> np.ndarray:
        if self.normalization_scale is not None:
            batch = batch * np.float32(self.normalization_scale) \
                + np.float32(self.normalization_bias)
        return batch

    def numpy_run(self) -> None:
        self.original_data.map_read()
        self.minibatch_indices.map_read()
        idx = self.minibatch_indices.mem
        self.minibatch_data.map_invalidate()
        self.minibatch_data.mem[...] = self._normalize_np(
            self.original_data.mem[idx].astype(np.float32))
        if self.has_labels:
            self.original_labels.map_read()
            self.minibatch_labels.map_invalidate()
            self.minibatch_labels.mem[...] = self.original_labels.mem[idx]

    def xla_run(self) -> None:
        if self._on_device_schedule():
            cursor = self.sched_cursor.devmem
            start = jnp.take(self.sched_starts.devmem, cursor)
            count = jnp.take(self.sched_counts.devmem, cursor)
            offs = jnp.arange(self.max_minibatch_size, dtype=jnp.int32)
            # short tail pads by repeating the first sample (host
            # semantics); masking uses minibatch_valid as before
            pos = start + jnp.where(offs < count, offs, 0)
            idx = jnp.take(self.sched_perm.devmem, pos)
            self.minibatch_indices.devmem = idx
            self.minibatch_valid.devmem = count.astype(jnp.int32)
            self.sched_cursor.devmem = \
                (cursor + 1) % np.int32(len(self._schedule))
        else:
            idx = self.minibatch_indices.devmem
        batch = jnp.take(
            self.original_data.devmem, idx, axis=0).astype(jnp.float32)
        if self.normalization_scale is not None:
            # fused into the gather program: dataset stays in its raw
            # dtype in HBM (uint8 = 4× less gather traffic + memory)
            batch = batch * jnp.float32(self.normalization_scale) \
                + jnp.float32(self.normalization_bias)
        self.minibatch_data.devmem = batch
        if self.has_labels:
            self.minibatch_labels.devmem = jnp.take(
                self.original_labels.devmem, idx, axis=0)


class ArrayLoader(FullBatchLoader):
    """FullBatchLoader fed directly with numpy arrays per class — the
    workhorse for samples and tests (reference analogue: the ad-hoc
    per-sample loader subclasses in ``znicz/samples/*``)."""

    def __init__(self, workflow,
                 train_data: np.ndarray,
                 train_labels: np.ndarray | None = None,
                 valid_data: np.ndarray | None = None,
                 valid_labels: np.ndarray | None = None,
                 test_data: np.ndarray | None = None,
                 test_labels: np.ndarray | None = None,
                 **kwargs) -> None:
        # before super().__init__: bypass the linked-attr machinery
        object.__setattr__(self, "_arrays",
                           (test_data, test_labels, valid_data, valid_labels,
                            train_data, train_labels))
        super().__init__(workflow, **kwargs)

    def load_data(self) -> None:
        (test_d, test_l, valid_d, valid_l, train_d, train_l) = self._arrays
        datas, labels = [], []
        lengths = [0, 0, 0]
        for cls, (d, l) in zip((TEST, VALID, TRAIN),
                               ((test_d, test_l), (valid_d, valid_l),
                                (train_d, train_l))):
            if d is None:
                if l is not None:
                    raise ValueError(f"{self}: labels without data for "
                                     f"class {cls}")
                continue
            lengths[cls] = len(d)
            datas.append(np.asarray(d))
            labels.append(None if l is None
                          else np.asarray(l, dtype=np.int32))
        if any(l is not None for l in labels):
            # labels index by GLOBAL sample position — partial labels
            # would silently misalign the gather
            missing = [i for i, l in enumerate(labels) if l is None]
            if missing:
                raise ValueError(
                    f"{self}: labels given for some classes but not "
                    f"others — provide labels for every supplied split")
            self.original_labels.reset(np.concatenate(labels, axis=0))
        self.class_lengths = lengths
        self.original_data.reset(np.concatenate(datas, axis=0))
