"""Full-batch loaders: whole dataset resident in HBM, minibatch
assembly is an on-device gather (reference:
``veles/loader/fullbatch.py`` — ``FullBatchLoader`` with its
gather-by-index kernel; here the kernel is ``jnp.take`` fused into the
jit region so minibatch assembly costs no host↔device traffic).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from znicz_tpu.loader.base import Loader, TEST, TRAIN, VALID
from znicz_tpu.memory import Vector


class FullBatchLoader(Loader):
    """Loader whose subclass provides the entire dataset as arrays.

    Subclasses implement :meth:`load_data` and fill
    ``original_data`` / ``original_labels`` plus ``class_lengths``.
    Samples must be ordered test, validation, train along axis 0.
    """

    # the dataset itself: large, immutable, rebuilt by load_data on
    # resume — never serialized into snapshots
    SNAPSHOT_EXCLUDE = Loader.SNAPSHOT_EXCLUDE + (
        "original_data", "original_labels")

    def __init__(self, workflow, name: str | None = None,
                 normalization_scale: float | None = None,
                 normalization_bias: float = 0.0,
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.original_data = Vector(name=f"{self.name}.original_data")
        self.original_labels = Vector(name=f"{self.name}.original_labels")
        #: optional affine normalization x*scale + bias applied on load
        self.normalization_scale = normalization_scale
        self.normalization_bias = normalization_bias

    @property
    def has_labels(self) -> bool:
        return bool(self.original_labels)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.normalization_scale is not None:
            data = self.original_data.mem.astype(np.float32)
            data *= self.normalization_scale
            data += self.normalization_bias
            self.original_data.reset(data)
        self.init_vectors(self.original_data, self.original_labels)

    def create_minibatch_data(self) -> None:
        sample_shape = self.original_data.shape[1:]
        self.minibatch_data.reset(np.zeros(
            (self.max_minibatch_size,) + tuple(sample_shape),
            dtype=np.float32))
        if self.has_labels:
            self.minibatch_labels.reset(np.zeros(
                self.max_minibatch_size, dtype=np.int32))

    # -- the gather -----------------------------------------------------
    def numpy_run(self) -> None:
        self.original_data.map_read()
        self.minibatch_indices.map_read()
        idx = self.minibatch_indices.mem
        self.minibatch_data.map_invalidate()
        self.minibatch_data.mem[...] = \
            self.original_data.mem[idx].astype(np.float32)
        if self.has_labels:
            self.original_labels.map_read()
            self.minibatch_labels.map_invalidate()
            self.minibatch_labels.mem[...] = self.original_labels.mem[idx]

    def xla_run(self) -> None:
        idx = self.minibatch_indices.devmem
        self.minibatch_data.devmem = jnp.take(
            self.original_data.devmem, idx, axis=0).astype(jnp.float32)
        if self.has_labels:
            self.minibatch_labels.devmem = jnp.take(
                self.original_labels.devmem, idx, axis=0)


class ArrayLoader(FullBatchLoader):
    """FullBatchLoader fed directly with numpy arrays per class — the
    workhorse for samples and tests (reference analogue: the ad-hoc
    per-sample loader subclasses in ``znicz/samples/*``)."""

    def __init__(self, workflow,
                 train_data: np.ndarray,
                 train_labels: np.ndarray | None = None,
                 valid_data: np.ndarray | None = None,
                 valid_labels: np.ndarray | None = None,
                 test_data: np.ndarray | None = None,
                 test_labels: np.ndarray | None = None,
                 **kwargs) -> None:
        # before super().__init__: bypass the linked-attr machinery
        object.__setattr__(self, "_arrays",
                           (test_data, test_labels, valid_data, valid_labels,
                            train_data, train_labels))
        super().__init__(workflow, **kwargs)

    def load_data(self) -> None:
        (test_d, test_l, valid_d, valid_l, train_d, train_l) = self._arrays
        datas, labels = [], []
        lengths = [0, 0, 0]
        for cls, (d, l) in zip((TEST, VALID, TRAIN),
                               ((test_d, test_l), (valid_d, valid_l),
                                (train_d, train_l))):
            if d is None:
                if l is not None:
                    raise ValueError(f"{self}: labels without data for "
                                     f"class {cls}")
                continue
            lengths[cls] = len(d)
            datas.append(np.asarray(d))
            labels.append(None if l is None
                          else np.asarray(l, dtype=np.int32))
        if any(l is not None for l in labels):
            # labels index by GLOBAL sample position — partial labels
            # would silently misalign the gather
            missing = [i for i, l in enumerate(labels) if l is None]
            if missing:
                raise ValueError(
                    f"{self}: labels given for some classes but not "
                    f"others — provide labels for every supplied split")
            self.original_labels.reset(np.concatenate(labels, axis=0))
        self.class_lengths = lengths
        self.original_data.reset(np.concatenate(datas, axis=0))
