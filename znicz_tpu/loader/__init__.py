"""Dataset loaders (reference: ``veles/loader/``): the minibatch
engine with TRAIN/VALID/TEST class splits, per-epoch shuffling, and
device-resident full-batch variants whose minibatch assembly is a
gather that runs *inside* the jit region.
"""

from znicz_tpu.loader.base import (Loader, TEST, VALID, TRAIN,  # noqa: F401
                                   CLASS_NAME, epoch_permutation)
from znicz_tpu.loader.fullbatch import FullBatchLoader, ArrayLoader  # noqa: F401
from znicz_tpu.loader.streaming import (StreamingLoader,  # noqa: F401
                                        ShardReader, write_shards)
