"""Loader base: the minibatch engine.

Rebuilds the reference's ``veles/loader/base.py``:

- three sample classes ``TEST=0 / VALID=1 / TRAIN=2`` with
  ``class_lengths``; one *epoch* walks every non-empty class in order
  (test, validation, train), the reference's schedule that lets the
  Decision unit account errors per class;
- train indices reshuffled every epoch — **counter-based**: the
  permutation for epoch *e* is a pure function of ``(shuffle_seed,
  e)`` through a Philox CBRNG (:func:`epoch_permutation`), not a
  stateful stream.  Any component can therefore compute any epoch's
  order without replaying history: prefetchers legally look across
  epoch boundaries (:meth:`Loader.schedule_entry`), every process of a
  multi-host run derives the same global order from the shared seed
  and reads only its 1/N slice, and a resumed run reproduces the
  exact remaining sequence from the snapshotted seed;
- the last minibatch of a class is **padded** to the static minibatch
  size (static shapes for XLA) and ``minibatch_valid`` carries the
  true count as a device scalar so evaluators mask the tail —
  replacing the reference's dynamic short minibatches, which would
  force recompilation on TPU;
- flags consumed by Decision: ``minibatch_class``, ``last_minibatch``,
  ``epoch_ended``, ``epoch_number``.

The index-picking bookkeeping is ``host_run`` (control plane); the
data gather is the device path (see ``fullbatch.py``) so it fuses into
the jit region.
"""

from __future__ import annotations

import threading

import numpy as np

from znicz_tpu.accelerated_units import AcceleratedUnit
from znicz_tpu.memory import Vector
from znicz_tpu.mutable import Bool
from znicz_tpu.utils import prng

TEST, VALID, TRAIN = 0, 1, 2
CLASS_NAME = {TEST: "test", VALID: "validation", TRAIN: "train"}

_U64 = (1 << 64) - 1


def epoch_permutation(seed: int, epoch: int, n: int) -> np.ndarray:
    """The framework's one shuffle function: a permutation of ``n``
    as a pure function of ``(seed, epoch)`` via the Philox
    counter-based RNG.  Every loader family (full-batch, streaming,
    image) derives its train order here, so a streamed epoch
    reproduces the resident loader's shuffled order bit-for-bit for
    the same seed — the determinism contract the streaming data
    plane's cross-epoch prefetch and per-process sharding rest on."""
    gen = np.random.Generator(np.random.Philox(
        key=np.array([seed & _U64, epoch & _U64], dtype=np.uint64)))
    return gen.permutation(n).astype(np.int32)


class Loader(AcceleratedUnit):
    """Abstract minibatch provider.

    Subclasses implement :meth:`load_data` (set ``class_lengths`` and
    storage), :meth:`create_minibatch_data` (allocate the minibatch
    Vectors) and the gather (``numpy_run``/``xla_run``).
    """

    SNAPSHOT_ATTRS = ("epoch_number", "_cursor", "_shuffled",
                      "_shuffle_seed", "minibatch_class",
                      "minibatch_size", "minibatch_offset")
    # transient per-step buffers; resume regenerates them next step
    SNAPSHOT_EXCLUDE = ("minibatch_data", "minibatch_labels",
                        "minibatch_indices", "minibatch_valid")

    def __init__(self, workflow, name: str | None = None,
                 minibatch_size: int = 100,
                 shuffle_limit: int = np.iinfo(np.int64).max,
                 prng_name: str = "default",
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.max_minibatch_size = int(minibatch_size)
        self.shuffle_limit = shuffle_limit  # epochs to keep shuffling
        self._prng_name = prng_name
        # outputs
        self.minibatch_data = Vector(name=f"{self.name}.minibatch_data",
                                     batch_major=True)
        self.minibatch_labels = Vector(
            name=f"{self.name}.minibatch_labels", batch_major=True)
        self.minibatch_indices = Vector(
            name=f"{self.name}.minibatch_indices", batch_major=True)
        self.minibatch_valid = Vector(name=f"{self.name}.minibatch_valid")
        # schedule state
        self.class_lengths = [0, 0, 0]
        self.epoch_number = 0
        self.minibatch_class = TRAIN
        self.minibatch_size = 0          # true sample count this step
        self.minibatch_offset = 0
        self.last_minibatch = Bool(False)
        self.epoch_ended = Bool(False)
        self.train_ended = Bool(False)
        self._schedule: list[tuple[int, int, int]] = []  # (class, lo, hi)
        self._cursor = 0
        self._shuffled: np.ndarray | None = None
        #: root of the counter-based shuffle: (seed, epoch) → order.
        #: Drawn once from the loader PRNG at initialize (so the global
        #: seed still decides the trajectory) and snapshotted.
        self._shuffle_seed = 0
        self._order_cache: dict[tuple[int, int], np.ndarray] = {}
        #: producer threads (streaming prefetch, decode pools) call
        #: train_order concurrently with the control plane
        self._order_lock = threading.Lock()
        self._host_indices: np.ndarray | None = None
        #: device-resident schedule copies need (re)uploading
        self._sched_dirty = True

    # ------------------------------------------------------------------
    @property
    def total_samples(self) -> int:
        return int(sum(self.class_lengths))

    @property
    def class_offsets(self) -> list[int]:
        """Global index where each class's samples start."""
        off, out = 0, []
        for length in self.class_lengths:
            out.append(off)
            off += length
        return out

    def class_index_range(self, cls: int) -> tuple[int, int]:
        lo = self.class_offsets[cls]
        return lo, lo + self.class_lengths[cls]

    # ------------------------------------------------------------------
    # subclass API
    # ------------------------------------------------------------------
    def load_data(self) -> None:
        raise NotImplementedError

    def create_minibatch_data(self) -> None:
        raise NotImplementedError

    # ------------------------------------------------------------------
    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        self.rnd = prng.get(self._prng_name)
        self.load_data()
        if self.total_samples == 0:
            raise ValueError(f"{self}: load_data produced no samples")
        self.max_minibatch_size = min(self.max_minibatch_size,
                                      max(self.class_lengths))
        shards = getattr(self.device, "n_data_shards", 1)
        if self.max_minibatch_size % shards:
            aligned = (self.max_minibatch_size // shards) * shards
            if aligned == 0:
                raise ValueError(
                    f"{self}: minibatch_size {self.max_minibatch_size} "
                    f"cannot be sharded over the mesh's {shards} data "
                    f"shards")
            self.warning(
                "minibatch_size %d not divisible by %d data shards — "
                "clamped to %d", self.max_minibatch_size, shards, aligned)
            self.max_minibatch_size = aligned
        self.minibatch_indices.reset(
            np.zeros(self.max_minibatch_size, dtype=np.int32))
        self.minibatch_valid.reset(np.zeros((), dtype=np.int32))
        self.create_minibatch_data()
        self.init_vectors(self.minibatch_data, self.minibatch_labels,
                          self.minibatch_indices, self.minibatch_valid)
        # one draw from the shared stream roots ALL epoch permutations
        # (snapshot resume restores the saved seed over this one)
        self._shuffle_seed = int(self.rnd.randint(0, 2 ** 63))
        self._order_cache.clear()
        self._build_schedule()
        if (self._shuffled is None
                or len(self._shuffled) != self.total_samples):
            # fresh start; on snapshot resume the restored permutation
            # and cursor are kept so the trajectory continues exactly
            self._shuffled = np.arange(self.total_samples, dtype=np.int32)
            self._cursor = 0
            self._shuffle_train()

    def _build_schedule(self) -> None:
        self._schedule = []
        for cls in (TEST, VALID, TRAIN):
            lo, hi = self.class_index_range(cls)
            for start in range(lo, hi, self.max_minibatch_size):
                self._schedule.append(
                    (cls, start, min(start + self.max_minibatch_size, hi)))

    # ------------------------------------------------------------------
    # deterministic counter-based epoch order
    # ------------------------------------------------------------------
    def train_order(self, epoch: int) -> np.ndarray:
        """Global indices of the TRAIN segment in the order epoch
        ``epoch`` visits them — a pure function of the snapshotted
        ``_shuffle_seed`` (any epoch, past or future, no state
        replay).  ``shuffle_limit`` freezes the order at the last
        shuffled epoch, matching the stateful semantics it replaces."""
        lo, hi = self.class_index_range(TRAIN)
        n = hi - lo
        if n <= 0 or self.shuffle_limit <= 0:
            return np.arange(lo, hi, dtype=np.int32)
        eff = min(int(epoch), int(self.shuffle_limit) - 1)
        key = (self._shuffle_seed, eff)
        with self._order_lock:
            perm = self._order_cache.get(key)
            if perm is None:
                if len(self._order_cache) >= 4:  # current + lookahead
                    self._order_cache.pop(next(iter(self._order_cache)))
                perm = self._order_cache[key] = epoch_permutation(
                    self._shuffle_seed, eff, n)
        return (lo + perm).astype(np.int32)

    def epoch_order(self, epoch: int) -> np.ndarray:
        """The full global sample order of epoch ``epoch`` (test and
        validation segments ride in natural order; train shuffled)."""
        order = np.arange(self.total_samples, dtype=np.int32)
        lo, hi = self.class_index_range(TRAIN)
        if hi > lo:
            order[lo:hi] = self.train_order(epoch)
        return order

    def schedule_entry(self, epoch: int, cursor: int
                       ) -> tuple[np.ndarray, int, int]:
        """Deterministic ``(padded indices, class, true count)`` for
        ANY schedule position — including future epochs.  This is what
        lets prefetchers (streaming producer threads, the image
        loader's decode pool) run ahead across epoch boundaries: the
        order there is already decided by the counter-based shuffle,
        no stale-order hazard."""
        cls, lo, hi = self._schedule[cursor]
        count = hi - lo
        order = self.epoch_order(epoch)
        idx = np.empty(self.max_minibatch_size, dtype=np.int32)
        idx[:count] = order[lo:hi]
        if count < self.max_minibatch_size:  # pad: repeat the first
            idx[count:] = idx[0]
        return idx, cls, count

    def _shuffle_train(self) -> None:
        if self.epoch_number >= self.shuffle_limit:
            return
        lo, hi = self.class_index_range(TRAIN)
        if hi > lo:
            assert self._shuffled is not None
            self._shuffled[lo:hi] = self.train_order(self.epoch_number)
            self._sched_dirty = True  # device-resident copy is stale

    # ------------------------------------------------------------------
    # per-step control plane
    # ------------------------------------------------------------------
    def host_run(self) -> None:
        if self._cursor >= len(self._schedule):
            # previous step ended the epoch; begin the next one
            self._cursor = 0
            self.epoch_number += 1
            self._shuffle_train()
        cls, lo, hi = self._schedule[self._cursor]
        self._cursor += 1
        count = hi - lo
        idx = np.empty(self.max_minibatch_size, dtype=np.int32)
        idx[:count] = self._shuffled[lo:hi]
        if count < self.max_minibatch_size:  # pad by repeating the first
            idx[count:] = idx[0]
        self.minibatch_class = cls
        self.minibatch_size = count
        self.minibatch_offset = lo
        self._host_indices = idx  # host copy (streaming loaders read
        #                           it back without a device round-trip)
        at_end = self._cursor >= len(self._schedule)
        self.last_minibatch.value = (
            at_end or self._schedule[self._cursor][0] != cls)
        self.epoch_ended.value = at_end
        self.train_ended.value = at_end and cls == TRAIN
        if self._on_device_schedule():
            # indices/valid are computed ON DEVICE from the resident
            # schedule (sched_* leaves) — no per-step host→device
            # uploads, the big per-step cost on remote/tunneled TPUs
            self._sync_device_schedule()
            return
        self.minibatch_indices.map_invalidate()
        self.minibatch_indices.mem[...] = idx
        self.minibatch_valid.map_invalidate()
        self.minibatch_valid.mem[...] = count
        # device path (gather) needs indices on device
        if self.device is not None and not self.device.is_host_only:
            self.minibatch_indices.unmap()
            self.minibatch_valid.unmap()

    # device-resident schedule hooks (implemented by FullBatchLoader;
    # streaming loaders stage data host-side anyway, so they keep the
    # host-upload path)
    def _on_device_schedule(self) -> bool:
        return False

    def _sync_device_schedule(self) -> None:  # pragma: no cover - hook
        raise NotImplementedError

    @property
    def forward_mode(self) -> str:
        """"train" on train minibatches, else "eval" — linked (one-way)
        into stochastic units (dropout, stochastic pooling) so their
        region variants track the current minibatch class."""
        return "train" if self.minibatch_class == TRAIN else "eval"

    # stats ------------------------------------------------------------
    def class_minibatch_count(self, cls: int) -> int:
        return sum(1 for c, _, _ in self._schedule if c == cls)
