"""Dataset acquisition for the sample zoo.

The reference's samples downloaded MNIST/CIFAR/ImageNet; this
environment has zero egress, so every sample dataset resolves in two
steps:

1. real files under ``root.common.dirs.datasets`` when present
   (MNIST idx/ubyte, CIFAR-10 binary batches — same formats the
   reference's loaders consumed);
2. otherwise a **procedural stand-in** with the same shapes/dtypes and
   a learnable class structure (random class prototypes + noise +
   class-dependent spatial patterns), deterministic per seed.

Functional tests and benchmarks therefore run anywhere; with real
data present the same samples train the real task.
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from znicz_tpu.utils.config import root


def _dataset_path(*parts: str) -> str:
    return os.path.join(str(root.common.dirs.datasets), *parts)


# ----------------------------------------------------------------------
# real-file readers (reference formats)
# ----------------------------------------------------------------------
def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, = struct.unpack(">I", f.read(4))
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


def load_mnist() -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """(train_x[60000,28,28], train_y, test_x[10000,28,28], test_y) —
    real files if present, else synthetic MNIST-shaped digits."""
    names = ["train-images-idx3-ubyte", "train-labels-idx1-ubyte",
             "t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"]
    found = []
    for name in names:
        for cand in (_dataset_path("mnist", name),
                     _dataset_path("mnist", name + ".gz")):
            if os.path.exists(cand):
                found.append(cand)
                break
    if len(found) == 4:
        return (_read_idx(found[0]), _read_idx(found[1]),
                _read_idx(found[2]), _read_idx(found[3]))
    return synthetic_images(n_train=6000, n_test=1000, size=28,
                            channels=0, n_classes=10, seed=42)


def load_cifar10() -> tuple[np.ndarray, np.ndarray, np.ndarray,
                            np.ndarray]:
    """(train_x[N,32,32,3] u8, train_y, test_x, test_y)."""
    base = _dataset_path("cifar-10-batches-bin")
    batch_names = [f"data_batch_{i}.bin" for i in range(1, 6)]
    if all(os.path.exists(os.path.join(base, b))
           for b in batch_names + ["test_batch.bin"]):
        xs, ys = [], []
        for b in batch_names + ["test_batch.bin"]:
            raw = np.fromfile(os.path.join(base, b), dtype=np.uint8)
            raw = raw.reshape(-1, 3073)
            ys.append(raw[:, 0].astype(np.int32))
            xs.append(raw[:, 1:].reshape(-1, 3, 32, 32)
                      .transpose(0, 2, 3, 1))  # → NHWC
        train_x = np.concatenate(xs[:5])
        train_y = np.concatenate(ys[:5])
        return train_x, train_y, xs[5], ys[5]
    return synthetic_images(n_train=5000, n_test=1000, size=32,
                            channels=3, n_classes=10, seed=43)


def load_wine() -> tuple[np.ndarray, np.ndarray]:
    """The REAL UCI Wine dataset (178×13, 3 classes) — the reference's
    'hello world' functional workload (reference:
    ``znicz/samples/Wine``; its functional test asserted golden error
    counts on exactly this data).  scikit-learn bundles the csv inside
    the package, so no egress is needed.  Features are standardized
    (zero mean, unit variance) like the reference's wine loader did;
    falls back to a same-shape synthetic stand-in without sklearn."""
    try:
        from sklearn.datasets import load_wine as _sk_load_wine
    except ImportError:
        return _synthetic_wine()
    bunch = _sk_load_wine()
    data = bunch.data.astype(np.float32)
    data -= data.mean(axis=0)
    data /= data.std(axis=0) + 1e-8
    labels = bunch.target.astype(np.int32)
    rng = np.random.default_rng(170)
    order = rng.permutation(len(data))
    return data[order], labels[order]


def _synthetic_wine() -> tuple[np.ndarray, np.ndarray]:
    rng = np.random.default_rng(17)
    centers = rng.normal(0, 1, (3, 13))
    data = np.concatenate([
        c + 0.4 * rng.normal(size=(59, 13)) for c in centers
    ]).astype(np.float32)
    labels = np.repeat(np.arange(3), 59).astype(np.int32)
    order = rng.permutation(len(data))
    return data[order], labels[order]


def load_digits() -> tuple[np.ndarray, np.ndarray]:
    """Real handwritten digits (sklearn's bundled 1797×8×8 uint-valued
    UCI optdigits) — the offline real-image stand-in for MNIST golden
    -bound functional tests; same (x, y) contract as :func:`load_wine`.
    Pixels scaled to [0, 1]."""
    try:
        from sklearn.datasets import load_digits as _sk_load_digits
    except ImportError:
        x, y, _, _ = synthetic_images(n_train=1800, n_test=0, size=8,
                                      channels=0, n_classes=10, seed=45)
        return (x.reshape(len(x), -1).astype(np.float32) / 255.0,
                y.astype(np.int32))
    bunch = _sk_load_digits()
    data = (bunch.data / 16.0).astype(np.float32)
    labels = bunch.target.astype(np.int32)
    rng = np.random.default_rng(180)
    order = rng.permutation(len(data))
    return data[order], labels[order]


def mnist_is_real() -> bool:
    """True when ALL four real MNIST idx files are present on disk
    (the same condition under which :func:`load_mnist` uses them)."""
    names = ["train-images-idx3-ubyte", "train-labels-idx1-ubyte",
             "t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"]
    return all(
        os.path.exists(_dataset_path("mnist", name))
        or os.path.exists(_dataset_path("mnist", name + ".gz"))
        for name in names)


def synthetic_images(n_train: int, n_test: int, size: int, channels: int,
                     n_classes: int, seed: int,
                     dtype=np.uint8,
                     noise: float = 64.0) -> tuple[np.ndarray, np.ndarray,
                                                   np.ndarray, np.ndarray]:
    """Class-prototype images + noise, uint8, learnable but not
    trivial.  ``channels=0`` → (N, size, size) grayscale like MNIST.
    ``noise`` sets the per-pixel sigma around the prototypes — raise
    it to overlap the classes and give the task a nonzero Bayes error
    floor (convergence artifacts need validation error that neither
    saturates at zero nor stays at chance)."""
    rng = np.random.default_rng(seed)
    shape = (size, size) if channels == 0 else (size, size, channels)
    protos = rng.uniform(0, 255, size=(n_classes,) + shape)

    def make(n: int):
        per = n // n_classes
        xs, ys = [], []
        for c in range(n_classes):
            xs.append(np.clip(
                protos[c] + rng.normal(0, noise, size=(per,) + shape),
                0, 255))
            ys.append(np.full(per, c, dtype=np.int32))
        x = np.concatenate(xs).astype(dtype)
        y = np.concatenate(ys)
        order = rng.permutation(len(x))
        return x[order], y[order]

    train_x, train_y = make(n_train)
    test_x, test_y = make(n_test)
    return train_x, train_y, test_x, test_y


def synthetic_imagenet(n_samples: int, size: int = 227,
                       n_classes: int = 1000,
                       seed: int = 44) -> tuple[np.ndarray, np.ndarray]:
    """Throughput-bench stand-in for ImageNet: uint8 NHWC images with
    uniform random content (content does not affect step time)."""
    rng = np.random.default_rng(seed)
    x = rng.integers(0, 256, size=(n_samples, size, size, 3),
                     dtype=np.uint8)
    y = rng.integers(0, n_classes, size=n_samples).astype(np.int32)
    return x, y
