"""Benchmark: AlexNet training throughput (images/sec/chip) + MFU.

North star (BASELINE.json): stock ImageNet AlexNet StandardWorkflow at
≥8000 images/sec on a TPU v4-32 ⇒ 250 images/sec/chip.  This bench
runs the full training step (loader gather → forwards → softmax CE →
backward chain → SGD update, one fused XLA program) on one chip with
synthetic ImageNet-geometry data and reports ONE JSON line:

    {"metric": "alexnet_train_images_per_sec_per_chip",
     "value": <img/s>, "unit": "images/sec/chip",
     "vs_baseline": <img/s ÷ 250>, "mfu": <model-flops util>, ...}

Environment hardening: the TPU tunnel here is known-flaky — backend
init can raise UNAVAILABLE transiently or hang outright.  The bench
therefore (a) probes the backend in a watchdog thread with bounded
retries + backoff, (b) runs a global watchdog so a wedged RPC still
produces a machine-readable failure line (value 0 + "error" field)
instead of silence, and (c) fast-fails when no usable backend exists.

Knobs (env): BENCH_BATCH, BENCH_PRECISION (bfloat16|float32),
BENCH_TIMEOUT_S (global watchdog), BENCH_PROFILE=<dir> (where the
jax.profiler trace of the timed loop goes — ON by default into
profiles/bench_default at ~1-2% overhead for the device-resident
mode, OFF by default in stream mode where the trace thread competes
with the single-core decode pool; set BENCH_PROFILE="" to disable
everywhere), BENCH_PEAK_TFLOPS (override
chip peak for MFU), BENCH_INPUT=stream (feed through the streaming
FileImageLoader: real JPEG decode via the native C++ pool with
double-buffered prefetch, instead of the device-resident store —
measures the END-TO-END fed-at-rate number; synthetic JPEGs are
generated once under the cache dir).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

#: batch 768 is the round-5 measured sweet spot on v5e — the bf16
#: LRN-denominator + optimizer-state changes shifted the balance
#: upward from round 3's 384 (sweep in PERF.md round 5: 256→0.493,
#: 384→0.495-0.502, 512→0.488-0.510, 768→0.499-0.513, 1024→0.505)
BATCH = int(os.environ.get("BENCH_BATCH", "768"))
INPUT_MODE = os.environ.get("BENCH_INPUT", "resident")  # resident|stream
#: steps per device dispatch (lax.scan chunk; device-resident schedule).
#: 1 = per-step dispatch (round-2 behavior).  Streaming input is
#: host-fed per step, so stream mode forces 1.
CHUNK = max(1, int(os.environ.get("BENCH_CHUNK", "16")))
if INPUT_MODE == "stream":
    CHUNK = 1
#: canonical AlexNet geometry; smaller for smoke runs on slow backends
IMAGE_SIZE = int(os.environ.get("BENCH_IMAGE_SIZE", "227"))
#: bf16 matmul/conv inputs with f32 params+accumulation — the
#: MXU-native training mode (override: BENCH_PRECISION=float32)
PRECISION = os.environ.get("BENCH_PRECISION", "bfloat16")
#: BENCH_PALLAS=1 opts into every Pallas variant; a comma list of op
#: names (BENCH_PALLAS=dropout) opts in per-op — the in-graph A/B
#: lever (plain XLA is the measured winner — see PALLAS_BENCH.md).
#: Unknown op names are rejected loudly: a typo silently matching no
#: op would measure the XLA path while labelled as the Pallas arm.
_PALLAS_OPS = ("lrn", "dropout")
_pallas_env = os.environ.get("BENCH_PALLAS", "0")
_pallas_toks = [t for t in _pallas_env.replace(" ", "").split(",") if t]
if _pallas_toks and all(t in _PALLAS_OPS for t in _pallas_toks):
    PALLAS = _pallas_toks
elif any(c.isalpha() for c in _pallas_env):
    raise SystemExit(f"BENCH_PALLAS={_pallas_env!r}: expected 0/1 or "
                     f"a comma list of {_PALLAS_OPS}")
else:
    PALLAS = _pallas_env != "0"
#: BENCH_S2D=1 opts into the space-to-depth conv rewrite (A/B lever)
S2D = os.environ.get("BENCH_S2D", "0") != "0"
#: BENCH_WGRAD_IM2COL=1: conv1 weight grad as a patches GEMM (A/B
#: lever for the geometry-starved first-layer wgrad, PERF.md round 4)
WGRAD_IM2COL = os.environ.get("BENCH_WGRAD_IM2COL", "0") != "0"
#: BENCH_LRN_BAND_BF16=1: bf16 operands into the LRN band GEMMs (A/B
#: lever for the bandwidth-bound band adjoints, PERF.md round 4)
LRN_BAND_BF16 = os.environ.get("BENCH_LRN_BAND_BF16", "0") != "0"
#: BENCH_LRN_D_BF16: bf16 STORAGE for the shared LRN denominator
#: tensors (~1.5 GB/step of f32 traffic at b384 — PERF.md round 5,
#: measured +5.4%).  Unset = the engine's auto default (on in bf16
#: mode); 0/1 forces the A/B arm.
LRN_D_BF16 = os.environ.get("BENCH_LRN_D_BF16", "")
TIMEOUT_S = float(os.environ.get("BENCH_TIMEOUT_S", "900"))
#: default ON: every bench run leaves a local trace of the timed loop
#: (~3 MB; ~1-2% overhead) — perf numbers should never be
#: unexplainable.  The default path is GITIGNORED (profiles/ holds
#: regenerable binaries, not version-controlled evidence — the
#: decisions each trace drove live in PERF.md).  BENCH_PROFILE=""
#: disables; set a path to move (user paths are never cleaned).
#: ``--profile <dir>``: wrap the timed loop in
#: ``observe.profile_window`` — the dir receives the jax.profiler
#: device trace AND the window's host spans
#: (``host_spans.trace.json``), so every committed BENCH row can carry
#: a trace readable by ``benchmarks/trace_top.py <dir> <steps>
#: --spans <dir>``.  Unlike BENCH_PROFILE (env), the flag also
#: profiles on CPU and never cleans the target dir.
_PROFILE_FLAG = None
if "--profile" in sys.argv:
    _i = sys.argv.index("--profile")
    if _i + 1 >= len(sys.argv):
        raise SystemExit("--profile requires a directory argument")
    _PROFILE_FLAG = sys.argv[_i + 1]
PROFILE_DIR = _PROFILE_FLAG if _PROFILE_FLAG is not None else \
    os.environ.get(
        "BENCH_PROFILE",
        # stream mode is HOST-bound (single-core decode pool) and the
        # profiler competes for that core — measured 816 → 294 img/s
        # with default tracing on; only the device-resident mode
        # profiles by default
        "" if INPUT_MODE == "stream" else
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "profiles", "bench_default"))
WARMUP_STEPS = 6
TIMED_STEPS = 30
BASELINE_IMG_PER_SEC_PER_CHIP = 250.0  # 8000 img/s ÷ 32 chips (v4-32)
METRIC = "alexnet_train_images_per_sec_per_chip"
UNIT = "images/sec/chip"

#: bf16 MXU peak per chip, TFLOP/s, by device_kind substring (MFU is
#: reported against bf16 peak; f32 runs will show lower utilization)
PEAK_TFLOPS_BY_KIND = (
    ("v6", 918.0), ("v5p", 459.0), ("v5", 197.0),  # v5 lite / v5e
    ("v4", 275.0), ("v3", 123.0), ("v2", 45.0),
)


def emit(payload: dict) -> None:
    print(json.dumps(payload), flush=True)


def fail(error: str, rc: int = 1) -> None:
    """Always leave one parseable JSON line, even on a wedged backend."""
    emit({"metric": METRIC, "value": 0.0, "unit": UNIT,
          "vs_baseline": 0.0, "error": error,
          "batch": BATCH, "precision": PRECISION})
    # os._exit: a hung TPU RPC thread cannot be joined; don't try
    os._exit(rc)


def start_watchdog(seconds: float) -> None:
    timer = threading.Timer(
        seconds, fail,
        args=(f"watchdog: bench exceeded {seconds:.0f}s "
              f"(TPU tunnel wedged?)",))
    timer.daemon = True
    timer.start()


def init_backend(retries: int = 4, probe_timeout_s: float = 120.0):
    """jax.devices() behind a per-attempt timeout: transient
    UNAVAILABLE errors are retried with backoff; a hang (tunnel wedge)
    fails fast with a structured line rather than blocking forever."""
    import jax

    last_error = "no attempt made"
    for attempt in range(1, retries + 1):
        result: dict = {}

        def probe():
            try:
                result["devices"] = jax.devices()
            except Exception as exc:  # noqa: BLE001 — report any init error
                result["error"] = repr(exc)

        thread = threading.Thread(target=probe, daemon=True)
        thread.start()
        thread.join(probe_timeout_s)
        if thread.is_alive():
            fail(f"backend init hung >{probe_timeout_s:.0f}s on attempt "
                 f"{attempt} (TPU tunnel wedged)")
        if "devices" in result:
            return result["devices"]
        last_error = result.get("error", "unknown")
        if attempt < retries:
            time.sleep(min(5.0 * 2 ** (attempt - 1), 30.0))
    fail(f"backend init failed after {retries} attempts: {last_error}")


def peak_tflops(device) -> float:
    if "BENCH_PEAK_TFLOPS" in os.environ:
        return float(os.environ["BENCH_PEAK_TFLOPS"])
    kind = getattr(device, "device_kind", "").lower()
    for tag, tflops in PEAK_TFLOPS_BY_KIND:
        if tag in kind:
            return tflops
    return 275.0  # assume v4 (the north-star hardware) when unknown


def train_step_flops(wf) -> float:
    """Analytic AlexNet fwd+bwd FLOPs per step: 2·MACs for each conv /
    FC forward, ×3 for training (forward + input-grad + weight-grad
    are each one GEMM of the same volume).  Elementwise/pool/LRN ops
    are not counted (standard model-FLOPs accounting)."""
    import numpy as np

    flops_fwd = 0.0
    for unit in wf.forwards:
        weights = getattr(unit, "weights", None)
        if weights is None or not weights:
            continue
        if hasattr(unit, "kx"):  # conv: output NHWC, kernel kx·ky·Cin
            c_in = unit.input.shape[-1]
            flops_fwd += 2.0 * float(np.prod(unit.output.shape)) \
                * unit.kx * unit.ky * c_in
        else:  # fully-connected: one B×in → B×out GEMM
            batch = unit.output.shape[0]
            flops_fwd += 2.0 * batch * float(np.prod(weights.shape))
    return 3.0 * flops_fwd


def make_jpeg_tree(n_images: int, n_classes: int = 8,
                   hw: tuple = (256, 256)) -> str:
    """Synthetic class-per-subdir JPEG tree for the streaming mode,
    generated once under the cache dir (content doesn't matter for
    throughput; decode cost does)."""
    import numpy as np
    from PIL import Image

    from znicz_tpu.utils.config import root

    base = os.path.join(str(root.common.dirs.cache), "bench_jpegs",
                        f"{n_images}x{hw[0]}")
    marker = os.path.join(base, ".complete")
    if os.path.exists(marker):
        return base
    rng = np.random.default_rng(0)
    for i in range(n_images):
        cls_dir = os.path.join(base, f"class_{i % n_classes:03d}")
        os.makedirs(cls_dir, exist_ok=True)
        Image.fromarray(
            rng.integers(0, 256, size=hw + (3,), dtype=np.uint8)
        ).save(os.path.join(cls_dir, f"img_{i:05d}.jpg"), quality=90)
    with open(marker, "w") as fh:
        fh.write("ok")
    return base


def main() -> None:
    start_watchdog(TIMEOUT_S)
    # BENCH_PLATFORM/BENCH_CPU_DEVICES: pin a platform before the
    # first backend touch (the container's sitecustomize imports jax
    # at interpreter start, freezing env-derived config — this is the
    # only remaining lever, same pattern as __graft_entry__).  Lets
    # the 2-process bring-up below be exercised on CPU hosts.
    platform = os.environ.get("BENCH_PLATFORM")
    if platform:
        import jax as _jax
        n_cpu = int(os.environ.get("BENCH_CPU_DEVICES", "0"))
        if n_cpu:
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + f" --xla_force_host_platform_device_count"
                            f"={n_cpu}").strip()
        try:
            _jax.config.update("jax_platforms", platform)
            if n_cpu:
                _jax.config.update("jax_num_cpu_devices", n_cpu)
        except (RuntimeError, AttributeError):
            pass
    # pod-scale bring-up (env contract: ZNICZ_COORDINATOR /
    # ZNICZ_NUM_PROCESSES / ZNICZ_PROCESS_ID) — must run BEFORE the
    # first backend touch so jax.devices() is the GLOBAL list; a
    # single-process run is untouched
    from znicz_tpu.parallel.distributed import ensure_initialized
    is_distributed = ensure_initialized()
    devices = init_backend()
    if not devices:
        fail("no devices visible after backend init")
    platform = devices[0].platform
    # the environment's TPU tunnel plugin reports platform "axon"
    tpu_like = platform not in ("cpu", "gpu")

    from znicz_tpu.backends import XLADevice
    from znicz_tpu.models.samples import alexnet
    from znicz_tpu.utils.config import root

    root.common.precision_type = PRECISION
    root.common.engine.use_pallas = PALLAS
    root.common.engine.space_to_depth = S2D
    root.common.engine.conv_wgrad_im2col = WGRAD_IM2COL
    root.common.engine.lrn_band_bf16 = LRN_BAND_BF16
    if LRN_D_BF16:
        root.common.engine.lrn_d_bf16 = LRN_D_BF16 != "0"

    # dataset sized a whole number of chunks per epoch so a scanned
    # chunk never spans the epoch-boundary reshuffle (ceil to a
    # CHUNK multiple ≥ 8 steps)
    steps_per_epoch = max(1, -(-8 // CHUNK)) * CHUNK
    n_train = steps_per_epoch * BATCH
    streaming_dir = None
    if INPUT_MODE == "stream":
        streaming_dir = make_jpeg_tree(n_train)
    wf = alexnet.build(
        streaming_dir=streaming_dir,
        minibatch_size=BATCH,
        image_size=IMAGE_SIZE,
        n_train_samples=n_train,
        n_valid_samples=0,  # pure train steps for steady-state timing
        max_epochs=10 ** 6)
    if is_distributed:
        # SPMD over the global mesh: the batch shards over every
        # host's chips and XLA lays the gradient all-reduce over
        # ICI/DCN — the same workflow, unmodified
        from znicz_tpu.parallel import make_mesh
        device = XLADevice(mesh=make_mesh())
    else:
        device = XLADevice()
    wf.initialize(device=device)
    assert wf._region_unit is not None
    region_unit = wf._region_unit
    jit_region = region_unit.region  # the JitRegion (owns run_chunk)

    # round 18: supervisable pod bench — with the elastic heartbeat
    # channel configured (ZNICZ_HEARTBEAT_DIR), every process beats its
    # dispatch counter so the coordinator-side monitor (or an
    # ElasticSupervisor wrapping the bench) sees a hung chip as a
    # stalled step counter instead of a silent wedge
    from znicz_tpu.resilience.supervisor import (HeartbeatWriter,
                                                 worker_config)
    heartbeat = None
    hb_cfg = worker_config()
    if hb_cfg is not None:
        import jax as _jax
        heartbeat = HeartbeatWriter(hb_cfg["directory"],
                                    _jax.process_index()).start()
    dispatches = 0

    def step():
        """One dispatch: CHUNK scanned steps (device-resident
        schedule) or a single region step."""
        nonlocal dispatches
        if CHUNK > 1:
            for _ in range(CHUNK):
                wf.loader.run()   # host bookkeeping only (no uploads)
            jit_region.run_chunk(CHUNK)
        else:
            wf.loader.run()
            region_unit.run()
        dispatches += 1
        if heartbeat is not None:
            heartbeat.beat(dispatches)

    warmup_dispatches = max(1, WARMUP_STEPS // CHUNK)
    timed_dispatches = max(2, TIMED_STEPS // CHUNK)
    for _ in range(warmup_dispatches):
        step()
    wf.forwards[-1].weights.devmem.block_until_ready()

    profiling = bool(PROFILE_DIR) and (tpu_like
                                       or _PROFILE_FLAG is not None)
    from contextlib import nullcontext
    window = nullcontext()
    if profiling:
        if "BENCH_PROFILE" not in os.environ and _PROFILE_FLAG is None:
            # one trace per directory, DEFAULT path only: jax writes a
            # new timestamped subdir per run, which would grow without
            # bound under the default-on policy.  A user-supplied
            # --profile / BENCH_PROFILE dir is never cleaned — it may
            # hold prior results.
            import shutil

            shutil.rmtree(PROFILE_DIR, ignore_errors=True)
        from znicz_tpu import observe

        # device trace + the window's host spans in one capture dir
        window = observe.profile_window(
            PROFILE_DIR, n_steps=timed_dispatches * CHUNK)
    with window:
        start = time.perf_counter()
        for _ in range(timed_dispatches):
            step()
        wf.forwards[-1].weights.devmem.block_until_ready()
        elapsed = time.perf_counter() - start

    step_time = elapsed / (timed_dispatches * CHUNK)
    # per-chip normalization: under a mesh the global batch spread
    # over every chip, so chips divide out of both throughput and MFU
    n_chips = len(devices) if is_distributed else 1
    img_per_sec = BATCH / step_time / n_chips
    mfu = train_step_flops(wf) / step_time / n_chips \
        / (peak_tflops(devices[0]) * 1e12)
    if heartbeat is not None:
        heartbeat.stop()
    if is_distributed:
        import jax as _jax
        if _jax.process_index() != 0:
            os._exit(0)  # master owns the result line
    emit({
        "metric": METRIC,
        "value": round(img_per_sec, 2),
        "unit": UNIT,
        "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC_PER_CHIP, 4),
        "mfu": round(mfu, 4),
        "step_time_ms": round(step_time * 1e3, 3),
        "batch": BATCH,
        "precision": PRECISION,
        "input": INPUT_MODE,
        "chunk": CHUNK,
        "platform": platform,
        "device_kind": getattr(devices[0], "device_kind", "unknown"),
        "profile": PROFILE_DIR if profiling else None,
    })
    os._exit(0)  # don't wait on lingering TPU RPC threads


if __name__ == "__main__":
    main()
