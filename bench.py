"""Benchmark: AlexNet training throughput (images/sec/chip).

North star (BASELINE.json): stock ImageNet AlexNet StandardWorkflow at
≥8000 images/sec on a TPU v4-32 ⇒ 250 images/sec/chip.  This bench
runs the full training step (loader gather → forwards → softmax CE →
backward chain → SGD update, one fused XLA program) on one chip with
synthetic ImageNet-geometry data and reports

    {"metric": "alexnet_train_images_per_sec_per_chip",
     "value": <img/s>, "unit": "images/sec/chip",
     "vs_baseline": <img/s ÷ 250>}
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BATCH = int(os.environ.get("BENCH_BATCH", "128"))
#: bf16 matmul/conv inputs with f32 params+accumulation — the
#: MXU-native training mode (override: BENCH_PRECISION=float32)
PRECISION = os.environ.get("BENCH_PRECISION", "bfloat16")
WARMUP_STEPS = 6
TIMED_STEPS = 30
BASELINE_IMG_PER_SEC_PER_CHIP = 250.0  # 8000 img/s ÷ 32 chips (v4-32)


def main() -> None:
    from znicz_tpu.backends import XLADevice
    from znicz_tpu.models.samples import alexnet
    from znicz_tpu.utils.config import root

    root.common.precision_type = PRECISION

    wf = alexnet.build(
        minibatch_size=BATCH,
        n_train_samples=8 * BATCH,
        n_valid_samples=0,  # pure train steps for steady-state timing
        max_epochs=10 ** 6)
    wf.initialize(device=XLADevice())
    assert wf._region_unit is not None
    region = wf._region_unit

    def step():
        wf.loader.run()
        region.run()

    for _ in range(WARMUP_STEPS):
        step()
    wf.forwards[-1].weights.devmem.block_until_ready()

    start = time.perf_counter()
    for _ in range(TIMED_STEPS):
        step()
    wf.forwards[-1].weights.devmem.block_until_ready()
    elapsed = time.perf_counter() - start

    img_per_sec = TIMED_STEPS * BATCH / elapsed
    print(json.dumps({
        "metric": "alexnet_train_images_per_sec_per_chip",
        "value": round(img_per_sec, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC_PER_CHIP,
                             4),
    }))


if __name__ == "__main__":
    main()
