"""End-to-end functional test: the minimum slice of SURVEY.md §7 —
an MLP StandardWorkflow (All2AllTanh → All2AllSoftmax → evaluator →
GD chain → decision) trains to convergence on both backends, and the
XLA jit-region path matches the numpy oracle step-for-step
(reference pattern: ``znicz/tests/functional/test_wine.py``)."""

import numpy as np

from tests.conftest import make_blobs
from znicz_tpu.backends import NumpyDevice, XLADevice
from znicz_tpu.loader.fullbatch import ArrayLoader
from znicz_tpu.models.standard_workflow import StandardWorkflow
from znicz_tpu.utils import prng

N_CLASSES, DIM = 3, 10


def build(max_epochs, minibatch_size=20):
    data, labels = make_blobs(40, N_CLASSES, DIM)
    n_train = 90
    wf = StandardWorkflow(
        name="mlp",
        loader_factory=lambda w: ArrayLoader(
            w,
            train_data=data[:n_train], train_labels=labels[:n_train],
            valid_data=data[n_train:], valid_labels=labels[n_train:],
            minibatch_size=minibatch_size),
        layers=[
            {"type": "all2all_tanh",
             "->": {"output_sample_shape": 16},
             "<-": {"learning_rate": 0.1, "gradient_moment": 0.9}},
            {"type": "softmax",
             "->": {"output_sample_shape": N_CLASSES},
             "<-": {"learning_rate": 0.1, "gradient_moment": 0.9}},
        ],
        decision_config={"max_epochs": max_epochs})
    wf._max_fires = 100_000
    return wf


def test_numpy_backend_converges():
    wf = build(max_epochs=12)
    wf.initialize(device=NumpyDevice())
    wf.run()
    assert wf.decision.min_validation_n_err_pt <= 10.0


def test_xla_backend_converges_with_region():
    wf = build(max_epochs=12)
    wf.initialize(device=XLADevice())
    assert wf._region_unit is not None  # hot chain actually compiled
    wf.run()
    assert wf.decision.min_validation_n_err_pt <= 10.0
    # device-accumulated CE loss curve: populated and decreasing
    train_loss = wf.decision.epoch_loss[2]
    assert train_loss is not None and 0.0 < train_loss < 0.7


def test_xla_region_matches_numpy_oracle():
    """One epoch, identical seeds: the fused XLA program and the eager
    numpy chain must produce near-identical weights and identical
    error counts — the cross-backend invariant the reference's test
    suite was built on."""
    stats = {}
    for backend, device in (("np", NumpyDevice()), ("xla", XLADevice())):
        prng.seed_all(1234)
        # one epoch: XLA CPU thread-pool reassociation adds run-to-run
        # float noise that longer horizons amplify chaotically
        wf = build(max_epochs=1)
        wf.initialize(device=device)
        wf.run()
        for vec in (wf.forwards[0].weights, wf.forwards[1].weights):
            vec.map_read()
        stats[backend] = {
            "w0": wf.forwards[0].weights.mem.copy(),
            "w1": wf.forwards[1].weights.mem.copy(),
            "val_err": wf.decision.min_validation_n_err,
        }
    np.testing.assert_allclose(stats["np"]["w0"], stats["xla"]["w0"],
                               rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(stats["np"]["w1"], stats["xla"]["w1"],
                               rtol=1e-3, atol=1e-4)
    assert stats["np"]["val_err"] == stats["xla"]["val_err"]


def test_padded_last_minibatch():
    """Minibatch size that does not divide the class sizes: padding +
    valid-count masking must not corrupt training or error counts."""
    wf = build(max_epochs=6, minibatch_size=17)
    wf.initialize(device=XLADevice())
    wf.run()
    assert wf.decision.min_validation_n_err_pt <= 15.0
