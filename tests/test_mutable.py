import pytest

from znicz_tpu.mutable import Bool, LinkableAttribute


def test_bool_basic():
    b = Bool(False)
    assert not b
    b << True
    assert b
    b.value = False
    assert not b


def test_bool_derived_views_are_live():
    a = Bool(False)
    b = Bool(True)
    inv = ~a
    conj = a & b
    disj = a | b
    assert inv and not conj and disj
    a << True
    assert not inv and conj and disj


def test_bool_derived_is_readonly():
    a = Bool(False)
    inv = ~a
    with pytest.raises(ValueError):
        inv.value = True


def test_bool_on_true_callbacks():
    a = Bool(False)
    fired = []
    a.on_true.append(lambda: fired.append(1))
    a << True
    a << True  # no re-fire while already True
    a << False
    a << True
    assert fired == [1, 1]


def test_linkable_attribute_two_way():
    class Obj:
        pass
    src = Obj()
    src.output = 41
    link = LinkableAttribute(src, "output")
    assert link.get() == 41
    link.set(42)
    assert src.output == 42


def test_linkable_attribute_one_way():
    class Obj:
        pass
    src = Obj()
    src.output = 1
    link = LinkableAttribute(src, "output", two_way=False)
    with pytest.raises(AttributeError):
        link.set(2)
