"""Web status dashboard + interactive Shell tests (reference:
``veles/web_status.py`` Tornado UI, ``veles/interaction.py`` Shell)."""

import json
import urllib.error
import urllib.request


from znicz_tpu.backends import NumpyDevice
from znicz_tpu.models.samples.wine import build
from znicz_tpu.utils import prng
from znicz_tpu.web_status import WebStatusServer, gather_status


def _get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.read()


def test_web_status_serves_json_and_html():
    prng.seed_all(1)
    wf = build(max_epochs=2)
    wf.initialize(device=NumpyDevice())
    wf.run()

    server = WebStatusServer(port=0)
    try:
        server.register(wf)
        blob = json.loads(_get(
            f"http://127.0.0.1:{server.port}/status.json"))
        assert blob["uptime_s"] >= 0
        [status] = blob["workflows"]
        assert status["name"] == "wine"
        assert status["epoch"] >= 1
        assert status["complete"] is True
        assert status["backend"] == "numpy"
        assert 0 <= status["min_validation_n_err_pt"] <= 100
        assert status["slowest_units"]

        page = _get(f"http://127.0.0.1:{server.port}/").decode()
        assert "wine" in page and "uptime" in page

        # 404 for unknown paths
        try:
            _get(f"http://127.0.0.1:{server.port}/nope")
            assert False, "expected 404"
        except urllib.error.HTTPError as exc:
            assert exc.code == 404
    finally:
        server.stop()


def test_web_status_register_unregister():
    server = WebStatusServer(port=0)
    try:
        wf = build(max_epochs=1)
        server.register(wf)
        server.register(wf)  # idempotent
        assert len(server.status()["workflows"]) == 1
        server.unregister(wf)
        assert server.status()["workflows"] == []
    finally:
        server.stop()


def test_gather_status_mid_training():
    """Status is readable for an uninitialized workflow too."""
    wf = build(max_epochs=1)
    status = gather_status(wf)
    assert status["name"] == "wine" and not status["initialized"]


def test_launcher_starts_web_status():
    from znicz_tpu.launcher import Launcher

    launcher = Launcher(backend="numpy", web_status=0)
    launcher._load(build, max_epochs=1)
    launcher._main()
    assert launcher.web_server is not None
    try:
        blob = json.loads(_get(
            f"http://127.0.0.1:{launcher.web_server.port}/status.json"))
        assert blob["workflows"][0]["name"] == "wine"
    finally:
        launcher.web_server.stop()


def test_shell_unit_fires_with_namespace():
    prng.seed_all(2)
    wf = build(max_epochs=1)
    seen = {}

    def fake_interact(banner, local):
        seen["banner"] = banner
        seen["local"] = dict(local)

    shell = wf.link_shell(interact_fn=fake_interact)
    wf.initialize(device=NumpyDevice())
    wf.run()
    assert "workflow" in seen["local"]
    assert seen["local"]["workflow"] is wf
    assert "loader" in seen["local"] and "decision" in seen["local"]
    assert "wine" in seen["banner"]


def test_shell_disable_stops_firing():
    prng.seed_all(3)
    wf = build(max_epochs=3)
    calls = {"n": 0}

    def fake_interact(banner, local):
        calls["n"] += 1
        local["shell"].enabled = False  # user opts out from inside

    wf.link_shell(interact_fn=fake_interact)
    wf.initialize(device=NumpyDevice())
    wf.run()
    assert calls["n"] == 1
