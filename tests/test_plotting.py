"""Observability subsystem: graphics service, plotter units,
accumulators, confusion matrix, image saver (reference patterns:
``veles/plotting_units.py``, ``znicz/nn_plotting_units.py``,
``znicz/accumulator.py``, ``znicz/image_saver.py``)."""

import json
import os

import numpy as np
import pytest

from tests.conftest import make_blobs
from znicz_tpu.backends import NumpyDevice, XLADevice
from znicz_tpu.graphics import GraphicsClient, GraphicsServer
from znicz_tpu.loader.fullbatch import ArrayLoader
from znicz_tpu.models.standard_workflow import StandardWorkflow
from znicz_tpu.ops.accumulator import FixAccumulator, RangeAccumulator
from znicz_tpu.ops.nn_plotting_units import tile_filters

N_CLASSES, DIM = 3, 10


# ----------------------------------------------------------------------
# graphics service
# ----------------------------------------------------------------------
def test_server_renders_and_logs_all_kinds(tmp_path):
    srv = GraphicsServer(out_dir=str(tmp_path), render=True)
    srv.submit({"kind": "curve", "name": "err",
                "series": {"train": [[0, 1, 2], [3.0, 2.0, 1.0]]},
                "step": 2})
    srv.submit({"kind": "matrix", "name": "conf",
                "data": np.arange(9).reshape(3, 3)})
    srv.submit({"kind": "image", "name": "img",
                "data": np.random.rand(8, 8)})
    srv.submit({"kind": "hist", "name": "h",
                "data": np.array([1, 5, 2]),
                "bin_centers": np.array([0.1, 0.2, 0.3]),
                "bar_width": 0.05})
    srv.stop()
    for name in ("err", "conf", "img", "h"):
        assert os.path.exists(tmp_path / f"{name}.png"), name
    events = [json.loads(line)
              for line in open(tmp_path / "events.jsonl")]
    assert len(events) == 4
    assert events[0]["series"]["train"] == [[0, 1, 2], [3.0, 2.0, 1.0]]


def test_server_summarizes_large_arrays(tmp_path):
    srv = GraphicsServer(out_dir=str(tmp_path), render=False)
    srv.submit({"kind": "image", "name": "big",
                "data": np.ones((64, 64))})
    srv.stop()
    event = json.loads(open(tmp_path / "events.jsonl").read())
    assert event["data"] == {"shape": [64, 64], "min": 1.0, "max": 1.0,
                             "mean": 1.0}


def test_zmq_pub_sub_roundtrip(tmp_path):
    """Reference-parity live-viewer channel: PUB server → SUB client
    renders the payload in another 'process' (same process here)."""
    srv = GraphicsServer(out_dir=str(tmp_path / "srv"), render=False,
                         publish_port=0)  # random free port
    cli = GraphicsClient(srv.endpoint, out_dir=str(tmp_path / "cli"))
    import time
    time.sleep(0.2)  # PUB/SUB joining is async
    got = False
    for _ in range(20):
        srv.submit({"kind": "image", "name": "live",
                    "data": np.random.rand(4, 4)})
        if cli.poll_once(200):
            got = True
            break
    cli.close()
    srv.stop()
    assert got
    assert os.path.exists(tmp_path / "cli" / "live.png")


# ----------------------------------------------------------------------
# accumulators
# ----------------------------------------------------------------------
def test_fix_accumulator():
    acc = FixAccumulator(None, lo=0.0, hi=1.0, n_bins=10)
    acc.observe(np.array([0.05, 0.15, 0.15, 5.0, -3.0]))
    h = acc.histogram.mem
    assert h[0] == 2  # 0.05 and the clamped -3.0
    assert h[1] == 2
    assert h[-1] == 1  # clamped 5.0
    assert acc.n_observed == 5


def test_range_accumulator_rebins():
    acc = RangeAccumulator(None, n_bins=4)
    acc.observe(np.array([0.0, 1.0]))
    assert acc.x_min == 0.0 and acc.x_max == 1.0
    acc.observe(np.array([3.0]))  # widens → rebin all 3 samples
    assert acc.x_max == 3.0
    assert int(acc.histogram.mem.sum()) == 3
    assert acc.n_observed == 3


# ----------------------------------------------------------------------
# tile_filters
# ----------------------------------------------------------------------
def test_tile_filters_square_inference():
    w = np.random.rand(16, 6).astype(np.float32)  # 4×4 fields, 6 units
    img = tile_filters(w)
    side = int(np.ceil(np.sqrt(6)))
    assert img.shape == (side * 5 + 1, side * 5 + 1)
    assert img.max() <= 1.0 and img.min() >= 0.0


def test_tile_filters_conv_kernels():
    w = np.random.rand(3, 3, 3, 5).astype(np.float32)
    img = tile_filters(w)
    assert img.ndim == 3 and img.shape[-1] == 3  # RGB kernels stay RGB
    # non-displayable channel counts collapse to grayscale (imshow
    # accepts only 1/3/4 channels)
    img2 = tile_filters(np.random.rand(3, 3, 2, 5).astype(np.float32))
    assert img2.ndim == 2


# ----------------------------------------------------------------------
# end-to-end: plotters + image saver riding a training workflow
# ----------------------------------------------------------------------
def build(tmp_path, device_cls, max_epochs=3):
    data, labels = make_blobs(40, N_CLASSES, DIM)
    wf = StandardWorkflow(
        name="mlp_plot",
        loader_factory=lambda w: ArrayLoader(
            w, train_data=data[:90], train_labels=labels[:90],
            valid_data=data[90:], valid_labels=labels[90:],
            minibatch_size=30),
        layers=[
            {"type": "all2all_tanh", "->": {"output_sample_shape": 16},
             "<-": {"learning_rate": 0.1, "gradient_moment": 0.9}},
            {"type": "softmax", "->": {"output_sample_shape": N_CLASSES},
             "<-": {"learning_rate": 0.1, "gradient_moment": 0.9}},
        ],
        evaluator_config={"compute_confusion": True},
        decision_config={"max_epochs": max_epochs})
    wf._max_fires = 100_000
    srv = GraphicsServer(out_dir=str(tmp_path / "plots"), render=True)
    wf.link_error_plotter(server=srv)
    wf.link_confusion_plotter(server=srv)
    wf.link_weights_plotter(server=srv)
    wf.link_image_saver(out_dir=str(tmp_path / "images"), limit=16)
    wf.initialize(device=device_cls())
    return wf, srv


@pytest.mark.parametrize("device_cls", [NumpyDevice, XLADevice])
def test_workflow_observability(tmp_path, device_cls):
    wf, srv = build(tmp_path, device_cls)
    wf.run()
    srv.stop()
    # curves got one point per epoch per non-empty class
    xs, _ys = wf.error_plotter.values["train"]
    assert len(xs) == 3
    # confusion matrix: valid-class counts sum to the valid set size
    cm = wf.decision.confusion_matrixes[1]
    assert cm is not None and cm.sum() == 30
    # trace of the matrix = correct predictions = total - errors
    # (epoch_n_err is reset after each epoch; last_epoch_n_err holds
    # the final epoch's counts)
    assert np.trace(cm) == 30 - wf.decision.last_epoch_n_err[1]
    for png in ("error_plotter.png", "confusion_matrix.png",
                "weights2d_l0.png"):
        assert os.path.exists(tmp_path / "plots" / png), png
    events = [json.loads(line)
              for line in open(tmp_path / "plots" / "events.jsonl")]
    assert len(events) == 9  # 3 epochs × 3 plotters
    # image saver wrote misclassified PNGs for the last epoch
    img_root = tmp_path / "images"
    epochs = sorted(os.listdir(img_root))
    assert epochs, "no image-saver output"
    files = os.listdir(img_root / epochs[-1])
    for f in files:
        assert f.endswith(".png")
    # file count bounded by limit and consistent with naming scheme
    assert 0 < len(files) <= 16
    name = files[0][:-4]
    idx, t, p = name.split("_")
    assert t.startswith("t") and p.startswith("p") and int(idx) >= 0


def test_mse_decision_error_plotter(tmp_path):
    """The error plotter also rides MSE workflows (epoch_mse metric)."""
    data, _labels = make_blobs(40, N_CLASSES, DIM)
    wf = StandardWorkflow(
        name="ae_plot",
        loader_factory=lambda w: ArrayLoader(
            w, train_data=data[:90], valid_data=data[90:],
            minibatch_size=30),
        layers=[
            {"type": "all2all_tanh", "->": {"output_sample_shape": 8},
             "<-": {"learning_rate": 0.05}},
            {"type": "all2all", "->": {"output_sample_shape": DIM},
             "<-": {"learning_rate": 0.05}},
        ],
        loss="mse",
        decision_config={"max_epochs": 2})
    wf._max_fires = 100_000
    srv = GraphicsServer(out_dir=str(tmp_path / "plots"), render=False)
    wf.link_error_plotter(server=srv)
    wf.initialize(device=NumpyDevice())
    wf.run()
    srv.stop()
    xs, ys = wf.error_plotter.values["validation"]
    assert len(xs) == 2 and all(np.isfinite(ys))
