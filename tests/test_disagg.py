"""Disaggregated serving data plane (round 22).

Prefill and decode run in separate replica pools over private paged
caches, connected by a page-table handoff (DistServe/Splitwise); cold
prefix-cache pages spill HBM→host DRAM and restore through the pinned
staging ring.  The bars, all oracle-anchored:

- token IDENTITY: disagg ≡ fused ≡ step-by-step numpy oracle, bitwise
  on token ids — pools may only move bytes, never change a token;
- exactly-once accounting: every page refcount equals its holders and
  every token-budget reservation is released exactly once across
  submit → prefill → handoff → decode → spill/restore/COW/eviction,
  including under seeded handoff-drop chaos;
- compile-free scale: growing a pool warms ZERO new XLA programs (the
  replicas share one warmed DecodeModel).
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from tests.test_paged_decode import VOCAB, _params, oracle_greedy
from znicz_tpu.observe import metrics as obs_metrics
from znicz_tpu.resilience.faults import FaultInjected
from znicz_tpu.serving import DecodeEngine, DisaggEngine
from znicz_tpu.utils.config import root


@pytest.fixture(scope="module")
def lm_bundle(tmp_path_factory):
    from benchmarks.serve_bench import train_and_export_lm
    path = str(tmp_path_factory.mktemp("disagg") / "lm.npz")
    return train_and_export_lm(path, vocab=VOCAB, epochs=3)


@pytest.fixture()
def chaos_recipe():
    """Set a fault recipe for one test; always clear it after."""
    def set_recipe(recipe):
        root.common.engine.faults = recipe
    yield set_recipe
    root.common.engine.faults = None


def _assert_tiered_accounting(cache, prefix, tier):
    """The hierarchical exactly-once invariant: every pool page's
    refcount equals its holders (slot tables + page-resident trie
    pins), every trie block lives in EXACTLY one tier (device page
    XOR host frame), the free list holds no referenced page, and the
    host tier's occupancy equals the spilled node count."""
    free = cache._free_pages
    assert len(set(free)) == len(free), "double-freed page"
    refs = np.zeros(cache.pool_pages, np.int64)
    for slot in range(cache.max_slots):
        for pid in cache.tables[slot]:
            if int(pid) != cache.trash_page:
                refs[int(pid)] += 1
    hosted = 0
    stack = list(prefix.root.children.values()) if prefix else []
    while stack:
        node = stack.pop()
        assert (node.page is None) != (node.host is None), \
            "trie block in zero or two tiers"
        if node.page is not None:
            refs[node.page] += 1
        else:
            hosted += 1
        stack.extend(node.children.values())
    assert np.array_equal(refs, cache.ref), (refs, cache.ref)
    assert all(int(cache.ref[p]) == 0 for p in free)
    if tier is not None:
        assert hosted == tier.used, (hosted, tier.used)
    else:
        assert hosted == 0


def _assert_engine_drained(eng):
    """After every future resolved: slots and non-trie pages returned
    in BOTH pools, reservations balanced."""
    assert eng.balanced(), "token budget unbalanced"
    for w in eng.prefill_pool.engines():
        assert w.cache.free_slots == w.cache.max_slots
        _assert_tiered_accounting(w.cache, w.prefix, w._spill)
    for w in eng.decode_pool.engines():
        assert w.cache.free_slots == w.cache.max_slots
        assert w.cache.pages_used() == 0, "decode pages leaked"


# ----------------------------------------------------------------------
# the core contract: oracle-exact through the handoff
# ----------------------------------------------------------------------
def test_disagg_serves_oracle_exact_with_handoffs(lm_bundle):
    """Concurrent ragged prompts through prefill-pool → handoff →
    decode-pool come back oracle-exact, every prompt crosses the
    handoff exactly once, and both pools drain clean."""
    man, P = _params(lm_bundle)
    rng = np.random.default_rng(23)
    prompts = [rng.integers(0, VOCAB, size=int(n)).astype(np.int32)
               for n in rng.integers(2, 13, size=5)]
    with DisaggEngine(lm_bundle, max_slots=2, max_t=32, max_prompt=16,
                      prompt_align=4, max_new_tokens=6,
                      page_tokens=8) as eng:
        futs = [eng.submit(p) for p in prompts]
        results = [list(f.result(timeout=240)) for f in futs]
        st = eng.stats()
        _assert_engine_drained(eng)
        # per-pool queue-age gauges registered under this engine
        fam = obs_metrics.REGISTRY.get("znicz_serving_queue_age_seconds")
        pools = {k[1] for k, _c in fam.items() if k[0] == eng._obs_id}
        assert pools == {"prefill", "decode"}, pools
    for i, (p, got) in enumerate(zip(prompts, results)):
        assert got == oracle_greedy(man, P, p, 6), f"prompt {i}"
    assert st["engine"] == "decode-disagg"
    assert st["handoffs"]["total"] == len(prompts), st["handoffs"]
    assert st["handoffs"]["pages_moved"] >= len(prompts)
    assert st["served"] == len(prompts) and st["rejected"] == 0


@pytest.mark.slow
def test_disagg_token_identity_vs_fused_and_compile_free_scale(
        lm_bundle):
    """The interference-free claim's correctness half: the fused
    engine and the disaggregated engine emit BITWISE-identical greedy
    tokens over the same ragged mix; then the decode pool scales up
    mid-flight warming ZERO new XLA programs (replicas share one
    warmed DecodeModel) and the grown pool still matches."""
    rng = np.random.default_rng(31)
    prompts = [rng.integers(0, VOCAB, size=int(n)).astype(np.int32)
               for n in rng.integers(1, 14, size=10)]
    with DecodeEngine(lm_bundle, max_slots=2, max_t=32, max_prompt=16,
                      prompt_align=4, max_new_tokens=8,
                      page_tokens=8) as eng:
        fused = [list(eng.generate(p, timeout=240)) for p in prompts]
    with DisaggEngine(lm_bundle, max_slots=2, max_t=32, max_prompt=16,
                      prompt_align=4, max_new_tokens=8, page_tokens=8,
                      decode_replicas=2) as eng:
        futs = [eng.submit(p) for p in prompts]
        got = [list(f.result(timeout=240)) for f in futs]
        assert got == fused, "disaggregation changed tokens"
        compiled = eng.stats()["programs_compiled"]
        eng.decode_pool.scale_to(3, "test")
        eng.prefill_pool.scale_to(2, "test")
        futs = [eng.submit(p) for p in prompts]
        regen = [list(f.result(timeout=240)) for f in futs]
        assert regen == fused, "scale-up changed tokens"
        assert eng.stats()["programs_compiled"] == compiled, \
            "pool scale-up compiled a new program"
        assert eng.stats()["pools"]["decode"]["live"] == 3
        _assert_engine_drained(eng)


# ----------------------------------------------------------------------
# handoff-drop chaos: retry on a fresh prefill, exactly-once budget
# ----------------------------------------------------------------------
def test_handoff_drop_retried_on_fresh_prefill(lm_bundle,
                                               chaos_recipe):
    """A dropped handoff re-queues the request at the FRONT with its
    reservation kept; the fresh prefill (a prefix hit — the trie kept
    the blocks) hands off again and the tokens are unchanged."""
    man, P = _params(lm_bundle)
    chaos_recipe({"disagg.handoff_drop": {"at": [1]}})
    prompt = np.arange(10, dtype=np.int32) % VOCAB
    with DisaggEngine(lm_bundle, max_slots=2, max_t=32, max_prompt=16,
                      prompt_align=4, max_new_tokens=5,
                      page_tokens=8) as eng:
        got = list(eng.generate(prompt, timeout=240))
        st = eng.stats()
        _assert_engine_drained(eng)
    assert got == oracle_greedy(man, P, prompt, 5), \
        "the retried request changed tokens"
    assert st["handoffs"] == {"total": 1, "dropped": 1, "retried": 1,
                              "pages_moved": st["handoffs"]
                              ["pages_moved"]}
    assert st["served"] == 1 and st["rejected"] == 0
    assert st["prefix_cache"]["hits"] >= 1, \
        "the retry re-computed what the trie already held"


def test_handoff_drop_past_budget_rejects_balanced(lm_bundle,
                                                   chaos_recipe):
    """Every retry dropped too: the request fails with FaultInjected,
    the reservation is released exactly once, and both pools come
    back clean — no page leaked across the dropped transfers."""
    chaos_recipe({"disagg.handoff_drop": {"after": 1}})  # persistent
    with DisaggEngine(lm_bundle, max_slots=2, max_t=32, max_prompt=16,
                      prompt_align=4, max_new_tokens=5, page_tokens=8,
                      handoff_retry_budget=1) as eng:
        fut = eng.submit(np.arange(6, dtype=np.int32) % VOCAB)
        with pytest.raises(FaultInjected, match="retry budget"):
            fut.result(timeout=240)
        st = eng.stats()
        _assert_engine_drained(eng)
    assert st["handoffs"]["dropped"] == 2  # first + the one retry
    assert st["handoffs"]["retried"] == 1
    assert st["rejected"] == 1 and st["served"] == 0


# ----------------------------------------------------------------------
# hierarchical prefix cache: spill → restore, exactly-once
# ----------------------------------------------------------------------
@pytest.mark.slow
def test_spill_restore_exactly_once_token_identical(lm_bundle):
    """Working set >> HBM pool: cold trie pages spill to the host
    tier and restore through the staging ring on re-match.  The spill
    arm must match the all-HBM arm's hit count AND tokens bitwise,
    with restores actually exercised and the tiered accounting exact
    at every checkpoint."""
    rng = np.random.default_rng(41)
    families = [rng.integers(0, VOCAB, size=16).astype(np.int32)
                for _ in range(12)]
    prompts = []
    for _ in range(2):  # two sweeps: sweep 2 re-matches spilled pages
        for f in families:
            prompts.append(np.concatenate(
                [f, rng.integers(0, VOCAB, size=4).astype(np.int32)]))
    with DecodeEngine(lm_bundle, max_slots=2, max_t=32, max_prompt=24,
                      prompt_align=4, max_new_tokens=4, page_tokens=8,
                      pool_tokens=2048) as eng:
        base = [list(eng.generate(p, timeout=240)) for p in prompts]
        hbm = eng.stats()["prefix_cache"]
    with DecodeEngine(lm_bundle, max_slots=2, max_t=32, max_prompt=24,
                      prompt_align=4, max_new_tokens=4, page_tokens=8,
                      pool_tokens=160, spill_pages=64) as eng:
        got = []
        for p in prompts:
            got.append(list(eng.generate(p, timeout=240)))
            _assert_tiered_accounting(eng.model.cache, eng.prefix,
                                      eng._spill)
        st = eng.stats()["prefix_cache"]
        # the spill→restore cycle moved real pages both ways
        assert st["migrations"]["spill"] > 0, st
        assert st["migrations"]["restore"] > 0, st
        assert st["spill_pages_used"] == st["spilled_nodes"]
        # capacity math: the 20-page pool alone could never pin the
        # 12-family × 2-block working set the hierarchy served
        assert eng.model.cache.pool_pages < 2 * len(families)
        # hit parity: spilling must not cost matches (the bar is
        # equality here; the ISSUE tolerance is 10%)
        assert st["hits"] == hbm["hits"], (st, hbm)
        # a hierarchical clear (the swap path) empties BOTH tiers
        eng.prefix.clear(eng.model.cache, tier=eng._spill)
        assert eng.model.cache.pages_used() == 0
        assert eng._spill.used == 0
    assert got == base, "the spill tier changed tokens"


@pytest.mark.slow
def test_disagg_spill_cow_eviction_chaos_accounting(lm_bundle,
                                                    chaos_recipe):
    """The full gauntlet on one engine: prefix sharing with COW
    forks, pool pressure driving spill AND eviction, handoff-drop
    chaos mid-stream — every request oracle-exact, every page and
    every reservation accounted exactly once when the dust settles."""
    man, P = _params(lm_bundle)
    chaos_recipe({"disagg.handoff_drop": {"at": [2, 5]}})
    rng = np.random.default_rng(53)
    families = [rng.integers(0, VOCAB, size=16).astype(np.int32)
                for _ in range(6)]
    prompts = []
    for _ in range(2):
        for f in families:
            fork = f.copy()
            fork[12:] = (fork[12:] + 1) % VOCAB  # COW off block 1
            prompts.extend([f, fork])
    with DisaggEngine(lm_bundle, max_slots=2, max_t=32, max_prompt=16,
                      prompt_align=4, max_new_tokens=4, page_tokens=8,
                      pool_tokens=128, spill_pages=8,
                      handoff_retry_budget=2) as eng:
        results = [list(eng.generate(p, timeout=240)) for p in prompts]
        st = eng.stats()
        _assert_engine_drained(eng)
    for i, (p, got) in enumerate(zip(prompts, results)):
        assert got == oracle_greedy(man, P, p, 4), f"prompt {i}"
    assert st["handoffs"]["dropped"] == 2
    assert st["handoffs"]["retried"] == 2
    assert st["served"] == len(prompts) and st["rejected"] == 0
    pc = st["prefix_cache"]
    assert pc["migrations"]["spill"] > 0, pc
    assert pc["hits"] > 0, pc


# ----------------------------------------------------------------------
# per-pool autoscaling: repair + growth from queue age
# ----------------------------------------------------------------------
@pytest.mark.slow
def test_pool_autoscaler_repairs_dead_decode_replica(lm_bundle):
    """A decode replica dies mid-service: the PoolAutoscaler's repair
    pass respawns it (compile-free — shared warmed model) and traffic
    keeps serving oracle-exact."""
    man, P = _params(lm_bundle)
    prompt = np.arange(8, dtype=np.int32) % VOCAB
    with DisaggEngine(lm_bundle, max_slots=2, max_t=32, max_prompt=16,
                      prompt_align=4, max_new_tokens=5, page_tokens=8,
                      decode_replicas=2, autoscale=True) as eng:
        assert list(eng.generate(prompt, timeout=240)) \
            == oracle_greedy(man, P, prompt, 5)
        compiled = eng.stats()["programs_compiled"]
        eng.decode_pool.kill_one()
        deadline = time.monotonic() + 20
        while eng.decode_pool.live() < 2 \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert eng.decode_pool.live() == 2, "repair never happened"
        assert eng.stats()["programs_compiled"] == compiled, \
            "the respawned replica compiled"
        assert list(eng.generate(prompt, timeout=240)) \
            == oracle_greedy(man, P, prompt, 5)
        _assert_engine_drained(eng)
