"""Autoregressive decode serving (round 12): KV-cache correctness,
prefill/decode AOT split, continuous token batching.

The ground truth everywhere is a step-by-step **full-forward numpy
oracle**: at every generated position it re-runs the whole causal
chain over the entire sequence so far (no cache, no incremental
state) and takes the argmax.  The engine — incremental KV-cache
attention, masked LSTM carries, bucketed prefill padding, scratch-slot
padded decode lanes — must reproduce the oracle's token ids EXACTLY
(integers, so equality is bitwise).
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from znicz_tpu.export import ExportedModel
from znicz_tpu.observe import metrics as obs_metrics
from znicz_tpu.ops.pos_encoding import sinusoid_table
from znicz_tpu.serving import (DecodeEngine, DecodeModel, Overloaded,
                               QueueFull)
from znicz_tpu.serving.batcher import DeadlineExceeded


@pytest.fixture(autouse=True)
def _no_aot_cache():
    """This module pins compile-count baselines (``compile_count``,
    warm-ladder deltas).  Under the opt-in suite AOT cache
    (``ZNICZ_TEST_AOT_CACHE``) warmed programs deserialize instead of
    compiling and those counts legitimately go to zero — so opt out
    and always exercise the real tracing path."""
    from znicz_tpu.utils.config import root
    root.common.engine.aot_cache = False
    yield

VOCAB = 12


# ----------------------------------------------------------------------
# trained bundles (one training run per module, not per test)
# ----------------------------------------------------------------------
@pytest.fixture(scope="module")
def lm_bundle(tmp_path_factory):
    """Tiny attention LM: embedding → pos_encoding → causal attention
    → last_token → softmax."""
    from benchmarks.serve_bench import train_and_export_lm
    path = str(tmp_path_factory.mktemp("decode") / "lm.npz")
    return train_and_export_lm(path, vocab=VOCAB, epochs=3)


@pytest.fixture(scope="module")
def rnn_bundle(tmp_path_factory):
    """Tiny LSTM LM: embedding → lstm(return_sequence=False) →
    softmax (the carry doubles as the sequence→sample bridge)."""
    from znicz_tpu.backends import XLADevice
    from znicz_tpu.loader.fullbatch import ArrayLoader
    from znicz_tpu.models.standard_workflow import StandardWorkflow
    from znicz_tpu.utils import prng

    path = str(tmp_path_factory.mktemp("decode") / "rnn_lm.npz")
    rng = np.random.default_rng(3)
    data = rng.integers(0, VOCAB, size=(128, 6)).astype(np.float32)
    labels = (data[:, -1].astype(np.int32) + 1) % VOCAB
    prng.seed_all(7)
    wf = StandardWorkflow(
        name="tiny_rnn_lm",
        loader_factory=lambda w: ArrayLoader(
            w, train_data=data[:96], train_labels=labels[:96],
            valid_data=data[96:], valid_labels=labels[96:],
            minibatch_size=32),
        layers=[
            {"type": "embedding",
             "->": {"vocab_size": VOCAB, "dim": 12},
             "<-": {"learning_rate": 0.1}},
            {"type": "lstm", "->": {"units": 20},
             "<-": {"learning_rate": 0.1}},
            {"type": "softmax", "->": {"output_sample_shape": VOCAB},
             "<-": {"learning_rate": 0.1}},
        ],
        decision_config={"max_epochs": 2})
    wf._max_fires = 10 ** 6
    wf.initialize(device=XLADevice())
    wf.run()
    wf.export_forward(path)
    return path


def _params(bundle):
    import json
    with np.load(bundle) as b:
        manifest = json.loads(bytes(b["manifest"]).decode())
        params = {k: np.array(b[k]) for k in b.files if k != "manifest"}
    return manifest, params


# ----------------------------------------------------------------------
# numpy oracles: full forward over the whole sequence, every step
# ----------------------------------------------------------------------
def attn_oracle_logits(man, P, seq):
    ids = np.asarray(seq, np.int32)
    x = P["layer0_weights"][ids][None].astype(np.float32)
    t, d = x.shape[1], x.shape[2]
    x = x + sinusoid_table(t, d)
    qkv = x.reshape(t, d) @ P["layer2_weights"] + P["layer2_bias"]
    h = man["layers"][2]["config"]["n_heads"]
    dh = d // h
    qkv = qkv.reshape(1, t, 3 * d)
    q = qkv[..., :d].reshape(1, t, h, dh)
    k = qkv[..., d:2 * d].reshape(1, t, h, dh)
    v = qkv[..., 2 * d:].reshape(1, t, h, dh)
    s = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(dh)
    mask = np.arange(t)[:, None] >= np.arange(t)[None, :]
    s = np.where(mask[None, None], s, -1e30)
    s = s - s.max(-1, keepdims=True)
    p = np.exp(s)
    p /= p.sum(-1, keepdims=True)
    o = np.einsum("bhqk,bkhd->bqhd", p, v)
    y = o.reshape(t, d) @ P["layer2_weights_out"] + P["layer2_bias_out"]
    feat = y.reshape(t, d)[-1]
    return feat @ P["layer4_weights"] + P["layer4_bias"]


def lstm_oracle_logits(man, P, seq):
    def sig(z):
        return 1.0 / (1.0 + np.exp(-z))

    w, b = P["layer1_weights"], P["layer1_bias"]
    hsz = w.shape[1] // 4
    h = np.zeros((1, hsz), np.float32)
    c = np.zeros((1, hsz), np.float32)
    for t in seq:
        x = P["layer0_weights"][int(t)][None].astype(np.float32)
        z = np.concatenate([x, h], 1) @ w + b
        i, f = sig(z[:, :hsz]), sig(z[:, hsz:2 * hsz])
        g, o = np.tanh(z[:, 2 * hsz:3 * hsz]), sig(z[:, 3 * hsz:])
        c = f * c + i * g
        h = o * np.tanh(c)
    return (h @ P["layer2_weights"] + P["layer2_bias"])[0]


def oracle_greedy(logits_fn, man, P, prompt, n):
    seq = [int(t) for t in prompt]
    out = []
    for _ in range(n):
        tok = int(np.argmax(logits_fn(man, P, seq)))
        out.append(tok)
        seq.append(tok)
    return out


# ----------------------------------------------------------------------
# manifest metadata (satellite: export round-trip)
# ----------------------------------------------------------------------
def test_manifest_records_kind_and_sequence(lm_bundle):
    man, _ = _params(lm_bundle)
    assert man["kind"] == "lm"
    seq = man["sequence"]
    assert seq["vocab"] == VOCAB and seq["train_t"] == 8
    assert seq["cache"] == [{"layer": 2, "kind": "attention",
                             "heads": 2, "head_dim": 8,
                             "features": 16}]
    model = ExportedModel.load(lm_bundle)
    assert model.kind == "lm" and model.sequence == seq


def test_manifest_scorer_kind(tmp_path):
    from benchmarks.serve_bench import train_and_export
    path = str(tmp_path / "fc.npz")
    train_and_export(path, epochs=1)
    model = ExportedModel.load(path)
    assert model.kind == "scorer" and model.sequence is None
    with pytest.raises(ValueError, match="'scorer'"):
        DecodeModel(model)


def test_legacy_bundle_rederives_metadata(lm_bundle):
    """A pre-round-12 bundle (no kind/sequence keys) must decode
    unchanged — the metadata re-derives from the layer table (the
    round-8 dtype-default pattern)."""
    man, params = _params(lm_bundle)
    legacy = {k: v for k, v in man.items()
              if k not in ("kind", "sequence")}
    model = ExportedModel(legacy, params)
    assert model.kind == "lm"
    assert model.sequence["vocab"] == VOCAB
    assert model.sequence["cache"][0]["kind"] == "attention"
    with DecodeEngine(model, max_slots=2, max_t=32, max_prompt=8,
                      prompt_align=4, max_new_tokens=4) as eng:
        got = list(eng.generate(np.array([1, 2, 3]), timeout=120))
    want = oracle_greedy(attn_oracle_logits, man, params, [1, 2, 3], 4)
    assert got == want


def test_lstm_sequence_metadata(rnn_bundle):
    model = ExportedModel.load(rnn_bundle)
    assert model.kind == "lm"
    assert model.sequence["cache"] == [
        {"layer": 1, "kind": "lstm", "hidden": 20}]


# ----------------------------------------------------------------------
# greedy decode ≡ numpy oracle, bitwise on token ids
# ----------------------------------------------------------------------
def test_greedy_attention_engine_vs_oracle(lm_bundle):
    man, P = _params(lm_bundle)
    with DecodeEngine(lm_bundle, max_slots=4, max_t=32, max_prompt=16,
                      prompt_align=4, max_new_tokens=8) as eng:
        for plen in (1, 3, 5, 11):
            prompt = (np.arange(plen) * 3) % VOCAB
            got = list(eng.generate(prompt, timeout=120))
            want = oracle_greedy(attn_oracle_logits, man, P, prompt, 8)
            assert got == want, f"prompt len {plen}"


def test_greedy_lstm_engine_vs_oracle(rnn_bundle):
    man, P = _params(rnn_bundle)
    with DecodeEngine(rnn_bundle, max_slots=2, max_t=32, max_prompt=8,
                      prompt_align=4, max_new_tokens=6) as eng:
        for plen in (1, 4, 7):
            prompt = (np.arange(plen) * 2 + 1) % VOCAB
            got = list(eng.generate(prompt, timeout=120))
            want = oracle_greedy(lstm_oracle_logits, man, P, prompt, 6)
            assert got == want, f"prompt len {plen}"


def test_continuous_admission_matches_sequential_oracle(lm_bundle):
    """More prompts than slots, submitted at once: admission happens
    MID-decode of earlier sequences, lanes sit at ragged depths, and
    every result must still equal the one-at-a-time oracle."""
    man, P = _params(lm_bundle)
    rng = np.random.default_rng(17)
    prompts = [rng.integers(0, VOCAB, size=int(n)).astype(np.int32)
               for n in rng.integers(1, 13, size=10)]
    budgets = [int(b) for b in rng.integers(3, 12, size=10)]
    with DecodeEngine(lm_bundle, max_slots=3, max_t=32, max_prompt=16,
                      prompt_align=4) as eng:
        futs = [eng.submit(p, max_new_tokens=b)
                for p, b in zip(prompts, budgets)]
        results = [list(f.result(timeout=240)) for f in futs]
    for i, (p, b, got) in enumerate(zip(prompts, budgets, results)):
        want = oracle_greedy(attn_oracle_logits, man, P, p, b)
        assert got == want, f"prompt {i} diverged under admission"


def test_static_admission_same_tokens(lm_bundle):
    """Run-to-completion scheduling (the serve_bench A/B arm) changes
    timing, never tokens."""
    man, P = _params(lm_bundle)
    prompts = [np.array([2, 5]), np.array([7]), np.array([1, 2, 3, 4]),
               np.array([9, 0, 4])]
    with DecodeEngine(lm_bundle, max_slots=2, max_t=32, max_prompt=8,
                      prompt_align=4, max_new_tokens=6,
                      admission="static") as eng:
        futs = [eng.submit(p) for p in prompts]
        for p, f in zip(prompts, futs):
            got = list(f.result(timeout=240))
            assert got == oracle_greedy(attn_oracle_logits, man, P,
                                        p, 6)


# ----------------------------------------------------------------------
# cache-slot lifecycle
# ----------------------------------------------------------------------
def test_slot_reuse_after_eviction_is_clean(lm_bundle):
    """A slot's stale rows from a LONG previous tenant must be
    unreachable for the next (shorter) one: prefill overwrites the
    live prefix and the decode mask hides everything past ``pos``."""
    man, P = _params(lm_bundle)
    long_p = (np.arange(14) * 5) % VOCAB
    short_p = np.array([4, 1])
    with DecodeEngine(lm_bundle, max_slots=1, max_t=32, max_prompt=16,
                      prompt_align=4, max_new_tokens=10) as eng:
        first = list(eng.generate(long_p, timeout=240))
        second = list(eng.generate(short_p, timeout=240))
    assert first == oracle_greedy(attn_oracle_logits, man, P,
                                  long_p, 10)
    assert second == oracle_greedy(attn_oracle_logits, man, P,
                                   short_p, 10), \
        "slot reuse leaked the previous tenant's cache rows"


def test_eos_evicts_slot(lm_bundle):
    man, P = _params(lm_bundle)
    prompt = np.array([3, 4, 5])
    full = oracle_greedy(attn_oracle_logits, man, P, prompt, 8)
    # an eos value whose FIRST occurrence is mid-stream, so the stop
    # point is unambiguous
    idx = next((i for i in range(1, len(full))
                if full[i] not in full[:i]), 0)
    eos = full[idx]
    with DecodeEngine(lm_bundle, max_slots=2, max_t=32, max_prompt=8,
                      prompt_align=4, max_new_tokens=8,
                      eos_token=eos) as eng:
        got = list(eng.generate(prompt, timeout=240))
        assert got == full[:idx + 1]
        assert eng.model.cache.free_slots == 2  # evicted


def test_max_t_page_boundary_force_finishes(lm_bundle):
    """A sequence hitting the bucketed max-T page is force-finished
    (truncated), never writes past the page."""
    with DecodeEngine(lm_bundle, max_slots=1, max_t=16, max_prompt=8,
                      prompt_align=4, max_new_tokens=1000) as eng:
        prompt = np.array([1, 2, 3, 4, 5])
        got = eng.generate(prompt, timeout=240)
    # positions prompt..max_t-1 hold generated inputs; the final
    # sampled token is never written back, so budget = max_t - len + 1
    assert len(got) == 16 - 5 + 1


def test_sampled_continuations_seeded(lm_bundle):
    """temperature > 0: same seed → same continuation, tokens in
    vocab; different seed → (almost surely) different continuation."""
    prompt = np.array([6, 7])

    def gen(seed):
        with DecodeEngine(lm_bundle, max_slots=1, max_t=32,
                          max_prompt=8, prompt_align=4,
                          max_new_tokens=12, temperature=1.0,
                          seed=seed) as eng:
            return list(eng.generate(prompt, timeout=240))

    a, b, c = gen(5), gen(5), gen(6)
    assert a == b
    assert all(0 <= t < VOCAB for t in a)
    assert a != c  # 12 draws over 12 tokens: collision ~impossible


# ----------------------------------------------------------------------
# retrace guard: ZERO compiles per warmed decode token
# ----------------------------------------------------------------------
def test_warmed_decode_loop_zero_compiles(lm_bundle):
    """The acceptance-bar pin: after warmup (both program families
    compiled), an arbitrary ragged generation mix adds ZERO entries to
    ``znicz_xla_compiles_total`` — no compile per token, per prompt
    length, per live-batch size."""
    prefill_c = obs_metrics.xla_compiles("serving-prefill")
    decode_c = obs_metrics.xla_compiles("serving-decode")
    with DecodeEngine(lm_bundle, max_slots=4, max_t=32, max_prompt=16,
                      prompt_align=4, max_new_tokens=9) as eng:
        # warmup compiled the WHOLE grid (prompt × block buckets for
        # prefill, batch × block buckets for paged decode) and nothing
        # else has: the live-program census IS the warmup count
        assert eng.warmup_compiles == eng.model.programs_live
        before = prefill_c.value + decode_c.value
        rng = np.random.default_rng(4)
        futs = [eng.submit(rng.integers(0, VOCAB, size=int(n)))
                for n in rng.integers(1, 16, size=9)]
        tokens = sum(len(f.result(timeout=240)) for f in futs)
        assert tokens >= 9 * 9
        assert prefill_c.value + decode_c.value == before, \
            "a warmed decode loop compiled a new XLA program"
        assert eng.stats()["programs_compiled"] == eng.warmup_compiles


# ----------------------------------------------------------------------
# resilience: TTFT deadline + breaker drain semantics
# ----------------------------------------------------------------------
def test_ttft_deadline_evicts_queued_prompt(lm_bundle):
    """deadline_ms bounds TIME-TO-FIRST-TOKEN: a prompt still queued
    when it passes fails fast and never occupies a slot; prompts
    without deadlines are untouched."""
    gate = threading.Event()
    with DecodeEngine(lm_bundle, max_slots=1, max_t=32, max_prompt=8,
                      prompt_align=4, max_new_tokens=4) as eng:
        real_prefill = eng.model.run_prefill

        def slow_prefill(tokens, slot, start=0):
            gate.wait(timeout=30)
            return real_prefill(tokens, slot, start)

        eng.model.run_prefill = slow_prefill
        blocker = eng.submit(np.array([1]))      # holds the scheduler
        doomed = eng.submit(np.array([2]), deadline_ms=30.0)
        survivor = eng.submit(np.array([3]))
        time.sleep(0.15)                         # deadline passes
        gate.set()
        with pytest.raises(DeadlineExceeded):
            doomed.result(timeout=60)
        assert blocker.result(timeout=120).shape == (4,)
        assert survivor.result(timeout=120).shape == (4,)
        assert eng.stats()["resilience"]["expired"] == 1


def test_breaker_sheds_new_prompts_while_inflight_drains(lm_bundle):
    """The drain contract: an OPEN breaker rejects new prompts with
    Overloaded, but sequences already generating run to completion."""
    with DecodeEngine(lm_bundle, max_slots=2, max_t=128, max_prompt=8,
                      prompt_align=4, max_new_tokens=600,
                      breaker_cooldown_ms=60_000.0) as eng:
        inflight = eng.submit(np.array([5, 6]))  # ~125-token runway
        time.sleep(0.01)                          # let it go live
        for _ in range(eng._outcomes.maxlen):     # force the trip
            eng._record_outcome(False)
        assert eng.breaker_state == "open"
        with pytest.raises(Overloaded):
            eng.submit(np.array([1]))
        assert eng.stats()["resilience"]["shed"] == 1
        out = inflight.result(timeout=300)        # drained, not killed
        assert len(out) > 0
        assert not eng.ready()


def test_breaker_opens_on_consecutive_prefill_failures(lm_bundle):
    """Organic trip: consecutive failed dispatches (injected prefill
    errors) open the breaker; the cooldown half-opens it and a
    healthy probe closes it again."""
    with DecodeEngine(lm_bundle, max_slots=1, max_t=32, max_prompt=8,
                      prompt_align=4, max_new_tokens=3,
                      retry_budget=0, breaker_window=4,
                      breaker_min_samples=4,
                      breaker_cooldown_ms=50.0) as eng:
        real_prefill = eng.model.run_prefill
        boom = {"on": True}

        def flaky_prefill(tokens, slot, start=0):
            if boom["on"]:
                raise RuntimeError("injected prefill failure")
            return real_prefill(tokens, slot, start)

        eng.model.run_prefill = flaky_prefill
        futs = [eng.submit(np.array([i + 1])) for i in range(4)]
        for f in futs:
            with pytest.raises(RuntimeError):
                f.result(timeout=60)
        deadline = time.monotonic() + 10
        while eng.breaker_state != "open" \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.breaker_state == "open"
        boom["on"] = False
        time.sleep(0.08)                 # cooldown → half-open probe
        deadline = time.monotonic() + 10
        tokens = None
        while time.monotonic() < deadline:
            try:
                tokens = eng.generate(np.array([3]), timeout=60)
                break
            except (Overloaded, QueueFull):
                time.sleep(0.02)
        assert tokens is not None and len(tokens) == 3
        assert eng.breaker_state == "closed"


def test_prefill_failure_isolated_to_its_prompt(lm_bundle):
    """One poisoned prompt fails alone — neighbors are served."""
    man, P = _params(lm_bundle)
    # prefix_cache off: every admission takes the single-prefill path
    # the poison hook patches (coalesced admissions have their own
    # wave-isolation contract)
    with DecodeEngine(lm_bundle, max_slots=2, max_t=32, max_prompt=8,
                      prompt_align=4, max_new_tokens=4,
                      prefix_cache=False, retry_budget=0) as eng:
        real_prefill = eng.model.run_prefill

        def poison_prefill(tokens, slot, start=0):
            if tokens[0] == 9:
                raise RuntimeError("poisoned prompt")
            return real_prefill(tokens, slot, start)

        eng.model.run_prefill = poison_prefill
        good1 = eng.submit(np.array([1, 2]))
        bad = eng.submit(np.array([9, 9]))
        good2 = eng.submit(np.array([3]))
        with pytest.raises(RuntimeError):
            bad.result(timeout=60)
        assert list(good1.result(timeout=120)) == oracle_greedy(
            attn_oracle_logits, man, P, [1, 2], 4)
        assert list(good2.result(timeout=120)) == oracle_greedy(
            attn_oracle_logits, man, P, [3], 4)
        assert eng.model.cache.free_slots == 2  # poisoned slot freed


# ----------------------------------------------------------------------
# API edges
# ----------------------------------------------------------------------
def test_submit_validation(lm_bundle):
    with DecodeEngine(lm_bundle, max_slots=1, max_t=32, max_prompt=8,
                      prompt_align=4) as eng:
        with pytest.raises(ValueError, match="empty"):
            eng.submit(np.array([], np.int32))
        with pytest.raises(ValueError, match="max_prompt"):
            eng.submit(np.arange(9))
        with pytest.raises(DeadlineExceeded):
            eng.submit(np.array([1]), deadline_ms=-1)
    with pytest.raises(RuntimeError, match="not started|shut down"):
        eng.submit(np.array([1]))


def test_queue_backpressure(lm_bundle):
    gate = threading.Event()
    with DecodeEngine(lm_bundle, max_slots=1, max_t=32, max_prompt=8,
                      prompt_align=4, max_new_tokens=2,
                      max_queue=1) as eng:
        real_prefill = eng.model.run_prefill

        def gated_prefill(tokens, slot, start=0):
            gate.wait(timeout=30)
            return real_prefill(tokens, slot, start)

        eng.model.run_prefill = gated_prefill
        first = eng.submit(np.array([1]))      # popped by scheduler
        time.sleep(0.05)
        second = eng.submit(np.array([2]))     # fills the queue
        with pytest.raises(QueueFull):
            eng.submit(np.array([3]))
        gate.set()
        assert first.result(timeout=120) is not None
        assert second.result(timeout=120) is not None
        assert eng.stats()["rejected"] == 1


def test_geometry_validation(lm_bundle):
    with pytest.raises(ValueError, match="max_prompt"):
        DecodeModel(ExportedModel.load(lm_bundle), max_t=16,
                    max_prompt=16)
    with pytest.raises(ValueError, match="ladder top"):
        DecodeModel(ExportedModel.load(lm_bundle), max_t=32,
                    max_prompt=30, prompt_align=12)
