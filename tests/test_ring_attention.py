"""Ring attention / sequence parallelism tests on the 8-device
virtual CPU mesh: the sharded ring must equal single-device attention
exactly (same math, different schedule)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from znicz_tpu.parallel.ring_attention import (local_attention,
                                               make_seq_mesh,
                                               sequence_sharded_attention)

RNG = np.random.default_rng(41)


def qkv(batch=2, time=32, heads=3, dim=8):
    shape = (batch, time, heads, dim)
    return tuple(RNG.normal(size=shape).astype(np.float32)
                 for _ in range(3))


def test_local_attention_is_softmax_attention():
    q, k, v = qkv(time=8)
    out = np.asarray(local_attention(*map(jnp.asarray, (q, k, v))))
    # independent einsum-free reference
    b, t, h, d = q.shape
    want = np.zeros_like(q)
    for bi in range(b):
        for hi in range(h):
            s = q[bi, :, hi] @ k[bi, :, hi].T / np.sqrt(d)
            p = np.exp(s - s.max(1, keepdims=True))
            p /= p.sum(1, keepdims=True)
            want[bi, :, hi] = p @ v[bi, :, hi]
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n_shards", [2, 4, 8])
def test_ring_equals_local(causal, n_shards):
    mesh = make_seq_mesh(n_shards)
    q, k, v = qkv(time=40 if n_shards != 8 else 64)
    want = np.asarray(local_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal))
    got = np.asarray(sequence_sharded_attention(
        mesh, jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        causal=causal))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_ring_output_stays_sequence_sharded():
    mesh = make_seq_mesh(4)
    q, k, v = qkv(time=16)
    out = sequence_sharded_attention(
        mesh, jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    [spec] = {s.spec for s in [out.sharding]}
    assert spec[1] == "seq"  # time axis still sharded — composable


def test_ring_long_sequence_jit():
    """Jit-compiled, longer sequence, causal — the long-context
    configuration the design targets."""
    mesh = make_seq_mesh(8)
    q, k, v = qkv(batch=1, time=256, heads=2, dim=16)

    fn = jax.jit(lambda a, b, c: sequence_sharded_attention(
        mesh, a, b, c, causal=True))
    got = np.asarray(fn(jnp.asarray(q), jnp.asarray(k),
                        jnp.asarray(v)))
    want = np.asarray(local_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
