"""Ring attention / sequence parallelism tests on the 8-device
virtual CPU mesh: the sharded ring must equal single-device attention
exactly (same math, different schedule)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from znicz_tpu.parallel.ring_attention import (local_attention,
                                               make_seq_mesh,
                                               sequence_sharded_attention)

RNG = np.random.default_rng(41)


def qkv(batch=2, time=32, heads=3, dim=8):
    shape = (batch, time, heads, dim)
    return tuple(RNG.normal(size=shape).astype(np.float32)
                 for _ in range(3))


def test_local_attention_is_softmax_attention():
    q, k, v = qkv(time=8)
    out = np.asarray(local_attention(*map(jnp.asarray, (q, k, v))))
    # independent einsum-free reference
    b, t, h, d = q.shape
    want = np.zeros_like(q)
    for bi in range(b):
        for hi in range(h):
            s = q[bi, :, hi] @ k[bi, :, hi].T / np.sqrt(d)
            p = np.exp(s - s.max(1, keepdims=True))
            p /= p.sum(1, keepdims=True)
            want[bi, :, hi] = p @ v[bi, :, hi]
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n_shards", [2, 4, 8])
def test_ring_equals_local(causal, n_shards):
    mesh = make_seq_mesh(n_shards)
    q, k, v = qkv(time=40 if n_shards != 8 else 64)
    want = np.asarray(local_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal))
    got = np.asarray(sequence_sharded_attention(
        mesh, jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        causal=causal))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_ring_output_stays_sequence_sharded():
    mesh = make_seq_mesh(4)
    q, k, v = qkv(time=16)
    out = sequence_sharded_attention(
        mesh, jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    [spec] = {s.spec for s in [out.sharding]}
    assert spec[1] == "seq"  # time axis still sharded — composable


def test_kernel_fold_output_stays_sequence_sharded():
    """The round-6 kernel fold must preserve the ring's composability
    contract: output still time-sharded (and the default request —
    no pallas_fold — still resolves to the scan fold on CPU)."""
    from znicz_tpu.parallel.ring_attention import ring_fold_choice
    mesh = make_seq_mesh(4)
    q, k, v = qkv(batch=1, time=32, heads=2, dim=8)
    fold, _, _ = ring_fold_choice(mesh, q.shape, pallas_fold=False)
    assert fold == "scan"        # the default stays the portable fold
    out = sequence_sharded_attention(
        mesh, jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        causal=True, pallas_fold=True, pallas_interpret=True)
    [spec] = {s.spec for s in [out.sharding]}
    assert spec[1] == "seq"


def test_ring_long_sequence_jit():
    """Jit-compiled, longer sequence, causal — the long-context
    configuration the design targets."""
    mesh = make_seq_mesh(8)
    q, k, v = qkv(batch=1, time=256, heads=2, dim=16)

    fn = jax.jit(lambda a, b, c: sequence_sharded_attention(
        mesh, a, b, c, causal=True))
    got = np.asarray(fn(jnp.asarray(q), jnp.asarray(k),
                        jnp.asarray(v)))
    want = np.asarray(local_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block_k", [4, 8, 16])
def test_blocked_equals_local(causal, block_k):
    """The flash-style blocked local attention must equal the plain
    form — forward AND vjp.  Compared under `highest` matmul precision
    (at the default precision both paths are individually correct but
    round differently, ~1e-3 on CPU)."""
    rng = np.random.default_rng(0)
    B, T, H, D = 2, 16, 2, 4
    q = jnp.asarray(rng.normal(size=(B, T, H, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, T, H, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, T, H, D)).astype(np.float32))
    from znicz_tpu.parallel.ring_attention import local_attention_blocked
    with jax.default_matmul_precision("highest"):
        ref = local_attention(q, k, v, causal=causal)
        got = local_attention_blocked(q, k, v, causal=causal,
                                      block_k=block_k)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
        ct = jnp.asarray(rng.normal(size=ref.shape).astype(np.float32))
        _, vjp_ref = jax.vjp(
            lambda a, b, c: local_attention(a, b, c, causal=causal),
            q, k, v)
        _, vjp_got = jax.vjp(
            lambda a, b, c: local_attention_blocked(
                a, b, c, causal=causal, block_k=block_k), q, k, v)
        for gr, gg in zip(vjp_ref(ct), vjp_got(ct)):
            np.testing.assert_allclose(np.asarray(gg), np.asarray(gr),
                                       rtol=2e-4, atol=2e-4)


def test_blocked_rejects_indivisible():
    from znicz_tpu.parallel.ring_attention import local_attention_blocked
    q = jnp.zeros((1, 6, 1, 4))
    with pytest.raises(ValueError, match="divisible"):
        local_attention_blocked(q, q, q, block_k=4)

@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize(
    "n_shards,block_k",
    [pytest.param(2, 4, marks=pytest.mark.slow),
     pytest.param(4, 8, marks=pytest.mark.slow),
     pytest.param(4, 4, marks=pytest.mark.slow)])
def test_blocked_ring_equals_local_fwd_and_vjp(causal, n_shards,
                                               block_k):
    """Flash-in-ring (round-4 verdict item 6): the sub-blocked fold
    inside each ring step must equal the plain ring AND the local
    oracle — forward and vjp — so the single-chip blocked memory
    behavior extends to T-per-device × ring."""
    mesh = make_seq_mesh(n_shards)
    rng = np.random.default_rng(7)
    B, T, H, D = 2, 16 * n_shards, 2, 4
    q, k, v = (jnp.asarray(rng.normal(size=(B, T, H, D))
                           .astype(np.float32)) for _ in range(3))
    with jax.default_matmul_precision("highest"):
        ref = local_attention(q, k, v, causal=causal)
        plain_ring = sequence_sharded_attention(
            mesh, q, k, v, causal=causal)
        got = sequence_sharded_attention(
            mesh, q, k, v, causal=causal, block_k=block_k)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(np.asarray(got),
                                   np.asarray(plain_ring),
                                   rtol=2e-4, atol=2e-5)
        ct = jnp.asarray(rng.normal(size=ref.shape).astype(np.float32))
        _, vjp_ref = jax.vjp(
            lambda a, b, c: local_attention(a, b, c, causal=causal),
            q, k, v)
        _, vjp_got = jax.vjp(
            lambda a, b, c: sequence_sharded_attention(
                mesh, a, b, c, causal=causal, block_k=block_k),
            q, k, v)
        for gr, gg in zip(vjp_ref(ct), vjp_got(ct)):
            np.testing.assert_allclose(np.asarray(gg), np.asarray(gr),
                                       rtol=3e-4, atol=3e-4)


def test_blocked_ring_rejects_indivisible_local_t():
    mesh = make_seq_mesh(2)
    q = jnp.zeros((1, 12, 1, 4))  # T_local = 6, not divisible by 4
    with pytest.raises(ValueError, match="divisible"):
        sequence_sharded_attention(mesh, q, q, q, block_k=4)


def test_blocked_ring_whole_tile_when_block_exceeds_local_t():
    """block_k ≥ T_local degrades to the whole-tile fold (the valid
    config seq_parallel + a single-chip-sized flash_block_k hits when
    the ring splits T below the block size)."""
    mesh = make_seq_mesh(4)
    rng = np.random.default_rng(9)
    q, k, v = (jnp.asarray(rng.normal(size=(1, 64, 2, 4))
                           .astype(np.float32)) for _ in range(3))
    with jax.default_matmul_precision("highest"):
        ref = local_attention(q, k, v, causal=True)
        got = sequence_sharded_attention(  # T_local=16 < block_k=32
            mesh, q, k, v, causal=True, block_k=32)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)
