"""Per-op correctness for the fully-connected family: numpy oracle vs
XLA path agreement (the reference's cross-backend test pattern,
SURVEY.md §4: ``znicz/tests/unit/test_all2all.py``)."""

import numpy as np
import pytest

from znicz_tpu.backends import NumpyDevice, XLADevice
from znicz_tpu.dummy import DummyUnit, DummyWorkflow
from znicz_tpu.memory import Vector
from znicz_tpu.ops import all2all


def build_unit(cls, device, x, n_out=5, **kwargs):
    wf = DummyWorkflow()
    source = DummyUnit(wf, output=Vector(np.asarray(x), name="x"))
    unit = cls(wf, n_out, **kwargs)
    unit.link_attrs(source, ("input", "output"))
    unit.initialize(device=device)
    return unit


def run_both(cls, x, n_out=5, **kwargs):
    """Run the same unit math on both backends with identical weights."""
    np_unit = build_unit(cls, NumpyDevice(), x, n_out, **kwargs)
    xla_unit = build_unit(cls, XLADevice(), x, n_out, **kwargs)
    # same parameters on both
    xla_unit.weights.reset(np_unit.weights.mem.copy())
    if xla_unit.include_bias:
        xla_unit.bias.reset(np_unit.bias.mem.copy())
        xla_unit.bias.initialize(xla_unit.device)
    xla_unit.weights.initialize(xla_unit.device)
    np_unit.run()
    xla_unit.run()
    np_unit.output.map_read()
    xla_unit.output.map_read()
    return np_unit, xla_unit


X = np.random.default_rng(3).normal(size=(16, 12)).astype(np.float32)


@pytest.mark.parametrize("cls", [
    all2all.All2All, all2all.All2AllTanh, all2all.All2AllRELU,
    all2all.All2AllStrictRELU, all2all.All2AllSigmoid])
def test_numpy_xla_agreement(cls):
    np_unit, xla_unit = run_both(cls, X)
    np.testing.assert_allclose(np_unit.output.mem, xla_unit.output.mem,
                               rtol=1e-5, atol=1e-6)


def test_linear_golden():
    """Hand-checkable case: identity-ish weights."""
    wf = DummyWorkflow()
    x = np.array([[1.0, 2.0], [3.0, 4.0]], dtype=np.float32)
    source = DummyUnit(wf, output=Vector(x, name="x"))
    unit = all2all.All2All(wf, 2)
    unit.link_attrs(source, ("input", "output"))
    unit.initialize(device=NumpyDevice())
    unit.weights.reset(np.eye(2, dtype=np.float32))
    unit.bias.reset(np.array([10.0, 20.0], dtype=np.float32))
    unit.run()
    unit.output.map_read()
    np.testing.assert_allclose(unit.output.mem,
                               [[11.0, 22.0], [13.0, 24.0]])


def test_multidim_input_flattened():
    x = np.random.default_rng(0).normal(size=(4, 3, 2, 2)).astype(np.float32)
    np_unit, xla_unit = run_both(all2all.All2AllTanh, x, n_out=7)
    assert np_unit.output.shape == (4, 7)
    np.testing.assert_allclose(np_unit.output.mem, xla_unit.output.mem,
                               rtol=1e-5, atol=1e-6)


def test_softmax_outputs_and_argmax():
    np_unit, xla_unit = run_both(all2all.All2AllSoftmax, X, n_out=5)
    np.testing.assert_allclose(np_unit.output.mem, xla_unit.output.mem,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np_unit.output.mem.sum(axis=1), 1.0,
                               rtol=1e-5)
    xla_unit.max_idx.map_read()
    np.testing.assert_array_equal(np_unit.max_idx.mem, xla_unit.max_idx.mem)
    np.testing.assert_array_equal(np_unit.max_idx.mem,
                                  np.argmax(np_unit.output.mem, axis=1))


def test_output_sample_shape_tuple():
    np_unit = build_unit(all2all.All2All, NumpyDevice(), X, (3, 4))
    np_unit.run()
    assert np_unit.output.shape == (16, 3, 4)
    assert np_unit.weights.shape == (12, 12)
