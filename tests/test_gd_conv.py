"""Conv backward: numpy col2im oracle vs the XLA vjp path, plus
numeric gradient checks (reference pattern:
``znicz/tests/unit/test_gd_conv.py``)."""

import numpy as np
import pytest

from znicz_tpu.backends import NumpyDevice, XLADevice
from znicz_tpu.dummy import DummyUnit, DummyWorkflow
from znicz_tpu.memory import Vector
from znicz_tpu.ops import conv, gd_conv

PAIRS = [
    (conv.Conv, gd_conv.GradientDescentConv),
    (conv.ConvTanh, gd_conv.GDTanhConv),
    (conv.ConvRELU, gd_conv.GDRELUConv),
    (conv.ConvStrictRELU, gd_conv.GDStrictRELUConv),
]

RNG = np.random.default_rng(31)
X = RNG.normal(size=(3, 6, 6, 2)).astype(np.float32)
LR = 0.05
GEOM = dict(n_kernels=4, kx=3, ky=3, sliding=(2, 2), padding=1)


def build_pair(fwd_cls, gd_cls, device, err):
    wf = DummyWorkflow()
    src = DummyUnit(wf, output=Vector(X.copy(), name="x"))
    fwd = fwd_cls(wf, **GEOM)
    fwd.link_attrs(src, ("input", "output"))
    fwd.initialize(device=device)
    err_src = DummyUnit(wf, err=Vector(err.copy(), name="err"))
    bwd = gd_cls(wf, learning_rate=LR)
    bwd.forward_unit = fwd
    bwd.link_attrs(fwd, "input", "output", "weights", "bias")
    bwd.link_attrs(err_src, ("err_output", "err"))
    bwd.initialize(device=device)
    return fwd, bwd


def make_err(fwd):
    return np.random.default_rng(9).normal(
        size=fwd.output.shape).astype(np.float32)


@pytest.mark.parametrize("fwd_cls,gd_cls", PAIRS)
def test_numpy_xla_agreement(fwd_cls, gd_cls):
    probe = build_pair(fwd_cls, gd_cls, NumpyDevice(), np.zeros(1))[0]
    err = make_err(probe)
    results = {}
    for name, device in (("np", NumpyDevice()), ("xla", XLADevice())):
        fwd, bwd = build_pair(fwd_cls, gd_cls, device, err)
        if name == "xla":
            fwd.weights.reset(results["w0"])
            fwd.weights.initialize(device)
            fwd.bias.reset(results["b0"])
            fwd.bias.initialize(device)
        else:
            results["w0"] = fwd.weights.mem.copy()
            results["b0"] = fwd.bias.mem.copy()
        fwd.run()
        bwd.run()
        for vec in (bwd.err_input, bwd.weights, bwd.bias):
            vec.map_read()
        results[f"{name}_err_input"] = bwd.err_input.mem.copy()
        results[f"{name}_w"] = bwd.weights.mem.copy()
        results[f"{name}_b"] = bwd.bias.mem.copy()
    for key in ("err_input", "w", "b"):
        np.testing.assert_allclose(results[f"np_{key}"],
                                   results[f"xla_{key}"],
                                   rtol=1e-3, atol=1e-4, err_msg=key)


def test_numeric_gradient_linear_conv():
    device = NumpyDevice()
    probe, _ = build_pair(conv.Conv, gd_conv.GradientDescentConv,
                          device, np.zeros(1))
    err = make_err(probe)
    fwd, bwd = build_pair(conv.Conv, gd_conv.GradientDescentConv,
                          device, err)
    w0 = fwd.weights.mem.copy()
    b0 = fwd.bias.mem.copy()
    fwd.run()
    bwd.run()
    grad_w = (w0 - bwd.weights.mem) / LR
    err_input = bwd.err_input.mem.copy()

    def loss(w, b, x):
        wf = DummyWorkflow()
        src = DummyUnit(wf, output=Vector(x, name="x"))
        f = conv.Conv(wf, **GEOM)
        f.link_attrs(src, ("input", "output"))
        f.initialize(device=device)
        f.weights.reset(w.copy())
        f.bias.reset(b.copy())
        f.run()
        return float(np.sum(err * f.output.mem))

    eps = 1e-2
    rng = np.random.default_rng(4)
    flat = w0.reshape(-1)
    for _ in range(4):
        k = rng.integers(flat.size)
        wp, wm = flat.copy(), flat.copy()
        wp[k] += eps
        wm[k] -= eps
        numeric = (loss(wp.reshape(w0.shape), b0, X)
                   - loss(wm.reshape(w0.shape), b0, X)) / (2 * eps)
        np.testing.assert_allclose(grad_w.reshape(-1)[k], numeric,
                                   rtol=2e-2, atol=1e-2)
    xflat = X.reshape(-1)
    for _ in range(4):
        k = rng.integers(xflat.size)
        xp_, xm_ = xflat.copy(), xflat.copy()
        xp_[k] += eps
        xm_[k] -= eps
        numeric = (loss(w0, b0, xp_.reshape(X.shape))
                   - loss(w0, b0, xm_.reshape(X.shape))) / (2 * eps)
        np.testing.assert_allclose(err_input.reshape(-1)[k], numeric,
                                   rtol=2e-2, atol=1e-2)


def test_wgrad_im2col_matches_transpose_conv():
    """The opt-in patches-GEMM weight grad (engine.conv_wgrad_im2col,
    for MXU-starved first layers) must equal the transposed gradient
    conv — same sums, reassociated."""
    from znicz_tpu.utils.config import root

    probe = build_pair(conv.Conv, gd_conv.GradientDescentConv,
                       NumpyDevice(), np.zeros(1))[0]
    err = make_err(probe)
    results = {}
    for mode in ("transpose", "im2col"):
        root.common.engine.conv_wgrad_im2col = mode == "im2col"
        try:
            fwd, bwd = build_pair(conv.Conv,
                                  gd_conv.GradientDescentConv,
                                  XLADevice(), err)
            assert bwd._wgrad_im2col == (mode == "im2col")
            if "w0" in results:
                fwd.weights.reset(results["w0"])
                fwd.weights.initialize(bwd.device)
                fwd.bias.reset(results["b0"])
                fwd.bias.initialize(bwd.device)
            else:
                results["w0"] = fwd.weights.mem.copy()
                results["b0"] = fwd.bias.mem.copy()
            fwd.run()
            bwd.run()
            bwd.weights.map_read()
            results[mode] = bwd.weights.mem.copy()
        finally:
            root.common.engine.conv_wgrad_im2col = False
    np.testing.assert_allclose(results["transpose"], results["im2col"],
                               rtol=1e-4, atol=1e-5)
