"""Cutter/GDCutter, MeanDispNormalizer, InputJoiner/GDInputJoiner:
oracle vs XLA agreement + golden semantics."""

import numpy as np

from znicz_tpu.backends import NumpyDevice, XLADevice
from znicz_tpu.dummy import DummyUnit, DummyWorkflow
from znicz_tpu.memory import Vector
from znicz_tpu.ops.cutter import Cutter, GDCutter
from znicz_tpu.ops.input_joiner import GDInputJoiner, InputJoiner
from znicz_tpu.ops.mean_disp_normalizer import (
    GDMeanDispNormalizer,
    MeanDispNormalizer,
)

RNG = np.random.default_rng(5)
X = RNG.normal(size=(2, 7, 9, 3)).astype(np.float32)


def test_cutter_fwd_bwd():
    padding = (2, 1, 3, 2)  # left, top, right, bottom
    outs = {}
    err = None
    for name, device in (("np", NumpyDevice()), ("xla", XLADevice())):
        wf = DummyWorkflow()
        src = DummyUnit(wf, output=Vector(X.copy(), name="x"))
        unit = Cutter(wf, padding=padding)
        unit.link_attrs(src, ("input", "output"))
        unit.initialize(device=device)
        unit.run()
        unit.output.map_read()
        assert unit.output.shape == (2, 7 - 3, 9 - 5, 3)
        if err is None:
            err = RNG.normal(size=unit.output.shape).astype(np.float32)
        err_src = DummyUnit(wf, err=Vector(err.copy(), name="err"))
        bwd = GDCutter(wf)
        bwd.forward_unit = unit
        bwd.link_attrs(unit, "input", "output")
        bwd.link_attrs(err_src, ("err_output", "err"))
        bwd.initialize(device=device)
        bwd.run()
        bwd.err_input.map_read()
        outs[name] = (unit.output.mem.copy(), bwd.err_input.mem.copy())
    np.testing.assert_array_equal(outs["np"][0], outs["xla"][0])
    np.testing.assert_array_equal(outs["np"][1], outs["xla"][1])
    np.testing.assert_array_equal(outs["np"][0], X[:, 1:5, 2:6, :])
    assert outs["np"][1].shape == X.shape
    np.testing.assert_allclose(outs["np"][1].sum(), err.sum(), rtol=1e-5)


def test_mean_disp_normalizer():
    mean = X.mean(axis=0)
    disp = X.std(axis=0) + 0.1
    outs = {}
    err = RNG.normal(size=X.shape).astype(np.float32)
    for name, device in (("np", NumpyDevice()), ("xla", XLADevice())):
        wf = DummyWorkflow()
        src = DummyUnit(wf, output=Vector(X.copy(), name="x"))
        unit = MeanDispNormalizer(wf)
        unit.link_attrs(src, ("input", "output"))
        unit.mean = Vector(mean.copy(), name="mean")
        unit.rdisp = Vector((1.0 / disp).astype(np.float32), name="rdisp")
        unit.initialize(device=device)
        unit.run()
        unit.output.map_read()
        err_src = DummyUnit(wf, err=Vector(err.copy(), name="err"))
        bwd = GDMeanDispNormalizer(wf)
        bwd.forward_unit = unit
        bwd.link_attrs(unit, "input", "output")
        bwd.link_attrs(err_src, ("err_output", "err"))
        bwd.initialize(device=device)
        bwd.run()
        bwd.err_input.map_read()
        outs[name] = (unit.output.mem.copy(), bwd.err_input.mem.copy())
    np.testing.assert_allclose(outs["np"][0], outs["xla"][0],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(outs["np"][1], outs["xla"][1],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(outs["np"][0],
                               (X - mean) / disp, rtol=1e-4, atol=1e-5)


def test_input_joiner_fwd_bwd():
    a = RNG.normal(size=(4, 5)).astype(np.float32)
    b = RNG.normal(size=(4, 2, 3)).astype(np.float32)  # flattened to 6
    err = RNG.normal(size=(4, 11)).astype(np.float32)
    outs = {}
    for name, device in (("np", NumpyDevice()), ("xla", XLADevice())):
        wf = DummyWorkflow()
        ua = DummyUnit(wf, output=Vector(a.copy(), name="a"))
        ub = DummyUnit(wf, output=Vector(b.copy(), name="b"))
        join = InputJoiner(wf)
        join.link_inputs(ua, ub)
        join.initialize(device=device)
        join.run()
        join.output.map_read()
        err_src = DummyUnit(wf, err=Vector(err.copy(), name="err"))
        bwd = GDInputJoiner(wf)
        bwd.forward_unit = join
        bwd.link_attrs(err_src, ("err_output", "err"))
        bwd.initialize(device=device)
        bwd.run()
        for vec in bwd.err_inputs:
            vec.map_read()
        outs[name] = (join.output.mem.copy(),
                      [v.mem.copy() for v in bwd.err_inputs])
    np.testing.assert_array_equal(outs["np"][0], outs["xla"][0])
    expected = np.concatenate([a, b.reshape(4, -1)], axis=1)
    np.testing.assert_array_equal(outs["np"][0], expected)
    np.testing.assert_array_equal(outs["np"][1][0], err[:, :5])
    np.testing.assert_array_equal(outs["np"][1][1],
                                  err[:, 5:].reshape(b.shape))
    for got, want in zip(outs["xla"][1], outs["np"][1]):
        np.testing.assert_array_equal(got, want)
