"""Process-sharding helpers (single-process semantics; the 2-process
behavior is proven by tests/test_distributed.py's genetics/ensemble
modes)."""

import numpy as np

from znicz_tpu.parallel.process_shard import (allgather_sum,
                                              broadcast_from_zero,
                                              merge_round_robin,
                                              merge_sharded_scores,
                                              pick_eval_device,
                                              process_info)


def test_process_info_single():
    assert process_info() == (0, 1)


def test_merge_sharded_scores_single_process_identity():
    scores = np.array([1.5, -2.0, 3.25])
    merged = merge_sharded_scores(scores, 1)
    np.testing.assert_array_equal(merged, scores)


def test_merge_round_robin_single_process():
    merged = merge_round_robin([5.0, 6.0, 7.0], 0, 1, 3)
    np.testing.assert_array_equal(merged, [5.0, 6.0, 7.0])


def test_allgather_sum_and_broadcast_bit_exact_f64():
    # values with no exact float32 representation: the uint32-pair
    # transport must round-trip them bit-exactly (jax canonicalizes
    # f64 -> f32 otherwise)
    vals = np.array([1.0 + 2.0 ** -40, np.pi, 1e300])
    total = allgather_sum(vals)
    np.testing.assert_array_equal(total, vals)  # 1 process: sum = self
    got = broadcast_from_zero(vals)
    np.testing.assert_array_equal(got, vals)
    ints = np.array([2 ** 40 + 3, -7], np.int64)
    np.testing.assert_array_equal(broadcast_from_zero(ints), ints)


def test_pick_eval_device_prefers_factory():
    sentinel = object()
    assert pick_eval_device(lambda: sentinel) is sentinel


def test_pick_eval_device_single_process_uses_config():
    from znicz_tpu.backends import NumpyDevice
    from znicz_tpu.utils.config import root

    old = root.common.engine.backend
    root.common.engine.backend = "numpy"
    try:
        assert isinstance(pick_eval_device(), NumpyDevice)
    finally:
        root.common.engine.backend = old
