"""Backward-unit correctness: numpy↔XLA agreement plus numeric
gradient checks against the forward oracle (reference pattern:
``znicz/tests/unit/test_gd.py``)."""

import numpy as np
import pytest

from znicz_tpu.backends import NumpyDevice, XLADevice
from znicz_tpu.dummy import DummyUnit, DummyWorkflow
from znicz_tpu.memory import Vector
from znicz_tpu.ops import all2all, gd

PAIRS = [
    (all2all.All2All, gd.GradientDescent),
    (all2all.All2AllTanh, gd.GDTanh),
    (all2all.All2AllRELU, gd.GDRELU),
    (all2all.All2AllStrictRELU, gd.GDStrictRELU),
    (all2all.All2AllSigmoid, gd.GDSigmoid),
]

RNG = np.random.default_rng(11)
X = RNG.normal(size=(8, 6)).astype(np.float32)
ERR = RNG.normal(size=(8, 4)).astype(np.float32)
LR = 0.05


def build_pair(fwd_cls, gd_cls, device, gd_kwargs=None):
    wf = DummyWorkflow()
    source = DummyUnit(wf, output=Vector(X.copy(), name="x"))
    fwd = fwd_cls(wf, 4)
    fwd.link_attrs(source, ("input", "output"))
    fwd.initialize(device=device)
    err_source = DummyUnit(wf, err=Vector(ERR.copy(), name="err"))
    bwd = gd_cls(wf, learning_rate=LR, **(gd_kwargs or {}))
    bwd.link_attrs(fwd, "input", "output", "weights", "bias")
    bwd.link_attrs(err_source, ("err_output", "err"))
    bwd.initialize(device=device)
    return fwd, bwd


@pytest.mark.parametrize("fwd_cls,gd_cls", PAIRS)
def test_numpy_xla_agreement(fwd_cls, gd_cls):
    results = {}
    for name, device in (("np", NumpyDevice()), ("xla", XLADevice())):
        fwd, bwd = build_pair(fwd_cls, gd_cls, device)
        if name == "xla":
            fwd.weights.reset(results["np_w0"])
            fwd.weights.initialize(device)
            fwd.bias.reset(results["np_b0"])
            fwd.bias.initialize(device)
        else:
            results["np_w0"] = fwd.weights.mem.copy()
            results["np_b0"] = fwd.bias.mem.copy()
        fwd.run()
        bwd.run()
        for vec in (bwd.err_input, bwd.weights, bwd.bias):
            vec.map_read()
        results[f"{name}_err_input"] = bwd.err_input.mem.copy()
        results[f"{name}_w"] = bwd.weights.mem.copy()
        results[f"{name}_b"] = bwd.bias.mem.copy()
    for key in ("err_input", "w", "b"):
        np.testing.assert_allclose(
            results[f"np_{key}"], results[f"xla_{key}"],
            rtol=1e-4, atol=1e-5, err_msg=key)


@pytest.mark.parametrize("fwd_cls,gd_cls", PAIRS)
def test_numeric_gradient(fwd_cls, gd_cls):
    """L = Σ err ⊙ act(xW+b): the gd unit's implicit dL/dW (recovered
    from the update) must match central finite differences."""
    device = NumpyDevice()
    fwd, bwd = build_pair(fwd_cls, gd_cls, device)
    w0 = fwd.weights.mem.copy()
    b0 = fwd.bias.mem.copy()
    fwd.run()
    bwd.run()
    grad_w = (w0 - bwd.weights.mem) / LR
    grad_b = (b0 - bwd.bias.mem) / LR
    err_input = bwd.err_input.mem.copy()

    def loss(w, b, x):
        wf = DummyWorkflow()
        src = DummyUnit(wf, output=Vector(x, name="x"))
        f = fwd_cls(wf, 4)
        f.link_attrs(src, ("input", "output"))
        f.initialize(device=device)
        f.weights.reset(w.copy())
        f.bias.reset(b.copy())
        f.run()
        return float(np.sum(ERR * f.output.mem))

    eps = 1e-3
    rng = np.random.default_rng(5)
    for _ in range(4):  # spot-check weight gradient entries
        i, j = rng.integers(w0.shape[0]), rng.integers(w0.shape[1])
        wp, wm = w0.copy(), w0.copy()
        wp[i, j] += eps
        wm[i, j] -= eps
        numeric = (loss(wp, b0, X) - loss(wm, b0, X)) / (2 * eps)
        np.testing.assert_allclose(grad_w[i, j], numeric,
                                   rtol=2e-2, atol=1e-3)
    for _ in range(2):  # bias gradient
        j = rng.integers(b0.shape[0])
        bp, bm = b0.copy(), b0.copy()
        bp[j] += eps
        bm[j] -= eps
        numeric = (loss(w0, bp, X) - loss(w0, bm, X)) / (2 * eps)
        np.testing.assert_allclose(grad_b[j], numeric, rtol=2e-2, atol=1e-3)
    for _ in range(4):  # err_input = dL/dx
        i, j = rng.integers(X.shape[0]), rng.integers(X.shape[1])
        xp_, xm_ = X.copy(), X.copy()
        xp_[i, j] += eps
        xm_[i, j] -= eps
        numeric = (loss(w0, b0, xp_) - loss(w0, b0, xm_)) / (2 * eps)
        np.testing.assert_allclose(err_input[i, j], numeric,
                                   rtol=2e-2, atol=1e-3)


def test_momentum_and_decay_update():
    """Momentum + L2 decay follow the documented update rule."""
    device = NumpyDevice()
    fwd, bwd = build_pair(all2all.All2All, gd.GradientDescent, device,
                          gd_kwargs=dict(gradient_moment=0.9,
                                         weights_decay=0.01))
    w0 = fwd.weights.mem.copy()
    fwd.run()
    x2d = X.reshape(8, -1)
    grad = x2d.T @ ERR + 0.01 * w0
    bwd.run()
    expected_acc = -LR * grad
    np.testing.assert_allclose(bwd.weights.mem, w0 + expected_acc,
                               rtol=1e-5, atol=1e-6)
    # second step accumulates momentum
    fwd.run()
    w1 = bwd.weights.mem.copy()
    grad1 = x2d.T @ ERR + 0.01 * w1
    bwd.run()
    np.testing.assert_allclose(
        bwd.weights.mem, w1 + (0.9 * expected_acc - LR * grad1),
        rtol=1e-5, atol=1e-6)


def test_need_err_input_false_skips_allocation():
    device = NumpyDevice()
    wf = DummyWorkflow()
    source = DummyUnit(wf, output=Vector(X.copy(), name="x"))
    fwd = all2all.All2All(wf, 4)
    fwd.link_attrs(source, ("input", "output"))
    fwd.initialize(device=device)
    err_source = DummyUnit(wf, err=Vector(ERR.copy(), name="err"))
    bwd = gd.GradientDescent(wf, learning_rate=LR, need_err_input=False)
    bwd.link_attrs(fwd, "input", "output", "weights", "bias")
    bwd.link_attrs(err_source, ("err_output", "err"))
    bwd.initialize(device=device)
    fwd.run()
    bwd.run()
    assert not bwd.err_input


def test_variance_preserving_fillings():
    """he / xavier fillings scale with fan-in (added beyond the
    reference's fixed-stddev uniform/gaussian/constant set; used by
    benchmarks/bf16_convergence.py for short-horizon training)."""
    from znicz_tpu.utils import prng

    prng.seed_all(3)
    unit = all2all.All2All(DummyWorkflow(), output_sample_shape=8)
    fan_in = 4096
    he = unit.fill_array((fan_in, 64), "he", None, fan_in=fan_in)
    xavier = unit.fill_array((fan_in, 64), "xavier", None, fan_in=fan_in)
    np.testing.assert_allclose(he.std(), np.sqrt(2.0 / fan_in), rtol=0.05)
    np.testing.assert_allclose(xavier.std(), np.sqrt(1.0 / fan_in),
                               rtol=0.05)
    assert abs(he.mean()) < 3 * he.std() / np.sqrt(he.size)
    with pytest.raises(ValueError, match="unknown filling"):
        unit.fill_array((4, 4), "nope", None, fan_in=4)
