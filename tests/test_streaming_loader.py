"""Streaming data plane (round 10): the StreamingLoader must be
*invisible* except for memory — same epoch order as the resident
loader bit-for-bit, same trained weights across a mid-epoch
snapshot/resume, zero new XLA compiles once warmed, per-process 1/N
shards partitioning the epoch exactly — while the input pipeline runs
in background threads and hides under the step."""

import time

import numpy as np
import pytest

from znicz_tpu.backends import NumpyDevice, XLADevice
from znicz_tpu.dummy import DummyWorkflow
from znicz_tpu.loader.base import TRAIN, VALID, epoch_permutation
from znicz_tpu.loader.fullbatch import ArrayLoader
from znicz_tpu.loader.streaming import (ShardReader, StreamingLoader,
                                        write_shards)
from znicz_tpu.models.standard_workflow import StandardWorkflow
from znicz_tpu.observe import metrics as obs_metrics
from znicz_tpu.utils import prng

N_CLASSES, DIM = 3, 12


def u8_blobs(n_per_class=60, seed=7):
    """Learnable gaussian blobs quantized to uint8 (the raw-dtype
    wire format the streaming plane is built for)."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(0, 1, (N_CLASSES, DIM))
    data = np.concatenate([
        c + 0.3 * rng.normal(size=(n_per_class, DIM)) for c in centers])
    data = np.clip((data + 4.0) * 32.0, 0, 255).astype(np.uint8)
    labels = np.repeat(np.arange(N_CLASSES),
                       n_per_class).astype(np.int32)
    order = rng.permutation(len(data))
    return data[order], labels[order]


@pytest.fixture
def shard_dir(tmp_path):
    data, labels = u8_blobs()
    d = str(tmp_path / "shards")
    write_shards(d, data[:144], labels[:144],
                 valid_data=data[144:], valid_labels=labels[144:],
                 rows_per_shard=50)
    return d, data, labels


def make_streaming(shard_dir, device=None, minibatch_size=24, seed=77,
                   **kwargs):
    prng.seed_all(seed)
    ld = StreamingLoader(DummyWorkflow(), shard_dir,
                         minibatch_size=minibatch_size, **kwargs)
    ld.initialize(device=device or NumpyDevice())
    return ld


# ----------------------------------------------------------------------
# on-disk format
# ----------------------------------------------------------------------
def test_shard_roundtrip(shard_dir):
    d, data, labels = shard_dir
    reader = ShardReader(d)
    assert reader.class_lengths == [0, 36, 144]
    assert reader.sample_shape == (DIM,)
    assert reader.dtype == np.uint8
    assert reader.nbytes == 180 * DIM
    # global order: valid block then train block
    glob = np.concatenate([data[144:], data[:144]])
    glob_lab = np.concatenate([labels[144:], labels[:144]])
    idx = np.asarray([0, 35, 36, 49, 50, 121, 179])  # spans shards
    out = np.empty((len(idx), DIM), dtype=np.uint8)
    reader.gather(idx, out)
    np.testing.assert_array_equal(out, glob[idx])
    np.testing.assert_array_equal(reader.labels(idx), glob_lab[idx])


def test_epoch_permutation_is_counter_based():
    a = epoch_permutation(123, 4, 50)
    b = epoch_permutation(123, 4, 50)
    np.testing.assert_array_equal(a, b)          # pure function
    assert not np.array_equal(a, epoch_permutation(123, 5, 50))
    assert not np.array_equal(a, epoch_permutation(124, 4, 50))
    assert sorted(a) == list(range(50))          # a permutation


# ----------------------------------------------------------------------
# determinism: streamed ≡ resident, bit for bit
# ----------------------------------------------------------------------
def consume_order(loader, n_steps):
    seq = []
    for _ in range(n_steps):
        loader.run()
        seq.append((loader.epoch_number, loader.minibatch_class,
                    tuple(int(i) for i in
                          loader._host_indices[:loader.minibatch_size])))
    return seq


def test_streamed_order_matches_fullbatch_bitwise(shard_dir):
    """The acceptance contract: a streamed epoch reproduces the
    FullBatchLoader shuffled order exactly for the same seed — across
    MULTIPLE epochs (different permutations each, crossing the
    boundary the prefetch runs through)."""
    d, data, labels = shard_dir
    prng.seed_all(77)
    ref = ArrayLoader(DummyWorkflow(),
                      train_data=data[:144], train_labels=labels[:144],
                      valid_data=data[144:], valid_labels=labels[144:],
                      minibatch_size=24)
    ref.initialize(device=NumpyDevice())
    steps = 3 * len(ref._schedule)
    want = consume_order(ref, steps)

    ld = make_streaming(d, seed=77)
    try:
        got = consume_order(ld, steps)
    finally:
        ld.stop()
    assert got == want
    # the orders genuinely differ between epochs (shuffle is live)
    train_by_epoch = {}
    for ep, cls, idx in got:
        if cls == TRAIN:
            train_by_epoch.setdefault(ep, []).extend(idx)
    assert train_by_epoch[0] != train_by_epoch[1]


def test_streamed_content_and_normalization(shard_dir):
    d, data, labels = shard_dir
    glob = np.concatenate([data[144:], data[:144]])
    glob_lab = np.concatenate([labels[144:], labels[:144]])
    ld = make_streaming(d, normalization_scale=1 / 127.5,
                        normalization_bias=-1.0)
    try:
        for _ in range(8):
            ld.run()
            idx = np.asarray(ld._host_indices)
            np.testing.assert_array_equal(ld.minibatch_raw.mem,
                                          glob[idx])
            np.testing.assert_array_equal(ld.minibatch_labels.mem,
                                          glob_lab[idx])
            ld.numpy_run()  # oracle normalize path
            np.testing.assert_allclose(
                ld.minibatch_data.mem,
                glob[idx].astype(np.float32) / 127.5 - 1.0, atol=1e-6)
    finally:
        ld.stop()


# ----------------------------------------------------------------------
# per-process 1/N sharded reads
# ----------------------------------------------------------------------
def test_two_process_split_partitions_epoch(shard_dir):
    """Simulated 2-process split: the union of both processes' local
    index slices over one epoch is EXACTLY the epoch — every sample
    once, none dropped, none read twice — and both derive the same
    global order from the shared seed."""
    d, _data, _labels = shard_dir
    loaders = [make_streaming(d, seed=5, process_index=p,
                              process_count=2) for p in range(2)]
    try:
        a, b = loaders
        assert a.local_batch == 12 and b.local_batch == 12
        n_sched = len(a._schedule)
        for epoch in (0, 1):
            per_proc = []
            for p, ld in enumerate((a, b)):
                rows = []
                for c in range(n_sched):
                    idx, _cls, count = ld.schedule_entry(epoch, c)
                    lo = p * ld.local_batch
                    hi = min(lo + ld.local_batch, count)
                    if lo < count:  # rows past count are pad (masked
                        #             by minibatch_valid, re-read of
                        #             the padded sample is by design)
                        rows.append(idx[lo:hi])
                per_proc.append(np.concatenate(rows))
            union = np.concatenate(per_proc)
            assert not set(per_proc[0]) & set(per_proc[1])  # disjoint
            assert sorted(union) == list(range(180))        # exact
            # identical global order on both processes
            np.testing.assert_array_equal(a.epoch_order(epoch),
                                          b.epoch_order(epoch))
    finally:
        for ld in loaders:
            ld.stop()


def test_process_split_must_divide_batch(shard_dir):
    d, _data, _labels = shard_dir
    prng.seed_all(1)
    ld = StreamingLoader(DummyWorkflow(), d, minibatch_size=25,
                         process_index=0, process_count=2)
    with pytest.raises(ValueError, match="not divisible"):
        ld.initialize(device=NumpyDevice())


# ----------------------------------------------------------------------
# prefetch behavior
# ----------------------------------------------------------------------
def test_prefetch_crosses_epoch_and_overlaps(shard_dir):
    """With a simulated compute window after each step, the pipeline
    must (a) serve nearly every step from prefetch including the
    first entry of later epochs (the recovered stall the old design
    always paid), and (b) keep the consumer's blocking wait a small
    fraction of the producer's staging work."""
    d, _data, _labels = shard_dir
    ld = make_streaming(d, prefetch_depth=2)
    n_sched = len(ld._schedule)
    steps = 3 * n_sched
    try:
        before_hit = obs_metrics.loader_prefetch(ld.name, "hit").value
        before_x = obs_metrics.loader_prefetch(
            ld.name, "epoch_cross").value
        for _ in range(steps):
            ld.run()
            time.sleep(0.002)  # the "device" chews the batch
        assert ld.prefetch_hits >= steps - 2, (
            ld.prefetch_hits, ld.prefetch_misses)
        assert ld.epoch_cross_prefetches >= 2  # both boundaries served
        # canonical series carry the same story
        assert obs_metrics.loader_prefetch(ld.name, "hit").value \
            - before_hit == ld.prefetch_hits
        assert obs_metrics.loader_prefetch(
            ld.name, "epoch_cross").value - before_x \
            == ld.epoch_cross_prefetches
        assert obs_metrics.REGISTRY.get(
            "znicz_input_wait_seconds") is not None
        assert obs_metrics.REGISTRY.get(
            "znicz_prefetch_depth") is not None
    finally:
        ld.stop()


def test_bounded_staging_memory(shard_dir):
    """The ring pins host staging at ring_slots × batch_bytes no
    matter the dataset size — the 'streams past the resident budget'
    guarantee in miniature."""
    d, _data, _labels = shard_dir
    ld = make_streaming(d, prefetch_depth=3, ring_slots=4)
    try:
        ld.run()
        ring = ld._pipe.ring
        assert ring.n_slots == 4
        assert ring.nbytes == 4 * 24 * DIM  # uint8 batches
        assert ring.nbytes < ld.dataset_nbytes
    finally:
        ld.stop()


# ----------------------------------------------------------------------
# snapshot / resume (mid-epoch)
# ----------------------------------------------------------------------
def test_mid_epoch_resume_consumes_identical_sequence(shard_dir):
    """Interrupt mid-epoch; the resumed loader must consume the exact
    remaining sample sequence of the uninterrupted run (the zero1
    resume-parity pattern applied to the input plane)."""
    d, _data, _labels = shard_dir
    ref = make_streaming(d, seed=5)
    n_sched = len(ref._schedule)
    cut = n_sched + 2            # two entries into epoch 1
    total = 3 * n_sched
    try:
        want = consume_order(ref, total)
    finally:
        ref.stop()

    a = make_streaming(d, seed=5)
    try:
        head = consume_order(a, cut)
        state = a.state_dict()
    finally:
        a.stop()
    assert head == want[:cut]
    prng.seed_all(999)  # resume must not depend on the ambient seed
    b = StreamingLoader(DummyWorkflow(), d, minibatch_size=24)
    b.initialize(device=NumpyDevice())
    b.load_state(state)
    try:
        tail = consume_order(b, total - cut)
    finally:
        b.stop()
    assert tail == want[cut:]


def build_stream_wf(shard_dir, max_epochs=2, minibatch_size=24):
    return StandardWorkflow(
        name="stream_resume",
        loader_factory=lambda w: StreamingLoader(
            w, shard_dir, minibatch_size=minibatch_size,
            prefetch_depth=2, normalization_scale=1 / 127.5,
            normalization_bias=-1.0),
        layers=[{"type": "all2all_tanh",
                 "->": {"output_sample_shape": 16},
                 "<-": {"learning_rate": 0.05,
                        "gradient_moment": 0.9}},
                {"type": "softmax", "->": {"output_sample_shape": 3},
                 "<-": {"learning_rate": 0.05,
                        "gradient_moment": 0.9}}],
        decision_config={"max_epochs": max_epochs})


def gather_params(wf):
    out = []
    for fwd in wf.forwards:
        for name in ("weights", "bias"):
            vec = getattr(fwd, name, None)
            if vec is not None and vec:
                vec.map_read()
                out.append(np.array(vec.mem, copy=True))
    return out


def test_streaming_resume_matches_uninterrupted_training(shard_dir):
    """Workflow-level: 1 epoch + snapshot + 1 more ≡ 2 straight
    epochs — trained weights match (the streamed input sequence after
    resume is the proof's substrate)."""
    d, _data, _labels = shard_dir
    prng.seed_all(3)
    straight = build_stream_wf(d, max_epochs=2)
    straight._max_fires = 100_000
    straight.initialize(device=XLADevice())
    straight.run()
    w_straight = gather_params(straight)
    straight.stop()

    prng.seed_all(3)
    wf1 = build_stream_wf(d, max_epochs=1)
    wf1._max_fires = 100_000
    wf1.initialize(device=XLADevice())
    wf1.run()
    state = wf1.state_dict()
    wf1.stop()
    prng.seed_all(999)
    wf2 = build_stream_wf(d, max_epochs=2)
    wf2._max_fires = 100_000
    wf2.initialize(device=XLADevice())
    wf2.load_state(state)
    wf2.run()
    w_resumed = gather_params(wf2)
    wf2.stop()
    for got, want in zip(w_resumed, w_straight):
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


# ----------------------------------------------------------------------
# end to end on the XLA backend + the mesh
# ----------------------------------------------------------------------
def test_streaming_trains_xla(shard_dir):
    d, _data, _labels = shard_dir
    prng.seed_all(3)
    wf = build_stream_wf(d, max_epochs=8)
    wf._max_fires = 100_000
    wf.initialize(device=XLADevice())
    wf.run()
    try:
        assert wf.decision.min_validation_n_err_pt <= 15.0
        assert wf.loader.prefetch_hits > 0
    finally:
        wf.stop()


def test_streaming_on_mesh_shards_batch(shard_dir):
    from znicz_tpu.parallel import make_mesh
    d, _data, _labels = shard_dir
    prng.seed_all(3)
    wf = build_stream_wf(d, max_epochs=2)
    wf._max_fires = 100_000
    wf.initialize(device=XLADevice(mesh=make_mesh()))
    wf.run()
    try:
        assert wf.decision.min_validation_n_err is not None
        raw = wf.loader.minibatch_raw.devmem
        assert len(raw.sharding.device_set) == 8  # data-sharded upload
        assert not raw.sharding.is_fully_replicated
    finally:
        wf.stop()


def test_streamed_equals_resident_training(shard_dir):
    """The whole point: swapping the resident loader for the streamed
    one changes NOTHING about the trajectory — same seed, same trained
    weights (the gather and normalize run in the same jit region
    either way)."""
    d, data, labels = shard_dir
    prng.seed_all(11)
    res = StandardWorkflow(
        name="resident_arm",
        loader_factory=lambda w: ArrayLoader(
            w, train_data=data[:144], train_labels=labels[:144],
            valid_data=data[144:], valid_labels=labels[144:],
            minibatch_size=24, normalization_scale=1 / 127.5,
            normalization_bias=-1.0),
        layers=[{"type": "all2all_tanh",
                 "->": {"output_sample_shape": 16},
                 "<-": {"learning_rate": 0.05,
                        "gradient_moment": 0.9}},
                {"type": "softmax", "->": {"output_sample_shape": 3},
                 "<-": {"learning_rate": 0.05,
                        "gradient_moment": 0.9}}],
        decision_config={"max_epochs": 2})
    res._max_fires = 100_000
    res.initialize(device=XLADevice())
    res.run()
    w_res = gather_params(res)
    res.stop()

    prng.seed_all(11)
    stream = build_stream_wf(d, max_epochs=2)
    stream._max_fires = 100_000
    stream.initialize(device=XLADevice())
    stream.run()
    w_stream = gather_params(stream)
    stream.stop()
    for got, want in zip(w_stream, w_res):
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_validation_schedule_streams_too(shard_dir):
    d, _data, _labels = shard_dir
    ld = make_streaming(d)
    try:
        classes = []
        for _ in range(len(ld._schedule)):
            ld.run()
            classes.append(ld.minibatch_class)
        assert VALID in classes and TRAIN in classes
    finally:
        ld.stop()


def test_unlabeled_shards(tmp_path):
    data = np.arange(40 * 4, dtype=np.float32).reshape(40, 4)
    d = str(tmp_path / "unlab")
    write_shards(d, data, rows_per_shard=16)
    prng.seed_all(1)
    ld = StreamingLoader(DummyWorkflow(), d, minibatch_size=8)
    ld.initialize(device=NumpyDevice())
    try:
        assert not ld.has_labels
        ld.run()
        assert ld.minibatch_raw.mem.shape == (8, 4)
    finally:
        ld.stop()
