"""ZeRO-1 data-axis optimizer sharding: the sharded update
(reduce-scatter grads → 1/N momentum/decay/clip update → all-gather
params, ``GradientDescentBase._apply_param_zero1``) must be
*invisible* — same trained weights as the replicated update on the
same mesh, for every GD family, every update feature, and across a
snapshot/resume boundary onto a DIFFERENT mesh size.

The replicated arm runs with ``root.common.engine.zero1 = False`` on
the SAME mesh, so the only difference between arms is the update
layout; tolerances are one-reassociation tight (the CPU backend's
all-reduce vs scatter lowerings sum in different orders).
"""

import numpy as np
import pytest

from tests.conftest import make_blobs
from znicz_tpu.backends import XLADevice
from znicz_tpu.loader.fullbatch import ArrayLoader
from znicz_tpu.models.standard_workflow import StandardWorkflow
from znicz_tpu.parallel import make_mesh, zero1_partition
from znicz_tpu.utils import prng
from znicz_tpu.utils.config import root

N_CLASSES, DIM = 3, 12

TIGHT = dict(rtol=1e-5, atol=1e-6)


def build_fc(hidden=16, gd_extra=None, minibatch_size=24, max_epochs=1,
             model_parallel=False):
    data, labels = make_blobs(40, N_CLASSES, DIM)
    gd_cfg = {"learning_rate": 0.1, "gradient_moment": 0.9,
              **(gd_extra or {})}
    col = "column" if model_parallel else None
    row = "row" if model_parallel else None
    wf = StandardWorkflow(
        name="zero1_fc",
        loader_factory=lambda w: ArrayLoader(
            w, train_data=data[:96], train_labels=labels[:96],
            valid_data=data[96:], valid_labels=labels[96:],
            minibatch_size=minibatch_size),
        layers=[
            {"type": "all2all_tanh",
             "->": {"output_sample_shape": hidden, "model_parallel": col},
             "<-": gd_cfg},
            {"type": "all2all_tanh",
             "->": {"output_sample_shape": 8, "model_parallel": row},
             "<-": gd_cfg},
            {"type": "softmax", "->": {"output_sample_shape": N_CLASSES},
             "<-": gd_cfg},
        ],
        decision_config={"max_epochs": max_epochs})
    wf._max_fires = 100_000
    return wf


def gather_params(wf):
    out = []
    for fwd in wf.forwards:
        for name in fwd.EXPORT_PARAMS:
            vec = getattr(fwd, name, None)
            if vec is not None and vec:
                vec.map_read()
                out.append(np.array(vec.mem, copy=True))
    return out


def run_arm(zero1, builder=build_fc, mesh=None, seed=1234, **build_kwargs):
    root.common.engine.zero1 = zero1
    prng.seed_all(seed)
    wf = builder(**build_kwargs)
    wf.initialize(device=XLADevice(mesh=mesh if mesh is not None
                                   else make_mesh()))
    wf.run()
    return gather_params(wf), wf


def assert_arms_match(gd_extra=None, builder=build_fc, mesh_fn=make_mesh,
                      tol=TIGHT, **kwargs):
    w_rep, _ = run_arm(False, builder=builder, mesh=mesh_fn(),
                       gd_extra=gd_extra, **kwargs)
    w_z1, wf = run_arm("auto", builder=builder, mesh=mesh_fn(),
                       gd_extra=gd_extra, **kwargs)
    assert any(getattr(g, "_zero1", False) for g in wf.gds), \
        "zero1 never engaged"
    for a, b in zip(w_rep, w_z1):
        np.testing.assert_allclose(a, b, **tol)
    return wf


# ----------------------------------------------------------------------
# engagement + storage layout
# ----------------------------------------------------------------------
def test_zero1_engages_and_shards_state():
    _, wf = run_arm("auto")
    gd0 = wf.gds[0]
    assert gd0._zero1
    acc = gd0.accumulated_gradient_weights
    assert acc.data_shard_dim == 1          # (12, 16): 16 % 8 == 0
    assert acc.data_shard_pad == 0
    shard = acc.devmem.sharding.shard_shape(acc.devmem.shape)
    assert shard == (12, 16 // 8)           # 1/N stored per chip
    # params come back gathered: every forward sees full weights
    assert wf.forwards[0].weights.devmem.sharding \
        .shard_shape(wf.forwards[0].weights.devmem.shape) == (12, 16)


def test_zero1_gate_off_keeps_replicated_state():
    _, wf = run_arm(False)
    gd0 = wf.gds[0]
    assert not gd0._zero1
    acc = gd0.accumulated_gradient_weights
    assert acc.data_shard_dim is None
    assert acc.devmem.sharding.is_fully_replicated


def test_zero1_single_device_never_engages():
    root.common.engine.zero1 = "auto"
    prng.seed_all(7)
    wf = build_fc()
    wf.initialize(device=XLADevice())  # no mesh
    assert not any(getattr(g, "_zero1", False) for g in wf.gds)


def test_zero1_partition_choice():
    # prefer the largest evenly-divisible dim
    assert zero1_partition((12, 16), 8) == (1, 0)
    assert zero1_partition((576, 32), 8) == (0, 0)
    # model dim excluded; falls to the other dim
    assert zero1_partition((12, 16), 8, model_shard_dim=1) == (0, 4)
    # nothing divides: largest dim, padded up
    assert zero1_partition((13, 5), 8) == (0, 3)
    # degenerate
    assert zero1_partition((), 8) == (None, 0)
    assert zero1_partition((16,), 1) == (None, 0)


# ----------------------------------------------------------------------
# parity: update-rule features (FC family exercises the base path)
# ----------------------------------------------------------------------
def test_zero1_matches_replicated_momentum_l2():
    assert_arms_match(gd_extra={"weights_decay": 0.01})


def test_zero1_matches_replicated_no_momentum():
    assert_arms_match(gd_extra={"gradient_moment": 0.0,
                                "weights_decay": 0.01})


def test_zero1_matches_replicated_l1_decay():
    assert_arms_match(gd_extra={"weights_decay": 0.01, "l1_vs_l2": 0.7})


def test_zero1_matches_replicated_clipping():
    wf = assert_arms_match(gd_extra={"gradient_clip": 0.05,
                                     "weights_decay": 0.01})
    assert wf.gds[0].gradient_clip == 0.05


def test_gradient_clip_actually_clips():
    """Oracle-level: a huge raw gradient is rescaled to the clip norm
    (the zero1-vs-replicated parity above proves layouts agree; this
    proves the feature does something), and a small one passes
    through untouched."""
    unit = build_fc().gds[0]
    unit.gradient_clip = 1.0
    g = np.full((4, 4), 100.0, np.float32)
    clipped = unit._clipped(np, g)
    np.testing.assert_allclose(np.sqrt((clipped ** 2).sum()), 1.0,
                               rtol=1e-5)
    small = np.full((4, 4), 1e-3, np.float32)
    np.testing.assert_allclose(unit._clipped(np, small), small)
    unit.gradient_clip = 0.0
    assert unit._clipped(np, g) is g


def test_zero1_matches_replicated_bf16_state():
    root.common.precision_type = "bfloat16"
    try:
        # bf16 rounds both arms identically only while layouts agree —
        # band is looser than f32 but still tiny for 1 epoch
        assert_arms_match(gd_extra={"weights_decay": 0.01},
                          tol=dict(rtol=1e-2, atol=1e-3))
    finally:
        root.common.precision_type = "float32"


def test_zero1_bf16_grad_comms_parity():
    """The bf16 reduce-scatter lever (default OFF, convergence-gated):
    engaging it on the virtual mesh must stay within a bf16-rounding
    band of the f32-comms zero1 run."""
    w_f32, _ = run_arm("auto", gd_extra={"weights_decay": 0.01})
    root.common.engine.bf16_grad_comms = True
    try:
        w_bf16, wf = run_arm("auto", gd_extra={"weights_decay": 0.01})
        assert any(g._grad_comms_bf16 for g in wf.gds)
    finally:
        root.common.engine.bf16_grad_comms = False
    for a, b in zip(w_f32, w_bf16):
        np.testing.assert_allclose(a, b, rtol=0.05, atol=5e-3)


# ----------------------------------------------------------------------
# parity: padding (indivisible weight shapes) and DP × TP
# ----------------------------------------------------------------------
def test_zero1_padding_indivisible_shape():
    wf = assert_arms_match(hidden=13)  # (12,13)/(13,8): nothing % 8
    gd0 = wf.gds[0]
    acc = gd0.accumulated_gradient_weights
    assert acc.data_shard_pad > 0
    assert acc.shape[acc.data_shard_dim] % 8 == 0
    # pad rows never accumulate anything
    acc.map_read()
    pad = acc.data_shard_pad
    dim = acc.data_shard_dim
    idx = [slice(None)] * len(acc.shape)
    idx[dim] = slice(acc.shape[dim] - pad, None)
    np.testing.assert_array_equal(np.asarray(acc.mem[tuple(idx)],
                                             dtype=np.float32), 0.0)


def test_zero1_dp_tp_compose():
    """ZeRO-1 over the data axis with Megatron column/row sharding
    over the model axis in the same program."""
    wf = assert_arms_match(mesh_fn=lambda: make_mesh(n_data=2, n_model=4),
                           model_parallel=True,
                           gd_extra={"weights_decay": 0.01})
    col_gd = wf.gds[0]
    acc = col_gd.accumulated_gradient_weights
    # column weights (12, 16): model rides dim 1, so data takes dim 0
    assert acc.model_shard_dim == 1
    assert acc.data_shard_dim == 0
    shard = acc.devmem.sharding.shard_shape(acc.devmem.shape)
    assert shard == (12 // 2, 16 // 4)


# ----------------------------------------------------------------------
# parity: conv / deconv / attention+layer-norm families
# ----------------------------------------------------------------------
def _image_blobs(n_per_class=24, size=8):
    rng = np.random.default_rng(5)
    protos = rng.normal(0, 1, size=(N_CLASSES, size, size, 1))
    data = np.concatenate([
        p + 0.4 * rng.normal(size=(n_per_class, size, size, 1))
        for p in protos]).astype(np.float32)
    labels = np.repeat(np.arange(N_CLASSES), n_per_class).astype(np.int32)
    order = rng.permutation(len(data))
    return data[order], labels[order]


def build_conv(gd_extra=None, max_epochs=1):
    data, labels = _image_blobs()
    gd_cfg = {"learning_rate": 0.02, "gradient_moment": 0.9,
              "weights_decay": 0.001, **(gd_extra or {})}
    wf = StandardWorkflow(
        name="zero1_conv",
        loader_factory=lambda w: ArrayLoader(
            w, train_data=data[:64], train_labels=labels[:64],
            valid_data=data[64:], valid_labels=labels[64:],
            minibatch_size=16),
        layers=[
            {"type": "conv_tanh",
             "->": {"n_kernels": 8, "kx": 3, "ky": 3}, "<-": gd_cfg},
            {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
            {"type": "softmax", "->": {"output_sample_shape": N_CLASSES},
             "<-": gd_cfg},
        ],
        decision_config={"max_epochs": max_epochs})
    wf._max_fires = 100_000
    return wf


def build_deconv_ae(gd_extra=None, max_epochs=1):
    data, labels = _image_blobs()
    gd_cfg = {"learning_rate": 0.02, "gradient_moment": 0.9,
              **(gd_extra or {})}
    wf = StandardWorkflow(
        name="zero1_ae",
        loader_factory=lambda w: ArrayLoader(
            w, train_data=data[:64], train_labels=labels[:64],
            minibatch_size=16),
        layers=[
            {"type": "conv_tanh",
             "->": {"n_kernels": 8, "kx": 3, "ky": 3}, "<-": gd_cfg},
            {"type": "deconv_tanh", "tied_to": 0, "<-": gd_cfg},
        ],
        loss="mse",
        decision_config={"max_epochs": max_epochs})
    wf._max_fires = 100_000
    return wf


def build_attention(gd_extra=None, max_epochs=1):
    from tests.conftest import positional_task_workflow
    gd_cfg = {"learning_rate": 0.05, "gradient_moment": 0.9,
              **(gd_extra or {})}
    wf = positional_task_workflow(
        layers=[
            {"type": "attention", "->": {"n_heads": 2}, "<-": gd_cfg},
            {"type": "layer_norm", "->": {}, "<-": gd_cfg},
            {"type": "softmax", "->": {"output_sample_shape": 3},
             "<-": gd_cfg},
        ],
        max_epochs=max_epochs)
    wf._max_fires = 100_000
    return wf


def test_zero1_matches_replicated_conv():
    assert_arms_match(builder=build_conv)


def test_zero1_matches_replicated_deconv():
    assert_arms_match(builder=build_deconv_ae)


def test_zero1_matches_replicated_attention_layer_norm():
    wf = assert_arms_match(builder=build_attention)
    gd_attn = next(g for g in wf.gds
                   if type(g).__name__ == "GDMultiHeadAttention")
    # the EXTRA parameter pair (output projection) shards too
    acc_out = gd_attn.accumulated_gradient_weights_out
    assert acc_out.data_shard_dim is not None
    assert not acc_out.devmem.sharding.is_fully_replicated


# ----------------------------------------------------------------------
# snapshot / resume, including onto a different mesh size
# ----------------------------------------------------------------------
@pytest.mark.slow
def test_zero1_resume_matches_uninterrupted():
    """1 epoch + snapshot + resume for 1 more epoch ≡ 2 straight
    epochs, all arms ZeRO-1 on the 8-way mesh."""
    w_straight, _ = run_arm("auto", max_epochs=2,
                            gd_extra={"weights_decay": 0.01})
    _, wf1 = run_arm("auto", max_epochs=1,
                     gd_extra={"weights_decay": 0.01})
    state = wf1.state_dict()
    prng.seed_all(1)  # resume must not depend on ambient seed
    root.common.engine.zero1 = "auto"
    wf2 = build_fc(max_epochs=2, gd_extra={"weights_decay": 0.01})
    wf2.initialize(device=XLADevice(mesh=make_mesh()))
    wf2.load_state(state)
    wf2.run()
    for got, want in zip(gather_params(wf2), w_straight):
        np.testing.assert_allclose(got, want, **TIGHT)


def test_zero1_snapshot_restores_bitwise_on_smaller_mesh():
    """The checkpoint is layout-independent: state saved from the
    8-way ZeRO-1 run restores BITWISE onto a 2-way mesh (whose padding
    and shard layout differ), and training continues."""
    _, wf8 = run_arm("auto", hidden=13,  # padded case: 13 → 16 on 8-way
                     gd_extra={"weights_decay": 0.01})
    state = wf8.state_dict()
    gd8 = wf8.gds[0]
    gd8.accumulated_gradient_weights.map_read()
    saved_logical = gd8.accumulated_gradient_weights.strip_data_pad(
        gd8.accumulated_gradient_weights.mem)

    root.common.engine.zero1 = "auto"
    prng.seed_all(77)
    wf2 = build_fc(hidden=13, max_epochs=2,
                   gd_extra={"weights_decay": 0.01})
    wf2.initialize(device=XLADevice(mesh=make_mesh(n_data=2, n_model=1)))
    wf2.load_state(state)
    gd2 = wf2.gds[0]
    acc2 = gd2.accumulated_gradient_weights
    assert acc2.data_shard_pad != \
        gd8.accumulated_gradient_weights.data_shard_pad  # 13→14 vs 13→16
    acc2.map_read()
    np.testing.assert_array_equal(acc2.strip_data_pad(acc2.mem),
                                  saved_logical)  # bitwise
    for fwd8, fwd2 in zip(wf8.forwards, wf2.forwards):
        fwd8.weights.map_read()
        fwd2.weights.map_read()
        np.testing.assert_array_equal(fwd2.weights.mem, fwd8.weights.mem)
    wf2.run()  # and the restored state actually trains on the new mesh
    assert wf2.decision.complete


def test_zero1_snapshot_restores_on_single_device():
    """ZeRO-1 state also restores onto a meshless single device (the
    export/serve regime): annotations are per-Vector, so a fresh
    single-device build simply never shards."""
    _, wf8 = run_arm("auto", gd_extra={"weights_decay": 0.01})
    state = wf8.state_dict()
    root.common.engine.zero1 = "auto"
    prng.seed_all(3)
    wf1 = build_fc(max_epochs=2, gd_extra={"weights_decay": 0.01})
    wf1.initialize(device=XLADevice())
    wf1.load_state(state)
    gd8, gd1 = wf8.gds[0], wf1.gds[0]
    gd8.accumulated_gradient_weights.map_read()
    gd1.accumulated_gradient_weights.map_read()
    np.testing.assert_array_equal(
        gd1.accumulated_gradient_weights.mem,
        gd8.accumulated_gradient_weights.strip_data_pad(
            gd8.accumulated_gradient_weights.mem))


# ----------------------------------------------------------------------
# chunked dispatch: the sharded update must survive lax.scan
# ----------------------------------------------------------------------
def test_zero1_chunked_matches_per_step():
    w_step, _ = run_arm("auto", gd_extra={"weights_decay": 0.01})
    root.common.engine.zero1 = "auto"
    prng.seed_all(1234)
    wf = build_fc(gd_extra={"weights_decay": 0.01})
    wf.initialize(device=XLADevice(mesh=make_mesh()))
    wf.run_chunked(steps_per_dispatch=4)
    for got, want in zip(gather_params(wf), w_step):
        np.testing.assert_allclose(got, want, **TIGHT)
    # state stayed sharded through the scan carry
    acc = wf.gds[0].accumulated_gradient_weights
    assert not acc.devmem.sharding.is_fully_replicated
