"""Unified telemetry (round 9): metrics registry, host-span tracer,
exposition formats and the web endpoints.

Covers the observe/ contract points:

- registry concurrency: a 4-thread hammer lands exactly the serial
  totals (counters, gauges, histogram sum/count);
- histogram percentile math against the numpy oracle (error bounded
  by the containing bucket's width);
- span nesting/ordering and the Chrome-trace event shape;
- Prometheus text exposition golden test;
- ``/metrics`` + ``/trace.json`` round-trip through WebStatusServer;
- the ``engine.telemetry`` gate actually gates;
- transfer-byte counters through the Vector map/unmap protocol;
- instrumented workflow training registers the core series.
"""

from __future__ import annotations

import json
import threading
import urllib.request

import numpy as np
import pytest

from znicz_tpu.observe import metrics as obs_metrics
from znicz_tpu.observe import tracing as obs_tracing
from znicz_tpu.observe.metrics import MetricsRegistry
from znicz_tpu.observe.tracing import SpanTracer


# ----------------------------------------------------------------------
# registry
# ----------------------------------------------------------------------
def test_counter_gauge_basics():
    reg = MetricsRegistry()
    c = reg.counter("c_total", "a counter")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("g", "a gauge")
    g.set(7)
    g.inc(3)
    g.dec(5)
    assert g.value == 5.0
    g.set_function(lambda: 42)
    assert g.value == 42.0


def test_family_redeclaration_idempotent_and_checked():
    reg = MetricsRegistry()
    a = reg.counter("x_total", labels=("k",))
    b = reg.counter("x_total", labels=("k",))
    assert a is b
    with pytest.raises(ValueError):
        reg.gauge("x_total")  # kind mismatch
    with pytest.raises(ValueError):
        reg.counter("x_total", labels=("other",))  # label mismatch
    with pytest.raises(ValueError):
        a.labels(wrong="v")  # undeclared label name
    with pytest.raises(ValueError):
        a.inc()  # labeled family has no solo child


def test_registry_concurrency_matches_serial_totals():
    """4-thread hammer ≡ serial totals (the registry's one lock)."""
    reg = MetricsRegistry()
    cnt = reg.counter("hammer_total", labels=("t",))
    hist = reg.histogram("hammer_seconds", buckets=(0.25, 0.5, 0.75))
    gauge = reg.gauge("hammer_gauge")
    n_per_thread = 2000

    def work(tid: int):
        child = cnt.labels(t=str(tid))
        for i in range(n_per_thread):
            child.inc()
            cnt.labels(t="shared").inc(2)
            hist.observe((i % 100) / 100.0)
            gauge.inc()

    threads = [threading.Thread(target=work, args=(t,))
               for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    for t in range(4):
        assert cnt.labels(t=str(t)).value == n_per_thread
    assert cnt.labels(t="shared").value == 2 * 4 * n_per_thread
    h = hist.labels()
    assert h.count == 4 * n_per_thread
    # serial oracle for the bucket counts and the sum
    vals = [(i % 100) / 100.0 for i in range(n_per_thread)] * 4
    assert h.sum == pytest.approx(sum(vals))
    assert h.counts[0] == sum(1 for v in vals if v <= 0.25)
    assert gauge.value == 4 * n_per_thread


def test_histogram_percentiles_vs_numpy_oracle():
    reg = MetricsRegistry()
    bounds = tuple(np.linspace(0.01, 1.0, 34))
    hist = reg.histogram("lat_seconds", buckets=bounds).labels()
    rng = np.random.default_rng(11)
    vals = rng.gamma(2.0, 0.08, size=5000)  # latency-shaped
    for v in vals:
        hist.observe(float(v))
    for q in (50, 90, 95, 99):
        est = hist.percentile(q)
        true = float(np.percentile(vals, q))
        # bucket-interpolated estimate: error bounded by the width of
        # the bucket the true quantile falls in
        import bisect
        i = bisect.bisect_left(bounds, true)
        lo = bounds[i - 1] if i > 0 else 0.0
        hi = bounds[i] if i < len(bounds) else float(vals.max())
        width = hi - lo
        assert abs(est - true) <= width + 1e-9, (q, est, true, width)
    assert hist.percentile(0) >= 0.0
    empty = reg.histogram("empty_seconds").labels()
    assert empty.percentile(50) == 0.0


def test_prometheus_exposition_golden():
    reg = MetricsRegistry()
    c = reg.counter("req_total", "Requests.", labels=("event",))
    c.labels(event="ok").inc(3)
    c.labels(event="err").inc()
    reg.gauge("depth", "Queue depth.").set(2.5)
    h = reg.histogram("lat_seconds", "Latency.", buckets=(0.1, 1.0))
    h.observe(0.0625)   # binary-exact values: the _sum line must
    h.observe(0.5)      # render without float fuzz
    h.observe(5.0)
    expected = "\n".join([
        "# HELP req_total Requests.",
        "# TYPE req_total counter",
        'req_total{event="ok"} 3',
        'req_total{event="err"} 1',
        "# HELP depth Queue depth.",
        "# TYPE depth gauge",
        "depth 2.5",
        "# HELP lat_seconds Latency.",
        "# TYPE lat_seconds histogram",
        'lat_seconds_bucket{le="0.1"} 1',
        'lat_seconds_bucket{le="1"} 2',
        'lat_seconds_bucket{le="+Inf"} 3',
        "lat_seconds_sum 5.5625",
        "lat_seconds_count 3",
    ]) + "\n"
    assert reg.to_prometheus() == expected


def test_json_exposition_shape():
    reg = MetricsRegistry()
    reg.counter("a_total", "A.", labels=("k",)).labels(k="x").inc(2)
    h = reg.histogram("b_seconds", buckets=(1.0,))
    h.observe(0.5)
    out = reg.to_json()
    assert out["a_total"]["type"] == "counter"
    assert out["a_total"]["values"] == [
        {"labels": {"k": "x"}, "value": 2.0}]
    hrow = out["b_seconds"]["values"][0]
    assert hrow["count"] == 1 and hrow["sum"] == 0.5
    assert hrow["buckets"]["1"] == 1 and hrow["buckets"]["+Inf"] == 0
    json.dumps(out)  # must be JSON-serializable as-is


def test_label_escaping():
    reg = MetricsRegistry()
    reg.counter("esc_total", labels=("p",)).labels(
        p='a"b\\c\nd').inc()
    text = reg.to_prometheus()
    assert r'p="a\"b\\c\nd"' in text


# ----------------------------------------------------------------------
# tracer
# ----------------------------------------------------------------------
def test_span_nesting_and_ordering():
    tracer = SpanTracer()
    with tracer.span("outer", cat="t"):
        with tracer.span("mid", cat="t"):
            with tracer.span("inner", cat="t"):
                pass
        with tracer.span("mid2", cat="t"):
            pass
    events = tracer.to_chrome_trace()["traceEvents"]
    spans = {ev["name"]: ev for ev in events if ev.get("ph") == "X"}
    assert list(ev["name"] for ev in events if ev.get("ph") == "X") \
        == ["inner", "mid", "mid2", "outer"]  # completion order
    assert spans["outer"]["args"]["depth"] == 0
    assert spans["mid"]["args"]["depth"] == 1
    assert spans["inner"]["args"]["depth"] == 2
    # interval containment: children inside parents
    for child, parent in (("inner", "mid"), ("mid", "outer"),
                          ("mid2", "outer")):
        c, p = spans[child], spans[parent]
        assert c["ts"] >= p["ts"] - 1e-6
        assert c["ts"] + c["dur"] <= p["ts"] + p["dur"] + 1e-6
    # mid2 starts after mid ends (ordering within a level)
    assert spans["mid2"]["ts"] >= spans["mid"]["ts"] + spans["mid"]["dur"]


def test_span_ring_buffer_bounded_and_mark():
    tracer = SpanTracer(max_events=8)
    for i in range(20):
        with tracer.span(f"s{i}"):
            pass
    assert len(tracer) == 8
    mark = tracer.mark()
    with tracer.span("after_mark"):
        pass
    windowed = tracer.to_chrome_trace(since=mark)["traceEvents"]
    names = [ev["name"] for ev in windowed if ev.get("ph") == "X"]
    assert names == ["after_mark"]


def test_tracer_exception_still_records_and_unwinds():
    tracer = SpanTracer()
    with pytest.raises(RuntimeError):
        with tracer.span("outer"):
            with tracer.span("boom"):
                raise RuntimeError("x")
    spans = [ev for ev in tracer.to_chrome_trace()["traceEvents"]
             if ev.get("ph") == "X"]
    assert [s["name"] for s in spans] == ["boom", "outer"]
    with tracer.span("fresh"):  # stack unwound: depth back to 0
        pass
    fresh = [ev for ev in tracer.to_chrome_trace()["traceEvents"]
             if ev.get("ph") == "X"][-1]
    assert fresh["args"]["depth"] == 0


def test_profile_window_writes_host_spans(tmp_path):
    from znicz_tpu.observe import profile_window
    tracer = SpanTracer()
    outdir = str(tmp_path / "win")
    with profile_window(outdir, n_steps=4, device=False,
                        tracer=tracer):
        with tracer.span("step"):
            pass
    path = tmp_path / "win" / "host_spans.trace.json"
    assert path.exists()
    data = json.loads(path.read_text())
    names = [ev["name"] for ev in data["traceEvents"]
             if ev.get("ph") == "X"]
    assert names == ["step", "profile_window"]
    window = [ev for ev in data["traceEvents"]
              if ev.get("name") == "profile_window"][0]
    assert window["args"]["n_steps"] == 4


# ----------------------------------------------------------------------
# the telemetry gate
# ----------------------------------------------------------------------
def test_telemetry_gate_disables_instrumentation():
    from znicz_tpu.units import Unit
    from znicz_tpu.utils.config import root

    root.common.engine.telemetry = False
    tracer_mark = obs_tracing.TRACER.mark()
    fam = obs_metrics.REGISTRY.get("znicz_unit_run_seconds")
    before = fam.labels(unit="gated_unit").count if fam else 0

    u = Unit(None, name="gated_unit")
    u._fire()
    assert u.run_count == 1  # the unit itself still runs + times
    assert obs_tracing.TRACER.mark() == tracer_mark  # no span
    fam = obs_metrics.REGISTRY.get("znicz_unit_run_seconds")
    after = fam.labels(unit="gated_unit").count if fam else 0
    assert after == before  # no histogram sample

    root.common.engine.telemetry = True
    u._fire()
    assert obs_metrics.REGISTRY.get("znicz_unit_run_seconds") \
        .labels(unit="gated_unit").count == before + 1
    assert obs_tracing.TRACER.mark() == tracer_mark + 1


def test_vector_transfer_byte_counters():
    from znicz_tpu.backends import XLADevice
    from znicz_tpu.memory import Vector

    h2d = obs_metrics.transfer_bytes("h2d")
    d2h = obs_metrics.transfer_bytes("d2h")
    base_up, base_down = h2d.value, d2h.value
    arr = np.arange(64, dtype=np.float32).reshape(8, 8)
    vec = Vector(arr, name="obs_probe")
    vec.initialize(XLADevice())          # upload: +256 bytes h2d
    assert h2d.value == base_up + arr.nbytes
    vec.devmem = vec.devmem + 1.0        # device-authoritative now
    vec.map_read()                       # fetch: +256 bytes d2h
    assert d2h.value == base_down + arr.nbytes
    vec.map_write()
    vec.unmap()                          # re-upload after host write
    assert h2d.value == base_up + 2 * arr.nbytes


# ----------------------------------------------------------------------
# web endpoints + end-to-end series registration
# ----------------------------------------------------------------------
def _get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=30) as resp:
        return resp.read()


def test_metrics_and_trace_endpoints_roundtrip():
    from znicz_tpu.web_status import WebStatusServer

    obs_metrics.REGISTRY.counter(
        "endpoint_probe_total", "Probe.").inc(5)
    with obs_tracing.TRACER.span("endpoint_probe_span"):
        pass
    server = WebStatusServer(port=0)
    try:
        text = _get(
            f"http://127.0.0.1:{server.port}/metrics").decode()
        assert "# TYPE endpoint_probe_total counter" in text
        assert "endpoint_probe_total 5" in text
        trace = json.loads(_get(
            f"http://127.0.0.1:{server.port}/trace.json"))
        names = [ev["name"] for ev in trace["traceEvents"]
                 if ev.get("ph") == "X"]
        assert "endpoint_probe_span" in names
    finally:
        server.stop()


def test_training_registers_core_series():
    """One tiny trained workflow populates compile counter, unit run
    histogram, region steps, epoch counter and transfer bytes — the
    series the dryrun attestation and the verify scrape assert on."""
    from conftest import make_blobs
    from znicz_tpu.backends import XLADevice
    from znicz_tpu.loader.fullbatch import ArrayLoader
    from znicz_tpu.models.standard_workflow import StandardWorkflow

    data, labels = make_blobs(24, 3, 10)
    wf = StandardWorkflow(
        name="obs_train",
        loader_factory=lambda w: ArrayLoader(
            w, train_data=data[:48], train_labels=labels[:48],
            valid_data=data[48:], valid_labels=labels[48:],
            minibatch_size=12),
        layers=[{"type": "all2all_tanh",
                 "->": {"output_sample_shape": 16},
                 "<-": {"learning_rate": 0.05}},
                {"type": "softmax", "->": {"output_sample_shape": 3},
                 "<-": {"learning_rate": 0.05}}],
        decision_config={"max_epochs": 2})
    wf._max_fires = 100_000
    wf.initialize(device=XLADevice())
    wf.run()

    compiles = obs_metrics.xla_compiles(
        f"region:{wf._region_unit.name}")
    assert compiles.value >= 2  # train + eval variants at least
    unit_hist = obs_metrics.REGISTRY.get("znicz_unit_run_seconds")
    fired = {key[0] for key, child in unit_hist.items()
             if child.count > 0}
    assert wf.loader.name in fired and wf._region_unit.name in fired
    assert obs_metrics.region_steps(wf._region_unit.name).value > 0
    assert obs_metrics.epochs_total("obs_train").value >= 2
    assert obs_metrics.transfer_bytes("h2d").value > 0
    # epochs left retroactive spans on the tracer
    epoch_spans = [ev for ev in
                   obs_tracing.TRACER.to_chrome_trace()["traceEvents"]
                   if ev.get("ph") == "X"
                   and ev.get("cat") == "epoch"
                   and ev.get("args", {}).get("workflow") == "obs_train"]
    assert len(epoch_spans) >= 2
    # and the prometheus exposition renders it all without error
    text = obs_metrics.REGISTRY.to_prometheus()
    assert "znicz_xla_compiles_total" in text
    assert "znicz_unit_run_seconds_bucket" in text
