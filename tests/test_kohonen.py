"""Kohonen SOM: forward winner math + trainer update vs oracle, and
functional self-organization (reference pattern:
``znicz/tests/unit/test_kohonen.py``)."""

import numpy as np
import pytest

from znicz_tpu.backends import NumpyDevice, XLADevice
from znicz_tpu.dummy import DummyUnit, DummyWorkflow
from znicz_tpu.memory import Vector
from znicz_tpu.models.samples import kohonen as kohonen_sample
from znicz_tpu.ops.kohonen import KohonenForward, KohonenTrainer

RNG = np.random.default_rng(77)


def build_pair(device, x, w, **trainer_kwargs):
    wf = DummyWorkflow()
    src = DummyUnit(wf, output=Vector(x.copy(), name="x"))
    fwd = KohonenForward(wf, shape=(3, 4))
    fwd.link_attrs(src, ("input", "output"))
    fwd.weights.reset(w.copy())
    fwd.initialize(device=device)
    tr = KohonenTrainer(wf, **trainer_kwargs)
    tr.link_attrs(src, ("input", "output"))
    tr.link_attrs(fwd, "weights", "winners")
    tr.shape_grid = (3, 4)
    tr.initialize(device=device)
    return fwd, tr


def test_forward_and_trainer_agreement():
    x = RNG.normal(size=(10, 5)).astype(np.float32)
    w = RNG.normal(size=(12, 5)).astype(np.float32)
    outs = {}
    for name, device in (("np", NumpyDevice()), ("xla", XLADevice())):
        fwd, tr = build_pair(device, x, w, learning_rate=0.4,
                             decay_steps=50)
        for _ in range(3):           # three steps advance the clock too
            fwd.run()
            tr.run()
        for vec in (fwd.winners, fwd.output, fwd.weights, tr.time):
            vec.map_read()
        outs[name] = (fwd.winners.mem.copy(), fwd.output.mem.copy(),
                      fwd.weights.mem.copy(), float(tr.time.mem))
    np.testing.assert_array_equal(outs["np"][0], outs["xla"][0])
    np.testing.assert_allclose(outs["np"][1], outs["xla"][1],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(outs["np"][2], outs["xla"][2],
                               rtol=1e-4, atol=1e-5)
    assert outs["np"][3] == outs["xla"][3] == 3.0


def test_forward_winner_golden():
    wf = DummyWorkflow()
    w = np.zeros((12, 2), np.float32)
    w[7] = [1.0, 1.0]
    x = np.array([[0.9, 1.1], [-5.0, -5.0]], np.float32)
    src = DummyUnit(wf, output=Vector(x, name="x"))
    fwd = KohonenForward(wf, shape=(3, 4))
    fwd.link_attrs(src, ("input", "output"))
    fwd.weights.reset(w)
    fwd.initialize(device=NumpyDevice())
    fwd.run()
    assert fwd.winners.mem[0] == 7          # nearest is the [1,1] neuron
    assert fwd.winners.mem[1] != 7
    fwd.hits.map_read()
    assert fwd.hits.mem.sum() == 2


def test_trainer_pulls_weights_toward_data():
    """One update moves the winner's weight strictly toward the sample."""
    x = np.tile([2.0, 2.0], (8, 1)).astype(np.float32)
    w = RNG.normal(size=(12, 2)).astype(np.float32)
    fwd, tr = build_pair(NumpyDevice(), x, w, learning_rate=0.5)
    fwd.run()
    before = np.linalg.norm(fwd.weights.mem - [2.0, 2.0], axis=1).copy()
    tr.run()
    after = np.linalg.norm(fwd.weights.mem - [2.0, 2.0], axis=1)
    assert (after < before + 1e-6).all()     # nobody moves away
    assert after[fwd.winners.mem[0]] < before[fwd.winners.mem[0]]


@pytest.mark.parametrize("device_cls", [NumpyDevice, XLADevice])
def test_som_sample_organizes(device_cls):
    """Functional: quantization error drops sharply vs the first epoch
    and most neurons get used (the map unfolds)."""
    wf = kohonen_sample.build(max_epochs=1)
    wf.initialize(device=device_cls())
    wf.run()
    first_qe = wf.decision.epoch_qe
    wf2 = kohonen_sample.build(max_epochs=10)
    wf2.initialize(device=device_cls())
    wf2.run()
    assert wf2.decision.best_qe < 0.5 * first_qe, (
        f"SOM did not organize: first {first_qe}, "
        f"best {wf2.decision.best_qe}")
