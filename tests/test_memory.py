"""Vector map/unmap state machine (reference test analogue:
``veles/tests/test_memory.py``)."""

import numpy as np
import pytest

from znicz_tpu.backends import NumpyDevice, XLADevice
from znicz_tpu.memory import Vector


def test_empty_vector_falsy():
    v = Vector(name="v")
    assert not v
    with pytest.raises(ValueError):
        v.map_read()
    with pytest.raises(ValueError):
        v.unmap()


def test_host_roundtrip_numpy_device():
    v = Vector(np.arange(6, dtype=np.float32).reshape(2, 3), name="v")
    v.initialize(NumpyDevice())
    v.map_read()
    assert v.mem.sum() == 15
    v.unmap()  # no-op on host-only backend
    assert v.mem.sum() == 15


def test_xla_upload_download():
    dev = XLADevice()
    v = Vector(np.arange(4, dtype=np.float32), name="v")
    v.initialize(dev)
    v.unmap()
    assert v.state_name == "DEVICE"
    # device access fine, host access must be guarded
    assert v.devmem.shape == (4,)
    with pytest.raises(ValueError):
        _ = v.mem
    v.map_read()
    np.testing.assert_array_equal(v.mem, [0, 1, 2, 3])


def test_host_write_uploads_on_unmap():
    dev = XLADevice()
    v = Vector(np.zeros(3, dtype=np.float32), name="v")
    v.initialize(dev)
    v.unmap()
    v.map_write()
    v.mem[...] = 7
    v.unmap()
    np.testing.assert_array_equal(np.asarray(v.devmem), [7, 7, 7])


def test_map_invalidate_skips_fetch():
    dev = XLADevice()
    v = Vector(np.zeros(3, dtype=np.float32), name="v")
    v.initialize(dev)
    v.unmap()
    v.map_invalidate()
    v.mem[...] = 5
    v.unmap()
    np.testing.assert_array_equal(np.asarray(v.devmem), [5, 5, 5])


def test_device_access_while_host_dirty_raises():
    dev = XLADevice()
    v = Vector(np.zeros(3, dtype=np.float32), name="v")
    v.initialize(dev)
    v.unmap()
    v.map_write()
    with pytest.raises(ValueError, match="unmap"):
        _ = v.devmem


def test_tracing_guards():
    v = Vector(np.zeros(3, dtype=np.float32), name="v")
    v._tracing = True
    with pytest.raises(RuntimeError, match="jit region"):
        v.map_read()
    with pytest.raises(RuntimeError, match="jit region"):
        v.unmap()


def test_sample_size_and_len():
    v = Vector(np.zeros((8, 3, 2), dtype=np.float32), name="v")
    assert len(v) == 8
    assert v.sample_size == 6
    assert v.size == 48
