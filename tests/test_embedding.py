"""Embedding op: gather forward vs oracle, scatter-add gradient vs
finite differences, and a token-sequence model trained end to end."""

import numpy as np

from znicz_tpu.backends import NumpyDevice, XLADevice
from znicz_tpu.dummy import DummyUnit, DummyWorkflow
from znicz_tpu.memory import Vector
from znicz_tpu.ops import embedding
from znicz_tpu.utils import prng

B, T, V, D = 3, 6, 11, 8


def build(device, tokens, gd=False):
    prng.seed_all(8)
    wf = DummyWorkflow()
    src = DummyUnit(wf, output=Vector(
        np.asarray(tokens, np.float32), name="tok"))
    fwd = embedding.Embedding(wf, vocab_size=V, dim=D)
    fwd.link_attrs(src, ("input", "output"))
    fwd.initialize(device=device)
    if not gd:
        return fwd
    unit = embedding.GDEmbedding(wf, learning_rate=0.1,
                                 gradient_moment=0.9)
    unit.forward_unit = fwd
    unit.link_attrs(fwd, "input", "output", "weights", "bias")
    unit.err_output = Vector(
        np.zeros((tokens.shape[0], tokens.shape[1], D), np.float32),
        name="err", batch_major=True)
    unit.initialize(device=device)
    return fwd, unit


def _tokens(seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, V, size=(B, T)).astype(np.int32)


def test_forward_oracle_agreement():
    tokens = _tokens()
    np_u = build(NumpyDevice(), tokens)
    xla_u = build(XLADevice(), tokens)
    xla_u.weights.reset(np_u.weights.mem.copy())
    xla_u.weights.initialize(xla_u.device)
    np_u.run()
    xla_u.run()
    np_u.output.map_read()
    xla_u.output.map_read()
    np.testing.assert_allclose(
        np.asarray(xla_u.output.mem, np.float32), np_u.output.mem,
        rtol=1e-4, atol=1e-5)
    # the gather really indexes the table
    np.testing.assert_allclose(np_u.output.mem[0, 0],
                               np_u.weights.mem[tokens[0, 0]])
    # out-of-vocab ids clamp instead of crashing
    np_u.input.reset(np.full((B, T), V + 3, np.float32))
    np_u.run()
    np.testing.assert_allclose(np_u.output.mem[0, 0],
                               np_u.weights.mem[V - 1])


def test_scatter_gradient_matches_oracle():
    """Repeated tokens must ACCUMULATE gradient (the classic
    scatter-add bug is last-writer-wins)."""
    tokens = np.zeros((1, 4), np.int32)  # all four positions, token 0
    err = np.random.default_rng(2).normal(
        size=(1, 4, D)).astype(np.float32)
    updated = {}
    for device in (NumpyDevice(), XLADevice()):
        fwd, gd_u = build(device, tokens, gd=True)
        w0 = fwd.weights.mem.copy()
        fwd.run()
        gd_u.err_output.reset(err.copy())
        gd_u.err_output.initialize(device)
        gd_u.run()
        fwd.weights.map_read()
        updated[type(device).__name__] = (w0, fwd.weights.mem.copy())
    for w0, w1 in updated.values():
        # token 0's row moved by lr * sum of all four errors
        expected = w0[0] - 0.1 * err[0].sum(axis=0)
        np.testing.assert_allclose(w1[0], expected, rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_array_equal(w1[1:], w0[1:])  # others frozen
    np.testing.assert_allclose(updated["NumpyDevice"][1],
                               updated["XLADevice"][1],
                               rtol=1e-4, atol=1e-5)


def test_token_model_trains():
    """embedding → pos_encoding → attention → softmax learns which
    marker TOKEN appears in the sequence (pure token-id input)."""
    from znicz_tpu.loader.fullbatch import ArrayLoader
    from znicz_tpu.models.standard_workflow import StandardWorkflow

    rng = np.random.default_rng(61)
    n, t, n_classes = 120, 8, 3
    # background tokens 3..10; class c plants marker token c somewhere
    x = rng.integers(3, V, size=(n, t)).astype(np.float32)
    y = rng.integers(0, n_classes, size=n).astype(np.int32)
    for i in range(n):
        x[i, rng.integers(0, t)] = y[i]
    prng.seed_all(62)
    wf = StandardWorkflow(
        name="token_wf",
        loader_factory=lambda w: ArrayLoader(
            w, train_data=x[:96], train_labels=y[:96],
            valid_data=x[96:], valid_labels=y[96:], minibatch_size=24),
        layers=[
            {"type": "embedding",
             "->": {"vocab_size": V, "dim": D, "weights_stddev": 0.5},
             "<-": {"learning_rate": 0.1, "gradient_moment": 0.9}},
            {"type": "pos_encoding", "->": {"scale": 0.1}},
            {"type": "attention", "->": {"n_heads": 2},
             "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
            {"type": "softmax", "->": {"output_sample_shape": n_classes},
             "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
        ],
        decision_config={"max_epochs": 40})
    wf._max_fires = 10 ** 6
    wf.initialize(device=XLADevice())
    wf.run()
    assert wf.decision.min_validation_n_err_pt <= 25.0


def test_bf16_storage_vocab_guard():
    """bf16 activation storage cannot represent token ids > 256
    exactly — the unit must refuse instead of training on silently
    corrupted ids."""
    import pytest

    from znicz_tpu.utils.config import root

    root.common.precision_type = "bfloat16"
    try:
        tokens = _tokens()
        prng.seed_all(8)
        wf = DummyWorkflow()
        src = DummyUnit(wf, output=Vector(
            np.asarray(tokens, np.float32), name="tok",
            batch_major=True))
        fwd = embedding.Embedding(wf, vocab_size=50_000, dim=D)
        fwd.link_attrs(src, ("input", "output"))
        # the input Vector here is f32 (DummyUnit-owned), so emulate
        # the loader's bf16 storage by re-declaring it
        import jax.numpy as jnp
        src.output.reset(np.asarray(tokens, jnp.bfloat16))
        with pytest.raises(ValueError, match="exactly"):
            fwd.initialize(device=XLADevice())
    finally:
        root.common.precision_type = "float32"
