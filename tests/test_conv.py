"""Conv forward correctness: numpy im2col oracle vs XLA native conv
(reference pattern: ``znicz/tests/unit/test_conv.py``)."""

import numpy as np
import pytest

from znicz_tpu.backends import NumpyDevice, XLADevice
from znicz_tpu.dummy import DummyUnit, DummyWorkflow
from znicz_tpu.memory import Vector
from znicz_tpu.ops import conv

RNG = np.random.default_rng(21)
X = RNG.normal(size=(4, 8, 8, 3)).astype(np.float32)


def build(cls, device, x, **kwargs):
    wf = DummyWorkflow()
    src = DummyUnit(wf, output=Vector(np.asarray(x), name="x"))
    unit = cls(wf, **kwargs)
    unit.link_attrs(src, ("input", "output"))
    unit.initialize(device=device)
    return unit


def run_both(cls, x, **kwargs):
    np_u = build(cls, NumpyDevice(), x, **kwargs)
    xla_u = build(cls, XLADevice(), x, **kwargs)
    xla_u.weights.reset(np_u.weights.mem.copy())
    xla_u.weights.initialize(xla_u.device)
    if xla_u.include_bias:
        xla_u.bias.reset(np_u.bias.mem.copy())
        xla_u.bias.initialize(xla_u.device)
    np_u.run()
    xla_u.run()
    np_u.output.map_read()
    xla_u.output.map_read()
    return np_u, xla_u


@pytest.mark.parametrize("cls", [conv.Conv, conv.ConvTanh, conv.ConvRELU,
                                 conv.ConvStrictRELU])
def test_numpy_xla_agreement(cls):
    np_u, xla_u = run_both(cls, X, n_kernels=5, kx=3, ky=3)
    np.testing.assert_allclose(np_u.output.mem, xla_u.output.mem,
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("sliding,padding", [
    ((1, 1), 0), ((2, 2), 0), ((1, 1), 1), ((2, 2), (1, 2)),
    ((1, 2), (1, 0, 2, 1)), ((3, 3), 2)])
def test_geometry_variants(sliding, padding):
    np_u, xla_u = run_both(conv.Conv, X, n_kernels=4, kx=3, ky=2,
                           sliding=sliding, padding=padding)
    np.testing.assert_allclose(np_u.output.mem, xla_u.output.mem,
                               rtol=1e-4, atol=1e-5)


def test_golden_identity_kernel():
    """1×1 identity kernel reproduces the input channel."""
    wf = DummyWorkflow()
    x = RNG.normal(size=(2, 5, 5, 2)).astype(np.float32)
    src = DummyUnit(wf, output=Vector(x, name="x"))
    unit = conv.Conv(wf, n_kernels=2, kx=1, ky=1)
    unit.link_attrs(src, ("input", "output"))
    unit.initialize(device=NumpyDevice())
    unit.weights.reset(np.eye(2, dtype=np.float32).reshape(1, 1, 2, 2))
    unit.bias.reset(np.zeros(2, dtype=np.float32))
    unit.run()
    np.testing.assert_allclose(unit.output.mem, x, rtol=1e-6)


def test_output_shape():
    np_u = build(conv.Conv, NumpyDevice(), X, n_kernels=7, kx=3, ky=3,
                 sliding=(2, 2), padding=1)
    assert np_u.output.shape == (4, 4, 4, 7)
    assert np_u.weights.shape == (3, 3, 3, 7)


def test_non_nhwc_input_rejected():
    wf = DummyWorkflow()
    src = DummyUnit(wf, output=Vector(np.zeros((4, 10), np.float32),
                                      name="x"))
    unit = conv.Conv(wf, n_kernels=2, kx=3, ky=3)
    unit.link_attrs(src, ("input", "output"))
    with pytest.raises(ValueError, match="NHWC"):
        unit.initialize(device=NumpyDevice())


def test_space_to_depth_exact_alexnet_conv1():
    """The stride-4 11x11 RGB conv (AlexNet conv1 geometry, small) must
    take the space-to-depth path and match the im2col oracle exactly
    (the rewrite is a re-indexing, not an approximation)."""
    import jax

    from znicz_tpu.utils.config import root

    root.common.engine.space_to_depth = True  # opt-in feature
    try:
        rng = np.random.default_rng(8)
        x = rng.normal(size=(2, 51, 51, 3)).astype(np.float32)
        np_u, xla_u = run_both(conv.Conv, x, n_kernels=8, kx=11, ky=11,
                               sliding=(4, 4))
    finally:
        root.common.engine.space_to_depth = False
    assert xla_u._s2d, "space-to-depth should engage for stride-4 RGB"
    np.testing.assert_allclose(np_u.output.mem, xla_u.output.mem,
                               rtol=1e-4, atol=1e-5)
    # gradient path: linear_transpose of the s2d conv vs the plain conv
    unit = xla_u
    w = unit.weights.devmem
    cot = rng.normal(
        size=unit.output.shape).astype(np.float32)
    t_x = jax.linear_transpose(lambda xx: unit.conv_raw(xx, w),
                               unit.input.devmem)
    (gx,) = t_x(cot)
    unit._s2d = False
    t_x_ref = jax.linear_transpose(lambda xx: unit.conv_raw(xx, w),
                                   unit.input.devmem)
    (gx_ref,) = t_x_ref(cot)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref),
                               rtol=1e-4, atol=1e-5)


def test_space_to_depth_guard_declines_inexact_geometry():
    """Geometries where the block count formula would over-produce
    outputs must fall back to the plain conv: hp=53 gives
    ceil(53/4)-ceil(11/4)+1 = 12 != (53-11)//4+1 = 11."""
    from znicz_tpu.utils.config import root

    root.common.engine.space_to_depth = True  # opt-in feature
    try:
        rng = np.random.default_rng(8)
        x = rng.normal(size=(2, 53, 53, 3)).astype(np.float32)
        np_u, xla_u = run_both(conv.Conv, x, n_kernels=8, kx=11, ky=11,
                               sliding=(4, 4))
    finally:
        root.common.engine.space_to_depth = False
    assert not xla_u._s2d
    np.testing.assert_allclose(np_u.output.mem, xla_u.output.mem,
                               rtol=1e-4, atol=1e-5)
