"""Worker process for the multi-process distributed bootstrap test.

Each OS process runs this script with a distinct ``process_id``; the
Launcher's ``--listen`` / ``--master`` path performs the PJRT
bootstrap (``jax.distributed.initialize``) — the TPU-first equivalent
of the reference's in-process master+slave localhost test (reference:
``veles/tests/test_client_server.py``; SURVEY.md §4 "distributed
tests").  Every process contributes 2 virtual CPU devices, the
Launcher builds the GLOBAL 4-device mesh, and the sample's workflow
trains SPMD over it.  On exit each process writes a JSON digest of the
trained weights; the parent test asserts both digests are identical —
the modern form of "master and slave agree on the trained model".

Run directly (the test spawns two of these):

    python tests/dist_worker.py <process_id> <n_processes> \
        <coordinator host:port> <out.json>
"""

import json
import sys


def build_workflow(tp_dir: "str | None" = None, learning_rate=0.1,
                   max_epochs=3, tp: "bool | None" = None):
    """Tiny blob-classification MLP, mirroring the layer/optimizer
    config of ``tests/test_parallel.build``.  The data generator is
    duplicated here on purpose: importing ``tests.conftest`` (where
    ``make_blobs`` lives) would pin 8 virtual devices per process at
    import time, while this worker needs exactly 2.

    ``tp_dir``: tensor-parallel variant — the hidden FC pair goes
    column+row over the global mesh's model axis and a Snapshotter
    writes into this directory (the lockstep collective-read snapshot
    path for model-sharded state)."""
    import numpy as np

    from znicz_tpu.loader.fullbatch import ArrayLoader
    from znicz_tpu.models.standard_workflow import StandardWorkflow

    tp = (tp_dir is not None) if tp is None else tp
    n_classes, dim, per_class = 3, 12, 40
    rnd = np.random.RandomState(7)
    centers = rnd.uniform(-4.0, 4.0, size=(n_classes, dim))
    data = np.concatenate(
        [centers[c] + rnd.normal(0.0, 1.0, size=(per_class, dim))
         for c in range(n_classes)]).astype(np.float32)
    labels = np.repeat(np.arange(n_classes, dtype=np.int32), per_class)
    order = rnd.permutation(len(data))
    data, labels = data[order], labels[order]
    n_train = 96
    wf = StandardWorkflow(
        name="dist_mlp",
        loader_factory=lambda w: ArrayLoader(
            w,
            train_data=data[:n_train], train_labels=labels[:n_train],
            valid_data=data[n_train:], valid_labels=labels[n_train:],
            minibatch_size=24),
        layers=[
            {"type": "all2all_tanh",
             "->": {"output_sample_shape": 16,
                    "model_parallel": "column" if tp else None},
             "<-": {"learning_rate": learning_rate,
                    "gradient_moment": 0.9}},
            {"type": "all2all_tanh",
             "->": {"output_sample_shape": 12,
                    "model_parallel": "row" if tp else None},
             "<-": {"learning_rate": learning_rate,
                    "gradient_moment": 0.9}},
            {"type": "softmax", "->": {"output_sample_shape": n_classes},
             "<-": {"learning_rate": learning_rate,
                    "gradient_moment": 0.9}},
        ],
        decision_config={"max_epochs": max_epochs},
        snapshotter_config=(
            None if tp_dir is None
            else {"prefix": "dist_tp", "directory": tp_dir}))
    wf._max_fires = 100_000
    return wf


def build_ring_workflow():
    """Sequence classifier with seq-parallel attention: the time axis
    shards over the global mesh's model axis, so the ring's ppermute
    crosses the PROCESS boundary (Gloo on CPU; ICI/DCN on pods) —
    the multi-process proof of the long-context path.  Reuses the
    attention_seq zoo sample (one source of truth for the task)."""
    from znicz_tpu.models.samples import attention_seq

    return attention_seq.build(
        seq_parallel=True, n_heads=2, seq_len=12, features=8,
        n_train=72, n_valid=24, minibatch_size=24, max_epochs=10,
        learning_rate=0.05)


def run_partition(shard_dir: str) -> dict:
    """Round 17: the declarative partition table under REAL
    multi-process SPMD — a TP (column+row) + ZeRO-1 net and a
    streaming-loader net with per-host 1/N reads, both placed
    entirely through the rule engine.  The digest carries the table
    dump and the resolved specs so the parent can assert every
    process resolved the IDENTICAL table (multi-host bring-up is a
    lookup, not a rewrite), plus warmed-step compile counts and the
    trained state for the single-process loss-parity check."""
    import jax
    import numpy as np

    from znicz_tpu.loader.streaming import StreamingLoader
    from znicz_tpu.models.standard_workflow import StandardWorkflow
    from znicz_tpu.observe import metrics as obs_metrics
    from znicz_tpu.utils import prng

    launcher = _partition_launcher
    wf_tp = build_workflow(tp=True, max_epochs=3)
    wf_tp.initialize(device=launcher.make_device())
    wf_tp.run()
    table = wf_tp.partition
    region_unit = wf_tp._region_unit
    compiles = obs_metrics.xla_compiles(f"region:{region_unit.name}")
    before = compiles.value
    wf_tp.loader.run()
    region_unit.run()
    warmed_delta = compiles.value - before
    wf_tp.forwards[0].weights.map_read()
    wf_tp.forwards[1].weights.map_read()

    # streaming net: per-host 1/N reads through put_local_batch
    prng.seed_all(4321)
    stream_wf = StandardWorkflow(
        name="dist_stream",
        loader_factory=lambda w: StreamingLoader(
            w, shard_dir, minibatch_size=16, prefetch_depth=2,
            normalization_scale=2.0 / 255.0, normalization_bias=-1.0),
        layers=[
            {"type": "all2all_tanh",
             "->": {"output_sample_shape": 16, "weights_filling": "he"},
             "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
            {"type": "softmax",
             "->": {"output_sample_shape": 4, "weights_filling": "he"},
             "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
        ],
        decision_config={"max_epochs": 6})
    stream_wf._max_fires = 10 ** 6
    stream_wf.initialize(device=launcher.device)
    loader = stream_wf.loader
    loader.warmup()
    # content proof for the per-host 1/N reads at a PINNED schedule
    # point (the first delivered batch): each host uploaded only its
    # local rows through put_local_batch; the assembled global batch
    # must be row-for-row identical to what one process reads whole.
    # Lockstep collective read (every process executes this).
    loader.run()
    first = np.asarray(launcher.device.get(
        loader.minibatch_raw._devmem), dtype=np.float64)
    first_labels = np.asarray(launcher.device.get(
        loader.minibatch_labels._devmem))
    stream_batch_rows = [float(r) for r in
                         first.reshape(first.shape[0], -1).sum(axis=1)]
    stream_batch_labels = [int(x) for x in first_labels]
    stream_wf.run()
    stream_region = stream_wf._region_unit
    scompiles = obs_metrics.xla_compiles(f"region:{stream_region.name}")
    sbefore = scompiles.value
    loader.run()
    stream_region.run()
    warmed_stream_delta = scompiles.value - sbefore
    stream_wf.forwards[0].weights.map_read()
    stream_wf.stop()

    col = wf_tp.forwards[0]
    return {
        "partition_table": table.dump(),
        "resolved_specs": {path: str(tuple(res.spec))
                           for path, res in sorted(table.leaves.items())},
        "col_weights_spec": str(tuple(
            table.leaves[f"{col.name}/weights"].spec)),
        "zero1_engaged": all(g._zero1 for g in wf_tp.gds
                             if g.weights is not None and g.weights),
        "warmed_step_compiles": int(warmed_delta),
        "warmed_stream_compiles": int(warmed_stream_delta),
        "w0_sum": float(wf_tp.forwards[0].weights.mem.sum()),
        "w1_sum": float(wf_tp.forwards[1].weights.mem.sum()),
        "w0_l2": float((wf_tp.forwards[0].weights.mem ** 2).sum()),
        "w1_l2": float((wf_tp.forwards[1].weights.mem ** 2).sum()),
        "min_validation_n_err": int(wf_tp.decision.min_validation_n_err),
        "stream_w_sum": float(stream_wf.forwards[0].weights.mem.sum()),
        "stream_w_l2": float(
            (np.asarray(stream_wf.forwards[0].weights.mem,
                        dtype=np.float64) ** 2).sum()),
        "stream_batch_rows": stream_batch_rows,
        "stream_batch_labels": stream_batch_labels,
        "stream_final_loss": [None if x is None else float(x)
                              for x in stream_wf.decision.epoch_loss],
        "stream_local_batch": int(loader.local_batch),
        "stream_prefetch_hits": int(loader.prefetch_hits),
        "stream_min_valid_n_err": int(
            stream_wf.decision.min_validation_n_err),
        "n_processes": jax.process_count(),
    }


#: launcher handle for run_partition (set by main before dispatch)
_partition_launcher = None


def run_genetics() -> dict:
    """Process-sharded GA: both processes hold the identical
    deterministic population, train disjoint genome slices on local
    devices, and all-gather the scores — the TPU restatement of the
    reference's genome-per-cluster-node farm (``veles/genetics/``)."""
    from znicz_tpu.genetics import GeneticsOptimizer, Tune

    opt = GeneticsOptimizer(
        build_fn=lambda **kw: build_workflow(**kw),
        space={"learning_rate": Tune(0.1, 0.02, 0.5)},
        population_size=4, generations=2, seed=11,
        train_kwargs={"max_epochs": 2})
    best = opt.run()
    return {
        "ga_best_genome": best,
        "ga_best_fitness": float(opt.best_fitness),
        "ga_local_evaluated": sorted(str(k) for k in opt.local_evaluated),
        "ga_n_unique": len(opt._cache),
    }


def run_ensemble() -> dict:
    """Process-sharded ensemble: 3 members round-robin over 2
    processes (0 trains members 0 and 2, 1 trains member 1), merged
    aggregate evaluation identical everywhere."""
    from znicz_tpu.ensemble import Ensemble
    from znicz_tpu.loader.base import VALID

    ens = Ensemble(build_workflow, n_models=3, base_seed=42,
                   train_kwargs={"max_epochs": 2})
    ens.train()
    result = ens.evaluate(VALID)
    return {
        "ens_member_ids": list(ens.member_ids),
        "ens_member_stats": ens.member_stats,
        "ens_result": result,
    }


def main() -> None:
    process_id = int(sys.argv[1])
    n_processes = int(sys.argv[2])
    coordinator = sys.argv[3]
    out_path = sys.argv[4]
    mode_arg = sys.argv[5] if len(sys.argv) > 5 else None
    ring_mode = mode_arg == "ring"
    shard_mode = mode_arg in ("genetics", "ensemble")
    partition_mode = mode_arg == "partition"
    tp_dir = None if (mode_arg is None or ring_mode or shard_mode
                      or partition_mode) else mode_arg

    # a fixed 4-device GLOBAL mesh split over however many processes
    # run (2 per process for the 2-proc smoke, all 4 for the
    # single-process loss-parity reference), configured BEFORE any jax
    # use (the container's sitecustomize already imported jax, so go
    # through jax.config like tests/conftest.py does).
    devices_per_proc = 4 // n_processes if partition_mode else 2
    import os
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        # read at backend init (post-import, pre-first-use) — the
        # fallback for jax versions without the config option below
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count="
                    f"{devices_per_proc}").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", devices_per_proc)
    except AttributeError:  # older jax: XLA_FLAGS above covers it
        pass
    # (jax_cpu_collectives_implementation=gloo is set by
    # parallel.distributed.ensure_initialized during the Launcher's
    # bootstrap — cross-process CPU computations fail without it)

    from znicz_tpu.launcher import Launcher
    from znicz_tpu.utils import prng

    n_model = 2 if (tp_dir or ring_mode or partition_mode) else 1
    if process_id == 0:
        launcher = Launcher(listen=coordinator, n_processes=n_processes,
                            n_model=n_model)
    else:
        launcher = Launcher(master=coordinator, n_processes=n_processes,
                            process_id=process_id, n_model=n_model)
    assert launcher.mode == ("master" if process_id == 0 else "slave")
    assert jax.process_count() == n_processes
    assert len(jax.devices()) == devices_per_proc * n_processes

    prng.seed_all(1234)

    if partition_mode:
        global _partition_launcher
        _partition_launcher = launcher
        digest = run_partition(sys.argv[6])
        digest.update({
            "process_id": process_id,
            "mode": launcher.mode,
            "n_global_devices": len(jax.devices()),
        })
        with open(out_path, "w") as fh:
            json.dump(digest, fh)
        print(f"worker {process_id}: OK partition", flush=True)
        return

    if shard_mode:
        digest = (run_genetics() if mode_arg == "genetics"
                  else run_ensemble())
        digest.update({
            "process_id": process_id,
            "mode": launcher.mode,
            "n_global_devices": len(jax.devices()),
        })
        with open(out_path, "w") as fh:
            json.dump(digest, fh)
        print(f"worker {process_id}: OK {digest}", flush=True)
        return

    def run(load, main):  # reference sample protocol
        if ring_mode:
            load(build_ring_workflow)
        else:
            load(build_workflow, tp_dir=tp_dir)
        main()

    wf = launcher.boot(run)

    snapshot_keys = -1
    if process_id == 0 and tp_dir is None and not ring_mode:
        # master-only snapshot: must NOT issue collective reads (the
        # slaves are not in lockstep here) — regression for the
        # Vector.needs_collective_read skip in Unit.state_dict
        state = wf.state_dict()
        snapshot_keys = sum(len(unit_state)
                            for unit_state in state["__units__"].values())
    tp_snapshot_full_shapes = None
    if tp_dir is not None:
        # the Snapshotter unit ran in lockstep on every process — its
        # file must hold the FULL (gathered) model-sharded weights
        import glob as _glob

        from znicz_tpu.utils.snapshotter import Snapshotter
        files = sorted(_glob.glob(tp_dir + "/dist_tp_*.pickle.gz"))
        assert files, "lockstep TP snapshot was not written"
        state = Snapshotter.load(files[-1])
        col = state["__units__"]["All2AllTanh"]["weights"]
        row = state["__units__"]["All2AllTanh_2"]["weights"]
        tp_snapshot_full_shapes = [list(col.shape), list(row.shape)]

    wf.forwards[0].weights.map_read()
    wf.forwards[1].weights.map_read()
    digest = {
        "ring_engaged": bool(getattr(wf.forwards[0], "ring_active",
                                     False)),
        "ring_time_sharded": getattr(wf.forwards[0].output,
                                     "model_shard_dim", None) == 1,
        "snapshot_keys": snapshot_keys,
        "tp_snapshot_full_shapes": tp_snapshot_full_shapes,
        "process_id": process_id,
        "mode": launcher.mode,
        "n_global_devices": len(jax.devices()),
        "data_shards": launcher.device.n_data_shards,
        "w0_sum": float(wf.forwards[0].weights.mem.sum()),
        "w1_sum": float(wf.forwards[1].weights.mem.sum()),
        "w0_l2": float((wf.forwards[0].weights.mem ** 2).sum()),
        "w1_l2": float((wf.forwards[1].weights.mem ** 2).sum()),
        "min_validation_n_err": int(wf.decision.min_validation_n_err),
    }
    with open(out_path, "w") as fh:
        json.dump(digest, fh)
    print(f"worker {process_id}: OK {digest}", flush=True)


if __name__ == "__main__":
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    main()
