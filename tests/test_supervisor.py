"""Elastic supervision (round 18): heartbeats, the coordinator-side
monitor, the preemption barrier + checkpoint-on-signal, the
process-0-only snapshot/publish write discipline, the bounded
``jax.distributed`` bring-up, and the gang supervisor's restart
classification (exercised on stub workers — no jax in the gang, so the
whole file stays in the fast tier; the real 2-process elastic drill
lives in ``tests/test_elastic.py``, slow)."""

import gzip
import hashlib
import json
import os
import pickle
import subprocess
import sys
import threading
import time

import pytest

from znicz_tpu.observe import metrics as obs_metrics
from znicz_tpu.resilience import faults as res_faults
from znicz_tpu.resilience import supervisor as sup
from znicz_tpu.utils.config import root
from znicz_tpu.utils.snapshotter import Snapshotter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------------------
# heartbeat writer + monitor
# ----------------------------------------------------------------------
def test_heartbeat_writer_beats_and_annotates(tmp_path):
    w = sup.HeartbeatWriter(str(tmp_path), 3, interval_s=0.05)
    w.start()
    w.beat(7)
    w.annotate(resumed_step=42)
    hb = json.load(open(sup.heartbeat_path(str(tmp_path), 3)))
    assert hb["process"] == 3 and hb["step"] == 7
    assert hb["resumed_step"] == 42 and hb["pid"] == os.getpid()
    t0 = hb["time"]
    time.sleep(0.15)  # interval thread refreshes wall-clock alone
    hb2 = json.load(open(sup.heartbeat_path(str(tmp_path), 3)))
    assert hb2["time"] > t0 and hb2["step"] == 7
    w.stop()


def test_heartbeat_freeze_keeps_time_flowing(tmp_path):
    w = sup.HeartbeatWriter(str(tmp_path), 0, interval_s=0.05)
    w.start()
    w.beat(5)
    w.freeze()
    w.beat(9)  # frozen: step must NOT advance
    time.sleep(0.12)
    hb = json.load(open(sup.heartbeat_path(str(tmp_path), 0)))
    assert hb["step"] == 5
    assert time.time() - hb["time"] < 1.0
    w.stop()


def test_monitor_ok_stale_and_grace(tmp_path):
    mon = sup.HeartbeatMonitor(str(tmp_path), 2, timeout_s=10.0,
                               start_grace_s=100.0)
    now = time.time()
    sup._atomic_write_json(sup.heartbeat_path(str(tmp_path), 0),
                           {"process": 0, "step": 3, "time": now})
    st = mon.poll(now=now)
    assert st[0]["status"] == "ok" and st[0]["step"] == 3
    # process 1 never beat: within grace → starting, not dead
    assert st[1]["status"] == "starting"
    assert mon.dead(now=now) == []
    # past the grace with still no file → missing/dead
    assert mon.poll(now=now + 200.0)[1]["status"] == "missing"
    # process 0's beats stop entirely → stale (the host vanished)
    st = mon.poll(now=now + 200.0)
    assert st[0]["status"] == "stale"
    assert set(mon.dead(now=now + 200.0)) == {(0, "loss"), (1, "loss")}


def test_monitor_detects_stalled_step_counter(tmp_path):
    mon = sup.HeartbeatMonitor(str(tmp_path), 1, timeout_s=60.0,
                               stall_timeout_s=3.0)
    t0 = time.time()
    path = sup.heartbeat_path(str(tmp_path), 0)
    sup._atomic_write_json(path, {"process": 0, "step": 5, "time": t0})
    assert mon.poll(now=t0)[0]["status"] == "ok"
    # wall-clock beats keep flowing, step frozen past the stall bound
    sup._atomic_write_json(path, {"process": 0, "step": 5,
                                  "time": t0 + 5.0})
    st = mon.poll(now=t0 + 5.0)
    assert st[0]["status"] == "stalled"
    assert st[0]["step_age_s"] == pytest.approx(5.0)
    assert mon.dead(now=t0 + 5.0) == [(0, "stall")]
    # step advances again → healthy
    sup._atomic_write_json(path, {"process": 0, "step": 6,
                                  "time": t0 + 6.0})
    assert mon.poll(now=t0 + 6.0)[0]["status"] == "ok"


def test_monitor_gauges_feed_canonical_series(tmp_path):
    mon = sup.HeartbeatMonitor(str(tmp_path), 2, timeout_s=5.0)
    mon.register_gauges()
    sup._atomic_write_json(sup.heartbeat_path(str(tmp_path), 0),
                           {"process": 0, "step": 1,
                            "time": time.time() - 2.5})
    age0 = obs_metrics.heartbeat_age_seconds(0).value
    assert 2.0 < age0 < 10.0
    assert obs_metrics.heartbeat_age_seconds(1).value == float("inf")
    fam = obs_metrics.REGISTRY.get("znicz_heartbeat_age_seconds")
    assert {k[0] for k, _ in fam.items()} >= {"0", "1"}


# ----------------------------------------------------------------------
# preemption: flag + barrier + checkpoint-on-signal
# ----------------------------------------------------------------------
class _StubWorkflow:
    """The minimal workflow surface the WorkerSupervisor touches."""

    name = "stub_wf"
    snapshotter = None
    loader = None

    def __init__(self):
        self._step_hooks = []
        self.stopped_calls = 0
        self.state = {"__units__": {"u": {"w": [1.0, 2.0]}}}

    def add_step_hook(self, fn):
        self._step_hooks.append(fn)

    def remove_step_hook(self, fn):
        self._step_hooks.remove(fn)

    def on_step_boundary(self):
        for fn in list(self._step_hooks):
            fn()

    def state_dict(self, allow_collective=False):
        assert allow_collective, \
            "checkpoint-on-signal must gather in lockstep"
        return self.state

    def stop(self):
        self.stopped_calls += 1


class _StubSnapshotter:
    def __init__(self, directory):
        self.directory = str(directory)
        self.prefix = "stub"


def test_preempt_flag_first_writer_wins(tmp_path):
    sup.request_preempt_flag(str(tmp_path), 12, 1, "first")
    sup.request_preempt_flag(str(tmp_path), 99, 0, "second")
    flag = sup.preempt_flag(str(tmp_path))
    assert flag["barrier_step"] == 12 and flag["requested_by"] == 1


def test_worker_supervisor_checkpoint_on_signal(tmp_path):
    wf = _StubWorkflow()
    wf.snapshotter = _StubSnapshotter(tmp_path / "snaps")
    supv = sup.WorkerSupervisor(
        wf, directory=str(tmp_path / "hb"), process_index=0,
        process_count=1, heartbeat_interval_s=0.05)
    supv.attach()
    before = obs_metrics.checkpoint_on_signal().value
    wf.on_step_boundary()
    wf.on_step_boundary()
    assert supv.step == 2
    supv.request_preempt("SIGTERM test")  # barrier = step + 1
    with pytest.raises(sup.Preempted) as err:
        wf.on_step_boundary()
    assert err.value.code == sup.EXIT_PREEMPTED
    path = err.value.snapshot_path
    assert path.endswith("preempt_s3.pickle.gz") and os.path.exists(path)
    # sha256 sidecar landed and verifies; the state round-trips
    digest = open(path + ".sha256").read().strip()
    assert hashlib.sha256(open(path, "rb").read()).hexdigest() == digest
    assert pickle.load(gzip.open(path, "rb")) == wf.state
    assert wf.stopped_calls == 1
    assert obs_metrics.checkpoint_on_signal().value == before + 1
    hb = json.load(open(sup.heartbeat_path(str(tmp_path / "hb"), 0)))
    assert hb["checkpoint_on_signal"] == 1
    assert hb["checkpoint_path"] == path
    supv.detach()


def test_worker_supervisor_peer_flag_joins_barrier(tmp_path):
    """A process that never saw the signal picks the preempt flag up
    from the channel at its next step boundary and checkpoints at the
    SAME barrier step."""
    wf = _StubWorkflow()
    wf.snapshotter = _StubSnapshotter(tmp_path / "snaps")
    supv = sup.WorkerSupervisor(
        wf, directory=str(tmp_path), process_index=0, process_count=1,
        heartbeat_interval_s=0.05)
    supv.attach()
    wf.on_step_boundary()
    sup.request_preempt_flag(str(tmp_path), 3, 1, "peer signal")
    wf.on_step_boundary()  # step 2 < barrier 3: keeps training
    with pytest.raises(sup.Preempted):
        wf.on_step_boundary()  # step 3 == barrier: checkpoint
    assert supv.step == 3
    supv.detach()


def test_watchdog_surfaces_peer_lost(tmp_path, monkeypatch):
    """A dead peer leaves this process blocked in a collective — the
    watchdog bounds time-in-step and surfaces a detectable PeerLost
    exit instead of an infinite gloo/ICI hang."""
    exits = []
    monkeypatch.setattr(sup.os, "_exit", lambda rc: exits.append(rc))
    wf = _StubWorkflow()
    supv = sup.WorkerSupervisor(
        wf, directory=str(tmp_path), process_index=0, process_count=2,
        heartbeat_interval_s=0.05, collective_timeout_s=0.3)
    supv.attach()
    time.sleep(0.6)
    assert exits == [], "watchdog fired during bring-up (step 0)"
    wf.on_step_boundary()  # first boundary arms the bound
    deadline = time.time() + 5.0
    while not exits and time.time() < deadline:
        time.sleep(0.05)
    assert sup.EXIT_PEER_LOST in exits
    hb = json.load(open(sup.heartbeat_path(str(tmp_path), 0)))
    assert hb.get("peer_lost") is True
    supv.detach()


def test_host_loss_site_respects_process_filter():
    plan = res_faults.FaultPlan(
        {"host.loss": {"process": 1, "at": [2]}})
    assert plan.fire("host.loss", process=0) is None
    assert plan.fire("host.loss", process=1) is None   # arrival 1
    payload = plan.fire("host.loss", process=1)        # arrival 2
    assert payload is not None and payload["arrival"] == 2
    # process-0 arrivals never consumed the ordinal stream
    assert plan.fire("host.loss", process=0) is None


def test_checkpoint_signal_corrupt_falls_back(tmp_path):
    """The corrupted checkpoint-on-signal is rejected on digest
    verification and resume lands on the older good snapshot."""
    wf = _StubWorkflow()
    snaps = tmp_path / "snaps"
    wf.snapshotter = _StubSnapshotter(snaps)
    good = Snapshotter.write({"good": True}, str(snaps), "stub", "e1")
    time.sleep(0.02)
    root.common.engine.faults = {"checkpoint.signal_corrupt": True}
    supv = sup.WorkerSupervisor(wf, directory=str(tmp_path / "hb"),
                                process_index=0, process_count=1)
    supv.attach()
    wf.on_step_boundary()
    supv.request_preempt("preempt with corruption")
    with pytest.raises(sup.Preempted) as err:
        wf.on_step_boundary()
    bad = err.value.snapshot_path
    # the newest-good picker skips the corrupt file...
    assert sup.newest_good_snapshot(str(snaps), "stub") == good
    # ...and the digest-verified loader falls back to it too
    assert Snapshotter.load(bad) == {"good": True}
    supv.detach()


# ----------------------------------------------------------------------
# satellite: process-0-only snapshot/publish writes + sidecar fence
# ----------------------------------------------------------------------
def _patch_process_info(monkeypatch, local):
    """Thread-keyed (index, count) so one test process can play both
    gang members concurrently."""
    from znicz_tpu.parallel import process_shard

    def fake_process_info():
        return getattr(local, "info", (0, 1))

    monkeypatch.setattr(process_shard, "process_info", fake_process_info)
    return fake_process_info


def test_snapshot_write_single_writer_under_two_processes(
        tmp_path, monkeypatch):
    """ISSUE 14 satellite: a 2-process lockstep gang calling
    ``Snapshotter.write`` everywhere produces EXACTLY ONE complete
    artifact — process 1 fences on the sidecar and never writes."""
    local = threading.local()
    _patch_process_info(monkeypatch, local)
    root.common.engine.snapshot_fence_timeout_s = 20.0
    state = {"w": list(range(1000))}
    results = {}

    def nonmaster():
        local.info = (1, 2)
        t0 = time.monotonic()
        results["path1"] = Snapshotter.write(
            state, str(tmp_path), "gang", "e1")
        results["fence_s"] = time.monotonic() - t0

    fencer = threading.Thread(target=nonmaster)
    fencer.start()
    time.sleep(0.3)  # the fence must actually wait for the master
    assert fencer.is_alive(), "non-master wrote without fencing"
    local.info = (0, 2)
    path0 = Snapshotter.write(state, str(tmp_path), "gang", "e1")
    fencer.join(timeout=30)
    assert not fencer.is_alive()
    assert results["path1"] == path0
    assert results["fence_s"] >= 0.25
    # exactly one artifact, untorn: digest verifies, content loads
    files = [f for f in os.listdir(tmp_path) if f.endswith(".pickle.gz")]
    assert files == ["gang_e1.pickle.gz"]
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".tmp")]
    digest = open(path0 + ".sha256").read().strip()
    assert hashlib.sha256(open(path0, "rb").read()).hexdigest() == digest
    assert Snapshotter.load(path0) == state


def test_snapshot_fence_times_out_with_actionable_error(
        tmp_path, monkeypatch):
    local = threading.local()
    _patch_process_info(monkeypatch, local)
    local.info = (1, 2)
    root.common.engine.snapshot_fence_timeout_s = 0.2
    with pytest.raises(OSError, match="fence"):
        Snapshotter.write({}, str(tmp_path), "gang", "never")


def test_publish_bundle_single_writer_under_two_processes(
        tmp_path, monkeypatch):
    local = threading.local()
    _patch_process_info(monkeypatch, local)
    from znicz_tpu import export as export_mod
    from znicz_tpu.resilience import publisher as pub

    writes = []

    def fake_export(workflow, path):
        time.sleep(0.2)  # a real export is not instant — widen the race
        with open(path, "wb") as fh:
            fh.write(b"bundle-bytes-" + str(workflow).encode())
        writes.append(path)

    monkeypatch.setattr(export_mod, "export_forward", fake_export)
    results = {}

    def nonmaster():
        local.info = (1, 2)
        results["fence"] = pub.publish_bundle("wf", str(tmp_path),
                                              prefix="m")

    fencer = threading.Thread(target=nonmaster)
    fencer.start()
    time.sleep(0.05)
    local.info = (0, 2)
    version, path = pub.publish_bundle("wf", str(tmp_path), prefix="m")
    fencer.join(timeout=30)
    assert not fencer.is_alive()
    assert (version, path) == results["fence"] == (
        1, os.path.join(str(tmp_path), "m_v000001.npz"))
    assert len(writes) == 1, "non-master exported a bundle"
    digest = open(path + ".sha256").read().strip()
    assert hashlib.sha256(open(path, "rb").read()).hexdigest() == digest


# ----------------------------------------------------------------------
# satellite: bounded jax.distributed bring-up
# ----------------------------------------------------------------------
def test_ensure_initialized_timeout_retry_backoff(monkeypatch):
    import jax

    from znicz_tpu.parallel import distributed
    calls = []
    sleeps = []

    def fake_initialize(**kwargs):
        calls.append(kwargs)
        raise RuntimeError("connect to coordinator failed (injected)")

    monkeypatch.setattr(jax.distributed, "initialize", fake_initialize)
    monkeypatch.setattr(jax.distributed, "shutdown", lambda: None)
    import time as time_mod
    monkeypatch.setattr(time_mod, "sleep",
                        lambda s: sleeps.append(s))
    monkeypatch.setattr(distributed, "_initialized", False)
    root.common.engine.dist_init_retries = 2
    root.common.engine.dist_init_backoff_s = 0.5
    with pytest.raises(RuntimeError) as err:
        distributed.ensure_initialized(
            coordinator="10.0.0.99:1", num_processes=2, process_id=1,
            timeout_s=7)
    msg = str(err.value)
    # actionable: names the spec, the env contract and the knob
    assert "10.0.0.99:1" in msg and "ZNICZ_COORDINATOR" in msg
    assert "dist_init_timeout_s" in msg and "3 attempt" in msg
    assert len(calls) == 3
    assert all(c["initialization_timeout"] == 7 for c in calls)
    assert sleeps == [0.5, 1.0]  # exponential backoff between retries
    assert not distributed._initialized


def test_ensure_initialized_no_spec_is_noop(monkeypatch):
    from znicz_tpu.parallel import distributed
    monkeypatch.setattr(distributed, "_initialized", False)
    monkeypatch.delenv("ZNICZ_COORDINATOR", raising=False)
    assert distributed.ensure_initialized() is False


# ----------------------------------------------------------------------
# gang supervisor on stub workers (no jax → fast tier)
# ----------------------------------------------------------------------
_STUB = """\
import json, os, sys, time
sys.path.insert(0, {repo!r})
from znicz_tpu.resilience import supervisor as sup
pid = int(os.environ["ZNICZ_PROCESS_ID"])
attempt = int(os.environ["ZNICZ_ELASTIC_ATTEMPT"])
hb_dir = os.environ["ZNICZ_HEARTBEAT_DIR"]
mode = os.environ.get("STUB_MODE", "ok")
w = sup.HeartbeatWriter(hb_dir, pid, interval_s=0.05).start()
w.annotate(resumed_step=7 if attempt else 0)
for step in range(1, 7):
    w.beat(step)
    time.sleep(0.05)
    if mode == "die" and pid == 1 and step == 3:
        os._exit(1)
    if mode == "preempt" and step == 3:
        sup.request_preempt_flag(hb_dir, step + 1, 1, "stub preempt")
        w.annotate(checkpoint_on_signal=1)
        w.stop()
        os._exit(sup.EXIT_PREEMPTED)
    if mode == "stall" and pid == 1:
        w.freeze()
        time.sleep(60)
    if mode == "stall" and pid == 0 and step == 4:
        # the victim: blocked in the dead peer's collective until its
        # watchdog exits it
        time.sleep(1.2)
        os._exit(sup.EXIT_PEER_LOST)
w.stop()
"""


def _stub_supervisor(tmp_path, mode, n=2, **kwargs):
    stub = tmp_path / "stub_worker.py"
    stub.write_text(_STUB.format(repo=REPO))

    def argv_for(pid, n_procs, attempt):
        return [sys.executable, str(stub)]

    defaults = dict(
        n_processes=n, work_dir=str(tmp_path / "work"),
        snapshot_dir=str(tmp_path / "snaps"),
        heartbeat_timeout_s=2.0, stall_timeout_s=1.0,
        start_grace_s=30.0, poll_interval_s=0.05, drain_s=5.0,
        max_restarts=2, fault_env={"STUB_MODE": mode})
    defaults.update(kwargs)
    return sup.ElasticSupervisor(argv_for, **defaults)


def test_gang_clean_run_no_restarts(tmp_path):
    summary = _stub_supervisor(tmp_path, "ok").run()
    assert summary["ok"] and summary["restarts"] == 0
    assert summary["losses"] == {} and summary["final_processes"] == 2


def test_gang_host_loss_restarts_on_survivors(tmp_path):
    before = obs_metrics.host_losses("loss").value
    restarts_before = obs_metrics.elastic_restarts().value
    summary = _stub_supervisor(tmp_path, "die").run()
    assert summary["ok"] and summary["restarts"] == 1
    assert summary["losses"] == {"loss": 1}
    assert summary["final_processes"] == 1
    # attempt-1 stubs annotated their resume position; the supervisor
    # folded it into its own registry story
    assert summary["resumed_step"] == 7
    assert obs_metrics.host_losses("loss").value == before + 1
    assert obs_metrics.elastic_restarts().value == restarts_before + 1


def test_gang_preemption_only_requester_is_lost(tmp_path):
    """Both gang members drain through the barrier and exit 75; ONLY
    the requester host is gone — the drained peer rejoins the smaller
    gang."""
    before = obs_metrics.host_losses("preempt").value
    cps_before = obs_metrics.checkpoint_on_signal().value
    summary = _stub_supervisor(tmp_path, "preempt").run()
    assert summary["ok"] and summary["restarts"] == 1
    assert summary["losses"] == {"preempt": 1}
    assert summary["final_processes"] == 1
    assert obs_metrics.host_losses("preempt").value == before + 1
    # both members checkpointed (fenced) — folded from the channel
    assert obs_metrics.checkpoint_on_signal().value == cps_before + 2


def test_gang_stall_culprit_detected_victim_rejoins(tmp_path):
    before = obs_metrics.host_losses("stall").value
    summary = _stub_supervisor(tmp_path, "stall").run()
    assert summary["ok"] and summary["restarts"] == 1
    assert summary["losses"] == {"stall": 1}
    assert summary["final_processes"] == 1
    assert obs_metrics.host_losses("stall").value == before + 1


def test_readyz_folds_heartbeat_ages(tmp_path):
    """Satellite: /readyz on process 0 folds per-process heartbeat
    ages — report-only by default, not-ready past
    ``engine.ready_max_heartbeat_s``."""
    from znicz_tpu.web_status import WebStatusServer

    mon = sup.HeartbeatMonitor(str(tmp_path), 2, timeout_s=5.0)
    mon.register_gauges()
    now = time.time()
    sup._atomic_write_json(sup.heartbeat_path(str(tmp_path), 0),
                           {"process": 0, "step": 9, "time": now})
    sup._atomic_write_json(sup.heartbeat_path(str(tmp_path), 1),
                           {"process": 1, "step": 4,
                            "time": now - 120.0})
    server = WebStatusServer(port=0)
    try:
        report = server.readiness()
        assert report["processes"]["0"]["heartbeat_age_s"] < 5.0
        assert report["processes"]["1"]["heartbeat_age_s"] > 100.0
        # unset threshold = report-only: the stale peer adds no reason
        assert not [r for r in report["reasons"] if "heartbeat" in r]
        json.dumps(report)  # the body must stay JSON-serializable
        root.common.engine.ready_max_heartbeat_s = 30.0
        report = server.readiness()
        assert not report["ready"]
        assert any("heartbeat" in r and "process 1" in r
                   for r in report["reasons"]), report["reasons"]
    finally:
        server.stop()


def test_launcher_sigterm_routes_to_preempt_not_emergency():
    """With a WorkerSupervisor attached, SIGTERM must request the
    barriered checkpoint-on-signal (deferred to the next step
    boundary) instead of the legacy immediate emergency snapshot; a
    second signal still hard-exits."""
    import signal as signal_mod

    from znicz_tpu.launcher import Launcher

    launcher = Launcher(backend="numpy")
    preempts = []

    class StubSup:
        def request_preempt(self, reason):
            preempts.append(reason)

    class StubWf:
        name = "stub"

        def __init__(self):
            self.stops = 0

        def stop(self):
            self.stops += 1

    wf = StubWf()
    launcher._worker_supervisor = StubSup()
    launcher._install_signal_handlers(wf)
    try:
        os.kill(os.getpid(), signal_mod.SIGTERM)
        time.sleep(0.05)  # delivery at the next bytecode boundary
        assert preempts == [f"signal {int(signal_mod.SIGTERM)}"]
        assert wf.stops == 0, "legacy emergency-stop path also ran"
        with pytest.raises(KeyboardInterrupt):
            os.kill(os.getpid(), signal_mod.SIGTERM)
            time.sleep(1.0)
    finally:
        launcher._restore_signal_handlers()


def test_newest_good_snapshot_skips_corrupt(tmp_path):
    a = Snapshotter.write({"v": 1}, str(tmp_path), "s", "a")
    time.sleep(0.02)
    b = Snapshotter.write({"v": 2}, str(tmp_path), "s", "b")
    assert sup.newest_good_snapshot(str(tmp_path), "s") == b
    with open(b, "r+b") as fh:  # corrupt the newest post-digest
        fh.write(b"XXXX")
    assert sup.newest_good_snapshot(str(tmp_path), "s") == a
