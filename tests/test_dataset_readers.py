"""Binary dataset readers exercised against synthetic files in the
REAL formats (reference pattern: ``znicz/tests/functional/`` ran
against actual MNIST idx / CIFAR binary files; this environment has
zero egress, so the formats are synthesized bit-exactly instead —
idx magic/dims/payload, CIFAR-10 3073-byte label+CHW records)."""

import gzip
import os
import struct

import numpy as np
import pytest

from znicz_tpu import datasets
from znicz_tpu.utils.config import root


def write_idx(path: str, arr: np.ndarray) -> None:
    """Serialize an array in idx-ubyte format (magic 0x080000nn with
    nn = ndim, big-endian dims, raw uint8 payload) — the exact layout
    of MNIST's train-images-idx3-ubyte / train-labels-idx1-ubyte."""
    arr = np.ascontiguousarray(arr, np.uint8)
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "wb") as f:
        f.write(struct.pack(">I", 0x800 | arr.ndim))
        f.write(struct.pack(">" + "I" * arr.ndim, *arr.shape))
        f.write(arr.tobytes())


def write_cifar_batch(path: str, images_nhwc: np.ndarray,
                      labels: np.ndarray) -> None:
    """Serialize CIFAR-10 binary records: 1 label byte + 3072 bytes of
    CHW planes per image (the format of ``data_batch_*.bin``)."""
    chw = np.ascontiguousarray(
        images_nhwc.transpose(0, 3, 1, 2), np.uint8)
    records = np.concatenate(
        [labels.astype(np.uint8)[:, None],
         chw.reshape(len(chw), -1)], axis=1)
    records.tofile(path)


@pytest.fixture
def datasets_dir(tmp_path):
    """Point ``root.common.dirs.datasets`` at a tmp tree; restore."""
    old = root.common.dirs.datasets
    root.common.dirs.datasets = str(tmp_path)
    yield tmp_path
    root.common.dirs.datasets = old


def test_read_idx_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, size=(7, 28, 28), dtype=np.uint8)
    labels = rng.integers(0, 10, size=7).astype(np.uint8)
    write_idx(str(tmp_path / "imgs"), images)
    write_idx(str(tmp_path / "imgs.gz"), images)
    write_idx(str(tmp_path / "labs"), labels)
    np.testing.assert_array_equal(
        datasets._read_idx(str(tmp_path / "imgs")), images)
    np.testing.assert_array_equal(
        datasets._read_idx(str(tmp_path / "imgs.gz")), images)
    np.testing.assert_array_equal(
        datasets._read_idx(str(tmp_path / "labs")), labels)


def _write_mnist_fixture(datasets_dir, n_train=600, n_test=100):
    """Learnable synthetic digits serialized through the idx format
    (mixed .gz and plain to cover both openers)."""
    tx, ty, sx, sy = datasets.synthetic_images(
        n_train=n_train, n_test=n_test, size=28, channels=0,
        n_classes=10, seed=9)
    mnist_dir = datasets_dir / "mnist"
    mnist_dir.mkdir()
    write_idx(str(mnist_dir / "train-images-idx3-ubyte"), tx)
    write_idx(str(mnist_dir / "train-labels-idx1-ubyte.gz"), ty)
    write_idx(str(mnist_dir / "t10k-images-idx3-ubyte.gz"), sx)
    write_idx(str(mnist_dir / "t10k-labels-idx1-ubyte"), sy)
    return tx, ty, sx, sy


def test_load_mnist_reads_idx_files(datasets_dir):
    tx, ty, sx, sy = _write_mnist_fixture(datasets_dir)
    assert datasets.mnist_is_real()
    got = datasets.load_mnist()
    np.testing.assert_array_equal(got[0], tx)
    np.testing.assert_array_equal(got[1], ty)
    np.testing.assert_array_equal(got[2], sx)
    np.testing.assert_array_equal(got[3], sy)


def test_load_cifar10_reads_binary_batches(datasets_dir):
    rng = np.random.default_rng(1)
    base = datasets_dir / "cifar-10-batches-bin"
    base.mkdir()
    train_parts, label_parts = [], []
    for i in range(1, 6):
        imgs = rng.integers(0, 256, size=(20, 32, 32, 3),
                            dtype=np.uint8)
        labs = rng.integers(0, 10, size=20).astype(np.int32)
        write_cifar_batch(str(base / f"data_batch_{i}.bin"), imgs, labs)
        train_parts.append(imgs)
        label_parts.append(labs)
    test_imgs = rng.integers(0, 256, size=(10, 32, 32, 3),
                             dtype=np.uint8)
    test_labs = rng.integers(0, 10, size=10).astype(np.int32)
    write_cifar_batch(str(base / "test_batch.bin"), test_imgs, test_labs)

    train_x, train_y, test_x, test_y = datasets.load_cifar10()
    assert train_x.shape == (100, 32, 32, 3)  # NHWC restored from CHW
    np.testing.assert_array_equal(train_x, np.concatenate(train_parts))
    np.testing.assert_array_equal(train_y, np.concatenate(label_parts))
    np.testing.assert_array_equal(test_x, test_imgs)
    np.testing.assert_array_equal(test_y, test_labs)


def test_mnist_sample_trains_from_idx_files(datasets_dir):
    """End-to-end: the MnistSimple sample consumes idx files from disk
    through the real parse path and trains well below chance."""
    from znicz_tpu.backends import XLADevice
    from znicz_tpu.models.samples import mnist
    from znicz_tpu.utils import prng

    _write_mnist_fixture(datasets_dir)
    prng.seed_all(3)
    wf = mnist.build(max_epochs=4, learning_rate=0.1)
    wf.initialize(device=XLADevice())
    wf.run()
    # 60 validation samples, 10 classes: chance ≈ 54 errors; the
    # prototype-structured digits are easily separable
    assert int(wf.decision.min_validation_n_err) <= 15
