"""Small vision demo samples (reference: ``znicz/samples/YaleFaces``,
``Hands``, ``Channels`` — SURVEY.md §2.4 model-zoo rows)."""

import numpy as np
import pytest

from znicz_tpu.backends import XLADevice
from znicz_tpu.utils.config import root


@pytest.mark.parametrize("module, max_err_pt", [
    ("yale_faces", 25.0),
    ("hands", 15.0),
    # channels is the heavy one (~20 s: widest synthetic images);
    # slow-tiered by the round-22 budget audit
    pytest.param("channels", 30.0, marks=pytest.mark.slow),
])
def test_sample_converges_synthetic(module, max_err_pt):
    import importlib

    mod = importlib.import_module(f"znicz_tpu.models.samples.{module}")
    wf = mod.build(max_epochs=8)
    wf.initialize(device=XLADevice())
    wf.run()
    assert wf.decision.min_validation_n_err_pt <= max_err_pt, \
        f"{module}: {wf.decision.min_validation_n_err_pt}"


def test_yale_faces_real_directory(tmp_path):
    """With a class-per-subdir tree under datasets/yalefaces the
    sample loads real files through the image stack (validation carve
    included)."""
    from PIL import Image

    rng = np.random.default_rng(3)
    base = tmp_path / "datasets" / "yalefaces"
    protos = rng.integers(0, 256, size=(4, 32, 32))
    for subject in range(4):
        d = base / f"subject{subject:02d}"
        d.mkdir(parents=True)
        for i in range(10):
            img = np.clip(protos[subject]
                          + rng.normal(0, 30, (32, 32)), 0, 255)
            Image.fromarray(img.astype(np.uint8), mode="L").save(
                d / f"img_{i}.png")
    root.common.dirs.datasets = str(tmp_path / "datasets")
    from znicz_tpu.models.samples import yale_faces

    wf = yale_faces.build(max_epochs=6, n_subjects=4, minibatch_size=8)
    wf.initialize(device=XLADevice())
    from znicz_tpu.loader.image import FullBatchImageLoader
    assert isinstance(wf.loader, FullBatchImageLoader)
    assert wf.loader.class_lengths[2] + wf.loader.class_lengths[1] == 40
    wf.run()
    assert wf.decision.min_validation_n_err_pt <= 50.0


@pytest.mark.slow
def test_imagenet_sample_streams_from_tree(tmp_path):
    """The imagenet sample builds over a class-per-subdir JPEG tree
    and trains a step through the streaming pipeline."""
    from PIL import Image

    rng = np.random.default_rng(9)
    base = tmp_path / "train"
    for cls in range(3):
        d = base / f"class{cls}"
        d.mkdir(parents=True)
        for i in range(8):
            Image.fromarray(rng.integers(0, 256, (64, 64, 3),
                                         dtype=np.uint8)
                            ).save(d / f"i{i}.jpg")
    from znicz_tpu.models.samples import imagenet

    wf = imagenet.build(train_dir=str(base), minibatch_size=4,
                        n_classes=3, image_size=35, resize_size=40,
                        max_epochs=1)
    wf.initialize(device=XLADevice())
    from znicz_tpu.loader.image import FileImageLoader
    assert isinstance(wf.loader, FileImageLoader)
    wf.loader.run()
    wf._region_unit.run()
    wf.forwards[-1].weights.devmem.block_until_ready()
