"""Input-pipeline throughput: the question ``native/pipeline.cpp``
exists to answer (SURVEY.md §7 "feed AlexNet at 8k img/s") gets a
measured, asserted number.

Context for the bound: this container exposes a SINGLE host core
(``nproc`` = 1); measured decode+resize+augment throughput here is
~1000 img/s (≈1 ms/image for a 256×256 JPEG → 227×227 crop).  The
north-star host (TPU v4 host with ~120 cores) scales the pool
linearly, so per-core throughput is the portable metric: the floor
asserts ≥400 img/s/core — half the measured rate, leaving headroom
for CI noise — which at ImageNet-host core counts clears the 8k img/s
target with an order of magnitude to spare.
"""

import os
import time

import numpy as np
import pytest

from znicz_tpu.native import ImagePipeline

pytestmark = pytest.mark.skipif(
    not ImagePipeline.available(),
    reason=f"native pipeline unavailable: {ImagePipeline.build_error()}")


def _make_jpegs(base, n_files=64, hw=(256, 256)) -> list[str]:
    from PIL import Image

    rng = np.random.default_rng(0)
    paths = []
    os.makedirs(base, exist_ok=True)
    for i in range(n_files):
        path = os.path.join(base, f"img_{i}.jpg")
        Image.fromarray(rng.integers(0, 256, size=hw + (3,),
                                     dtype=np.uint8)).save(path, quality=90)
        paths.append(path)
    return paths


@pytest.mark.slow
def test_decode_throughput_per_core(tmp_path):
    paths = _make_jpegs(str(tmp_path))
    pipe = ImagePipeline(n_threads=0)  # auto: one per core
    batch, reps = 64, 8
    out = np.zeros((batch,) + (227, 227, 3), np.float32)
    sel = [paths[i % len(paths)] for i in range(batch)]

    def run_once(seed):
        pipe.submit(sel, out, out_hw=(227, 227), resize_hw=(256, 256),
                    random_crop=True, random_flip=True,
                    scale=1 / 127.5, bias=-1.0, seed=seed)
        assert pipe.wait() == 0

    run_once(0)  # warm (first-use lib pings, page faults)
    start = time.perf_counter()
    for rep in range(reps):
        run_once(rep + 1)
    elapsed = time.perf_counter() - start

    n_cores = os.cpu_count() or 1
    img_per_sec = batch * reps / elapsed
    per_core = img_per_sec / n_cores
    print(f"\ndecode throughput: {img_per_sec:.0f} img/s total, "
          f"{per_core:.0f} img/s/core ({n_cores} cores)")
    assert per_core >= 400.0, \
        f"decode pool too slow: {per_core:.0f} img/s/core"
