"""Multi-head attention units: oracle↔XLA agreement, analytic-vs-vjp
gradients, the sequence-parallel ring path on the virtual mesh, and
end-to-end training through StandardWorkflow."""

import numpy as np
import pytest

from znicz_tpu.backends import NumpyDevice, XLADevice
from znicz_tpu.dummy import DummyUnit, DummyWorkflow
from znicz_tpu.memory import Vector
from znicz_tpu.ops import attention
from znicz_tpu.utils import prng

B, T, D, H = 2, 8, 12, 3


def build(device, x, gd=False, **kwargs):
    prng.seed_all(5)
    wf = DummyWorkflow()
    src = DummyUnit(wf, output=Vector(np.asarray(x), name="x"))
    fwd = attention.MultiHeadAttention(wf, n_heads=H, **kwargs)
    fwd.link_attrs(src, ("input", "output"))
    fwd.initialize(device=device)
    if not gd:
        return fwd
    err = Vector(np.zeros((x.shape[0], x.shape[1], x.shape[2]),
                          np.float32), name="err")
    unit = attention.GDMultiHeadAttention(
        wf, learning_rate=0.05, gradient_moment=0.9)
    unit.forward_unit = fwd
    unit.link_attrs(fwd, "input", "output", "weights", "bias")
    unit.err_output = err
    unit.initialize(device=device)
    return fwd, unit


def _rand(seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(0, 0.5, size=(B, T, D)).astype(np.float32)


@pytest.mark.parametrize("causal", [False, True])
def test_forward_oracle_agreement(causal):
    x = _rand()
    np_u = build(NumpyDevice(), x, causal=causal)
    xla_u = build(XLADevice(), x, causal=causal)
    for src, dst in ((np_u.weights, xla_u.weights),
                     (np_u.bias, xla_u.bias),
                     (np_u.weights_out, xla_u.weights_out),
                     (np_u.bias_out, xla_u.bias_out)):
        dst.reset(src.mem.copy())
        dst.initialize(xla_u.device)
    np_u.run()
    xla_u.run()
    np_u.output.map_read()
    xla_u.output.map_read()
    np.testing.assert_allclose(np_u.output.mem, xla_u.output.mem,
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_backward_oracle_vs_vjp(causal):
    """The analytic numpy backward and jax.vjp agree on every
    gradient (weights updated identically from identical errors)."""
    x = _rand(1)
    err = np.random.default_rng(2).normal(
        0, 0.1, size=(B, T, D)).astype(np.float32)
    results = {}
    for device in (NumpyDevice(), XLADevice()):
        fwd, gd_u = build(device, x, gd=True, causal=causal)
        if results:  # copy the numpy init into the XLA run
            (w0, wo0, b0, bo0) = results["init"]
            for vec, arr in ((fwd.weights, w0), (fwd.weights_out, wo0),
                             (fwd.bias, b0), (fwd.bias_out, bo0)):
                vec.reset(arr.copy())
                vec.initialize(device)
        else:
            results["init"] = (fwd.weights.mem.copy(),
                               fwd.weights_out.mem.copy(),
                               fwd.bias.mem.copy(),
                               fwd.bias_out.mem.copy())
        fwd.run()
        gd_u.err_output.reset(err.copy())
        gd_u.err_output.initialize(device)
        gd_u.run()
        for vec in (fwd.weights, fwd.weights_out, fwd.bias,
                    fwd.bias_out, gd_u.err_input):
            vec.map_read()
        results[type(device).__name__] = (
            fwd.weights.mem.copy(), fwd.weights_out.mem.copy(),
            fwd.bias.mem.copy(), fwd.bias_out.mem.copy(),
            gd_u.err_input.mem.astype(np.float32).copy())
    for a, b in zip(results["NumpyDevice"], results["XLADevice"]):
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=1e-4)


def test_numeric_gradient():
    """err_input from the analytic oracle matches finite differences
    of a scalar loss through the forward."""
    x = _rand(3)[:1, :4]  # tiny for FD cost
    np_u, gd_u = build(NumpyDevice(), x, gd=True)
    np_u.run()
    # loss = sum(y * c)
    c = np.random.default_rng(4).normal(
        size=np_u.output.shape).astype(np.float32)
    gd_u.err_output.reset(c.copy())
    gd_u.learning_rate = 0.0  # no weight update; just err_input
    gd_u.gradient_moment = 0.0
    gd_u.run()
    gd_u.err_input.map_read()
    analytic = gd_u.err_input.mem.copy()
    eps = 1e-3
    fd = np.zeros_like(x)
    for idx in np.ndindex(*x.shape):
        for sign in (1, -1):
            xp = x.copy()
            xp[idx] += sign * eps
            np_u.input.reset(xp)
            np_u.run()
            np_u.output.map_read()
            fd[idx] += sign * float((np_u.output.mem * c).sum())
    fd /= 2 * eps
    np.testing.assert_allclose(analytic, fd, rtol=2e-2, atol=2e-3)


def test_seq_parallel_matches_local():
    """Ring attention over the mesh's model axis produces the same
    output as the local path (the unit falls back to local when the
    mesh has no model axis)."""
    from znicz_tpu.parallel import make_mesh

    x = _rand(6)
    local = build(XLADevice(), x, causal=True)
    mesh = make_mesh(n_data=2, n_model=4)
    ring = build(XLADevice(mesh=mesh), x, causal=True,
                 seq_parallel=True)
    assert ring.ring_active, "mesh has a model axis; ring must engage"
    assert ring.output.model_shard_dim == 1
    for src, dst in ((local.weights, ring.weights),
                     (local.bias, ring.bias),
                     (local.weights_out, ring.weights_out),
                     (local.bias_out, ring.bias_out)):
        dst.reset(np.asarray(src).copy())
        dst.initialize(ring.device)
    local.run()
    ring.run()
    # DP composes with SP: the ring's shard_map spec threads the data
    # axis, so the output stays batch-sharded (2 shards) while the
    # time axis rides the model ring (4 shards)
    out_shard = ring.output.devmem.sharding.shard_shape(
        ring.output.devmem.shape)
    assert out_shard == (B // 2, T // 4, D), out_shard
    local.output.map_read()
    ring.output.map_read()
    np.testing.assert_allclose(np.asarray(ring.output.mem, np.float32),
                               np.asarray(local.output.mem, np.float32),
                               rtol=1e-4, atol=1e-5)


def test_trains_in_standard_workflow():
    """'attention' layer type end to end: classify which third of the
    sequence holds the marker token (needs cross-position mixing —
    attention solves it, and the loss must actually fall)."""
    from znicz_tpu.loader.fullbatch import ArrayLoader
    from znicz_tpu.models.standard_workflow import StandardWorkflow

    rng = np.random.default_rng(9)
    n, t, d, n_classes = 96, 9, 8, 3
    x = rng.normal(0, 0.3, size=(n, t, d)).astype(np.float32)
    y = rng.integers(0, n_classes, size=n).astype(np.int32)
    marker = np.ones(d, np.float32) * 2.0
    for i in range(n):
        x[i, y[i] * 3 + rng.integers(0, 3)] += marker
    prng.seed_all(11)
    wf = StandardWorkflow(
        name="attn_wf",
        loader_factory=lambda w: ArrayLoader(
            w, train_data=x[:72], train_labels=y[:72],
            valid_data=x[72:], valid_labels=y[72:], minibatch_size=24),
        layers=[
            {"type": "attention", "->": {"n_heads": 2},
             "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
            {"type": "softmax", "->": {"output_sample_shape": n_classes},
             "<-": {"learning_rate": 0.05, "gradient_moment": 0.9}},
        ],
        decision_config={"max_epochs": 25})
    wf._max_fires = 10 ** 6
    wf.initialize(device=XLADevice())
    wf.run()
    assert wf.decision.min_validation_n_err_pt <= 25.0


@pytest.mark.slow
def test_seq_parallel_backward_matches_local():
    """Training through the ring (jax.vjp differentiates the
    shard_map/ppermute loop) must update weights and propagate
    err_input identically to the local-attention path."""
    from znicz_tpu.parallel import make_mesh

    x = _rand(12)
    err = np.random.default_rng(13).normal(
        0, 0.1, size=(B, T, D)).astype(np.float32)
    results = {}
    init = None
    for mode in ("local", "ring"):
        if mode == "ring":
            device = XLADevice(mesh=make_mesh(n_data=2, n_model=4))
        else:
            device = XLADevice()
        fwd, gd_u = build(device, x, gd=True, causal=True,
                          seq_parallel=(mode == "ring"))
        if mode == "ring":
            assert fwd.ring_active
        if init is None:
            init = (fwd.weights.mem.copy(), fwd.weights_out.mem.copy(),
                    fwd.bias.mem.copy(), fwd.bias_out.mem.copy())
        else:
            for vec, arr in zip((fwd.weights, fwd.weights_out,
                                 fwd.bias, fwd.bias_out), init):
                vec.reset(arr.copy())
                vec.initialize(device)
        fwd.run()
        gd_u.err_output.reset(err.copy())
        gd_u.err_output.initialize(device)
        gd_u.run()
        for vec in (fwd.weights, fwd.weights_out, fwd.bias,
                    fwd.bias_out, gd_u.err_input):
            vec.map_read()
        results[mode] = (
            fwd.weights.mem.copy(), fwd.weights_out.mem.copy(),
            fwd.bias.mem.copy(), fwd.bias_out.mem.copy(),
            np.asarray(gd_u.err_input.mem, np.float32).copy())
    for a, b in zip(results["local"], results["ring"]):
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=1e-4)


def test_attention_seq_sample():
    """The zoo sample builds and trains through the CLI protocol."""
    from znicz_tpu.models.samples import attention_seq
    from znicz_tpu.utils.config import root

    prng.seed_all(17)
    prev = root.attention_seq.max_epochs
    root.attention_seq.max_epochs = 12
    try:
        wf = attention_seq.build()
        wf.initialize(device=XLADevice())
        wf.run()
    finally:
        root.attention_seq.max_epochs = prev
    assert wf.decision.min_validation_n_err_pt <= 20.0


def test_attention_export_roundtrip(tmp_path):
    """Export must carry BOTH attention parameter pairs (a fresh
    weights_out would silently corrupt served predictions)."""
    from znicz_tpu.export import ExportedModel, export_forward
    from znicz_tpu.loader.fullbatch import ArrayLoader
    from znicz_tpu.models.standard_workflow import StandardWorkflow

    rng = np.random.default_rng(21)
    x = rng.normal(0, 0.5, size=(48, 6, 8)).astype(np.float32)
    y = rng.integers(0, 3, size=48).astype(np.int32)
    prng.seed_all(22)
    wf = StandardWorkflow(
        name="attn_export",
        loader_factory=lambda w: ArrayLoader(
            w, train_data=x, train_labels=y, minibatch_size=16),
        layers=[{"type": "attention", "->": {"n_heads": 2},
                 "<-": {"learning_rate": 0.05}},
                {"type": "softmax", "->": {"output_sample_shape": 3},
                 "<-": {"learning_rate": 0.05}}],
        decision_config={"max_epochs": 2})
    wf._max_fires = 10 ** 6
    wf.initialize(device=XLADevice())
    wf.run()
    path = export_forward(wf, str(tmp_path / "attn.npz"))
    served = ExportedModel.load(path, device=XLADevice())
    batch = x[:8]
    probs = np.asarray(served(batch))
    # reference: the workflow's own forward math on the same weights
    fwd = wf.forwards[0]
    for vec in (fwd.weights, fwd.bias, fwd.weights_out, fwd.bias_out,
                wf.forwards[1].weights, wf.forwards[1].bias):
        vec.map_read()
    y1, _ = fwd._forward_np(batch)
    logits = y1.reshape(8, -1) @ wf.forwards[1].weights.mem \
        + wf.forwards[1].bias.mem
    e = np.exp(logits - logits.max(axis=1, keepdims=True))
    expected = e / e.sum(axis=1, keepdims=True)
    np.testing.assert_allclose(probs, expected, rtol=1e-3, atol=1e-4)


def test_export_refuses_missing_params(tmp_path):
    """A bundle lacking a parameter the rebuilt unit random-fills
    (e.g. pre-EXPORT_PARAMS attention exports) must refuse to serve,
    not silently substitute noise."""
    import io
    import json

    from znicz_tpu.export import ExportedModel, export_forward
    from znicz_tpu.loader.fullbatch import ArrayLoader
    from znicz_tpu.models.standard_workflow import StandardWorkflow

    rng = np.random.default_rng(23)
    x = rng.normal(size=(32, 4, 8)).astype(np.float32)
    y = rng.integers(0, 2, size=32).astype(np.int32)
    prng.seed_all(24)
    wf = StandardWorkflow(
        name="attn_trunc",
        loader_factory=lambda w: ArrayLoader(
            w, train_data=x, train_labels=y, minibatch_size=16),
        layers=[{"type": "attention", "->": {"n_heads": 2},
                 "<-": {"learning_rate": 0.05}},
                {"type": "softmax", "->": {"output_sample_shape": 2},
                 "<-": {"learning_rate": 0.05}}],
        decision_config={"max_epochs": 1})
    wf._max_fires = 10 ** 6
    wf.initialize(device=XLADevice())
    wf.run()
    path = export_forward(wf, str(tmp_path / "full.npz"))
    # rewrite the bundle WITHOUT the attention out-projection arrays —
    # the shape of a pre-EXPORT_PARAMS export
    with np.load(path) as bundle:
        arrays = {k: bundle[k] for k in bundle.files
                  if not k.endswith(("weights_out", "bias_out"))}
    trunc = str(tmp_path / "truncated.npz")
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    with open(trunc, "wb") as fh:
        fh.write(buf.getvalue())
    served = ExportedModel.load(trunc, device=XLADevice())
    with pytest.raises(ValueError, match="missing from the bundle"):
        served(x[:4])


def test_positional_encoding():
    """PE forward adds the exact sinusoid table (oracle == XLA) and
    the backward passes errors through untouched."""
    from znicz_tpu.ops import pos_encoding

    x = _rand(31)
    np_u = build_pe(NumpyDevice(), x)
    xla_u = build_pe(XLADevice(), x)
    np_u.run()
    xla_u.run()
    np_u.output.map_read()
    xla_u.output.map_read()
    table = pos_encoding.sinusoid_table(T, D)
    np.testing.assert_allclose(np_u.output.mem, x + table, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(xla_u.output.mem, np.float32), x + table,
        rtol=1e-4, atol=1e-5)
    # backward: identity pass-through of the error cotangent
    err = _rand(32)
    gd_u = pos_encoding.GDPositionalEncoding(np_u.workflow)
    gd_u.forward_unit = np_u
    gd_u.link_attrs(np_u, "input", "output")
    gd_u.err_output = Vector(err.copy(), name="err", batch_major=True)
    gd_u.initialize(device=NumpyDevice())
    gd_u.run()
    gd_u.err_input.map_read()
    np.testing.assert_array_equal(gd_u.err_input.mem, err)


def build_pe(device, x):
    from znicz_tpu.ops import pos_encoding

    wf = DummyWorkflow()
    src = DummyUnit(wf, output=Vector(np.asarray(x), name="x"))
    unit = pos_encoding.PositionalEncoding(wf)
    unit.link_attrs(src, ("input", "output"))
    unit.initialize(device=device)
    return unit


def test_pe_attention_trains_on_positional_task():
    """Class = which third of the sequence carries the energy bump;
    without positions the attention pool is permutation-invariant, so
    passing this bound certifies PE actually injects position."""
    from tests.conftest import positional_task_workflow

    gd = {"learning_rate": 0.05, "gradient_moment": 0.9}
    wf = positional_task_workflow(
        [{"type": "pos_encoding", "->": {}},
         {"type": "attention", "->": {"n_heads": 2}, "<-": gd},
         {"type": "softmax", "->": {"output_sample_shape": 3},
          "<-": gd}],
        data_seed=41, prng_seed=42)
    wf.initialize(device=XLADevice())
    wf.run()
    assert wf.decision.min_validation_n_err_pt <= 25.0
